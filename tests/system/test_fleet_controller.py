"""Elastic fleet control plane units (ISSUE 12): the manager HA lease
(epoch fencing, takeover, supersede), the watermark autoscaler policy
(sustain/cooldown/floors/ceilings/pending gating), the ONE
``_forget_server`` helper shared by eviction / URL replacement / drain
departure, and — satellite 3 — a REAL successor manager constructed
over a fake heartbeat + /metrics snapshot whose /status matches the
pre-kill manager's, as a unit (no multi-process e2e required to pin
the rebuild contract).

Time budget: ~10 s (two in-process managers over fake HTTP servers;
no jax engines)."""

import collections
import http.server
import json
import threading
import time
import urllib.request

import pytest

from areal_tpu.base import name_resolve, names
from areal_tpu.base.health import Heartbeat
from areal_tpu.system import fleet_controller as fc


@pytest.fixture()
def kv(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_FLEET_LEASE_TTL", "0.2")
    repo = name_resolve.reconfigure(
        "nfs", record_root=str(tmp_path / "name_resolve")
    )
    yield repo
    repo.reset()


EXP, TRIAL = "fleet-units", "t0"


# ----------------------------------------------------------------------
# ManagerLease
# ----------------------------------------------------------------------

def test_lease_first_boot_take_and_renew(kv):
    lease = fc.ManagerLease(EXP, TRIAL)
    assert lease.read() is None
    epoch = lease.take("http://m1:1", weight_version=0, prior=None)
    assert epoch == 1
    rec = lease.read()
    assert (rec.epoch, rec.addr, rec.weight_version) == (1, "http://m1:1", 0)
    assert not rec.expired()
    assert lease.renew(weight_version=5, force=True)
    assert lease.read().weight_version == 5


def test_lease_takeover_waits_expiry_and_fences_epoch(kv):
    old = fc.ManagerLease(EXP, TRIAL)
    old.take("http://m1:1", weight_version=3)
    successor = fc.ManagerLease(EXP, TRIAL)
    # Holder alive (fresh record): the standby parks.
    with pytest.raises(TimeoutError):
        successor.wait_expired(timeout=0.05)
    # Holder dies (stops renewing): takeover after ~3 TTLs.
    t0 = time.monotonic()
    prior = successor.wait_expired(timeout=10.0)
    assert prior.epoch == 1 and prior.weight_version == 3
    assert time.monotonic() - t0 < 5.0
    assert successor.take("http://m2:2", prior.weight_version,
                          prior=prior) == 2
    # The zombie predecessor's next renew sees the higher epoch and
    # reports it must stand down — WITHOUT clobbering the record.
    assert not old.renew(weight_version=3, force=True)
    assert successor.read().addr == "http://m2:2"


def test_lease_equal_epoch_duel_resolves(kv):
    """Two racing takeovers can write the SAME epoch (take() is
    last-writer-wins, not CAS): the one whose write lost the race must
    stand down on its next renew — same epoch, different address."""
    a = fc.ManagerLease(EXP, TRIAL)
    b = fc.ManagerLease(EXP, TRIAL)
    a.take("http://a:1", weight_version=0)
    b.take("http://b:2", weight_version=0)  # same epoch, later write
    assert a.epoch == b.epoch == 1
    # a's write lost: it stands down; b (the record holder) renews on.
    assert not a.renew(weight_version=0, force=True)
    assert b.renew(weight_version=0, force=True)
    assert b.read().addr == "http://b:2"


# ----------------------------------------------------------------------
# WatermarkAutoscaler
# ----------------------------------------------------------------------

def _scaler(**kw):
    now = [0.0]
    pol = fc.AutoscalePolicy(
        scale_out_queued_tokens=1000, scale_in_queued_tokens=10,
        scale_free_page_min_frac=0.5, pool_min_servers=1,
        pool_max_servers=4, cooldown_s=30.0, sustain_polls=2, **kw,
    )
    return fc.WatermarkAutoscaler(pol, clock=lambda: now[0]), now


def test_autoscaler_sustain_then_out_then_cooldown():
    a, now = _scaler()
    # One bursty poll must not launch.
    assert a.observe(2, 0, 5000.0, 1.0) is None
    assert a.observe(2, 0, 5000.0, 1.0) == "out"
    # Cooldown: no double launch even under sustained pressure.
    assert a.observe(2, 1, 5000.0, 1.0) is None
    assert a.observe(2, 1, 5000.0, 1.0) is None
    now[0] = 31.0
    # Pressure was sustained straight through the cooldown: the next
    # poll past it acts (the debounce already happened).
    assert a.observe(2, 1, 5000.0, 1.0) == "out"


def test_autoscaler_ceiling_counts_pending():
    a, _ = _scaler()
    # 3 routable + 1 joining = at the 4-server ceiling: never "out".
    for _ in range(5):
        assert a.observe(3, 1, 9000.0, 1.0) is None


def test_autoscaler_in_requires_idle_and_pages_and_floor():
    a, _ = _scaler()
    assert a.observe(2, 0, 0.0, 1.0) is None
    assert a.observe(2, 0, 0.0, 1.0) == "in"
    a2, _ = _scaler()
    # Free pages tight: scale-in blocked (draining would amplify it).
    for _ in range(4):
        assert a2.observe(2, 0, 0.0, 0.1) is None
    a3, _ = _scaler()
    # At the floor: never "in".
    for _ in range(4):
        assert a3.observe(1, 0, 0.0, 1.0) is None


def test_autoscaler_unroutable_fleet_counts_as_pressure():
    a, _ = _scaler()
    assert a.observe(0, 0, 0.0, 1.0) is None
    assert a.observe(0, 0, 0.0, 1.0) == "out"
    # With a launch already pending, an unroutable fleet must NOT
    # stack further launches onto a blip that resolves itself.
    a2, _ = _scaler()
    for _ in range(4):
        assert a2.observe(0, 1, 0.0, 1.0) is None


# ----------------------------------------------------------------------
# _forget_server (satellite: ONE helper for eviction / replacement /
# drain departure)
# ----------------------------------------------------------------------

A, B = "http://a:1", "http://b:2"


def _manager():
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.system.gserver_manager import GserverManager

    m = GserverManager.__new__(GserverManager)
    m.cfg = GserverManagerConfig(n_servers=2)
    m.server_urls = [A, B]
    m._healthy = set(m.server_urls)
    m._evicted = {}
    m._rr = 0
    m._lock = threading.Lock()
    m._server_reqs = {u: 3 for u in m.server_urls}
    m._server_tokens = {u: 1.0 for u in m.server_urls}
    m._server_tokens_pending = {u: 2.0 for u in m.server_urls}
    m._server_shed_until = {u: time.monotonic() + 99 for u in m.server_urls}
    m._server_shed_total = {u: 4.0 for u in m.server_urls}
    for attr in (
        "_server_gen_totals", "_server_prefix_hits",
        "_server_prefix_reused", "_server_gen_reqs",
        "_server_spec_emitted", "_server_spec_steps",
        "_server_queued_toks",
    ):
        setattr(m, attr, {u: 1.0 for u in m.server_urls})
    m._server_free_pages = {}
    m._server_total_pages = {}
    m._server_kv = {}
    m._server_elastic = {}
    m._server_ttft_hist = {}
    m._server_itl_hist = {}
    m._server_roles = {u: "unified" for u in m.server_urls}
    m._server_shards = {A: (0, 2), B: (1, 2)}
    # Multi-model plane (ISSUE 20): one more per-server sparse map.
    m._server_models = {u: "actor" for u in m.server_urls}
    m._server_versions = {u: 7 for u in m.server_urls}
    m._member_urls = {"generation_server/0": A, "generation_server/1": B}
    m._rerole_orig = {}
    m._rerole_log = []
    m._affinity = collections.OrderedDict({"q1": A, "q2": B})
    m._kv_index_size = 100
    m._prefix_index = collections.OrderedDict({
        "q1": {"url": A, "tier": "host"},
        "q2": {"url": B, "tier": "host"},
    })
    m._server_kv_index = {A: {"q1"}, B: {"q2"}}
    m._draining = {A}
    m._drain_deadline = {A: time.monotonic() + 99}
    m._join_t0 = {}
    m._join_info = {}
    m._last_gen_total = 0.0
    m.weight_version = 7
    return m


def test_forget_server_eviction_drops_everything_together():
    """Eviction (remove=False): affinity entries, prefix-index entries,
    shard row, shed window, and load estimates all go in ONE call — the
    drift the satellite kills (three ad-hoc pruning sites)."""
    m = _manager()
    with m._lock:
        m._forget_server(A)
    assert "q1" not in m._affinity and "q2" in m._affinity
    assert "q1" not in m._prefix_index and "q2" in m._prefix_index
    assert A not in m._server_shards and B in m._server_shards
    assert m._server_shed_until[A] == 0.0
    assert m._server_reqs[A] == 0 and m._server_tokens_pending[A] == 0.0
    assert A not in m._draining and A not in m._drain_deadline
    # Still a member (readmission may return it), version preserved.
    assert A in m.server_urls and m._server_versions[A] == 7


def test_forget_server_remove_drops_the_whole_row():
    m = _manager()
    with m._lock:
        m._forget_server(A, remove=True)
    assert m.server_urls == [B]
    for attr in ("_server_tokens", "_server_reqs", "_server_roles",
                 "_server_versions", "_server_shed_total"):
        assert A not in getattr(m, attr), attr
    assert "generation_server/0" not in m._member_urls
    assert A not in m._healthy and A not in m._evicted


def test_mark_unhealthy_routes_around_and_replace_uses_forget():
    m = _manager()
    m._draining = set()
    m._drain_deadline = {}
    m._mark_unhealthy(B, "client-reported request failure")
    assert B in m._evicted and B not in m._healthy
    assert "q2" not in m._affinity and "q2" not in m._prefix_index
    C = "http://c:3"
    m._replace_server_url(A, C)
    assert sorted(m.server_urls) == sorted([B, C])
    assert m._evicted[C] == "restarted at new address"
    assert m._server_versions[C] == 0 and "q1" not in m._affinity


# ----------------------------------------------------------------------
# Satellite 3: manager state rebuild as a UNIT — a real successor
# manager over a fake heartbeat/metrics snapshot matches the pre-kill
# manager's /status.
# ----------------------------------------------------------------------

class _FakeGserver:
    """A heartbeat + a canned /metrics endpoint — everything the
    manager's poll (and a successor's rebuild) reads."""

    def __init__(self, exp, trial, index, role="unified", shard=None,
                 shed_total=0.0, draining=False, version=0):
        lines = [
            "areal:num_used_tokens 0.0",
            "areal:num_running_reqs 0",
            f"areal:load_shed_total {float(shed_total)}",
            f"areal:role {role}",
            "areal:elastic 1.0",
            f"areal:weight_version {float(version)}",
            "areal:weight_shard "
            + (f"{shard[0]}/{shard[1]}" if shard else "-"),
            f"areal:draining {1.0 if draining else 0.0}",
        ]
        body = ("\n".join(lines) + "\n").encode()

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self, _body=body):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(_body)

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        payload = {"url": self.url, "server_index": index, "role": role}
        if shard:
            payload["weight_shard"] = list(shard)
        if draining:
            payload["draining"] = True
        self.hb = Heartbeat(
            exp, trial, f"generation_server/{index}", payload=payload,
            ttl=60.0,
        )
        name_resolve.add_subentry(names.gen_servers(exp, trial), self.url)

    def close(self):
        self.httpd.shutdown()


def _status(addr):
    with urllib.request.urlopen(addr + "/status", timeout=10) as r:
        return json.loads(r.read())


def test_successor_status_matches_prekill_manager(kv):
    """Pre-kill manager A (normal boot + one health/metrics poll) vs
    successor B (lease takeover, membership/roles/shards/shed rebuilt
    from the SAME heartbeats + /metrics): /status agrees on
    membership, healthy split, roles, shards, versions, shed totals,
    and in-progress drains. History (joins/drains logs) and the
    affinity map die with the incarnation by design."""
    import asyncio

    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.system.gserver_manager import GserverManager

    exp = "fleet-rebuild"
    fakes = [
        _FakeGserver(exp, TRIAL, 0, role="prefill", shard=(0, 2),
                     shed_total=3.0),
        _FakeGserver(exp, TRIAL, 1, role="decode", shard=(1, 2)),
        _FakeGserver(exp, TRIAL, 2, role="unified", shed_total=1.0,
                     draining=True),
    ]
    managers = []
    try:
        def mk():
            m = GserverManager()
            m.configure(GserverManagerConfig(
                experiment_name=exp, trial_name=TRIAL, n_servers=3,
                train_batch_size=4, health_check_interval=0.1,
            ))
            managers.append(m)
            return m

        a = mk()
        a._poll_health()
        asyncio.run_coroutine_threadsafe(
            a._poll_metrics(), a._http_loop
        ).result(timeout=20)
        st_a = _status(a.address)
        assert st_a["fleet"]["epoch"] == 1
        # A dies (poll loop never ran, so its lease never renews);
        # successor B takes over after lease expiry and rebuilds from
        # heartbeats + /metrics.
        b = mk()
        assert b is not a
        asyncio.run_coroutine_threadsafe(
            b._poll_metrics(), b._http_loop
        ).result(timeout=20)
        st_b = _status(b.address)
        assert st_b["fleet"]["epoch"] == 2
        for key in ("servers", "healthy_servers", "server_versions"):
            assert st_b[key] == st_a[key], key
        assert st_b["pools"]["roles"] == st_a["pools"]["roles"]
        assert (st_b["pools"]["weight_shards"]
                == st_a["pools"]["weight_shards"])
        assert (st_b["load_shed"]["per_server"]
                == st_a["load_shed"]["per_server"])
        assert st_b["fleet"]["draining"] == st_a["fleet"]["draining"]
        assert st_b["weight_version"] == st_a["weight_version"]
    finally:
        for m in managers:
            try:
                m._exit_hook()
            except Exception:
                pass
        for f in fakes:
            f.close()


def test_rebuild_fleet_state_pure(kv):
    """The pure rebuild: heartbeat payloads are authoritative for
    identity, /metrics refines live surfaces; stopped members are
    excluded."""
    hb = {
        "generation_server/0": {
            "url": "http://s0", "server_index": 0, "role": "prefill",
            "weight_shard": [0, 2],
        },
        "generation_server/1": {
            "url": "http://s1", "server_index": 1, "draining": True,
        },
        "generation_server/2": {
            "url": "http://s2", "server_index": 2, "stopped": True,
        },
    }
    metrics = {
        "http://s0": {"areal:weight_version": 4.0,
                      "areal:load_shed_total": 2.0},
        "http://s1": {"areal:role": "decode", "areal:elastic": 1.0,
                      "areal:weight_version": 3.0},
    }
    st = fc.rebuild_fleet_state(hb, metrics)
    assert st.urls == ["http://s0", "http://s1"]
    assert st.roles == {"http://s0": "prefill", "http://s1": "decode"}
    assert st.shards["http://s0"] == (0, 2)
    assert st.shards["http://s1"] is None
    assert st.versions == {"http://s0": 4, "http://s1": 3}
    assert st.shed_totals["http://s0"] == 2.0
    assert st.draining == ["http://s1"]
    assert st.server_indices == {"http://s0": 0, "http://s1": 1}


def test_takeover_evicts_version_behind_servers(kv):
    """A successor inheriting weight_version V from the lease starts
    servers reporting an older version EVICTED ('version behind at
    takeover') so the bootstrap path re-syncs them before routing."""
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.system.gserver_manager import GserverManager

    exp = "fleet-behind"
    fakes = [
        _FakeGserver(exp, TRIAL, 0, version=5),
        _FakeGserver(exp, TRIAL, 1, version=4),
    ]
    # A previous manager's lease at version 5, long expired.
    lease = fc.ManagerLease(exp, TRIAL)
    lease.take("http://dead:1", weight_version=5)
    time.sleep(lease.ttl * 3.5)
    m = GserverManager()
    try:
        m.configure(GserverManagerConfig(
            experiment_name=exp, trial_name=TRIAL, n_servers=2,
            train_batch_size=4,
        ))
        assert m.weight_version == 5
        assert m._server_versions[fakes[0].url] == 5
        assert fakes[0].url in m._healthy
        assert m._evicted[fakes[1].url] == "version behind at takeover"
        # /status reflects the split; the readmission path owns the
        # rest (weight re-sync needs a live dump — not this unit).
        st = _status(m.address)
        assert st["healthy_servers"] == [fakes[0].url]
    finally:
        try:
            m._exit_hook()
        except Exception:
            pass
        for f in fakes:
            f.close()

"""Prompt+answer dataset for SFT (reference impl/dataset/prompt_answer_dataset.py).

jsonl rows need "prompt" and "answer". Produces `packed_input_ids`
(prompt+answer+eos) and a boolean `prompt_mask` (True over prompt tokens;
the SFT loss masks these out).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api import data_api
from areal_tpu.base import logging

logger = logging.getLogger("prompt_answer_dataset")


class PromptAnswerDataset:
    def __init__(
        self,
        util: data_api.DatasetUtility,
        max_length: int,
        dataset_path: Optional[str] = None,
        dataset_builder: Optional[Callable[[], List[Dict]]] = None,
    ):
        self.util = util
        tok = util.tokenizer
        data = data_api.load_shuffle_split_dataset(util, dataset_path, dataset_builder)
        self.ids = [str(x["id"]) for x in data]
        # Tokenize prompt and answer SEPARATELY and concatenate, so the
        # prompt token span is a prefix of the sequence by construction —
        # joint tokenization can merge tokens across the boundary, which
        # would silently misalign the loss mask.
        # add_special_tokens=False on both halves: a tokenizer that appends
        # a suffix special token (T5-style trailing EOS) would otherwise
        # plant an EOS between prompt and answer. BOS is re-added manually.
        prompt_enc = tok(
            [x["prompt"] for x in data],
            truncation=True,
            max_length=max_length,
            padding=False,
            return_attention_mask=False,
            add_special_tokens=False,
        )
        answer_enc = tok(
            [x["answer"] for x in data],
            truncation=True,
            max_length=max_length,
            padding=False,
            return_attention_mask=False,
            add_special_tokens=False,
        )
        bos_ids = [tok.bos_token_id] if tok.bos_token_id is not None else []
        eos_ids = [tok.eos_token_id] if tok.eos_token_id is not None else []
        self.tokens: List[List[int]] = []
        self.prompt_masks: List[np.ndarray] = []
        for prompt_ids, answer_ids in zip(prompt_enc["input_ids"], answer_enc["input_ids"]):
            prompt_ids = bos_ids + prompt_ids
            seq_ids = (prompt_ids + answer_ids + eos_ids)[:max_length]
            plen = min(len(prompt_ids), len(seq_ids))
            mask = np.zeros(len(seq_ids), dtype=bool)
            mask[:plen] = True
            self.tokens.append(seq_ids)
            self.prompt_masks.append(mask)
        lens = [len(t) for t in self.tokens]
        plens = [int(m.sum()) for m in self.prompt_masks]
        logger.info(
            f"PromptAnswerDataset: #seqs={len(self.tokens)}, "
            f"avg prompt len={np.mean(plens):.1f}, "
            f"avg answer len={np.mean(lens) - np.mean(plens):.1f}"
        )

    def __len__(self):
        return len(self.tokens)

    def __getitem__(self, idx: int) -> data_api.SequenceSample:
        toks = np.asarray(self.tokens[idx], dtype=np.int32)
        return data_api.SequenceSample.from_default(
            ids=[self.ids[idx]],
            seqlens=[len(toks)],
            data=dict(packed_input_ids=toks, prompt_mask=self.prompt_masks[idx]),
        )


data_api.register_dataset("prompt_answer", PromptAnswerDataset)

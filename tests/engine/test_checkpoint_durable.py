"""Durable-training-plane checkpoint semantics (ISSUE 16): the manifest
commit record, RNG/LR-schedule state round-trips ("recovered" must mean
"same stream as uninterrupted"), and the async background writer whose
step-loop cost is a reference-snapshot handoff."""

import os
import pickle
import random

import numpy as np
import pytest

from areal_tpu.base import seeding
from areal_tpu.engine import checkpoint
from areal_tpu.engine.checkpoint import (
    AsyncCheckpointWriter,
    has_engine_state,
    load_engine_state,
    load_manifest,
    save_engine_state,
)
from tests.engine.test_checkpoint_orbax import (
    _assert_same_params,
    _step,
    make_engine,
)


@pytest.fixture(autouse=True)
def _pickle_backend(monkeypatch):
    monkeypatch.setenv("AREAL_CKPT_BACKEND", "pickle")
    yield


# ======================================================================
# Manifest: the commit record.
# ======================================================================


def test_manifest_committed_with_sync_save(tmp_path):
    eng = make_engine(21)
    _step(eng)
    eng.version = 4
    cursors = {"model_worker/0": {"epoch": 1, "offset": 128}}
    save_engine_state(eng, str(tmp_path), dataset_cursors=cursors)
    man = load_manifest(str(tmp_path))
    assert man is not None
    assert man["schema"] == "areal-train-ckpt/v1"
    assert man["version"] == 4
    assert man["version_steps"] == eng._lr_steps
    assert man["rng"] == eng.rng_state()
    assert man["dataset_cursors"] == cursors
    assert man["artifact"] == "engine_state.pkl"
    # tmp+fsync+rename discipline: no litter.
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_load_manifest_absent_or_foreign(tmp_path):
    assert load_manifest(str(tmp_path)) is None
    (tmp_path / "manifest.json").write_text('{"schema": "other/v1"}')
    assert load_manifest(str(tmp_path)) is None


# ======================================================================
# RNG + LR-schedule position round-trips.
# ======================================================================


def test_rng_and_version_steps_roundtrip(tmp_path):
    eng = make_engine(22)
    _step(eng)
    _step(eng, seed=3)
    eng._gen_calls = 9
    eng._lr_steps = 17  # schedule position deliberately != version
    eng.version = 2
    save_engine_state(eng, str(tmp_path))
    eng2 = make_engine(92)
    load_engine_state(eng2, str(tmp_path))
    assert eng2.rng_state() == eng.rng_state()
    assert eng2._lr_steps == 17
    assert eng2.version == 2


def test_host_rng_stream_continues_after_restore(tmp_path):
    eng = make_engine(23)
    seeding.set_random_seed(11, "trainer0")
    np.random.rand(3)
    random.random()
    save_engine_state(eng, str(tmp_path))
    expect_np = np.random.rand(4)
    expect_py = random.random()
    # A different process history...
    seeding.set_random_seed(55, "other")
    np.random.rand(7)
    # ...restores to the checkpointed cut and continues identically.
    eng2 = make_engine(93)
    load_engine_state(eng2, str(tmp_path))
    assert np.allclose(np.random.rand(4), expect_np)
    assert random.random() == expect_py


def test_legacy_pickle_without_new_fields_still_loads(tmp_path):
    """Checkpoints from before the durable plane (no version_steps/rng/
    host_rng keys, no manifest) keep loading; the LR schedule falls back
    to the version."""
    eng = make_engine(24)
    _step(eng)
    state = {
        "params": checkpoint._to_host(eng.get_params()),
        "opt_state": checkpoint._to_host(eng.opt_state),
        "version": 5,
    }
    with open(tmp_path / "engine_state.pkl", "wb") as f:
        pickle.dump(state, f)
    eng2 = make_engine(94)
    load_engine_state(eng2, str(tmp_path))
    assert eng2.version == 5
    assert eng2._lr_steps == 5
    _assert_same_params(eng, eng2)


def test_orbax_save_carries_manifest_and_rng_sidecar(tmp_path):
    eng = make_engine(25)
    _step(eng)
    eng._gen_calls = 6
    eng._lr_steps = 13
    save_engine_state(eng, str(tmp_path), backend="orbax")
    man = load_manifest(str(tmp_path))
    assert man is not None and man["version_steps"] == 13
    assert (tmp_path / "rng_state.pkl").exists()
    eng2 = make_engine(95)
    load_engine_state(eng2, str(tmp_path))
    assert eng2.rng_state() == eng.rng_state()
    assert eng2._lr_steps == 13


# ======================================================================
# Async writer.
# ======================================================================


def test_async_writer_roundtrip_and_read_barrier(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_CKPT_ASYNC", "1")
    eng = make_engine(26)
    _step(eng)
    eng.version = 3
    save_engine_state(eng, str(tmp_path))  # returns before the write
    # Stall stat records the handoff, not the full write.
    assert checkpoint.ckpt_stats["areal:train_ckpt_stall_ms"] >= 0.0
    # has/load take the read barrier themselves — no explicit wait.
    assert has_engine_state(str(tmp_path))
    man = load_manifest(str(tmp_path)) if checkpoint._ASYNC_WRITER else None
    eng2 = make_engine(96)
    load_engine_state(eng2, str(tmp_path))
    _assert_same_params(eng, eng2)
    assert eng2.version == 3
    # The committed manifest is there after the barrier.
    checkpoint.wait_pending_writes()
    assert load_manifest(str(tmp_path))["version"] == 3
    assert man is None or man["version"] == 3


def test_async_overlapping_submits_serialize(tmp_path, monkeypatch):
    """Back-to-back submits for the same directory must land in order —
    the final state on disk is the LAST submitted snapshot."""
    writer = AsyncCheckpointWriter()
    try:
        eng = make_engine(27)
        for v in range(1, 4):
            _step(eng, seed=v)
            eng.version = v
            writer.submit(eng, str(tmp_path))
        writer.wait(timeout=60)
        assert writer.pending() == 0
        assert writer.last_write_s() >= 0.0
        man = load_manifest(str(tmp_path))
        assert man["version"] == 3
        eng2 = make_engine(97)
        load_engine_state(eng2, str(tmp_path))
        _assert_same_params(eng, eng2)
    finally:
        writer.close()


def test_async_writer_error_surfaces_at_wait(tmp_path):
    writer = AsyncCheckpointWriter()
    try:
        eng = make_engine(28)
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        writer.submit(eng, str(blocker / "sub"))
        with pytest.raises(OSError):
            writer.wait(timeout=60)
        # The error is consumed: the writer is reusable afterwards.
        writer.submit(eng, str(tmp_path / "ok"))
        writer.wait(timeout=60)
        assert load_manifest(str(tmp_path / "ok")) is not None
    finally:
        writer.close()


def test_async_snapshot_is_crash_consistent_under_races(tmp_path):
    """The submit-time snapshot must reflect the step it was taken at
    even when training mutates the engine immediately after — jax/numpy
    arrays are replaced, not mutated, so snapshotted refs stay valid."""
    writer = AsyncCheckpointWriter()
    try:
        eng = make_engine(29)
        _step(eng)
        eng.version = 1
        # np.array(copy=True): on CPU jax, np.asarray would alias the
        # donated device buffer the next step overwrites in place.
        import jax

        v1_params = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True), eng.get_params()
        )
        writer.submit(eng, str(tmp_path))
        # Race ahead before the write necessarily finished.
        _step(eng, seed=9)
        eng.version = 2
        writer.wait(timeout=60)
        eng2 = make_engine(98)
        load_engine_state(eng2, str(tmp_path))
        assert eng2.version == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(v1_params),
            jax.tree_util.tree_leaves(eng2.get_params()),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        writer.close()

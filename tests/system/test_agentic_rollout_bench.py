"""ISSUE 18 acceptance (bench leg): the `agentic_rollout` phase banks
an attested CPU-proxy record — multi-turn tool-use episodes through a
real fleet + pooled executor, with the session-continuation re-prefill
measured against a session-blind full-re-prefill baseline and an
executor saturation sweep — and `validate_bench.py` refuses the failure
classes that would make such a record meaningless: failed episodes,
continuation arms whose re-prefill ratio never beat the baseline,
unengaged prefix affinity, starved tool calls, cold-only executor
pools, and saturation sweeps that never shed (backpressure untested).

The teeth run in tier-1 against a synthetic record; the full phase run
(ProcessFleet + executor services, ~2-4 min) is slow-marked."""

import importlib.util
import os

import pytest

from areal_tpu.bench import bank, runner
from tests.fixtures import scale_timeout

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _good_record():
    """A record shaped like a healthy banked measure pass."""
    return {
        "status": "ok",
        "pass": "measure",
        "value": {
            "episodes": 8.0,
            "turns_per_episode": 3.0,
            "failed_episodes": 0.0,
            "episodes_per_s": 0.5,
            "turn_ttft_p50_ms": 16.0,
            "turn_ttft_p99_ms": 40.0,
            "baseline_turn_ttft_p50_ms": 64.0,
            "baseline_turn_ttft_p99_ms": 120.0,
            "tool_calls": 16.0,
            "tool_failures": 0.0,
            "tool_call_ms_p50": 30.0,
            "tool_call_ms_p99": 80.0,
            "reprefill_tokens": 64.0,
            "full_prefill_tokens": 2600.0,
            "reprefill_ratio": 0.025,
            "affinity_prefix_hits": 8.0,
            "exec_jobs_total": 40.0,
            "exec_warm_hits": 38.0,
            "exec_worker_respawns": 0.0,
            "exec_workers_alive": 2.0,
            "sat_points": 3.0,
            "sat_peak_jobs_per_s": 30.0,
            "sat_failed": 0.0,
            "sat_shed_total": 83.0,
            "n_turns_total": 24.0,
            "wall_s": 60.0,
        },
    }


def test_agentic_rollout_teeth():
    v = _load_validator()
    assert v.validate_phase_value("agentic_rollout", _good_record()) == []

    # Each mutation is one failure class the validator must refuse.
    cases = [
        ("failed_episodes", 1.0, "failed episode"),
        ("reprefill_ratio", 1.0, "not below 1.0"),
        ("reprefill_tokens", 0.0, "zero re-prefill tokens"),
        ("affinity_prefix_hits", 0.0, "affinity never engaged"),
        ("tool_failures", 2.0, "starved mid-episode"),
        ("exec_warm_hits", 0.0, "cold spawn"),
        ("exec_workers_alive", 0.0, "no executor worker alive"),
        ("sat_shed_total", 0.0, "never shed"),
        ("sat_failed", 3.0, "saturation sweep"),
    ]
    for key, bad, needle in cases:
        rec = _good_record()
        rec["value"][key] = bad
        problems = v.validate_phase_value("agentic_rollout", rec)
        assert problems, f"validator swallowed {key}={bad}"
        assert any(needle in p for p in problems), (key, problems)

    # A missing schema key is refused before the semantic teeth.
    rec = _good_record()
    del rec["value"]["reprefill_ratio"]
    assert any(
        "reprefill_ratio" in p
        for p in v.validate_phase_value("agentic_rollout", rec)
    )


@pytest.mark.serial
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_agentic_rollout_record_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    monkeypatch.setenv("XLA_FLAGS", "")
    rec = runner.run_phase(
        "agentic_rollout", "measure", b, deadline_s=scale_timeout(360)
    )
    assert rec["status"] == "ok", rec
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"

    validator = _load_validator()
    assert validator.validate_phase_value("agentic_rollout", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # THE acceptance numbers: loss-free episodes whose continuation
    # turns re-prefilled measurably less than the session-blind
    # baseline, with affinity and executor backpressure both engaged.
    assert v["failed_episodes"] == 0.0
    assert v["reprefill_ratio"] < 1.0
    assert v["affinity_prefix_hits"] >= 1
    assert v["tool_failures"] == 0.0
    assert v["exec_warm_hits"] >= 1
    assert v["sat_shed_total"] >= 1 and v["sat_failed"] == 0.0

"""Scoped metric aggregation with masked denominators.

Counterpart of the reference's stats tracker (realhf/base/stats_tracker.py):
training code registers boolean *denominators* (e.g. which tokens are
response tokens) and float *stats* tied to a denominator; `export()`
reduces each stat over its mask with AVG/SUM/MIN/MAX semantics so logged
averages are semantically correct (per-token, per-sequence, ...).

Host-side numpy: engines pull device arrays once per step and feed them
here; cross-host aggregation happens naturally because under GSPMD each
host sees globally-reduced values (losses are psum'd inside jit).
"""

from __future__ import annotations

import contextlib
import enum
from typing import Dict, List, Optional

import numpy as np


class ReduceType(enum.Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"


# MoE layers deposit their aux losses here during the forward pass (keyed by
# loss name -> per-layer values); the tracker merges them at export time.
MOE_AUX_LOSSES: Dict[str, list] = {}


def _to_np(x) -> np.ndarray:
    return np.asarray(x)


class DistributedStatsTracker:

    def __init__(self, name: str = ""):
        self._scopes: List[str] = [name] if name else []
        self._denominators: Dict[str, List[np.ndarray]] = {}
        # Each stat entry is a (value, mask) pair captured at record time so
        # conditionally-logged stats can never mispair with older masks.
        self._stats: Dict[str, List[tuple]] = {}
        self._reduce_types: Dict[str, ReduceType] = {}
        self._scalars: Dict[str, List[float]] = {}
        self._scalar_types: Dict[str, ReduceType] = {}

    def _key(self, name: str) -> str:
        return "/".join(self._scopes + [name])

    @contextlib.contextmanager
    def scope(self, name: str):
        self._scopes.append(name)
        try:
            yield
        finally:
            self._scopes.pop()

    def denominator(self, **kwargs):
        for name, mask in kwargs.items():
            key = self._key(name)
            mask = _to_np(mask).astype(bool)
            self._denominators.setdefault(key, []).append(mask)

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType = ReduceType.AVG,
        **kwargs,
    ):
        denom_key = self._key(denominator)
        if denom_key not in self._denominators or not self._denominators[denom_key]:
            raise ValueError(f"unknown denominator {denominator!r} (key {denom_key})")
        mask = self._denominators[denom_key][-1]
        for name, value in kwargs.items():
            key = self._key(name)
            value = _to_np(value).astype(np.float32)
            if value.shape != mask.shape:
                raise ValueError(
                    f"stat {key} shape {value.shape} mismatches denominator "
                    f"{denom_key} shape {mask.shape}"
                )
            self._stats.setdefault(key, []).append((value, mask))
            self._reduce_types[key] = reduce_type

    def scalar(self, reduce_type: ReduceType = ReduceType.AVG, **kwargs):
        """Record scalar stats. `reduce_type` declares the CROSS-WORKER
        merge semantics shipped to the master (within-process records are
        always mean-reduced at export): AVG for rates/means, MAX for
        worst-case latencies (e.g. `perf/h2d_wait_ms` — the step blocks
        on the slowest DP worker, so averaging would understate it)."""
        for name, value in kwargs.items():
            key = self._key(name)
            self._scalars.setdefault(key, []).append(float(value))
            self._scalar_types[key] = reduce_type

    def moe_aux_losses(self):
        """Fold MoE aux losses recorded during forward into scalar stats."""
        for name, values in MOE_AUX_LOSSES.items():
            if values:
                self.scalar(**{f"moe_aux/{name}": float(np.mean([float(v) for v in values]))})
        MOE_AUX_LOSSES.clear()

    @staticmethod
    def _match(key: Optional[str], k: str) -> bool:
        # Prefix match on full name components only: "train" matches
        # "train/loss" but not "train_eval/acc".
        return key is None or k == key or k.startswith(key.rstrip("/") + "/")

    def export(
        self,
        key: Optional[str] = None,
        reset: bool = True,
        return_types: bool = False,
    ):
        """Reduce recorded stats to floats.

        With `return_types=True` also returns {key: "sum"|"avg"|...} so a
        cross-process aggregator (the master merging DP-worker replies,
        system/model_function_call.merge_worker_stats) can reduce with the
        declared semantics instead of guessing — the control-plane
        equivalent of the reference's process-group reduce
        (realhf/base/stats_tracker.py:105).
        """
        out: Dict[str, float] = {}
        types: Dict[str, str] = {}
        for k, masks in self._denominators.items():
            if not self._match(key, k):
                continue
            out[k] = float(sum(m.sum() for m in masks))
            types[k] = "sum"
        for k, pairs in self._stats.items():
            if not self._match(key, k):
                continue
            rt = self._reduce_types[k]
            types[k] = rt.value
            masked = [v[m] for v, m in pairs]
            flat = np.concatenate(masked) if masked else np.array([])
            if flat.size == 0:
                continue
            if rt == ReduceType.AVG:
                out[k] = float(flat.mean())
            elif rt == ReduceType.SUM:
                out[k] = float(flat.sum())
            elif rt == ReduceType.MIN:
                out[k] = float(flat.min())
            elif rt == ReduceType.MAX:
                out[k] = float(flat.max())
        for k, vals in self._scalars.items():
            if not self._match(key, k):
                continue
            out[k] = float(np.mean(vals))
            types.setdefault(
                k, self._scalar_types.get(k, ReduceType.AVG).value
            )
        if reset:
            for k in [k for k in self._denominators if self._match(key, k)]:
                del self._denominators[k]
            for k in [k for k in self._stats if self._match(key, k)]:
                del self._stats[k]
                self._reduce_types.pop(k, None)
            for k in [k for k in self._scalars if self._match(key, k)]:
                del self._scalars[k]
                self._scalar_types.pop(k, None)
        if return_types:
            return out, types
        return out


# Process-global default tracker, mirroring the reference's module-level API.
DEFAULT_TRACKER = DistributedStatsTracker()

scope = DEFAULT_TRACKER.scope
denominator = DEFAULT_TRACKER.denominator
stat = DEFAULT_TRACKER.stat
scalar = DEFAULT_TRACKER.scalar
export = DEFAULT_TRACKER.export
moe_aux_losses = DEFAULT_TRACKER.moe_aux_losses

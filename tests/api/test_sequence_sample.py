"""SequenceSample invariants (mirrors reference tests/data/test_sequence_gather_split.py)."""

import numpy as np
import pytest

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample


def make_sample(n, seed=0, keys=("packed_input_ids", "rewards")):
    rng = np.random.RandomState(seed)
    seqlens = rng.randint(3, 20, size=n).tolist()
    ids = [f"s{seed}-{i}" for i in range(n)]
    data = {}
    if "packed_input_ids" in keys:
        data["packed_input_ids"] = rng.randint(0, 100, size=sum(seqlens))
    if "rewards" in keys:
        data["rewards"] = rng.rand(n).astype(np.float32)
    return SequenceSample.from_default(ids=ids, seqlens=seqlens, data=data)


def test_from_default_infers_seqlens():
    s = make_sample(5)
    assert s.bs == 5
    assert s.seqlens["rewards"] == [[1]] * 5
    assert s.total_seqlen("packed_input_ids") == sum(s.seqlens_of())


def test_gather_split_roundtrip():
    parts = [make_sample(3, seed=i) for i in range(4)]
    g = SequenceSample.gather(parts)
    assert g.bs == 12
    back = g.split_with_partitions([[0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11]])
    for orig, rec in zip(parts, back):
        assert orig.ids == rec.ids
        np.testing.assert_array_equal(
            orig.data["packed_input_ids"], rec.data["packed_input_ids"]
        )
        np.testing.assert_array_equal(orig.data["rewards"], rec.data["rewards"])


def test_gather_duplicate_ids_raises():
    s = make_sample(3)
    with pytest.raises(ValueError):
        SequenceSample.gather([s, s])


def test_select_ids_and_keys():
    s = make_sample(6)
    sub = s.select_ids([s.ids[4], s.ids[1]])
    assert sub.ids == [s.ids[4], s.ids[1]]
    assert sub.sample_total_len(0) == s.sample_total_len(4)
    ks = s.select_keys(["rewards"])
    assert ks.keys == {"rewards"}
    np.testing.assert_array_equal(ks.data["rewards"], s.data["rewards"])


def test_mb_split_and_reorder_output():
    s = make_sample(10)
    mbs, fwd, bwd = s.split(MicroBatchSpec(n_mbs=3, max_tokens_per_mb=60))
    assert len(mbs) >= 3
    assert sorted(fwd) == list(range(10))
    # Simulate per-token outputs computed per micro-batch, then reorder.
    outs = [mb.data["packed_input_ids"] * 2 for mb in mbs]
    merged = SequenceSample.reorder_output(
        np.concatenate(outs),
        [mb.seqlens_of() for mb in mbs],
        bwd,
    )
    np.testing.assert_array_equal(merged, s.data["packed_input_ids"] * 2)


def test_update_and_remap():
    s = make_sample(4)
    logp = np.random.rand(s.total_seqlen()).astype(np.float32)
    other = SequenceSample(
        ids=list(s.ids),
        keys={"logprobs"},
        data={"logprobs": logp},
        seqlens={"logprobs": s.seqlens["packed_input_ids"]},
    )
    s.update_(other)
    assert "logprobs" in s.keys
    s.remap_keys_({"logprobs": "old_logprobs"})
    assert "old_logprobs" in s.keys and "logprobs" not in s.keys
    np.testing.assert_array_equal(s.data["old_logprobs"], logp)


def test_meta_carries_no_data():
    s = make_sample(3)
    m = s.meta()
    assert all(v is None for v in m.data.values())
    assert m.seqlens == s.seqlens
    assert m.dtypes["packed_input_ids"] == s.dtypes["packed_input_ids"]


def test_metadata_alignment():
    s = make_sample(3)
    with pytest.raises(ValueError):
        SequenceSample(
            ids=["a", "b"],
            keys={"x"},
            data={"x": np.zeros(2)},
            seqlens={"x": [[1], [1]]},
            metadata={"scores": [1.0]},
        )
    sub = SequenceSample(
        ids=["a", "b"],
        keys={"x"},
        data={"x": np.zeros(2)},
        seqlens={"x": [[1], [1]]},
        metadata={"scores": [1.0, 2.0]},
    )._select_indices([1])
    assert sub.metadata["scores"] == [2.0]


def test_gather_pads_stream_specific_metadata():
    """Mixed-stream batches (ISSUE 19): agentic samples stamp
    turns/tool_calls, math samples don't — gather pads the absent
    samples with None instead of refusing the batch, keeping per-sample
    alignment for the train-step folds (which filter on isinstance)."""
    agentic = make_sample(2, seed=1)
    agentic.metadata.update(
        {"task": ["agentic", "agentic"], "tool_calls": [2, 1]}
    )
    math = make_sample(2, seed=2)
    math.metadata.update({"task": ["math", "math"]})
    g = SequenceSample.gather([agentic, math])
    assert g.metadata["task"] == ["agentic", "agentic", "math", "math"]
    assert g.metadata["tool_calls"] == [2, 1, None, None]
    # The padding survives a split back out.
    back = g._select_indices([2, 0])
    assert back.metadata["tool_calls"] == [None, 2]


def test_grouped_inner_seqlens():
    # One id holding a group of 2 sequences under one key (GRPO-style).
    s = SequenceSample(
        ids=["p0"],
        keys={"seq"},
        data={"seq": np.arange(7)},
        seqlens={"seq": [[3, 4]]},
    )
    assert s.sample_total_len(0, "seq") == 7
    u = s.unpack()
    assert len(u) == 1 and u[0].seqlens["seq"] == [[3, 4]]


def test_data_shape_validation():
    with pytest.raises(ValueError):
        SequenceSample(
            ids=["a"], keys={"x"}, data={"x": np.zeros(5)}, seqlens={"x": [[3]]}
        )

"""Request/reply + push/pull stream tests (mirrors reference
tests/system/test_push_pull_stream.py and the req/rep protocol of
realhf/system/request_reply_stream.py)."""

import threading
import time

import numpy as np
import pytest

from areal_tpu.system import push_pull_stream as pps
from areal_tpu.system import request_reply_stream as rrs


def test_request_reply_roundtrip(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "model_worker/0")

    try:
        [rid] = master.request(["model_worker/0"], "spec", [{"x": 1}])

        # Worker sees the request and replies.
        req = worker.poll(block=True, timeout_ms=5000)
        assert req.handle_name == "spec"
        assert req.data == {"x": 1}
        worker.reply_to(req, data={"y": 2})

        reply = master.poll(rid, block=True, timeout=10)
        assert reply.data == {"y": 2}
    finally:
        master.close()
        worker.close()


def test_request_reply_syn_ack(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "model_worker/0")
    try:
        [rid] = master.request(
            ["model_worker/0"], "train_step", [None], no_syn=False
        )
        req = worker.poll(block=True, timeout_ms=5000)
        # Syn arrives before the (delayed) reply.
        master.await_syn(rid, timeout=10)
        worker.reply_to(req, data="done")
        assert master.poll(rid, block=True, timeout=10).data == "done"
    finally:
        master.close()
        worker.close()


def test_request_reply_numpy_payload_compression(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "w0")
    try:
        big = np.zeros((1024, 64), dtype=np.float32)  # compresses well
        [rid] = master.request(["w0"], "data", [big])
        req = worker.poll(block=True, timeout_ms=5000)
        np.testing.assert_array_equal(req.data, big)
        worker.reply_to(req, data=req.data.sum())
        assert master.poll(rid, block=True, timeout=10).data == 0.0
    finally:
        master.close()
        worker.close()


def test_call_many_workers(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    workers = [rrs.make_worker_stream(exp, trial, f"w{i}") for i in range(4)]

    def serve(w):
        req = w.poll(block=True, timeout_ms=10000)
        w.reply_to(req, data=req.data * 2)

    threads = [threading.Thread(target=serve, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    try:
        out = master.call([f"w{i}" for i in range(4)], "double", [1, 2, 3, 4], timeout=15)
        assert out == [2, 4, 6, 8]
    finally:
        for t in threads:
            t.join(timeout=5)
        master.close()
        for w in workers:
            w.close()


def test_push_pull_grouping():
    assert pps.grouping(4, 2) == {0: [0, 1], 1: [2, 3]}
    assert pps.grouping(5, 2) == {0: [0, 1, 2], 1: [3, 4]}
    g = pps.grouping(7, 3)
    assert sorted(sum(g.values(), [])) == list(range(7))


def test_push_pull_json(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    puller = pps.NameResolvingZmqPuller(exp, trial, puller_index=0)
    pushers = [
        pps.NameResolvingZmqPusher(exp, trial, pusher_index=i, n_pushers=2, n_pullers=1)
        for i in range(2)
    ]
    try:
        for i, p in enumerate(pushers):
            p.push({"traj": [1, 2, 3], "src": i})
        got = sorted(
            (puller.pull(timeout_ms=5000) for _ in range(2)), key=lambda d: d["src"]
        )
        assert [g["src"] for g in got] == [0, 1]
        assert got[0]["traj"] == [1, 2, 3]
        with pytest.raises(TimeoutError):
            puller.pull(timeout_ms=50)
    finally:
        puller.close()
        for p in pushers:
            p.close()

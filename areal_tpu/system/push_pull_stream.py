"""Rollout -> trainer trajectory transport: ZMQ PUSH/PULL of JSON dicts.

Counterpart of the reference's push-pull stream
(realhf/system/push_pull_stream.py:18-177): M rollout-worker pushers are
deterministically grouped onto N trainer-side pullers, addresses are
discovered via name_resolve, and messages are newline-free JSON objects
(trajectories are token-id lists — cheap to serialize, and JSON keeps the
stream debuggable, matching the reference's choice).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import zmq

from areal_tpu.base import logging, name_resolve, names, network, tracing

logger = logging.getLogger("push_pull_stream")


class ZMQJsonPusher:
    """PUSH end. Connects to a puller's bound address."""

    def __init__(self, host: str, port: int, hwm: int = 1000):
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUSH)
        self.sock.setsockopt(zmq.SNDHWM, hwm)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.connect(f"tcp://{host}:{port}")

    def push(self, data: Dict[str, Any]):
        # Best-effort RL-trace propagation: the current span context rides
        # the JSON under a reserved key the puller strips back off (one
        # no-op branch when tracing is disabled).
        data = tracing.inject_into(data)
        self.sock.send_string(json.dumps(data, separators=(",", ":")), flags=0)

    def close(self):
        self.sock.close()


class ZMQJsonPuller:
    """PULL end. Binds and accepts many pushers."""

    # RL-trace context of the most recent message (None before the first
    # pull, when absent, or when tracing is disabled).
    last_trace_ctx = None

    def __init__(self, host: str = "0.0.0.0", port: Optional[int] = None, hwm: int = 1000,
                 default_timeout_ms: int = 100):
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PULL)
        self.sock.setsockopt(zmq.RCVHWM, hwm)
        self.sock.setsockopt(zmq.LINGER, 0)
        if port is None:
            self.port = self.sock.bind_to_random_port(f"tcp://{host}")
        else:
            self.sock.bind(f"tcp://{host}:{port}")
            self.port = port
        self.host = host
        self.default_timeout_ms = default_timeout_ms

    def pull(self, timeout_ms: Optional[int] = None) -> Dict[str, Any]:
        """Blocking with timeout; raises queue-empty style TimeoutError.

        Strips the pusher's RL-trace context off the payload and exposes
        it as `last_trace_ctx` (None when absent/disabled) so consumers
        can parent their spans without the key leaking into the data."""
        t = self.default_timeout_ms if timeout_ms is None else timeout_ms
        # Reset first: a timeout must not leave a previous message's
        # context attributed to whatever the caller reads next.
        self.last_trace_ctx = None
        if not self.sock.poll(t):
            raise TimeoutError("no message within timeout")
        d = json.loads(self.sock.recv_string())
        self.last_trace_ctx = tracing.extract_from(d)
        return d

    def close(self):
        self.sock.close()


def grouping(n_pushers: int, n_pullers: int) -> Dict[int, List[int]]:
    """puller index -> pusher indices, contiguous blocks (reference
    push_pull_stream.py:125)."""
    assert n_pushers >= n_pullers > 0
    base = n_pushers // n_pullers
    rem = n_pushers % n_pullers
    out: Dict[int, List[int]] = {}
    start = 0
    for i in range(n_pullers):
        cnt = base + (1 if i < rem else 0)
        out[i] = list(range(start, start + cnt))
        start += cnt
    return out


class NameResolvingZmqPuller(ZMQJsonPuller):
    """Puller that registers its address under the stream name."""

    def __init__(self, experiment_name: str, trial_name: str, puller_index: int, **kwargs):
        host_ip = network.gethostip()
        super().__init__(host=host_ip, **kwargs)
        key = names.push_pull_stream(
            experiment_name, trial_name, f"puller{puller_index}"
        )
        name_resolve.add(key, f"{host_ip}:{self.port}", keepalive_ttl=60, replace=True)


class NameResolvingZmqPusher(ZMQJsonPusher):
    """Pusher that looks up its assigned puller by the grouping rule."""

    def __init__(self, experiment_name: str, trial_name: str, pusher_index: int,
                 n_pushers: int, n_pullers: int, **kwargs):
        group = grouping(n_pushers, n_pullers)
        puller_index = next(i for i, pushers in group.items() if pusher_index in pushers)
        key = names.push_pull_stream(experiment_name, trial_name, f"puller{puller_index}")
        addr = name_resolve.wait(key, timeout=300)
        host, port = addr.rsplit(":", 1)
        super().__init__(host, int(port), **kwargs)

"""Shared experiment-building helpers (reference experiments/common/)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from areal_tpu.api.cli_args import (
    BaseExperimentConfig,
    DatasetConfig,
    ModelTrainEvalConfig,
)
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.data_api import MicroBatchSpec
from areal_tpu.api.system_api import (
    MasterWorkerConfig,
    ModelShardSpec,
    ModelWorkerConfig,
)
from areal_tpu.parallel.mesh import AllocationMode


def model_abstraction(m: ModelTrainEvalConfig, tokenizer_path: Optional[str],
                      is_critic: bool = False,
                      mesh_spec: Optional[str] = None,
                      device_ids: Optional[List[int]] = None,
                      ) -> ModelAbstraction:
    """``mesh_spec``/``device_ids`` (usually from ``train_mesh_for_worker``)
    place the model on its slice of the allocation; an explicit per-model
    ``m.mesh_spec`` always wins (the pre-PR-9 worker-local knob)."""
    args: Dict = dict(
        tokenizer_path=tokenizer_path or m.path,
        is_critic=is_critic or m.is_critic,
        dtype=m.dtype,
        mesh_spec=m.mesh_spec or mesh_spec,
    )
    if m.mesh_spec is None and device_ids is not None:
        args["device_ids"] = list(device_ids)
    if m.path and not m.init_from_scratch:
        args["model_path"] = m.path
    else:
        assert m.config is not None, "need model config for scratch init"
        args["config"] = _apply_moe_overrides(m, dict(m.config))
    return ModelAbstraction("tpu_transformer", args=args)


def _apply_moe_overrides(m: ModelTrainEvalConfig, config: Dict) -> Dict:
    """Overlay the flat moe_* CLI knobs onto the nested config['moe']
    block (TransformerConfig.__post_init__ coerces the dict to an
    MoEConfig). Setting a knob on a dense model (no 'moe' block) is a
    silently-ignored sweep bug — refuse it."""
    overrides = {
        "dispatch": m.moe_dispatch,
        "capacity_factor": m.moe_capacity_factor,
        "aux_loss_coef": m.moe_aux_loss_coef,
    }
    overrides = {k: v for k, v in overrides.items() if v is not None}
    if not overrides:
        return config
    if not config.get("moe"):
        raise ValueError(
            f"moe_* overrides {sorted(overrides)} set but the model "
            f"config has no 'moe' block — they would be silently ignored"
        )
    moe = dict(config["moe"]) if isinstance(config["moe"], dict) else (
        dataclasses.asdict(config["moe"])
    )
    moe.update(overrides)
    config["moe"] = moe
    return config


def train_mesh_for_worker(
    cfg: BaseExperimentConfig, worker_index: int, n_workers: int
) -> Tuple[Optional[str], Optional[List[int]]]:
    """(mesh_spec, device_ids) for one model worker's slice of the
    allocation's TRAIN partition — the system-layer wiring that makes
    `allocation_mode` actually drive sharded training (previously only
    the data axis was consumed, as the worker count; fsdp/tensor/seq
    axes were silently dropped).

    - Single-host (train_n_hosts == 1): the train data axis splits
      across workers (each worker is one DP rank of the MFC layer, as
      before); worker i gets a LOCAL (data/n_workers, fsdp, seq, tensor)
      mesh over its contiguous device slice (offset past the gen
      partition when the allocation is decoupled).
    - Multi-host (train_n_hosts > 1): every worker-host builds the
      GLOBAL train mesh over the jax.distributed world's devices
      (device_ids None = all); DP happens inside the mesh.
    - Returns (None, None) for single-device allocations or when the
      data axis doesn't divide the worker count (legacy behavior:
      single-device mesh per worker).
    """
    try:
        alloc = AllocationMode.parse(cfg.allocation_mode)
    except (ValueError, AttributeError):
        return None, None
    ts = alloc.train_spec
    if ts.size <= 1:
        return None, None
    n_hosts = int(getattr(cfg, "train_n_hosts", 1) or 1)
    if n_hosts > 1:
        # One worker per host; the global mesh spans the distributed
        # world's devices, so no per-worker device slice applies.
        return str(ts), None
    if ts.data % max(1, n_workers) != 0:
        return None, None
    local = dataclasses.replace(ts, data=ts.data // max(1, n_workers))
    offset = alloc.gen_spec.size if alloc.decoupled else 0
    start = offset + worker_index * local.size
    return str(local), list(range(start, start + local.size))


def backend_abstraction(m: ModelTrainEvalConfig, train: bool = True) -> ModelBackendAbstraction:
    if m.backend.startswith("mock"):
        return ModelBackendAbstraction(m.backend)
    name = "jax_train" if train else "jax_inference"
    args = dict(
        remat=m.remat,
        attn_impl=m.attn_impl,
        row_len_multiple=m.row_len_multiple,
        max_row_len=m.max_row_len,
        prefetch_depth=m.prefetch_depth,
        stats_fetch_interval=m.stats_fetch_interval,
    )
    if train:
        args["optimizer"] = dataclasses.asdict(m.optimizer)
    return ModelBackendAbstraction(name, args=args)


def dataset_abstraction(d: DatasetConfig) -> DatasetAbstraction:
    args = dict(d.args)
    if d.path is not None:
        args.setdefault("dataset_path", d.path)
    if d.max_length is not None and d.type_ in ("prompt_answer", "prompt", "rw_pair"):
        args.setdefault("max_length", d.max_length)
    return DatasetAbstraction(d.type_, args=args)


def mb_spec(cfg: BaseExperimentConfig, mfc=None) -> MicroBatchSpec:
    """Global micro-batch spec, optionally overridden per MFC
    (reference: each MFCConfig carries its own MicroBatchSpec)."""
    n_mbs = cfg.mb_spec_n_mbs
    max_tokens = cfg.mb_spec_max_tokens
    if mfc is not None:
        if mfc.n_mbs is not None:
            n_mbs = mfc.n_mbs
        if mfc.max_tokens_per_mb is not None:
            max_tokens = mfc.max_tokens_per_mb
    return MicroBatchSpec(n_mbs=n_mbs, max_tokens_per_mb=max_tokens)


def worker_names(n: int) -> List[str]:
    return [f"model_worker/{i}" for i in range(n)]


def resolve_n_workers(cfg: BaseExperimentConfig) -> int:
    """The local single-host launcher maps the allocation's train data axis
    onto model workers when n_model_workers is left at default. With
    train_n_hosts > 1 there is exactly one worker per host of the shared
    jax.distributed train mesh."""
    if int(getattr(cfg, "train_n_hosts", 1) or 1) > 1:
        return int(cfg.train_n_hosts)
    if cfg.n_model_workers > 1:
        return cfg.n_model_workers
    try:
        alloc = AllocationMode.parse(cfg.allocation_mode)
        return max(1, alloc.train_spec.data)
    except Exception:
        return cfg.n_model_workers


def base_model_worker(
    cfg: BaseExperimentConfig,
    index: int,
    n_workers: int,
    shards: List[ModelShardSpec],
    with_dataset: bool = True,
    stream_dataset: bool = False,
) -> ModelWorkerConfig:
    # Multi-host SPMD training: every worker-host iterates the SAME
    # dataset shard (dp_rank 0 of 1) so the hosts dispatch identical
    # global programs in lockstep — DP happens inside the shared mesh,
    # not across workers (training/multihost.py's contract, now at the
    # system layer).
    multihost = int(getattr(cfg, "train_n_hosts", 1) or 1) > 1
    return ModelWorkerConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        worker_index=index,
        shards=shards,
        datasets=[dataset_abstraction(cfg.dataset)] if with_dataset else [],
        tokenizer_path=cfg.tokenizer_path,
        dataset_dp_rank=0 if multihost else index,
        dataset_dp_size=1 if multihost else n_workers,
        train_n_hosts=int(getattr(cfg, "train_n_hosts", 1) or 1),
        train_host_rank=index if multihost else 0,
        train_batch_size=cfg.train_batch_size,
        total_train_epochs=resolved_total_train_epochs(cfg),
        seed=cfg.seed,
        stream_dataset=stream_dataset,
        n_pullers=n_workers if stream_dataset else 1,
        weight_plane=bool(getattr(cfg, "gen_weight_plane", False)),
        weight_chunk_bytes=int(getattr(cfg, "gen_weight_chunk_mb", 8)) << 20,
        weight_wire_dtype=getattr(cfg, "gen_weight_wire_dtype", None),
    )


def dataset_line_count(dataset_cfg) -> int:
    """Number of usable samples in a jsonl prompt dataset (0 if unknown);
    used by async experiments to size epochs master-side. math_code_prompt
    datasets are counted through their own validator (invalid rows are
    dropped at load, so a raw line count would overstate the epoch)."""
    path = getattr(dataset_cfg, "path", None)
    if not path:
        return 0
    try:
        if getattr(dataset_cfg, "type_", None) == "math_code_prompt":
            from areal_tpu.datasets.math_code_prompt import load_metadata

            id2info, _ = load_metadata(path)
            return len(id2info)
        with open(path, "rb") as f:
            return sum(1 for line in f if line.strip())
    except (OSError, AssertionError):
        return 0


def resolved_total_train_epochs(cfg: BaseExperimentConfig) -> int:
    """One source of truth for the epoch budget. `cfg.total_train_epochs`
    is the documented knob (it already drives the LR schedule via
    FinetuneSpec); `exp_ctrl.total_train_epochs` defaults to None =
    inherit, and wins when set explicitly (including an explicit 1).
    Previously the master stopped on the exp_ctrl copy (default 1)
    regardless of the top-level field, so `total_train_epochs=3` trained
    one epoch with a 3-epoch LR schedule (ADVICE r1 finding a)."""
    if cfg.exp_ctrl.total_train_epochs is not None:
        return cfg.exp_ctrl.total_train_epochs
    return cfg.total_train_epochs


def base_master(cfg: BaseExperimentConfig, rpcs, model_topos, n_workers: int) -> MasterWorkerConfig:
    import dataclasses as _dc

    exp_ctrl = _dc.replace(
        cfg.exp_ctrl, total_train_epochs=resolved_total_train_epochs(cfg)
    )
    return MasterWorkerConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        exp_ctrl=exp_ctrl,
        rpcs=rpcs,
        model_topos=model_topos,
        data_hosts=worker_names(n_workers),
        n_model_workers=n_workers,
        train_batch_size=cfg.train_batch_size,
        recover_mode=cfg.recover_mode,
    )

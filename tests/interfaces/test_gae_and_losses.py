"""GAE scan vs numpy oracle; PPO loss properties (mirrors reference
tests/cpp_extensions/test_cugae.py + tests/data/test_dual_clip.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.interfaces.functional import (
    AdaptiveKLController,
    actor_loss_fn,
    critic_loss_fn,
    RunningMeanStd,
)
from areal_tpu.models.packing import pack_sequences
from areal_tpu.ops.gae import gae_rows


def numpy_gae_single(rewards, values, bootstrap, gamma, lam):
    """Slow per-sequence oracle (mirrors pygae1d_nolp_misalign semantics)."""
    T = len(rewards)
    adv = np.zeros(T)
    next_adv, next_v = 0.0, bootstrap
    for t in reversed(range(T)):
        delta = rewards[t] + gamma * next_v - values[t]
        adv[t] = delta + gamma * lam * next_adv
        next_adv = adv[t]
        next_v = values[t]
    return adv


@pytest.mark.parametrize("gamma,lam", [(1.0, 1.0), (0.97, 0.95)])
def test_gae_rows_matches_oracle(gamma, lam):
    rng = np.random.RandomState(0)
    lens = [5, 9, 3, 12]
    seqs = [np.zeros(l, np.int32) for l in lens]
    b = pack_sequences(seqs, row_len=16)
    rewards = rng.randn(*b.input_ids.shape).astype(np.float32) * (b.segment_ids > 0)
    values = rng.randn(*b.input_ids.shape).astype(np.float32) * (b.segment_ids > 0)
    boots = np.zeros_like(rewards)
    # Mark sequence 1 as truncated with bootstrap value 0.7 at its last token.
    span1 = b.spans[1]
    boots[span1.row, span1.start + span1.length - 1] = 0.7

    adv, ret = gae_rows(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(b.segment_ids),
        jnp.asarray(boots), gamma=gamma, lam=lam,
    )
    adv, ret = np.asarray(adv), np.asarray(ret)
    for i, span in enumerate(b.spans):
        sl = slice(span.start, span.start + span.length)
        r = rewards[span.row, sl]
        v = values[span.row, sl]
        boot = 0.7 if i == 1 else 0.0
        expect = numpy_gae_single(r, v, boot, gamma, lam)
        np.testing.assert_allclose(adv[span.row, sl], expect, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(ret[span.row, sl], expect + v, atol=1e-4, rtol=1e-4)
    assert (adv[b.segment_ids == 0] == 0).all()


def test_actor_loss_plain_ppo_clipping():
    lp = jnp.asarray(np.log(np.array([[0.5, 0.5, 0.5]])))
    old = jnp.asarray(np.log(np.array([[0.5, 0.25, 0.9]])))
    adv = jnp.asarray(np.array([[1.0, 1.0, -1.0]]))
    mask = jnp.ones((1, 3))
    loss, st = actor_loss_fn(lp, old, adv, eps_clip=0.2, loss_mask=mask)
    # token0: ratio 1 -> -1; token1: ratio 2 clipped to 1.2 -> -1.2;
    # token2: ratio .56 clipped .8, adv -1 -> max(-surr)=+0.8... min(surr1,surr2)
    # surr1=-0.556, surr2=-0.8 -> min=-0.8 -> loss 0.8
    np.testing.assert_allclose(float(loss), -1.0 - 1.2 + 0.8, atol=1e-2)
    assert float(st["clip_ratio"]) == 2.0


def test_actor_loss_dual_clip_bounds_negative_adv():
    lp = jnp.asarray(np.log(np.array([[0.9]])))
    old = jnp.asarray(np.log(np.array([[0.01]])))  # huge ratio 90
    adv = jnp.asarray(np.array([[-1.0]]))
    mask = jnp.ones((1, 1))
    loss_no_dual, _ = actor_loss_fn(lp, old, adv, 0.2, mask)
    loss_dual, st = actor_loss_fn(lp, old, adv, 0.2, mask, c_clip=3.0)
    assert float(loss_no_dual) > float(loss_dual)
    np.testing.assert_allclose(float(loss_dual), 3.0, atol=1e-3)
    assert float(st["dual_clip_ratio"]) == 1.0


def test_decoupled_loss_behav_cap_drops_tokens():
    lp = jnp.asarray(np.zeros((1, 2)))
    prox = jnp.asarray(np.log(np.array([[1.0, 0.9]])))
    old = jnp.asarray(np.log(np.array([[1.0, 0.0001]])))  # behav weight huge on tok1
    adv = jnp.asarray(np.ones((1, 2)))
    mask = jnp.ones((1, 2))
    _, st_uncapped = actor_loss_fn(
        lp, old, adv, 0.2, mask, proximal_logprobs=prox
    )
    _, st_capped = actor_loss_fn(
        lp, old, adv, 0.2, mask, proximal_logprobs=prox, behav_imp_weight_cap=10.0
    )
    assert float(st_uncapped["actor_denom"]) == 2.0
    assert float(st_capped["actor_denom"]) == 1.0


def test_critic_loss_clip():
    v = jnp.asarray(np.array([[2.0]]))
    old = jnp.asarray(np.array([[0.0]]))
    tgt = jnp.asarray(np.array([[0.5]]))
    mask = jnp.ones((1, 1))
    loss, st = critic_loss_fn(v, old, tgt, value_eps_clip=0.2, loss_mask=mask)
    # clipped value 0.2: l1=(2-.5)^2=2.25, l2=(0.2-0.5)^2=0.09 -> max=2.25? no:
    # loss takes max(l1,l2)=2.25 -> 0.5*2.25
    np.testing.assert_allclose(float(loss), 0.5 * 2.25, atol=1e-5)


def test_adaptive_kl_controller():
    c = AdaptiveKLController(0.1, target=6.0, horizon=100)
    c.update(12.0, 10)  # kl above target -> coef grows
    assert c.value > 0.1
    c2 = AdaptiveKLController(0.1, target=6.0, horizon=100)
    c2.update(1.0, 10)
    assert c2.value < 0.1


def test_running_mean_std():
    rms = RunningMeanStd(beta=0.5)
    data = np.array([1.0, 3.0])
    for _ in range(50):
        rms.update(data)
    np.testing.assert_allclose(rms.debiased_mean, 2.0, atol=1e-3)
    norm = rms.normalize(data)
    denorm = rms.denormalize(norm)
    np.testing.assert_allclose(denorm, data, atol=1e-4)

"""Pooled sandboxed reward-execution service (docs/agentic.md).

The seed stack verified rewards with a fresh ``subprocess.run`` per
case (functioncall/code_verify.py): every sympy equivalence or python
tool call paid a cold interpreter fork + imports, which cannot scale
with rollout traffic (ROADMAP item 4). This module promotes that
sandbox into a small service:

- a pool of WARM worker subprocesses that apply the code_verify guard
  ONCE at spawn (RLIMIT_AS, neutered ``os.system``/``fork``/…) and are
  then REUSED across jobs over a line-delimited JSON pipe protocol;
- kill-on-timeout per job — an overrun or crash costs exactly one
  worker respawn, never the pool or the caller;
- an HTTP front (``POST /rexec/submit``, batched) with a bounded
  pending queue and 429 + Retry-After backpressure past the watermark,
  mirroring the generation server's admission contract;
- the PR 1 health/lease treatment: a heartbeat under
  ``health/reward_executor/<id>`` plus a URL record at
  ``names.reward_executor_url`` so clients (functioncall/remote.py)
  discover executors, load-balance, and fail over on death;
- an ``areal:rexec_*`` /metrics text surface on the fleet's standard
  contract (base/metrics_registry.py);
- chaos points ``rexec.case`` (one job fails in the sandbox) and
  ``rexec.die`` (the whole service dies) armable via ``AREAL_FAULTS``.

Job kinds on the wire:

- ``{"kind": "python", "code": str, "stdin": str?}`` — guarded exec,
  returns ``{"ok", "stdout", "stderr"}``;
- ``{"kind": "sympy_equal", "a": str, "b": str}`` — warm-import sympy
  equivalence (math_grader routes here when a pool is registered),
  returns ``{"ok", "equal"}``;
- ``{"kind": "ping"}`` — worker identity probe, returns
  ``{"ok", "pid", "reuse"}`` (the warm-reuse tests pin pid stability).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import queue
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from areal_tpu.base import (
    env_registry,
    logging,
    name_resolve,
    names,
    network,
    rpc,
)
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.health import Heartbeat

logger = logging.getLogger("reward_executor")

# The warm worker program. Runs OUTSIDE the repo's lint scope (string
# literal): applies the code_verify guard once at spawn, then loops
# jobs over stdin/stdout JSON lines. Deliberately tiny and stdlib-only
# until a sympy job forces the (one-time, warm thereafter) import.
_WORKER_SOURCE = r"""
import io, json, os, sys, traceback

mem_bytes = int(os.environ.get("_REXEC_MEM_MB", "1024")) << 20
try:
    import resource
    resource.setrlimit(resource.RLIMIT_AS, (mem_bytes, mem_bytes))
except Exception:
    pass
# Neuter the escape hatches (code_verify guard, paid once per worker).
for _name in ("system", "popen", "execv", "execve", "execvp", "execvpe",
              "fork", "forkpty", "killpg"):
    if hasattr(os, _name):
        setattr(os, _name, None)

_reuse = 0
_sympy_equal_raw = None


def _run_python(job):
    out, err = io.StringIO(), io.StringIO()
    ns = {"__name__": "__rexec__"}
    stdin_data = job.get("stdin") or ""
    old_stdin = sys.stdin
    sys.stdin = io.StringIO(stdin_data)
    try:
        from contextlib import redirect_stdout, redirect_stderr
        with redirect_stdout(out), redirect_stderr(err):
            exec(compile(job.get("code") or "", "<rexec>", "exec"), ns)
        return {"ok": True, "stdout": out.getvalue(),
                "stderr": err.getvalue()}
    except SystemExit as e:
        ok = not e.code
        return {"ok": ok, "stdout": out.getvalue(),
                "stderr": err.getvalue() + (f"exit {e.code}" if not ok
                                            else "")}
    except BaseException:
        return {"ok": False, "stdout": out.getvalue(),
                "stderr": err.getvalue() + traceback.format_exc(limit=4)}
    finally:
        sys.stdin = old_stdin


def _run_sympy(job):
    global _sympy_equal_raw
    if _sympy_equal_raw is None:
        from areal_tpu.functioncall.math_grader import _sympy_equal_raw as f
        _sympy_equal_raw = f
    try:
        return {"ok": True,
                "equal": bool(_sympy_equal_raw(job.get("a", ""),
                                               job.get("b", "")))}
    except BaseException:
        return {"ok": False, "equal": False,
                "stderr": traceback.format_exc(limit=2)}


for line in sys.stdin:
    line = line.strip()
    if not line:
        continue
    _reuse += 1
    try:
        job = json.loads(line)
        kind = job.get("kind")
        if kind == "python":
            res = _run_python(job)
        elif kind == "sympy_equal":
            res = _run_sympy(job)
        elif kind == "ping":
            res = {"ok": True, "pid": os.getpid(), "reuse": _reuse}
        else:
            res = {"ok": False, "stderr": f"unknown kind {kind!r}"}
    except BaseException:
        res = {"ok": False, "stderr": traceback.format_exc(limit=2)}
    sys.stdout.write(json.dumps(res, separators=(",", ":")) + "\n")
    sys.stdout.flush()
"""


def _repo_pythonpath() -> str:
    import areal_tpu

    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(areal_tpu.__file__)
    ))
    existing = os.environ.get("PYTHONPATH", "")
    if repo_root in existing.split(os.pathsep):
        return existing
    return repo_root + (os.pathsep + existing if existing else "")


class _Worker:
    """One warm sandbox subprocess. Owned by at most one pool thread at
    a time (the pool hands workers out through a Queue), so run() needs
    no internal locking."""

    def __init__(self, mem_mb: int):
        self.mem_mb = mem_mb
        env = dict(os.environ)
        env["_REXEC_MEM_MB"] = str(mem_mb)
        env["PYTHONPATH"] = _repo_pythonpath()
        # The sandbox must never inherit a device grab.
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _WORKER_SOURCE],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            env=env,
            text=True,
        )
        self.jobs_served = 0

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass

    def run(self, job: Dict[str, Any], timeout_s: float) -> Dict[str, Any]:
        """One job round trip; kills the worker at the wall timeout (the
        pool respawns it). Returns the result dict, always containing
        "ok"."""
        if not self.alive():
            return {"ok": False, "error": "worker dead"}
        fired = threading.Event()

        def _on_timeout():
            fired.set()
            self.kill()

        timer = threading.Timer(timeout_s, _on_timeout)
        timer.daemon = True
        timer.start()
        try:
            self.proc.stdin.write(
                json.dumps(job, separators=(",", ":")) + "\n"
            )
            self.proc.stdin.flush()
            line = self.proc.stdout.readline()
        except Exception:
            line = ""
        finally:
            timer.cancel()
        if not line:
            # EOF from the job pipe means the worker is gone (the loop
            # never closes stdout while alive). Reap it here so the
            # pool's alive() check sees the death deterministically.
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.kill()
            if fired.is_set():
                return {"ok": False, "error": "timeout", "timeout": True}
            return {"ok": False, "error": "worker died"}
        self.jobs_served += 1
        try:
            return json.loads(line)
        except ValueError:
            return {"ok": False, "error": "garbled worker reply"}


class WorkerPool:
    """Warm worker fleet with kill-on-timeout + respawn semantics.

    submit() is synchronous and thread-safe; the HTTP front calls it
    through run_in_executor. Counters back the /metrics surface."""

    def __init__(
        self,
        n_workers: Optional[int] = None,
        mem_mb: Optional[int] = None,
        max_reuse: Optional[int] = None,
        default_timeout_s: Optional[float] = None,
    ):
        self.n_workers = n_workers or env_registry.get_int(
            "AREAL_REXEC_WORKERS"
        )
        self.mem_mb = mem_mb or env_registry.get_int("AREAL_REXEC_MEM_MB")
        self.max_reuse = (
            max_reuse
            if max_reuse is not None
            else env_registry.get_int("AREAL_REXEC_MAX_REUSE")
        )
        self.default_timeout_s = default_timeout_s or env_registry.get_float(
            "AREAL_REXEC_TIMEOUT_S"
        )
        self._free: "queue.Queue[_Worker]" = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False
        self.counters: Dict[str, int] = {
            "jobs_total": 0,
            "job_failures": 0,
            "timeouts": 0,
            "worker_respawns": 0,
            "warm_hits": 0,
            "pending": 0,
        }
        self._workers: List[_Worker] = []
        for _ in range(self.n_workers):
            w = _Worker(self.mem_mb)
            self._workers.append(w)
            self._free.put(w)
        self._exec = ThreadPoolExecutor(
            max_workers=self.n_workers,
            thread_name_prefix="rexec-pool",
        )

    def _incr(self, key: str, by: int = 1):
        with self._lock:
            self.counters[key] += by

    def workers_alive(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers if w.alive())

    def _replace(self, dead: _Worker) -> _Worker:
        dead.kill()
        fresh = _Worker(self.mem_mb)
        with self._lock:
            self.counters["worker_respawns"] += 1
            try:
                self._workers.remove(dead)
            except ValueError:
                pass
            self._workers.append(fresh)
        return fresh

    def submit_one(
        self, job: Dict[str, Any], timeout_s: Optional[float] = None
    ) -> Dict[str, Any]:
        """Run one job on the next free warm worker; blocking."""
        timeout_s = timeout_s or self.default_timeout_s
        worker = self._free.get()
        try:
            try:
                # Chaos: one sandboxed case fails (guarded exec raises,
                # OOM-kill) — must come back as a failed RESULT.
                faults.maybe_fail("rexec.case")
            except Exception as e:
                self._incr("jobs_total")
                self._incr("job_failures")
                return {"ok": False, "error": f"case fault: {e}"}
            was_warm = worker.jobs_served > 0 or worker.alive()
            res = worker.run(job, timeout_s)
            self._incr("jobs_total")
            if res.get("timeout"):
                self._incr("timeouts")
            if not res.get("ok"):
                self._incr("job_failures")
            elif was_warm:
                self._incr("warm_hits")
            return res
        finally:
            if not worker.alive() or (
                self.max_reuse and worker.jobs_served >= self.max_reuse
            ):
                worker = self._replace(worker)
            self._free.put(worker)

    def _queued_one(
        self, job: Dict[str, Any], timeout_s: Optional[float]
    ) -> Dict[str, Any]:
        try:
            return self.submit_one(job, timeout_s)
        finally:
            self._incr("pending", -1)

    def submit(
        self, jobs: List[Dict[str, Any]],
        timeout_s: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Batched submit: jobs fan out over the free workers.

        ``pending`` counts from ENQUEUE, not from worker pickup: the
        service's bounded-queue watermark must see jobs still waiting in
        the fan-out executor's backlog, or concurrent batches would
        stack up invisibly and the 429 shed would never fire."""
        self._incr("pending", len(jobs))
        if len(jobs) == 1:
            return [self._queued_one(jobs[0], timeout_s)]
        futs = [
            self._exec.submit(self._queued_one, j, timeout_s)
            for j in jobs
        ]
        return [f.result() for f in futs]

    def pending(self) -> int:
        with self._lock:
            return self.counters["pending"]

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._exec.shutdown(wait=False)
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            w.kill()


class RewardExecutorService:
    """One pooled executor endpoint: HTTP front + warm pool + lease.

    The supervisor loop is the service's ONLY heartbeat producer — a
    wedged service stops beating and clients fail over, exactly the
    health-registry doctrine (base/health.py)."""

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        executor_id: int = 0,
        port: int = 0,
        n_workers: Optional[int] = None,
        queue_max: Optional[int] = None,
    ):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.executor_id = int(executor_id)
        self.member = f"reward_executor/{self.executor_id}"
        self.queue_max = queue_max or env_registry.get_int(
            "AREAL_REXEC_QUEUE_MAX"
        )
        self.pool = WorkerPool(n_workers=n_workers)
        self._port = port
        self._shed_total = 0
        self.address: Optional[str] = None
        self._heartbeat: Optional[Heartbeat] = None
        self._http_loop: Optional[asyncio.AbstractEventLoop] = None
        self._http_ready = threading.Event()
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        faults.set_scope(self.member)

    # -- HTTP front ----------------------------------------------------

    def _run_http(self):
        from aiohttp import web

        self._http_loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._http_loop)
        app = web.Application(client_max_size=64 << 20)
        app.router.add_post("/rexec/submit", self._h_submit)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/health", self._h_health)
        runner = web.AppRunner(app)
        self._http_loop.run_until_complete(runner.setup())
        host = network.gethostip()
        port = self._port or network.find_free_port()
        site = web.TCPSite(runner, host, port)
        self._http_loop.run_until_complete(site.start())
        self.address = f"http://{host}:{port}"
        self._http_ready.set()
        self._http_loop.run_forever()

    async def _h_submit(self, request):
        from aiohttp import web

        # Chaos: the whole service dies mid-flight (armed `die` via
        # AREAL_FAULTS); clients must fail over on the stale lease.
        faults.maybe_fail("rexec.die")
        d = await request.json()
        jobs = d.get("jobs") or []
        deadline = rpc.Deadline.from_headers(request.headers)
        if deadline is not None and deadline.expired():
            self._shed_total += 1
            return web.json_response(
                {"error": "deadline expired", "retry_after": 0.0},
                status=429, headers={"Retry-After": "0"},
            )
        if self.pool.pending() + len(jobs) > self.queue_max:
            # Bounded queue: shed instead of letting reward latency
            # grow unbounded; the client fails over / backs off.
            self._shed_total += 1
            return web.json_response(
                {"error": "overloaded", "retry_after": 0.5,
                 "queue_depth": self.pool.pending()},
                status=429, headers={"Retry-After": "1"},
            )
        timeout_s = d.get("timeout_s")
        if deadline is not None:
            remaining = deadline.remaining()
            timeout_s = min(
                timeout_s or self.pool.default_timeout_s, max(0.1, remaining)
            )
        loop = asyncio.get_event_loop()
        results = await loop.run_in_executor(
            None, self.pool.submit, jobs, timeout_s
        )
        return web.json_response({"results": results})

    async def _h_metrics(self, request):
        from aiohttp import web

        c = dict(self.pool.counters)
        lines = [
            f"areal:rexec_jobs_total {c['jobs_total']}",
            f"areal:rexec_job_failures {c['job_failures']}",
            f"areal:rexec_timeouts {c['timeouts']}",
            f"areal:rexec_shed_total {self._shed_total}",
            f"areal:rexec_queue_depth {c['pending']}",
            f"areal:rexec_workers_alive {self.pool.workers_alive()}",
            f"areal:rexec_worker_respawns {c['worker_respawns']}",
            f"areal:rexec_warm_hits {c['warm_hits']}",
        ]
        return web.Response(text="\n".join(lines) + "\n")

    async def _h_health(self, request):
        from aiohttp import web

        return web.json_response(
            {"status": "ok", "workers_alive": self.pool.workers_alive()}
        )

    # -- lifecycle -----------------------------------------------------

    def _supervise(self):
        ttl = self._heartbeat.ttl if self._heartbeat else 10.0
        while not self._stop.wait(max(0.05, ttl / 3)):
            # Respawn any crashed workers outside the job path, then
            # beat: the lease renews only while supervision runs.
            with self.pool._lock:
                dead = [w for w in self.pool._workers if not w.alive()]
            for w in dead:
                try:
                    fresh = self.pool._replace(w)
                    self.pool._free.put(fresh)
                except Exception:
                    logger.warning("worker respawn failed", exc_info=True)
            if self._heartbeat is not None:
                self._heartbeat.beat()

    def start(self, timeout: float = 30.0) -> str:
        self._http_thread = threading.Thread(
            target=self._run_http, daemon=True, name="rexec-http"
        )
        self._http_thread.start()
        if not self._http_ready.wait(timeout):
            raise TimeoutError("reward executor HTTP front did not start")
        name_resolve.add(
            names.reward_executor_url(
                self.experiment_name, self.trial_name,
                str(self.executor_id),
            ),
            self.address,
            delete_on_exit=True,
            replace=True,
        )
        self._heartbeat = Heartbeat(
            self.experiment_name,
            self.trial_name,
            self.member,
            payload={"url": self.address, "workers": self.pool.n_workers},
        )
        self._sup_thread = threading.Thread(
            target=self._supervise, daemon=True, name="rexec-supervise"
        )
        self._sup_thread.start()
        logger.info(
            f"reward executor {self.member} serving at {self.address} "
            f"({self.pool.n_workers} warm workers)"
        )
        return self.address

    def stop(self):
        self._stop.set()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        try:
            name_resolve.delete(
                names.reward_executor_url(
                    self.experiment_name, self.trial_name,
                    str(self.executor_id),
                )
            )
        except Exception:
            pass
        if self._http_loop is not None:
            self._http_loop.call_soon_threadsafe(self._http_loop.stop)
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
        self.pool.close()


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description="pooled reward executor")
    p.add_argument("--experiment", default="rexec")
    p.add_argument("--trial", default="local")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--name-resolve-root", default=None)
    p.add_argument(
        "--selftest",
        action="store_true",
        help="spawn the pool, probe /metrics + one sandboxed job, "
        "tear down; exit 0 iff healthy (chip_runbook preflight)",
    )
    args = p.parse_args(argv)
    if args.name_resolve_root:
        name_resolve.reconfigure("nfs", record_root=args.name_resolve_root)
    else:
        name_resolve.reconfigure("memory")
    svc = RewardExecutorService(
        args.experiment, args.trial, executor_id=args.index,
        port=args.port, n_workers=args.workers,
    )
    url = svc.start()
    if args.selftest:
        import urllib.request

        try:
            res = svc.pool.submit(
                [{"kind": "python", "code": "print(6*7)"}], timeout_s=10.0
            )[0]
            assert res.get("ok") and "42" in res.get("stdout", ""), res
            policy = rpc.default_policy()
            probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
            with urllib.request.urlopen(
                url + "/metrics", timeout=policy.attempt_timeout(probe_dl)
            ) as r:
                text = r.read().decode()
            assert "areal:rexec_jobs_total" in text, text
            print(f"rexec selftest ok: {url}")
            return 0
        except Exception as e:
            print(f"rexec selftest FAILED: {e}", file=sys.stderr)
            return 1
        finally:
            svc.stop()
    print(url, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

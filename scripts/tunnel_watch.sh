#!/bin/bash
# Tunnel watcher: probe the TPU tunnel every 2 minutes; when it answers,
# run the banked-perf sequence (bench + MFU sweep + long-context probes +
# on-chip kernel parity) and record everything under /tmp/r5_chip/.
# The tunnel flaps, so each step re-probes and the bench gets one retry.
# Exits after the full sequence completes once, or after MAX_WAIT_S.
set -u
OUT=/tmp/r5_chip
mkdir -p "$OUT"
MAX_WAIT_S=${MAX_WAIT_S:-36000}
START=$(date +%s)
probe() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}
log() { echo "[$(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }
wait_up() {
  while true; do
    now=$(date +%s)
    if (( now - START > MAX_WAIT_S )); then
      log "gave up after ${MAX_WAIT_S}s"
      exit 1
    fi
    if probe; then log "tunnel UP"; return 0; fi
    log "tunnel down"
    sleep 120
  done
}
run_step() {  # name, timeout_s, cmd...
  local name=$1 tmo=$2; shift 2
  log "step $name: $*"
  timeout "$tmo" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  local rc=$?
  log "step $name done rc=$rc"
  return $rc
}
log "watcher started"
cd /root/repo
wait_up
run_step bench 3000 python bench.py || { wait_up; run_step bench2 3000 python bench.py; }
wait_up; run_step sweep_blocks 3000 python scripts/mfu_sweep.py blocks
wait_up; run_step sweep_ce 2400 python scripts/mfu_sweep.py ce
wait_up; run_step sweep_seqlen 2400 python scripts/mfu_sweep.py seqlen
wait_up; run_step probe_t16k 1800 python scripts/long_context_probe.py train16k
wait_up; run_step probe_t32k 2400 python scripts/long_context_probe.py train32k
wait_up; run_step probe_gen 2400 python scripts/long_context_probe.py gen
# int8 KV A/B (chip_runbook.sh step 5): same gen probe with quantized
# pool — the measurement that gates flipping the int8 default.
wait_up; run_step probe_gen_int8 2400 env AREAL_KV_CACHE_DTYPE=int8 \
    python scripts/long_context_probe.py gen
# speculative decoding A/B (runbook step 5b): greedy baseline vs
# greedy+spec — the regime where prompt-lookup drafts are meaningful.
wait_up; run_step probe_gen_greedy 2400 env AREAL_PROBE_GREEDY=1 \
    python scripts/long_context_probe.py gen
wait_up; run_step probe_gen_spec 2400 env AREAL_PROBE_GREEDY=1 \
    AREAL_SPEC_DRAFT=4 python scripts/long_context_probe.py gen
# int8 decode weights A/B (runbook step 5c).
wait_up; run_step probe_gen_w8 2400 env AREAL_DECODE_WEIGHT_DTYPE=int8 \
    python scripts/long_context_probe.py gen
wait_up; run_step probe_sortskip 2400 python scripts/long_context_probe.py sortskip
# dense-decode anchor for the paged-engine tok/s (VERDICT r4 weak #5).
wait_up; run_step probe_densegen 2400 python scripts/long_context_probe.py densegen
# AREAL_ONCHIP_TESTS=1: without it tests/conftest.py pins jax to CPU and
# the compiled-kernel parity gate silently skips instead of running.
wait_up; run_step flash_parity 1800 env AREAL_ONCHIP_TESTS=1 \
    python -m pytest tests/model/test_flash_attn.py -q --no-header
wait_up; run_step sweep_mbs 2400 python scripts/mfu_sweep.py mbs
log "sequence complete"

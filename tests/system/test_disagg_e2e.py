"""ISSUE 7 acceptance: disaggregated prefill/decode serving across real
process boundaries — 2 prefill-role + 1 decode-role GenerationServer
processes (real ServingEngines on CPU jax) behind a real GserverManager,
driven by the real PartialRolloutManager client.

Asserted end to end:
- mixed-length rollouts complete with the KV handed off over HTTP
  (hash-verified chunk pull: decode-side kv_import counters match the
  prefill-side exports, bytes > 0);
- the manager's pairing routes prefill by queued-prompt-token load and
  decode by free pages, with the pairing visible in /status pools;
- chaos (AREAL_FAULTS): a prefill server killed MID-HANDOFF (after the
  KV export, before the decode POST completes) -> the client's failover
  resubmits through the manager, which evicts the dead server and
  re-routes to the surviving prefill server; every rollout completes —
  zero failed rollouts.

Time budget: ~50 s (3 CPU-jax child processes + warm XLA cache; the
chaos phase reuses the same fleet).
"""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
import uuid

import pytest

from tests import fixtures

# Multi-process, compile-bound: keep off shared workers (pytest.ini).
pytestmark = [pytest.mark.serial, pytest.mark.chaos]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

MODEL_CFG = dict(
    n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
    intermediate_dim=64, vocab_size=64, compute_dtype="float32",
    param_dtype="float32",
)
ROLES = ["prefill", "prefill", "decode"]

CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
import jax; jax.config.update("jax_platforms", "cpu")
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.api.config import ModelAbstraction
from areal_tpu.system.generation_server import GenerationServer
import areal_tpu.engine.factories  # registry
cfg = GenerationServerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, server_index=%(idx)d,
    model=ModelAbstraction("tpu_transformer", args=dict(config=%(model_cfg)r)),
    max_concurrent_requests=2, max_seq_len=512, kv_page_size=8,
    decode_block_steps=4, prompt_bucket=16, prefill_chunk=16,
    prefix_cache_tokens=4096, role=%(role)r, seed=0,
)
w = GenerationServer()
w.configure(cfg, experiment_name=cfg.experiment_name, trial_name=cfg.trial_name,
            worker_name=cfg.worker_name)
w.run()
'''


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _metrics(url):
    text = urllib.request.urlopen(url + "/metrics", timeout=30).read().decode()
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                out[parts[0]] = parts[1]
    return out


def _wait_until(cond, timeout, msg, proc_check=None):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        if proc_check is not None:
            proc_check()
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.timeout(600)
def test_disagg_fleet_handoff_and_prefill_death_failover(
    tmp_path, monkeypatch
):
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.base import constants, name_resolve, names
    from areal_tpu.system.gserver_manager import GserverManager
    from areal_tpu.system.partial_rollout import PartialRolloutManager

    nr = str(tmp_path / "nr")
    exp, trial = f"disagg-{uuid.uuid4().hex[:6]}", "t0"
    monkeypatch.setenv("AREAL_HEALTH_TTL", "1.0")
    monkeypatch.setattr(
        constants, "PARAM_REALLOC_ROOT", str(tmp_path / "realloc")
    )
    repo = name_resolve.reconfigure("nfs", record_root=nr)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["AREAL_HEALTH_TTL"] = "1.0"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs, logs, cleanup = [], [], []
    loop = asyncio.new_event_loop()
    try:
        for idx, role in enumerate(ROLES):
            child_env = dict(env)
            if idx == 0:
                # Chaos arm: server 0's FIRST kv-export handoff dies
                # mid-flight — after the KV left the engine, before the
                # decode server's pull completes. The client sees a dead
                # socket on /generate.
                child_env["AREAL_FAULTS"] = (
                    "gserver.kv_export@generation_server/0=die:k=1"
                )
            log_path = tmp_path / f"server{idx}.log"
            log_f = open(log_path, "w")
            logs.append(log_path)
            cleanup.append(log_f.close)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", CHILD % dict(
                    repo=REPO, nr=nr, exp=exp, trial=trial, idx=idx,
                    model_cfg=MODEL_CFG, role=role,
                )],
                env=child_env, cwd=REPO, stdout=log_f,
                stderr=subprocess.STDOUT,
            ))

        def alive(indices=range(len(ROLES))):
            for i in indices:
                assert procs[i].poll() is None, (
                    f"server {i} died:\n" + logs[i].read_text()[-3000:]
                )

        urls = {}

        def discovered():
            alive()
            for i in range(len(ROLES)):
                if i not in urls:
                    try:
                        urls[i] = name_resolve.get(
                            names.gen_server_url(exp, trial, str(i))
                        )
                    except name_resolve.NameEntryNotFoundError:
                        return False
            return True

        _wait_until(discovered, 240, "server discovery")

        m = GserverManager()
        m.configure(GserverManagerConfig(
            experiment_name=exp, trial_name=trial, model_name="actor",
            n_servers=len(ROLES), train_batch_size=4,
            max_head_offpolicyness=1000,
            flush_request_timeout=fixtures.scale_timeout(30.0),
            health_check_interval=0.2,
        ))
        mt = threading.Thread(target=m.run, daemon=True)
        mt.start()
        cleanup.append(lambda: mt.join(timeout=10))
        _wait_until(
            lambda: len(m._healthy_urls()) == len(ROLES), 60,
            "manager sees 3 healthy servers", proc_check=alive,
        )
        _wait_until(
            lambda: [
                m._server_roles.get(urls[i]) for i in range(len(ROLES))
            ] == ROLES,
            30, "manager learned the pool roles", proc_check=alive,
        )

        prm = PartialRolloutManager(
            m.address, request_timeout=fixtures.scale_timeout(120),
            max_retries=8, retry_backoff_s=0.05,
        )
        cleanup.append(lambda: loop.run_until_complete(prm.close()))

        # Mixed-length rollouts, concurrently: long prompts take the
        # chunked-prefill path on the prefill pool, short ones the
        # batched path; every decode stream runs on the decode server.
        # Rollout q0 (first scheduled) lands on prefill server 0, whose
        # chaos arm kills it mid-handoff.
        prompts = {
            "q0": list(range(1, 33)),        # 32 tokens: chunked path
            "q1": [3, 5, 7, 9, 11, 13, 15, 17],
            "q2": list(range(2, 50)),        # 48 tokens: chunked path
            "q3": [8, 6, 4, 2, 10, 12, 14, 16],
        }

        async def run_all():
            g = GenerationHyperparameters(max_new_tokens=10, greedy=True)
            outs = await asyncio.gather(*[
                prm._generate_one(qid, p, g) for qid, p in prompts.items()
            ])
            return dict(zip(prompts, outs))

        outs = loop.run_until_complete(run_all())
        # ZERO failed rollouts: every episode completed its full budget
        # despite the prefill-server death mid-handoff.
        for qid, out in outs.items():
            assert len(out.output_ids) == 10, (qid, out)

        # The chaos arm fired: server 0 died and was evicted; the
        # survivors carried the fleet.
        _wait_until(
            lambda: procs[0].poll() is not None, 30, "chaos kill landed"
        )
        _wait_until(lambda: urls[0] in m._evicted, 30, "eviction")
        assert set(m._healthy_urls()) == {urls[1], urls[2]}

        # KV crossed real process boundaries, hash-verified: the decode
        # server imported at least as many blobs as completed handoffs,
        # with real bytes.
        m_dec = _metrics(urls[2])
        assert m_dec["areal:role"] == "decode"
        assert m_dec["areal:kv_import_total"] >= 3.0, m_dec
        assert m_dec["areal:kv_import_bytes"] > 0
        m_p1 = _metrics(urls[1])
        assert m_p1["areal:kv_export_total"] >= 1.0
        # Decode streams ran where they should: the decode engine
        # emitted the tokens, the surviving prefill server only ever
        # prefilled (1 token per handed-off request).
        assert m_dec["areal:total_generated_tokens"] >= 3 * 9

        # Pools + fleet handoff totals on the manager surface.
        _wait_until(
            lambda: _get_json(m.address + "/status")["pools"][
                "kv_handoff"]["imports"] >= 3,
            30, "fleet kv_handoff totals",
        )
        st = _get_json(m.address + "/status")
        assert st["pools"]["roles"][urls[2]] == "decode"
        assert urls[1] in st["pools"]["prefill"]
        assert urls[2] not in st["pools"]["prefill"]

        # The fleet still serves new sessions after the death.
        post = loop.run_until_complete(run_one(prm, "post/0"))
        assert len(post.output_ids) == 6

        name_resolve.add(
            names.experiment_status(exp, trial), "COMPLETE", replace=True
        )
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE", replace=True
            )
        except Exception:
            pass
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for fn in cleanup:
            try:
                fn()
            except Exception:
                pass
        loop.close()
        repo.reset()


async def run_one(prm, qid):
    from areal_tpu.api.model_api import GenerationHyperparameters

    return await prm._generate_one(
        qid, [5, 6, 7, 8, 9, 10, 11, 12],
        GenerationHyperparameters(max_new_tokens=6, greedy=True),
    )

"""In-process end-to-end experiments on CPU (mirrors reference
tests/experiments/test_math_ppo.py and test_sft.py): master inline +
model workers as spawned subprocesses, mock or tiny-real engines."""

import os
import uuid

import numpy as np
import pytest

from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.data_api import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import (
    ExperimentConfig,
    ExperimentSaveEvalControl,
    MasterWorkerConfig,
    ModelShardSpec,
    ModelWorkerConfig,
)
from areal_tpu.system.controller import LocalController
from tests import fixtures

TINY_CFG = dict(
    vocab_size=128,
    hidden_dim=32,
    n_layers=2,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    max_position_embeddings=256,
    compute_dtype="float32",
)


def _mk_tokenizer_files(tmp_path):
    rows = fixtures.make_sft_rows(32, seed=3)
    texts = [r["prompt"] + " " + r["answer"] for r in rows]
    tok = fixtures.train_tiny_tokenizer(texts, tmp_path)
    tok_dir = str(tmp_path / "tok_full")
    tok.save_pretrained(tok_dir)
    return rows, tok_dir


def _worker_env(tmp_path):
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "AREAL_FILEROOT": str(tmp_path / "fileroot"),
    }


# n_workers=2 repeats the same control-plane path with one more spawned
# worker for ~2x the wall clock (~45s): slow-marked to keep tier-1 under
# budget; the 1-worker variant still pins the full DFG in tier-1.
@pytest.mark.parametrize(
    "n_workers", [1, pytest.param(2, marks=pytest.mark.slow)]
)
def test_sft_e2e_mock(tmp_path, n_workers):
    """SFT DFG on the mock engine: control plane, dataset hosting, DP
    dispatch, data plane pulls, save/ckpt/exit."""
    exp, trial = f"e2e-sft-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    data_path = fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")

    sft = MFCDef(
        name="sft_train",
        model_name=ModelName("default", 0),
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=8,
        input_keys=("packed_input_ids", "prompt_mask"),
        mb_spec=MicroBatchSpec(n_mbs=1),
    )
    workers = [f"model_worker/{i}" for i in range(n_workers)]
    model_workers = []
    for i in range(n_workers):
        model_workers.append(
            ModelWorkerConfig(
                experiment_name=exp,
                trial_name=trial,
                worker_index=i,
                shards=[
                    ModelShardSpec(
                        id=ModelShardID(ModelName("default", 0), host_rank=i, n_hosts=n_workers),
                        model=ModelAbstraction(
                            "tpu_transformer",
                            args=dict(config=TINY_CFG, tokenizer_path=tok_dir),
                        ),
                        backend=ModelBackendAbstraction("mock_train"),
                        interface=ModelInterfaceAbstraction("sft"),
                    )
                ],
                datasets=[
                    DatasetAbstraction(
                        "prompt_answer",
                        args=dict(max_length=64, dataset_path=data_path),
                    )
                ],
                tokenizer_path=tok_dir,
                dataset_dp_rank=i,
                dataset_dp_size=n_workers,
                train_batch_size=8,
                total_train_epochs=2,
            )
        )
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(
            total_train_epochs=2, ckpt_freq_steps=2, benchmark_steps=6
        ),
        rpcs=[sft],
        model_topos={str(ModelName("default", 0)): workers},
        data_hosts=workers,
        n_model_workers=n_workers,
        train_batch_size=8,
    )
    cfg = ExperimentConfig(
        experiment_name=exp,
        trial_name=trial,
        master=master,
        model_workers=model_workers,
    )
    ctl = LocalController(
        cfg,
        name_resolve_cfg={
            "backend": "nfs",
            "record_root": str(tmp_path / "name_resolve"),
        },
        worker_env=_worker_env(tmp_path),
    )
    result = ctl.run()
    assert result["global_step"] == 6


@pytest.mark.serial
@pytest.mark.slow  # ~44s: the sync-PPO loop is covered at unit level
def test_sync_ppo_e2e_tiny_real(tmp_path):
    """Sync PPO DFG (gen -> {rew, ref} -> train) with the real JAX engine
    on a tiny model, single worker hosting actor+ref+reward."""
    exp, trial = f"e2e-ppo-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    mc_rows = fixtures.make_math_code_rows(16, seed=5)
    # keep only math rows (code exec is slow in CI-style runs)
    mc_rows = [r for r in mc_rows if r["task"] == "math"]
    data_path = fixtures.write_jsonl(mc_rows, tmp_path / "mc.jsonl")

    actor = ModelName("actor", 0)
    ref = ModelName("ref", 0)
    rew = ModelName("reward", 0)
    n_seqs = 4

    rpcs = [
        MFCDef(
            name="actor_gen",
            model_name=actor,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=("packed_prompts",),
            output_keys=(
                "packed_input_ids",
                "prompt_mask",
                "packed_logprobs",
                "seq_no_eos_mask",
            ),
        ),
        MFCDef(
            name="rew_inf",
            model_name=rew,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=("packed_input_ids", "prompt_mask"),
            output_keys=("rewards",),
        ),
        MFCDef(
            name="ref_inf",
            model_name=ref,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=("packed_input_ids", "prompt_mask"),
            output_keys=("logprobs",),
            output_key_remap={"logprobs": "ref_logprobs"},
        ),
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=(
                "packed_input_ids",
                "prompt_mask",
                "packed_logprobs",
                "ref_logprobs",
                "rewards",
                "seq_no_eos_mask",
            ),
        ),
    ]

    gconfig = dict(n=2, max_new_tokens=8, greedy=False, temperature=1.0)
    shards = [
        ModelShardSpec(
            id=ModelShardID(actor),
            model=ModelAbstraction(
                "tpu_transformer",
                args=dict(config=TINY_CFG, tokenizer_path=tok_dir, dtype="float32"),
            ),
            backend=ModelBackendAbstraction(
                "jax_train", args=dict(optimizer=dict(lr=1e-4), remat=False,
                                       row_len_multiple=8)
            ),
            interface=ModelInterfaceAbstraction(
                "ppo_actor", args=dict(gconfig=gconfig, kl_ctl=0.1)
            ),
        ),
        ModelShardSpec(
            id=ModelShardID(ref),
            model=ModelAbstraction(
                "tpu_transformer",
                args=dict(config=TINY_CFG, tokenizer_path=tok_dir, dtype="float32"),
            ),
            backend=ModelBackendAbstraction(
                "jax_inference", args=dict(row_len_multiple=8)
            ),
            interface=ModelInterfaceAbstraction(
                "ppo_actor", args=dict(gconfig=gconfig)
            ),
        ),
        ModelShardSpec(
            id=ModelShardID(rew),
            model=ModelAbstraction(
                "tpu_transformer",
                args=dict(config=TINY_CFG, tokenizer_path=tok_dir),
            ),
            backend=ModelBackendAbstraction("mock_inference"),
            interface=ModelInterfaceAbstraction("rw-math-code"),
        ),
    ]
    mw = ModelWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        shards=shards,
        datasets=[
            DatasetAbstraction(
                "math_code_prompt", args=dict(dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        train_batch_size=n_seqs,
        total_train_epochs=1,
    )
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(total_train_epochs=1, benchmark_steps=2),
        rpcs=rpcs,
        model_topos={
            str(actor): ["model_worker/0"],
            str(ref): ["model_worker/0"],
            str(rew): ["model_worker/0"],
        },
        data_hosts=["model_worker/0"],
        n_model_workers=1,
        train_batch_size=n_seqs,
    )
    cfg = ExperimentConfig(
        experiment_name=exp, trial_name=trial, master=master, model_workers=[mw]
    )
    ctl = LocalController(
        cfg,
        name_resolve_cfg={
            "backend": "nfs",
            "record_root": str(tmp_path / "name_resolve"),
        },
        worker_env=_worker_env(tmp_path),
    )
    result = ctl.run()
    assert result["global_step"] == 2


@pytest.mark.slow  # ~110s: the heaviest single tier-1 test; the recover
# metadata round-trip stays pinned by tests/base/test_recover.py
def test_recovery_e2e_mock(tmp_path):
    """Checkpoint -> relaunch -> resume: the second run continues from the
    recover info instead of restarting (mirrors reference
    test_buffer_recover.py + apps/main.py relaunch loop)."""
    exp, trial = f"e2e-rec-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    data_path = fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")

    def build_cfg(benchmark_steps, recover_mode):
        sft = MFCDef(
            name="sft_train",
            model_name=ModelName("default", 0),
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=None,
            n_seqs=8,
            input_keys=("packed_input_ids", "prompt_mask"),
        )
        mw = ModelWorkerConfig(
            experiment_name=exp,
            trial_name=trial,
            worker_index=0,
            shards=[
                ModelShardSpec(
                    id=ModelShardID(ModelName("default", 0)),
                    model=ModelAbstraction(
                        "tpu_transformer",
                        args=dict(config=TINY_CFG, tokenizer_path=tok_dir),
                    ),
                    backend=ModelBackendAbstraction("mock_train"),
                    interface=ModelInterfaceAbstraction("sft"),
                )
            ],
            datasets=[
                DatasetAbstraction(
                    "prompt_answer", args=dict(max_length=64, dataset_path=data_path)
                )
            ],
            tokenizer_path=tok_dir,
            train_batch_size=8,
            total_train_epochs=10,
        )
        master = MasterWorkerConfig(
            experiment_name=exp,
            trial_name=trial,
            exp_ctrl=ExperimentSaveEvalControl(
                total_train_epochs=10,
                ckpt_freq_steps=2,
                benchmark_steps=benchmark_steps,
            ),
            rpcs=[sft],
            model_topos={str(ModelName("default", 0)): ["model_worker/0"]},
            data_hosts=["model_worker/0"],
            n_model_workers=1,
            train_batch_size=8,
            recover_mode=recover_mode,
        )
        return ExperimentConfig(
            experiment_name=exp, trial_name=trial, master=master, model_workers=[mw]
        )

    nr = {"backend": "nfs", "record_root": str(tmp_path / "name_resolve")}
    env = _worker_env(tmp_path)

    r1 = LocalController(build_cfg(4, "disabled"), name_resolve_cfg=nr, worker_env=env).run()
    assert r1["global_step"] == 4

    # Second launch resumes at step 5 (ckpt was dumped at step 4).
    r2 = LocalController(build_cfg(6, "auto"), name_resolve_cfg=nr, worker_env=env).run()
    assert r2["global_step"] == 6

    from areal_tpu.base import recover

    info = recover.load(exp, trial)
    assert info.last_step_info.global_step >= 4

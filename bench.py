"""Benchmark: training throughput per chip on the flagship architecture.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: achieved model TFLOP/s per chip for the full training step
(fwd + bwd + sharded optimizer) on a Qwen2.5-style packed-varlen model in
bfloat16. FLOPs are computed analytically from the model dims (the
reference does the same for its TFLOP/s logs — realhf/base/monitor.py:288
llama formulas, realhf/system/flops_counter.py).

vs_baseline: ratio against 198 TFLOP/s/GPU — the reference's efficiency
class on its H800 benchmark hardware (~40% MFU of H800 dense bf16
~495 TFLOP/s; its headline runs are throughput-bound on exactly this
train path, benchmark/verl_v0_3_0_post1_76084d3/README.md). >1.0 means a
chip running this framework outruns an H800 running the reference.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from areal_tpu.utils.jaxenv import apply_jax_platform_override

apply_jax_platform_override()

BASELINE_TFLOPS = 198.0


# ----------------------------------------------------------------------
# Flap tolerance: persistent XLA compilation cache + per-phase resume.
# A remote-tunneled TPU run that dies mid-compile (VERDICT r5: one lost
# tunnel window killed an entire bench) restarts with (a) warm compiled
# programs and (b) every already-measured phase loaded from disk, so
# only the interrupted phase re-runs.
# ----------------------------------------------------------------------


def enable_compilation_cache():
    """Point JAX's persistent compilation cache at a stable directory
    (min-compile-time floors dropped so every bench program caches)."""
    import jax

    cache_dir = os.environ.get(
        "AREAL_XLA_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "areal_xla_cache"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        log(f"bench: persistent compilation cache at {cache_dir}")
    except Exception as e:  # older jax: cache flags absent — bench still runs
        log(f"bench: compilation cache unavailable ({e!r})")


def init_devices(max_tries: int = None, backoff_s: float = None):
    """`jax.devices()` with bounded retry + exponential backoff: a TPU
    tunnel flap at backend init previously killed the whole bench
    instantly (VERDICT r5: bench must bank numbers inside flap windows).
    Each retry clears cached backends so the next attempt re-dials the
    device rather than replaying the cached failure. Raises the last
    error once the retry budget is spent."""
    import jax

    if max_tries is None:
        max_tries = int(os.environ.get("AREAL_BENCH_INIT_RETRIES", 5))
    if backoff_s is None:
        backoff_s = float(os.environ.get("AREAL_BENCH_INIT_BACKOFF_S", 15.0))
    delay = backoff_s
    last = None
    for attempt in range(max(1, max_tries)):
        try:
            return jax.devices()
        except Exception as e:  # backend init failed (tunnel down?)
            last = e
            log(f"bench: backend init failed (attempt {attempt + 1}/"
                f"{max_tries}): {e!r}")
            if attempt + 1 >= max_tries:
                break
            try:
                jax.clear_backends()
            except Exception:
                pass  # older jax / partial init: retry cold
            time.sleep(delay)
            delay = min(delay * 2, 120.0)
    raise last


def state_path() -> str:
    return os.environ.get(
        "AREAL_BENCH_STATE",
        os.path.join(tempfile.gettempdir(), "areal_bench_state.json"),
    )


def bench_json_path() -> str:
    return os.environ.get(
        "AREAL_BENCH_JSON",
        os.path.join(tempfile.gettempdir(), "areal_bench_result.json"),
    )


def result_json(state: dict, partial: bool = False, error: str = None) -> dict:
    """The bench's JSON result assembled from whatever phases completed.
    Written to bench_json_path() after EVERY phase (a mid-run tunnel drop
    still banks completed phases on disk) and printed at the end."""
    train = state.get("train_tflops")
    out = {
        "metric": "train_tflops_per_chip",
        "value": round(train, 2) if train is not None else 0.0,
        "unit": "TFLOP/s",
        "vs_baseline": (
            round(train / BASELINE_TFLOPS, 3) if train is not None else 0.0
        ),
    }
    ov = state.get("train_overlap") or {}
    for k in ("packing_efficiency", "h2d_wait_ms", "dispatch_gap_ms"):
        if k in ov:
            out[f"train_{k}"] = round(float(ov[k]), 4)
    # RL-trace verdict (AREAL_RL_TRACE=1 during an async phase / run in
    # this process tree): timeline-derived scalars next to the overlap
    # pipeline series. See docs/observability.md.
    rl = state.get("rl_trace") or {}
    for k in (
        "overlap_score", "rollout_e2e_p50_ms", "rollout_e2e_p95_ms",
        "reprefill_tokens",
    ):
        if k in rl:
            out[f"rl_{k}"] = round(float(rl[k]), 4)
    if rl.get("staleness_hist"):
        out["rl_staleness_hist"] = rl["staleness_hist"]
    if state.get("gen_tps") is not None:
        out["gen_tokens_per_sec_per_chip"] = round(float(state["gen_tps"]), 1)
    if state.get("gen_long_tps") is not None:
        out["gen_long_tokens_per_sec_per_chip"] = round(
            float(state["gen_long_tps"]), 1
        )
    if partial:
        out["partial"] = True
    if error:
        out["error"] = error
    return out


def flush_result(state: dict, partial: bool = True):
    path = bench_json_path()
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(result_json(state, partial=partial), f)
        os.replace(tmp, path)
    except OSError as e:
        log(f"bench: result flush failed ({e!r})")


def load_state(platform: str, max_age_s: float = None) -> dict:
    """Previously-measured phase results, if fresh and from the same
    platform; {} otherwise (stale results from an old round must not be
    reported as this round's)."""
    if max_age_s is None:
        max_age_s = float(os.environ.get("AREAL_BENCH_STATE_TTL_S", 6 * 3600))
    try:
        with open(state_path()) as f:
            st = json.load(f)
    except (OSError, ValueError):
        return {}
    if st.get("platform") != platform:
        return {}
    if time.time() - float(st.get("saved_at", 0)) > max_age_s:
        return {}
    return st


def save_phase(state: dict, platform: str, key: str, value) -> dict:
    state = dict(state)
    state[key] = value
    state["platform"] = platform
    state["saved_at"] = time.time()
    path = state_path()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)
    return state


def clear_state():
    try:
        os.remove(state_path())
    except OSError:
        pass


def flagship_cfg(max_pos: int = 40960, attn_bias: bool = True):
    """The benchmark model shape: R1-Distill-Qwen-1.5B-class layers
    (hidden 1536, 12 q / 2 kv heads, head_dim 128, ffn 8960 — the family
    the reference's headline benchmark trains,
    benchmark/verl_v0_3_0_post1_76084d3/README.md:38-44), trimmed to 16
    layers / 32k vocab so params + fp32 Adam moments + activations fit
    one v5e chip's 16 GB HBM. Shared by bench.py and the perf scripts
    (mfu_sweep, long_context_probe) so every banked number measures the
    SAME model."""
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=16, hidden_dim=1536, n_q_heads=12, n_kv_heads=2,
        head_dim=128, intermediate_dim=8960, vocab_size=32768,
        attn_bias=attn_bias, compute_dtype="bfloat16",
        param_dtype="bfloat16", max_position_embeddings=max_pos,
    )


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def train_step_flops(cfg, n_params: int, seqlens) -> float:
    """Analytic fwd+bwd FLOPs for a packed batch (llama-formula style:
    6*N per token for matmuls, plus causal attention score/context terms)."""
    total = 0.0
    q_dim = cfg.n_q_heads * cfg.head_dim
    for l in seqlens:
        total += 6.0 * n_params * l
        # QK^T + AV: 2 * (2 * l^2 * q_dim) * 0.5 (causal) per layer, x3 for bwd.
        total += 6.0 * cfg.n_layers * q_dim * float(l) * l
    return total


def gen_bench(on_tpu: bool, long_form: bool = False) -> float:
    """Generation throughput on the ServingEngine (paged KV, batched
    prefill, jitted decode blocks): sustained output tokens/sec/chip at a
    realistic batch + context. The reference's headline gains are
    generation-side (async RL is generation-bound, blog/AReaL_v0_3.md:125)
    but it publishes only relative deltas, so this is reported as an
    absolute alongside the train metric.

    long_form=True is the 8k-new-tokens-class workload (the reference's
    headline benchmark generates ~31k tokens/sample): moderate batch,
    fixed-shape chunked prefill, and sustained long decode through the
    paged pool — the regime the async design is supposed to win on,
    which the 512+512 short mode does not speak to."""
    import threading

    import jax

    from areal_tpu.engine.serving import GenRequest, ServingEngine
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params

    if on_tpu:
        cfg = flagship_cfg()
        if long_form:
            # ~1.2 GB of paged KV at bf16 alongside the 3.5 GB params.
            n_reqs, plen, max_new, page, block = 8, 1024, 8192, 128, 32
            chunk = 512
        else:
            n_reqs, plen, max_new, page, block = 32, 512, 512, 128, 32
            chunk = None
    else:
        cfg = TransformerConfig(
            n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
            intermediate_dim=128, vocab_size=256, compute_dtype="float32",
        )
        if long_form:
            n_reqs, plen, max_new, page, block = 2, 32, 64, 8, 4
            chunk = 16
        else:
            n_reqs, plen, max_new, page, block = 2, 16, 8, 8, 4
            chunk = None

    params = init_params(cfg, jax.random.PRNGKey(1))
    eng = ServingEngine(
        cfg, params,
        max_batch_size=n_reqs,
        max_seq_len=plen + max_new + page,
        decode_block_steps=block,
        prompt_bucket=page,
        eos_token_id=None,  # budget-bound: every request emits max_new
        page_size=page,
        kv_pool_tokens=n_reqs * (plen + max_new + page),
        prefill_chunk=chunk,
    )
    eng.start()
    rng = np.random.RandomState(1)

    def run(n, new_tokens, tag):
        done = threading.Event()
        got = []

        def cb(res):
            got.append(len(res.output_ids))
            if len(got) == n:
                done.set()

        t0 = time.perf_counter()
        for i in range(n):
            eng.submit(GenRequest(
                qid=f"{tag}{i}",
                input_ids=rng.randint(0, cfg.vocab_size, size=plen).tolist(),
                max_new_tokens=new_tokens,
                done_cb=cb,
            ))
        assert done.wait(1800), f"gen bench stalled: {len(got)}/{n}"
        return sum(got), time.perf_counter() - t0

    # Warmup compiles prefill buckets (or the one chunked program) + the
    # decode block.
    _, wdt = run(min(n_reqs, 8), 2 * block, "w")
    tag = "gen-long" if long_form else "gen"
    log(f"bench: {tag} warmup {wdt:.2f}s")
    toks, dt = run(n_reqs, max_new, "g")
    eng.stop()
    tps = toks / dt
    log(f"bench: {tag} {toks} tokens in {dt:.2f}s -> {tps:.0f} tok/s/chip")
    return tps


def train_bench() -> tuple:
    """Train-throughput phase. Runs in its own frame so every reference to
    the engine (closures included) dies on return and the ~9 GB of params
    + Adam moments actually leave HBM before the generation phase."""
    import jax

    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import count_params, init_params
    from areal_tpu.ops.loss import sft_loss_from_logprobs

    devices = init_devices()
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    log(f"bench: platform={platform} n_devices={len(devices)}")

    if on_tpu:
        # flagship_cfg: params in bf16 with fp32 optimizer moments
        # (weights stream at half the bytes; update math stays fp32 —
        # measured +18 TFLOP/s over fp32 params, scripts/perf_probe.py).
        cfg = flagship_cfg()
        seqlen, n_seqs, n_warmup, n_steps = 2048, 16, 2, 5
    else:
        # CPU smoke mode so dev runs terminate quickly.
        cfg = TransformerConfig(
            n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
            intermediate_dim=128, vocab_size=256, compute_dtype="float32",
        )
        seqlen, n_seqs, n_warmup, n_steps = 128, 4, 1, 2

    params = init_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    log(f"bench: n_params={n_params/1e6:.1f}M")

    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=1e-4, warmup_steps_proportion=0.0),
        total_train_steps=1000, row_len_multiple=seqlen, max_row_len=seqlen,
        # save_attn: keep the flash kernel's residuals, recompute the rest
        # in backward — the best single-chip throughput/memory point for
        # this model size (see scripts/perf_probe.py measurements).
        remat="save_attn" if on_tpu else "full",
    )

    rng = np.random.RandomState(0)
    seqlens = [seqlen] * n_seqs
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seqs)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, cfg.vocab_size, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )

    def packed_loss(lp, rows):
        tot, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
        return tot, {}

    def weight(mb):
        return float(np.sum(mb.data["loss_mask"]))

    def one_step(i):
        return eng.train_batch(batch, MicroBatchSpec(n_mbs=1), packed_loss, weight,
                               version_steps=i, loss_name="bench")

    for i in range(n_warmup):
        t = time.perf_counter()
        one_step(i)
        log(f"bench: warmup step {i} {time.perf_counter() - t:.2f}s")

    # Drain warmup-recorded pipeline stats so the exported overlap
    # telemetry below covers ONLY the timed steps.
    from areal_tpu.base import stats_tracker

    stats_tracker.export(key="perf")

    t0 = time.perf_counter()
    for i in range(n_steps):
        one_step(n_warmup + i)
    jax.block_until_ready(eng.params)
    dt = (time.perf_counter() - t0) / n_steps

    flops = train_step_flops(cfg, n_params, seqlens)
    tflops = flops / dt / 1e12
    tokens_per_sec = total / dt
    log(f"bench: {dt:.3f}s/step {tokens_per_sec:.0f} tok/s {tflops:.1f} TFLOP/s")
    # Input-pipeline health of the timed loop (jax_engine overlap
    # telemetry): packing density of what shipped to HBM + how much of
    # each step the host was blocked packing/transferring.
    perf = stats_tracker.export(key="perf")
    overlap = {
        k[len("perf/"):]: v for k, v in perf.items()
        if k in ("perf/packing_efficiency", "perf/h2d_wait_ms",
                 "perf/dispatch_gap_ms")
    }
    log(f"bench: overlap telemetry {overlap}")

    return tflops, on_tpu, overlap


# Phases completed so far, mirrored for the deadline handler: a gen-phase
# hang must not discard an already-measured train number.
_PARTIAL = {}


def _arm_deadline(seconds: float):
    """If the result line hasn't printed by the deadline, emit an honest
    JSON (with whatever phases DID complete) and hard-exit. A wedged
    device tunnel otherwise hangs the whole bench at jax.devices() with
    NOTHING recorded for the round."""
    import threading

    def fire():
        log(f"bench: deadline {seconds:.0f}s exceeded; device/tunnel stuck")
        phase = "train" if _PARTIAL.get("train_tflops") is None else "generation"
        out = result_json(
            _PARTIAL, partial=True,
            error=f"bench deadline {seconds:.0f}s exceeded in the "
                  f"{phase} phase",
        )
        print(json.dumps(out), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def main():
    deadline = _arm_deadline(float(os.environ.get("AREAL_BENCH_DEADLINE_S", 2700)))
    enable_compilation_cache()
    import gc

    devices = init_devices()
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    state = load_state(platform)
    _PARTIAL.update(state)

    if state.get("train_tflops") is not None:
        tflops = float(state["train_tflops"])
        log(f"bench: resuming train phase from checkpoint "
            f"({tflops:.1f} TFLOP/s)")
    else:
        tflops, on_tpu, overlap = train_bench()
        state = save_phase(state, platform, "train_tflops", tflops)
        state = save_phase(state, platform, "train_overlap", overlap)
        _PARTIAL.update(state)
        flush_result(state)  # bank the phase NOW; a tunnel drop later
        # in the run must not lose an already-measured number.

    gc.collect()  # drop the train frame's device buffers before gen
    if state.get("gen_tps") is not None:
        gen_tps = float(state["gen_tps"])
        log(f"bench: resuming gen phase from checkpoint ({gen_tps:.0f} tok/s)")
    else:
        gen_tps = gen_bench(on_tpu)
        state = save_phase(state, platform, "gen_tps", gen_tps)
        _PARTIAL.update(state)
        flush_result(state)
    gc.collect()
    # Re-arm for the long-form phase: it compiles its own chunked
    # program and decodes 8x8192 tokens — a healthy run must not be
    # killed by whatever is left of the first deadline.
    deadline.cancel()
    deadline = _arm_deadline(
        float(os.environ.get("AREAL_BENCH_LONG_DEADLINE_S", 1200))
    )
    if state.get("gen_long_tps") is not None:
        log(f"bench: resuming gen-long phase from checkpoint "
            f"({float(state['gen_long_tps']):.0f} tok/s)")
    else:
        gen_long_tps = gen_bench(on_tpu, long_form=True)
        state = save_phase(state, platform, "gen_long_tps", gen_long_tps)
        _PARTIAL.update(state)

    deadline.cancel()
    state = maybe_collect_rl_trace(state, platform)
    flush_result(state, partial=False)
    # Completed: the next invocation is a fresh round, not a resume.
    clear_state()
    print(json.dumps(result_json(state)))


def maybe_collect_rl_trace(state: dict, platform: str) -> dict:
    """With AREAL_RL_TRACE=1, fold the RL-trace verdict (overlap score,
    rollout latency, staleness) into the bench JSON — shards come from
    whatever traced run wrote AREAL_RL_TRACE_DIR (e.g. an async e2e
    launched alongside the bench)."""
    from areal_tpu.base import tracing

    if not tracing.enabled():
        return state
    try:
        from areal_tpu.utils import rl_trace

        summary = rl_trace.summarize(tracing.trace_dir())
    except Exception as e:
        log(f"bench: rl_trace summary unavailable ({e!r})")
        return state
    return save_phase(state, platform, "rl_trace", summary)


if __name__ == "__main__":
    main()

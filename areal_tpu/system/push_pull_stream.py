"""Rollout -> trainer trajectory transport: ZMQ PUSH/PULL of JSON dicts.

Counterpart of the reference's push-pull stream
(realhf/system/push_pull_stream.py:18-177): M rollout-worker pushers are
deterministically grouped onto N trainer-side pullers, addresses are
discovered via name_resolve, and messages are newline-free JSON objects
(trajectories are token-id lists — cheap to serialize, and JSON keeps the
stream debuggable, matching the reference's choice).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Tuple

import zmq

from areal_tpu.base import env_registry, logging, name_resolve, names, network, tracing

logger = logging.getLogger("push_pull_stream")

# Reserved payload keys for the exactly-once ledger (AREAL_WAL): the
# pusher's minted sequence id and its ack return address ride the JSON
# like the trace context does, and the puller strips them back off.
SEQ_KEY = "__wal_seq__"
ACK_KEY = "__ack__"


class ZMQJsonPusher:
    """PUSH end. Connects to a puller's bound address.

    With ``ack=True`` the pusher also binds a PULL socket for acks and
    keeps every pushed sample in an unacked window until the puller
    confirms it journaled the sample durably; `redeliver()` re-sends
    samples whose ack timed out (a killed/restarted puller), so a
    trainer SIGKILL never loses an in-flight rollout.
    """

    def __init__(self, host: str, port: int, hwm: int = 1000, ack: bool = False):
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUSH)
        self.sock.setsockopt(zmq.SNDHWM, hwm)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.addr = f"tcp://{host}:{port}"
        self.sock.connect(self.addr)
        self._ack_enabled = ack
        # seq -> (payload, last_send_monotonic, redeliveries so far).
        self._unacked: Dict[str, Tuple[Dict[str, Any], float, int]] = {}
        self.counters = {"areal:train_samples_lost_total": 0}
        if ack:
            ack_host = network.gethostip()
            self.ack_sock = self.ctx.socket(zmq.PULL)
            self.ack_sock.setsockopt(zmq.LINGER, 0)
            ack_port = self.ack_sock.bind_to_random_port(f"tcp://{ack_host}")
            self.ack_addr = f"{ack_host}:{ack_port}"

    def push(self, data: Dict[str, Any], seq: Optional[str] = None):
        # Best-effort RL-trace propagation: the current span context rides
        # the JSON under a reserved key the puller strips back off (one
        # no-op branch when tracing is disabled).
        data = tracing.inject_into(data)
        if self._ack_enabled and seq is not None:
            data = {**data, SEQ_KEY: seq, ACK_KEY: self.ack_addr}
            self._unacked[seq] = (data, time.monotonic(), 0)
        self.sock.send_string(json.dumps(data, separators=(",", ":")), flags=0)

    def drain_acks(self) -> int:
        """Consume pending acks off the ack socket; returns how many
        samples left the unacked window."""
        if not self._ack_enabled:
            return 0
        n = 0
        while self.ack_sock.poll(0):
            seq = self.ack_sock.recv_string()
            if self._unacked.pop(seq, None) is not None:
                n += 1
        return n

    def unacked(self) -> int:
        return len(self._unacked)

    def redeliver(self, timeout_s: Optional[float] = None,
                  max_redeliver: Optional[int] = None) -> int:
        """Re-send samples unacked for AREAL_WAL_ACK_TIMEOUT_S. The
        puller-side ledger makes redelivery idempotent, so over-sending
        is safe; under the default unbounded AREAL_WAL_REDELIVER_MAX
        budget nothing is ever dropped (exactly-once). Returns the
        number redelivered."""
        if not self._ack_enabled or not self._unacked:
            return 0
        if timeout_s is None:
            timeout_s = env_registry.get_float("AREAL_WAL_ACK_TIMEOUT_S")
        if max_redeliver is None:
            max_redeliver = env_registry.get_int("AREAL_WAL_REDELIVER_MAX")
        now = time.monotonic()
        redelivered = 0
        for seq, (data, sent_at, attempts) in list(self._unacked.items()):
            if now - sent_at < timeout_s:
                continue
            if max_redeliver and attempts >= max_redeliver:
                del self._unacked[seq]
                self.counters["areal:train_samples_lost_total"] += 1
                logger.error("sample %s dropped after %d redeliveries", seq, attempts)
                continue
            self.sock.send_string(json.dumps(data, separators=(",", ":")), flags=0)
            self._unacked[seq] = (data, now, attempts + 1)
            redelivered += 1
        return redelivered

    def reconnect(self, host: str, port: int):
        """Point the PUSH socket at a (possibly new) puller address — a
        restarted puller binds a fresh random port, so redelivery after
        a trainer kill must re-target before it can land."""
        addr = f"tcp://{host}:{port}"
        if addr == self.addr:
            return
        try:
            self.sock.disconnect(self.addr)
        except zmq.ZMQError:
            pass
        self.addr = addr
        self.sock.connect(addr)

    def close(self):
        self.sock.close()
        if self._ack_enabled:
            self.ack_sock.close()


class ZMQJsonPuller:
    """PULL end. Binds and accepts many pushers."""

    # RL-trace context of the most recent message (None before the first
    # pull, when absent, or when tracing is disabled).
    last_trace_ctx = None
    # Sequence id + ack return address of the most recent message (None
    # when the pusher is not in ack mode). The consumer acks via
    # `ack(seq, addr)` only AFTER the sample is durable (WAL fsync) —
    # acking earlier would let a kill between ack and fsync lose it.
    last_seq = None
    last_ack_addr = None

    def __init__(self, host: str = "0.0.0.0", port: Optional[int] = None, hwm: int = 1000,
                 default_timeout_ms: int = 100):
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PULL)
        self.sock.setsockopt(zmq.RCVHWM, hwm)
        self.sock.setsockopt(zmq.LINGER, 0)
        if port is None:
            self.port = self.sock.bind_to_random_port(f"tcp://{host}")
        else:
            self.sock.bind(f"tcp://{host}:{port}")
            self.port = port
        self.host = host
        self.default_timeout_ms = default_timeout_ms
        self._ack_socks: Dict[str, zmq.Socket] = {}

    def pull(self, timeout_ms: Optional[int] = None) -> Dict[str, Any]:
        """Blocking with timeout; raises queue-empty style TimeoutError.

        Strips the pusher's RL-trace context off the payload and exposes
        it as `last_trace_ctx` (None when absent/disabled) so consumers
        can parent their spans without the key leaking into the data;
        same treatment for the ledger's seq/ack-address keys."""
        t = self.default_timeout_ms if timeout_ms is None else timeout_ms
        # Reset first: a timeout must not leave a previous message's
        # context attributed to whatever the caller reads next.
        self.last_trace_ctx = None
        self.last_seq = None
        self.last_ack_addr = None
        if not self.sock.poll(t):
            raise TimeoutError("no message within timeout")
        d = json.loads(self.sock.recv_string())
        self.last_trace_ctx = tracing.extract_from(d)
        self.last_seq = d.pop(SEQ_KEY, None)
        self.last_ack_addr = d.pop(ACK_KEY, None)
        return d

    def ack(self, seq: str, addr: str):
        """Confirm `seq` durable to the pusher that sent it (addr from
        the message's ack key). Best-effort: a dead pusher's socket just
        buffers and is dropped on close — redelivery handles the rest."""
        sock = self._ack_socks.get(addr)
        if sock is None:
            sock = self.ctx.socket(zmq.PUSH)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(f"tcp://{addr}")
            self._ack_socks[addr] = sock
        try:
            sock.send_string(seq, flags=zmq.NOBLOCK)
        except zmq.Again:
            logger.warning("ack %s to %s dropped (pusher backlogged/gone)", seq, addr)

    def close(self):
        self.sock.close()
        for sock in self._ack_socks.values():
            sock.close()
        self._ack_socks.clear()


def grouping(n_pushers: int, n_pullers: int) -> Dict[int, List[int]]:
    """puller index -> pusher indices, contiguous blocks (reference
    push_pull_stream.py:125)."""
    assert n_pushers >= n_pullers > 0
    base = n_pushers // n_pullers
    rem = n_pushers % n_pullers
    out: Dict[int, List[int]] = {}
    start = 0
    for i in range(n_pullers):
        cnt = base + (1 if i < rem else 0)
        out[i] = list(range(start, start + cnt))
        start += cnt
    return out


class NameResolvingZmqPuller(ZMQJsonPuller):
    """Puller that registers its address under the stream name."""

    def __init__(self, experiment_name: str, trial_name: str, puller_index: int, **kwargs):
        host_ip = network.gethostip()
        super().__init__(host=host_ip, **kwargs)
        key = names.push_pull_stream(
            experiment_name, trial_name, f"puller{puller_index}"
        )
        name_resolve.add(key, f"{host_ip}:{self.port}", keepalive_ttl=60, replace=True)


class NameResolvingZmqPusher(ZMQJsonPusher):
    """Pusher that looks up its assigned puller by the grouping rule."""

    def __init__(self, experiment_name: str, trial_name: str, pusher_index: int,
                 n_pushers: int, n_pullers: int, **kwargs):
        group = grouping(n_pushers, n_pullers)
        puller_index = next(i for i, pushers in group.items() if pusher_index in pushers)
        self.stream_key = names.push_pull_stream(
            experiment_name, trial_name, f"puller{puller_index}"
        )
        addr = name_resolve.wait(self.stream_key, timeout=300)
        host, port = addr.rsplit(":", 1)
        super().__init__(host, int(port), **kwargs)

    def re_resolve(self, timeout: float = 5) -> bool:
        """Re-look-up the puller and reconnect if its address changed —
        a restarted puller re-registers under the same stream name with
        a fresh port, so the redelivery path calls this before
        re-sending. Returns False when the name is (still) absent."""
        try:
            addr = name_resolve.wait(self.stream_key, timeout=timeout)
        except TimeoutError:
            return False
        host, port = addr.rsplit(":", 1)
        self.reconnect(host, int(port))
        return True

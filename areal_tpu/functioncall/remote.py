"""Remote verifier-service client: batched async HTTP with retries.

Counterpart of the reference's remote functioncall client
(functioncall/base/call.py:81-240 — async_invoke_function with
exponential backoff, batch_function_call_async with a concurrency
semaphore, and the FUNCTIONCALL_SERVICE_DOMAIN switch in
math_rw_interface.py:37-39), built from scratch.

Service contract (same as the reference's verifier service): POST
`{domain}/{task}_verify` with a JSON list of payloads
`{"uid", "solution", "answer"/"test_cases"}`, response is a JSON list of
`{"uid", "success": bool}` in any order. A payload whose verification
ultimately fails (exhausted retries, malformed response) scores False —
a reward must never take the trainer down.

Enable by setting FUNCTIONCALL_SERVICE_DOMAIN (e.g.
"http://verifier.internal:8080"); when unset, `remote_enabled()` is
False and callers use the local verifiers.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging as areal_logging
from areal_tpu.base import name_resolve, names, rpc
from areal_tpu.base.health import HealthRegistry

logger = areal_logging.getLogger("functioncall.remote")

ENV_DOMAIN = "FUNCTIONCALL_SERVICE_DOMAIN"
DEFAULT_TIMEOUT_S = 60.0
MAX_RETRIES = 3
INITIAL_RETRY_S = 0.5
MAX_RETRY_S = 10.0
DEFAULT_CONCURRENCY = 256
DEFAULT_BATCH_SIZE = 64


def service_domain() -> Optional[str]:
    return os.environ.get(ENV_DOMAIN) or None


def remote_enabled() -> bool:
    return service_domain() is not None


async def _post_with_retries(
    session, url: str, batch: List[Dict], timeout_s: float
) -> List[Dict]:
    """One batch POST under the unified RPC policy (base/rpc.py):
    the substrate owns attempts/backoff/per-attempt timeout; the
    verifier keeps only its contract — every failure is retryable
    (a reward must never take the trainer down) and exhaustion scores
    the whole batch False via []."""
    import aiohttp

    async def attempt(attempt_timeout: float) -> List[Dict]:
        async with session.post(
            url, json=batch,
            timeout=aiohttp.ClientTimeout(total=attempt_timeout),
        ) as resp:
            if resp.status >= 500:
                raise OSError(f"server error {resp.status}")
            resp.raise_for_status()
            out = await resp.json()
            if not isinstance(out, list):
                raise ValueError(f"malformed response: {type(out)}")
            return out

    try:
        # No deadline on purpose: the historical contract grants every
        # attempt the FULL timeout_s with backoff sleeps on top (a
        # shared budget would silently shorten the last attempts) — a
        # reward verifier answers to the trainer's patience, not to a
        # propagated rollout budget.
        return await rpc.retry_async(
            attempt,
            policy=rpc.RetryPolicy(
                attempts=MAX_RETRIES + 1,
                backoff_base_s=INITIAL_RETRY_S,
                backoff_max_s=MAX_RETRY_S,
                attempt_timeout_s=timeout_s,
            ),
            retryable=(Exception,),
            what=f"verifier {url}",
        )
    except rpc.RpcError as e:
        logger.error(f"verifier batch failed permanently: {e!r}")
        return []


async def batch_verify_async(
    payloads: List[Dict[str, Any]],
    task: str,
    domain: Optional[str] = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
    concurrency: int = DEFAULT_CONCURRENCY,
    batch_size: int = DEFAULT_BATCH_SIZE,
) -> List[bool]:
    """Verify payloads against `{domain}/{task}_verify`, split into
    batches under a concurrency cap. Returns per-payload success aligned
    with the input order; failed/missing entries are False."""
    import aiohttp

    domain = domain or service_domain()
    assert domain, f"{ENV_DOMAIN} not configured"
    url = f"{domain.rstrip('/')}/{task}_verify"
    for i, p in enumerate(payloads):
        p.setdefault("uid", str(i))

    sem = asyncio.Semaphore(concurrency)
    results: Dict[str, bool] = {}

    async with aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit=concurrency)
    ) as session:

        async def one_batch(batch: List[Dict]):
            async with sem:
                out = await _post_with_retries(session, url, batch, timeout_s)
            for entry in out:
                if isinstance(entry, dict) and "uid" in entry:
                    results[str(entry["uid"])] = bool(entry.get("success"))

        batches = [
            payloads[i : i + batch_size]
            for i in range(0, len(payloads), batch_size)
        ]
        await asyncio.gather(*[one_batch(b) for b in batches])

    return [results.get(str(p["uid"]), False) for p in payloads]


def batch_verify(
    payloads: List[Dict[str, Any]],
    task: str,
    domain: Optional[str] = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
) -> List[bool]:
    """Sync wrapper (used from the reward interface's thread pool)."""
    return asyncio.run(
        batch_verify_async(payloads, task, domain=domain, timeout_s=timeout_s)
    )


# ----------------------------------------------------------------------
# Pooled reward-executor client (system/reward_executor.py)
# ----------------------------------------------------------------------


def _post_json_sync(
    url: str,
    payload: Dict[str, Any],
    timeout: float,
    deadline: Optional[rpc.Deadline] = None,
) -> Any:
    """One POST attempt on the executor wire, mapped onto the substrate's
    exception contract: 429 -> RpcShed (Retry-After floored backoff),
    5xx/connection -> retryable OSError, other codes -> terminal."""
    import urllib.error
    import urllib.request

    dl = deadline or rpc.Deadline.after(timeout)
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers=dl.headers({"Content-Type": "application/json"}),
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        if e.code == 429:
            ra = e.headers.get("Retry-After") if e.headers else None
            raise rpc.RpcShed(url, float(ra or 1.0)) from e
        if e.code >= 500:
            raise OSError(f"{url}: server error {e.code}") from e
        raise rpc.RpcError(f"{url}: HTTP {e.code}") from e
    except urllib.error.URLError as e:
        raise OSError(f"{url}: {e.reason}") from e


class ExecutorPoolClient:
    """Client for the pooled reward-executor fleet.

    Discovery rides the PR 1 health registry (members
    ``reward_executor/<id>``, payload carries the URL) with the
    ``names.reward_executor_url`` records as fallback, so a freshly
    armed `rexec.die` chaos kill drops out of the candidate set within
    one staleness window. Submits round-robin across live executors and
    fail over on connection errors/sheds via the unified retry loop
    (base/rpc.py) — every retry RE-discovers, so a death mid-batch
    lands on a survivor. Exhaustion returns failed RESULTS, never an
    exception: a reward must never take the trainer down."""

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        policy: Optional[rpc.RetryPolicy] = None,
    ):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self._registry = HealthRegistry(
            experiment_name, trial_name, prefix="reward_executor"
        )
        self._policy = policy
        self._rr = 0
        self._lock = threading.Lock()
        self._cache: List[str] = []
        self._cache_ts = -1e9

    def discover(self, fresh: bool = False, max_age_s: float = 2.0) -> List[str]:
        """Live executor URLs, heartbeat-fresh first. Cached briefly so
        hot grading paths don't pay a registry walk per call; failover
        retries pass ``fresh=True`` to re-scan past a just-died peer."""
        import time as _time

        now = _time.monotonic()
        with self._lock:
            if not fresh and now - self._cache_ts < max_age_s:
                return list(self._cache)
        urls = self._discover_uncached()
        with self._lock:
            self._cache = list(urls)
            self._cache_ts = now
        return urls

    def _discover_uncached(self) -> List[str]:
        urls: List[str] = []
        for _m, rec in sorted(self._registry.snapshot().items()):
            u = rec.get("url")
            if u:
                urls.append(u)
        if not urls:
            root = names.reward_executor_url_root(
                self.experiment_name, self.trial_name
            ).rstrip("/")
            for key in sorted(name_resolve.find_subtree(root)):
                try:
                    urls.append(name_resolve.get(key))
                except name_resolve.NameEntryNotFoundError:
                    continue
        return urls

    def available(self) -> bool:
        return bool(self.discover())

    def submit(
        self,
        jobs: List[Dict[str, Any]],
        timeout_s: Optional[float] = None,
        deadline: Optional[rpc.Deadline] = None,
    ) -> List[Dict[str, Any]]:
        """Run a job batch on some live executor; aligned results."""
        if not jobs:
            return []
        from areal_tpu.base import env_registry

        timeout_s = timeout_s or env_registry.get_float(
            "AREAL_REXEC_TIMEOUT_S"
        )
        # The HTTP attempt must outlive the sandbox wall timeout of the
        # slowest wave of jobs across the pool, plus dispatch slack.
        http_timeout = timeout_s * max(1, len(jobs)) + 10.0
        policy = self._policy or rpc.default_policy(
            attempt_timeout_s=http_timeout
        )

        attempt_no = {"n": 0}

        def attempt(attempt_timeout: float) -> List[Dict[str, Any]]:
            attempt_no["n"] += 1
            urls = self.discover(fresh=attempt_no["n"] > 1)
            if not urls:
                raise OSError("no live reward executor")
            with self._lock:
                self._rr += 1
                url = urls[self._rr % len(urls)]
            out = _post_json_sync(
                url + "/rexec/submit",
                {"jobs": jobs, "timeout_s": timeout_s},
                attempt_timeout,
                deadline,
            )
            results = out.get("results") if isinstance(out, dict) else None
            if not isinstance(results, list) or len(results) != len(jobs):
                raise ValueError("malformed executor reply")
            return results

        try:
            return rpc.retry_sync(
                attempt, policy=policy, deadline=deadline,
                what="rexec submit",
            )
        except (rpc.RpcError, Exception) as e:
            logger.error(f"executor pool submit failed permanently: {e!r}")
            return [
                {"ok": False, "error": f"executor unavailable: {e}"}
                for _ in jobs
            ]


_executor_pool: Optional[ExecutorPoolClient] = None
_executor_pool_lock = threading.Lock()


def register_executor_pool(client: Optional[ExecutorPoolClient]):
    """Install (or clear, with None) the process-wide executor-pool
    client. Rollout/trainer workers register one at startup when the
    experiment runs a pooled executor fleet; math_grader and the tool
    envs then route sandboxed work through it."""
    global _executor_pool
    with _executor_pool_lock:
        _executor_pool = client


def get_executor_pool() -> Optional[ExecutorPoolClient]:
    with _executor_pool_lock:
        return _executor_pool

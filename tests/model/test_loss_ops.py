import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.packing import pack_sequences
from areal_tpu.ops.loss import (
    gather_logprobs,
    masked_normalization,
    next_token_logprobs,
    sft_loss,
)


def test_gather_logprobs_matches_log_softmax():
    rng = np.random.RandomState(0)
    logits = rng.randn(4, 10).astype(np.float32)
    labels = rng.randint(0, 10, size=4)
    out = np.asarray(gather_logprobs(jnp.asarray(logits), jnp.asarray(labels)))
    ref = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))[
        np.arange(4), labels
    ]
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_next_token_logprobs_segment_boundaries():
    rng = np.random.RandomState(1)
    seqs = [rng.randint(0, 50, size=l) for l in [4, 3]]
    b = pack_sequences(seqs, row_len=16)
    logits = rng.randn(b.n_rows, b.row_len, 50).astype(np.float32)
    lp = np.asarray(
        next_token_logprobs(
            jnp.asarray(logits), jnp.asarray(b.input_ids), jnp.asarray(b.segment_ids)
        )
    )
    # Within a sequence, position t scores token t+1.
    for span in b.spans:
        seq = seqs[span.seq_index]
        for t in range(span.length - 1):
            col = span.start + t
            row_logits = logits[span.row, col]
            expect = row_logits[seq[t + 1]] - np.log(np.exp(row_logits).sum())
            np.testing.assert_allclose(lp[span.row, col], expect, atol=1e-4)
        # Final position of each sequence contributes 0.
        assert lp[span.row, span.start + span.length - 1] == 0.0
    # Padding positions are 0.
    assert (lp[b.segment_ids == 0] == 0).all()


def test_sft_loss_counts_masked_tokens():
    rng = np.random.RandomState(2)
    seqs = [rng.randint(0, 50, size=6)]
    b = pack_sequences(seqs, row_len=8)
    logits = rng.randn(1, 8, 50).astype(np.float32)
    mask = np.zeros((1, 8), np.float32)
    mask[0, 2:5] = 1.0  # predictions at t=2,3,4 count
    total, n = sft_loss(
        jnp.asarray(logits), jnp.asarray(b.input_ids), jnp.asarray(b.segment_ids),
        jnp.asarray(mask),
    )
    assert float(n) == 3.0
    assert float(total) > 0


def test_masked_normalization():
    x = jnp.asarray(np.array([[1.0, 2.0, 3.0, 100.0]]))
    mask = jnp.asarray(np.array([[1.0, 1.0, 1.0, 0.0]]))
    out = np.asarray(masked_normalization(x, mask))
    vals = out[0, :3]
    assert abs(vals.mean()) < 1e-5
    assert out[0, 3] == 0.0
    np.testing.assert_allclose(np.std(vals, ddof=1), 1.0, atol=0.05)


def test_fused_next_token_logprobs_matches_unfused():
    from areal_tpu.ops.loss import fused_next_token_logprobs

    rng = np.random.RandomState(3)
    R, T, D, V = 2, 32, 16, 64
    hidden = rng.randn(R, T, D).astype(np.float32)
    head_w = (rng.randn(D, V) * 0.1).astype(np.float32)
    input_ids = rng.randint(0, V, size=(R, T)).astype(np.int32)
    seg = np.zeros((R, T), np.int32)
    seg[0, :20] = 1
    seg[0, 20:29] = 2
    seg[1, :15] = 1
    logits = hidden @ head_w
    ref = np.asarray(
        next_token_logprobs(jnp.asarray(logits), jnp.asarray(input_ids), jnp.asarray(seg))
    )
    for chunk in (4096, 16, 7):
        out = np.asarray(
            fused_next_token_logprobs(
                jnp.asarray(hidden), jnp.asarray(head_w),
                jnp.asarray(input_ids), jnp.asarray(seg), chunk_size=chunk,
            )
        )
        np.testing.assert_allclose(out, ref, atol=1e-4)


def test_fused_next_token_logprobs_grads_match():
    import jax

    from areal_tpu.ops.loss import fused_next_token_logprobs

    rng = np.random.RandomState(4)
    R, T, D, V = 2, 16, 8, 32
    hidden = jnp.asarray(rng.randn(R, T, D), jnp.float32)
    head_w = jnp.asarray(rng.randn(D, V) * 0.1, jnp.float32)
    input_ids = jnp.asarray(rng.randint(0, V, size=(R, T)), jnp.int32)
    seg = jnp.ones((R, T), jnp.int32)

    def loss_fused(h, w):
        return -jnp.sum(fused_next_token_logprobs(h, w, input_ids, seg, chunk_size=8))

    def loss_ref(h, w):
        logits = (h @ w).astype(jnp.float32)
        return -jnp.sum(next_token_logprobs(logits, input_ids, seg))

    gh1, gw1 = jax.grad(loss_fused, argnums=(0, 1))(hidden, head_w)
    gh2, gw2 = jax.grad(loss_ref, argnums=(0, 1))(hidden, head_w)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), atol=1e-4)

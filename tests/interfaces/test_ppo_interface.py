"""PPO interface integration: generate -> reward -> inference -> train_step
on a tiny model (counterpart of reference tests/experiments/test_math_ppo.py
algorithm core, without the worker system)."""

import jax
import numpy as np
import pytest

from areal_tpu.api.config import ModelName
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import GenerationHyperparameters, Model
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.interfaces.ppo import PPOActorInterface, PPOCriticInterface
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params


def small_cfg(**kw):
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32", **kw,
    )


def make_actor(lr=1e-3):
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0),
        total_train_steps=100, row_len_multiple=32,
    )
    return Model(name=ModelName("actor"), module=eng, tokenizer=None)


def make_prompts(n=4, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(3, 8, size=n).tolist()
    return SequenceSample.from_default(
        ids=[f"p{i}" for i in range(n)],
        seqlens=lens,
        data={"packed_prompts": rng.randint(1, 64, size=sum(lens))},
    )


@pytest.fixture(scope="module")
def rollout():
    model = make_actor()
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=10, greedy=False),
        n_minibatches=2, adv_norm=True,
    )
    prompts = make_prompts()
    sample = itf.generate(model, prompts, MicroBatchSpec())
    return model, itf, prompts, sample


def test_generate_builds_grouped_sample(rollout):
    model, itf, prompts, sample = rollout
    assert sample.bs == prompts.bs
    assert all(len(sl) == 2 for sl in sample.seqlens["packed_input_ids"])
    total = sample.total_seqlen("packed_input_ids")
    assert sample.data["packed_input_ids"].shape[0] == total
    assert sample.data["prompt_mask"].shape[0] == total
    # Behavior logprobs: zero on prompts (except final prompt position).
    pm = sample.data["prompt_mask"]
    lp = sample.data["packed_logprobs"]
    offset = 0
    for sl in sample.seqlens["packed_input_ids"]:
        for l in sl:
            seq_pm = pm[offset : offset + l]
            seq_lp = lp[offset : offset + l]
            plen = int(seq_pm.sum())
            assert (seq_lp[: plen - 1] == 0).all()
            assert (seq_lp[plen - 1 : l - 1] != 0).any() or l - plen <= 1
            offset += l
    assert sample.data["seq_no_eos_mask"].shape[0] == prompts.bs * 2


def _attach_rewards_and_logps(model, sample, with_critic=False, seed=1):
    rng = np.random.RandomState(seed)
    n_seqs = sum(len(sl) for sl in sample.seqlens["packed_input_ids"])
    sl_tok = [list(s) for s in sample.seqlens["packed_input_ids"]]
    sl_seq = [[1] * len(s) for s in sample.seqlens["packed_input_ids"]]
    total = sample.total_seqlen("packed_input_ids")
    add = SequenceSample(
        ids=list(sample.ids),
        keys={"rewards", "ref_logprobs"},
        data={
            "rewards": rng.choice([5.0, -5.0], size=n_seqs).astype(np.float32),
            "ref_logprobs": (sample.data["packed_logprobs"]
                             + 0.01 * rng.randn(total)).astype(np.float32),
        },
        seqlens={"rewards": sl_seq, "ref_logprobs": sl_tok},
    )
    sample.update_(add)
    if with_critic:
        vals = rng.randn(total).astype(np.float32) * 0.1
        sample.update_(SequenceSample(
            ids=list(sample.ids), keys={"values"},
            data={"values": vals}, seqlens={"values": sl_tok},
        ))


def test_train_step_grpo_mode(rollout):
    model, itf, prompts, sample = rollout
    sample = SequenceSample.gather([sample])  # copy-ish
    _attach_rewards_and_logps(model, sample)
    v0 = model.version
    stats = itf.train_step(model, sample, MicroBatchSpec())
    assert model.version == v0 + 1
    assert np.isfinite(stats["ppo_actor/loss"])
    assert np.isfinite(stats["ppo_actor/kl"])
    assert stats["ppo_actor/n_tokens"] > 0
    assert "ppo_actor/head_offpolicyness" in stats


def test_train_step_decoupled_with_critic(rollout):
    model, _, prompts, sample0 = rollout
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=10),
        n_minibatches=2, use_decoupled_loss=True, behav_imp_weight_cap=10.0,
        group_adv_norm=True,
    )
    sample = SequenceSample.gather([sample0])
    _attach_rewards_and_logps(model, sample, with_critic=True, seed=3)
    # Proximal logprobs from the current policy (actor inference MFC).
    prox = itf.inference(model, sample, MicroBatchSpec())
    sample.update_(prox)
    stats = itf.train_step(model, sample, MicroBatchSpec())
    assert np.isfinite(stats["ppo_actor/loss"])
    assert stats["ppo_actor/importance_weight"] > 0


def test_critic_interface_roundtrip(rollout):
    model_actor, _, prompts, sample0 = rollout
    ccfg = small_cfg(is_critic=True)
    cparams = init_params(ccfg, jax.random.PRNGKey(9))
    ceng = JaxTrainEngine(
        ccfg, cparams,
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=100, row_len_multiple=32,
    )
    cmodel = Model(name=ModelName("critic"), module=ceng, tokenizer=None)
    citf = PPOCriticInterface(n_minibatches=2)

    sample = SequenceSample.gather([sample0])
    vals = citf.inference(cmodel, sample, MicroBatchSpec())
    assert vals.keys == {"values"}
    sample.update_(vals)
    _attach_rewards_and_logps(cmodel, sample, seed=5)
    stats = citf.train_step(cmodel, sample, MicroBatchSpec())
    assert np.isfinite(stats["ppo_critic/loss"])

"""Sequence packing / partitioning algorithms.

Counterpart of the reference's datapack utilities (realhf/base/datapack.py):
first-fit-decreasing bin packing for token-budget micro-batch splitting and
balanced contiguous partitioning for data-parallel dispatch. Pure numpy —
these run on the host in the control plane, never inside jit.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def flat2d(lists: Sequence[Sequence]) -> List:
    return [x for sub in lists for x in sub]


def ffd_allocate(
    lengths: Sequence[int],
    capacity: int,
    min_groups: int = 1,
) -> List[List[int]]:
    """First-fit-decreasing bin packing (dispatches to the native C++
    implementation in csrc/host_ops.cpp when available; this Python body is
    the fallback and the parity reference).

    Partition items with the given `lengths` into bins of at most `capacity`
    total length (a single item longer than capacity gets its own bin),
    producing at least `min_groups` bins. Returns a list of index groups.
    """
    if len(lengths) > 64:  # native pays off only past trivial sizes
        from areal_tpu.ops import host_ops

        # wait=False: never stall the dispatch hot path on a g++ compile —
        # the first calls use the Python body while the .so builds.
        if host_ops.native_available(wait=False):
            return host_ops.ffd_allocate_native(lengths, capacity, min_groups)
    return ffd_allocate_py(lengths, capacity, min_groups)


def ffd_allocate_py(
    lengths: Sequence[int],
    capacity: int,
    min_groups: int = 1,
) -> List[List[int]]:
    """Pure-Python FFD; parity reference for the native path."""
    lengths = np.asarray(lengths)
    order = np.argsort(-lengths, kind="stable")
    groups: List[List[int]] = [[] for _ in range(min_groups)]
    sums = [0] * min_groups
    for idx in order:
        idx = int(idx)
        l = int(lengths[idx])
        # Least-loaded bin with room (keeps the min_groups bins balanced);
        # empty bins always accept, so oversized items get their own bin.
        candidates = [g for g in range(len(groups)) if sums[g] + l <= capacity or not groups[g]]
        if candidates:
            g = min(candidates, key=lambda g: sums[g])
            groups[g].append(idx)
            sums[g] += l
        else:
            groups.append([idx])
            sums.append(l)
    # Drop empty bins (possible when min_groups > n items).
    out = [g for g in groups if g]
    return out


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def pack_shape(
    lengths: Sequence[int],
    row_len_multiple: int = 128,
    n_rows_multiple: int = 1,
    max_row_len: int = None,
) -> tuple:
    """(n_rows, row_len) that `models.packing.pack_sequences` will
    allocate for these sequence lengths — the padded [R, T] footprint,
    computable without materializing the pack (mirrors its row_len
    bucketing + FFD row grouping). One divergence: where pack_sequences
    RAISES (a sequence longer than max_row_len), the estimator widens
    the rows to fit it — it is used on inputs the caller may not control
    (telemetry fallback), and must always return a footprint the data
    actually fits, never a >1.0 density."""
    lengths = [int(l) for l in lengths]
    if not lengths:
        raise ValueError("cannot compute pack shape of zero sequences")
    longest = max(lengths)
    row_len = _round_up(max(longest, row_len_multiple), row_len_multiple)
    if max_row_len is not None:
        row_len = min(row_len, _round_up(max_row_len, row_len_multiple))
        row_len = max(row_len, _round_up(longest, row_len_multiple))
    groups = ffd_allocate(lengths, capacity=row_len, min_groups=1)
    n_rows = _round_up(len(groups), n_rows_multiple)
    return n_rows, row_len


def packing_density(
    lengths: Sequence[int],
    row_len_multiple: int = 128,
    n_rows_multiple: int = 1,
    max_row_len: int = None,
) -> float:
    """Tokens per padded token of the FFD pack of `lengths`: real tokens
    divided by the [R, T] cells shipped to the device. 1.0 = no pad
    waste; every (1 - density) fraction of the step's FLOPs is spent on
    padding. This is the `packing_efficiency` series surfaced in the
    master's perf history and bench.py output."""
    n_rows, row_len = pack_shape(
        lengths, row_len_multiple, n_rows_multiple, max_row_len
    )
    return float(sum(int(l) for l in lengths)) / float(n_rows * row_len)


def min_abs_diff_partition(nums: Sequence[int], k: int) -> List[List[int]]:
    """Split `nums` into k *contiguous* groups with balanced sums.

    Returns index groups. Used for data-parallel dispatch where sample order
    must be preserved. Greedy prefix walking against the ideal per-group sum;
    guarantees each group is non-empty when len(nums) >= k.
    """
    n = len(nums)
    if k <= 0:
        raise ValueError("k must be positive")
    if n < k:
        raise ValueError(f"cannot partition {n} items into {k} non-empty groups")
    cum = np.cumsum(np.asarray(nums, dtype=np.float64))
    total = cum[-1]
    bounds = [0]
    for g in range(1, k):
        ideal = total * g / k
        j = int(np.searchsorted(cum, ideal))
        # Pick the neighbor closest to the ideal prefix sum, then clamp so
        # every remaining group stays non-empty.
        if j + 1 <= n - (k - g) and j >= 1:
            if abs(cum[j] - ideal) < abs(cum[j - 1] - ideal):
                j = j + 1
        j = max(bounds[-1] + 1, min(j, n - (k - g)))
        bounds.append(j)
    bounds.append(n)
    groups = [list(range(bounds[i], bounds[i + 1])) for i in range(k)]
    assert len(groups) == k and all(groups), [len(g) for g in groups]
    return groups


def balanced_partition(nums: Sequence[int], k: int) -> List[List[int]]:
    """Split into k groups balanced by sum, order-free (greedy LPT)."""
    order = np.argsort(-np.asarray(nums), kind="stable")
    groups: List[List[int]] = [[] for _ in range(k)]
    sums = np.zeros(k)
    for idx in order:
        g = int(np.argmin(sums))
        groups[g].append(int(idx))
        sums[g] += nums[int(idx)]
    return [sorted(g) for g in groups]

"""ISSUE 8 acceptance (bench leg): the `weight_plane_sharded` phase
banks an attested CPU-proxy record showing per-server ingress
bytes/version ~ full_payload/TP for TP in {1, 2} and ~half that again
with the int8 wire, with origin full_payload_equivalents ~1.0 per
version, the dequant-parity check stamped, and assemble-side
greedy-decode parity asserted against the float unsharded baseline —
and `validate_bench.py` accepts the record (rejecting ones whose
ingress doesn't shrink or that lack the parity field).

Byte accounting is sha256-verified loopback-HTTP transfer, exact and
machine-independent — which is why a CPU-proxy record is real evidence
for this phase.

Time budget: ~40 s (transfer arms are host-side; the decode-parity leg
compiles two tiny engines on the virtual CPU mesh, warm XLA cache).
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.timeout(420)
def test_sharded_plane_record_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import weight_plane_sharded_phase

    val = weight_plane_sharded_phase("measure")
    path = bank.write_record(
        bank.make_record("weight_plane_sharded", "measure", "ok", value=val),
        b,
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("weight_plane_sharded", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # THE acceptance numbers: ingress ~ full/TP, ~half again quantized.
    assert v["tp1_ingress_frac"] == pytest.approx(1.0)
    assert 0.5 <= v["tp2_ingress_frac"] <= 0.55
    assert v["tp2_int8_ingress_frac"] <= 0.6 * v["tp2_ingress_frac"]
    # O(1)-origin invariant holds per version even with sliced streams
    # (sum over a TP group's shards ~ one full payload + epsilon).
    assert 1.0 <= v["origin_full_payloads"] <= 1.05
    # Same-shard replica was fed entirely by its peer.
    assert v["replica_bytes_from_origin"] == 0
    # Quantized-wire record carries its dequant-parity proof.
    assert v["dequant_parity_ok"] == 1.0
    assert v["dequant_max_abs_err"] > 0  # lossy wire, honest about it
    # Assemble-side greedy-decode parity vs the float unsharded
    # baseline ran on the virtual mesh (conftest forces 8 devices).
    assert v["decode_parity_checked"] == 1.0
    assert v["decode_parity_ok"] == 1.0

    # The validator refuses records where ingress does not shrink with
    # TP degree...
    bad = json.loads(json.dumps(rec))
    bad["value"]["tp2_ingress_frac"] = bad["value"]["tp1_ingress_frac"]
    assert any(
        "does not shrink with TP degree" in p
        for p in validator.validate_phase_value("weight_plane_sharded", bad)
    )
    # ...where the quantized wire doesn't pay for itself...
    bad = json.loads(json.dumps(rec))
    bad["value"]["tp2_int8_ingress_frac"] = bad["value"]["tp2_ingress_frac"]
    assert any(
        "quantized wire does not shrink" in p
        for p in validator.validate_phase_value("weight_plane_sharded", bad)
    )
    # ...and quantized-wire records lacking the dequant-parity field.
    bad = json.loads(json.dumps(rec))
    del bad["value"]["dequant_parity_ok"]
    problems = validator.validate_phase_value("weight_plane_sharded", bad)
    assert any("dequant-parity" in p for p in problems)

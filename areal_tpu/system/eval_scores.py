"""Curriculum eval-score store: per-prompt success rates -> dataset filter.

Counterpart of the reference's dataset_eval_scores.json flow: the reward
MFC attaches per-prompt mean scores to its result metadata, the model
worker persists them (realhf/system/model_worker.py:956-994), and the
dataset-hosting worker calls `dataset.filter(scores)` at each dataloader
epoch boundary, snapshotting the filtered `active_indices` for recovery
(realhf/system/model_worker.py:576-618, :368-385;
realhf/system/rollout_worker.py:115-176).

TPU-native difference: the reference all-gathers scores over the DP torch
process group before the dp-head rank writes the file. Workers here are
independent processes with no collective group on the control plane, so
every scoring worker merges its local {id: score} slice into the shared
JSON under an fcntl lockfile instead — same merged file, no collective.
"""

from __future__ import annotations

import fcntl
import json
import os
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from areal_tpu.base import constants, logging

logger = logging.getLogger("eval_scores")

_SCORES_FILE = "dataset_eval_scores.json"
_INDICES_DIR = "dataset_indices"


def scores_path(experiment_name: str, trial_name: str) -> str:
    return os.path.join(
        constants.get_save_path(experiment_name, trial_name), _SCORES_FILE
    )


@contextmanager
def _locked(path: str):
    lock = path + ".lock"
    with open(lock, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def merge_scores(
    experiment_name: str, trial_name: str, scores: Dict[str, float]
) -> None:
    """Merge a local {sample_id: score} slice into the shared file
    (read-modify-write + atomic rename under an exclusive lock)."""
    if not scores:
        return
    path = scores_path(experiment_name, trial_name)
    with _locked(path):
        merged: Dict[str, float] = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    merged = json.load(f)
            except (json.JSONDecodeError, OSError):
                logger.warning(f"corrupt {path}; rebuilding from this slice")
        merged.update({str(k): float(v) for k, v in scores.items()})
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, path)


def load_scores(
    experiment_name: str, trial_name: str
) -> Optional[Dict[str, float]]:
    path = scores_path(experiment_name, trial_name)
    if not os.path.exists(path):
        return None
    with _locked(path):
        try:
            with open(path) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return None


def _indices_path(experiment_name: str, trial_name: str, tag: str) -> str:
    d = os.path.join(
        constants.get_save_path(experiment_name, trial_name), _INDICES_DIR
    )
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{tag}.npy")


def apply_filter(
    dataset, experiment_name: str, trial_name: str, tag: str,
    min_size: int = 0,
) -> bool:
    """Epoch-boundary curriculum step: feed the merged scores to
    `dataset.filter` and snapshot the surviving indices so a recovery
    restart resumes with the same curriculum state. Returns whether the
    filter ran (it doesn't when no scores have been recorded yet).

    `min_size` floors the curriculum: once the active set is at (or one
    filter call could take it below) the per-rank fetch batch size, the
    batch assembler could never fill a training batch again and the
    master would livelock fetching — so the caller passes its batch size
    and filtering stops there."""
    if not hasattr(dataset, "filter"):
        return False
    if min_size and len(dataset) <= min_size:
        logger.info(
            f"curriculum filter skipped ({tag}): active set {len(dataset)} "
            f"already at floor {min_size}"
        )
        return False
    scores = load_scores(experiment_name, trial_name)
    if not scores:
        return False
    n = len(dataset)
    if min_size and hasattr(dataset, "max_filter_percentage"):
        # Clamp this call's drop budget so the active set can't fall
        # through the floor (filter removes at most int(n * pct)).
        orig = dataset.max_filter_percentage
        dataset.max_filter_percentage = min(orig, (n - min_size) / n)
        try:
            dataset.filter(scores)
        finally:
            dataset.max_filter_percentage = orig
    else:
        dataset.filter(scores)
    np.save(
        _indices_path(experiment_name, trial_name, tag),
        np.asarray(dataset.active_indices, dtype=np.int64),
    )
    return True


def restore_indices(
    dataset, experiment_name: str, trial_name: str, tag: str
) -> bool:
    """Recovery: reload the filtered-index snapshot taken by apply_filter
    (reference model_worker.py:368-385 / rollout_worker.py:122-134)."""
    if not hasattr(dataset, "filter"):
        return False
    path = _indices_path(experiment_name, trial_name, tag)
    if not os.path.exists(path):
        return False
    indices: List[int] = np.load(path).tolist()
    logger.info(
        f"restoring curriculum indices ({tag}): "
        f"{len(dataset.active_indices)} -> {len(indices)}"
    )
    dataset.active_indices = indices
    return True

"""Colored, multi-sink logging.

TPU-native counterpart of the reference logging utilities
(reference: realhf/base/logging.py). Provides `getLogger` with optional
file sinks and a helper that mirrors scalar metrics to wandb /
tensorboard when available.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Dict, Optional

from areal_tpu.base import env_registry

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_LEVEL_COLORS = {
    logging.DEBUG: "\033[36m",  # cyan
    logging.INFO: "\033[32m",  # green
    logging.WARNING: "\033[33m",  # yellow
    logging.ERROR: "\033[31m",  # red
    logging.CRITICAL: "\033[41m",  # red background
}
_RESET = "\033[0m"

_configured_sinks = set()


class _ColorFormatter(logging.Formatter):

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _LEVEL_COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def getLogger(name: str = "areal_tpu", file_path: Optional[str] = None) -> logging.Logger:
    """Return a configured logger; optionally tee to ``file_path``."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_ColorFormatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
        logger.addHandler(handler)
        logger.setLevel(env_registry.get_str("AREAL_LOG_LEVEL").upper())
        logger.propagate = False
    if file_path is not None and (name, file_path) not in _configured_sinks:
        if os.path.dirname(file_path):
            os.makedirs(os.path.dirname(file_path), exist_ok=True)
        fh = logging.FileHandler(file_path)
        fh.setFormatter(logging.Formatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
        logger.addHandler(fh)
        _configured_sinks.add((name, file_path))
    return logger


def log_scalars_to_trackers(
    scalars: Dict[str, float],
    step: int,
    summary_writer=None,
    wandb_run=None,
):
    """Mirror scalar metrics to tensorboard / wandb when configured.

    Counterpart of the reference's log_swanlab_wandb_tensorboard; swanlab
    is not available in this environment and is intentionally omitted.
    """
    if summary_writer is not None:
        for k, v in scalars.items():
            summary_writer.add_scalar(k, v, step)
    if wandb_run is not None:
        wandb_run.log(dict(scalars), step=step)

"""Multi-turn math agent + rollout-worker generation servicing
(reference: realhf/impl/agent/math_multi_turn_agent.py and the obs/act
queue protocol of tests/agent/test_math_single_step_agent.py)."""

import asyncio

import numpy as np
import pytest

from areal_tpu.agents.math_multi_turn import MathMultiTurnAgent
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.model_api import BundledGenerationOutputs


class StubTokenizer:
    def decode(self, ids):
        return " ".join(str(i) for i in ids)

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [90, 91]}  # fixed feedback tokens


class StubEnv:
    """Fails until `succeed_at_turn`, then succeeds."""

    def __init__(self, succeed_at_turn):
        self.succeed_at_turn = succeed_at_turn
        self.calls = 0

    async def reset(self, *a, **kw):
        return None

    async def step(self, action):
        self.calls += 1
        return [self.calls >= self.succeed_at_turn], 0.0, True, False, {}


def make_prompt(qid="q0", ids=(1, 2, 3)):
    return SequenceSample.from_default(
        ids=[qid],
        seqlens=[len(ids)],
        data={"packed_prompts": np.asarray(ids, np.int64)},
        metadata={"tasks": ["math"], "solutions": ["42"]},
    )


async def serve_generations(obs_queue, act_queue, gen_len=2):
    """Loop like the fixed rollout_worker.service_gen: one bundle per
    observation, echoing the growing prompt."""
    token = 50
    while True:
        qid, prompt_ids, gconfig = await obs_queue.get()
        seq = list(prompt_ids) + [token, token + 1]
        token += 10
        bundle = BundledGenerationOutputs(
            qid=str(qid),
            prompt_ids=list(prompt_ids),
            seqs=[seq],
            logprobs=[[0.0] * len(prompt_ids) + [-0.5, -0.7]],
            no_eos=[False],
            version_start=[3],
            version_end=[3],
        )
        await act_queue.put(bundle)


def run_episode(agent, env, prompt):
    async def main():
        obs_q, act_q = asyncio.Queue(), asyncio.Queue()
        server = asyncio.create_task(serve_generations(obs_q, act_q))
        try:
            return await asyncio.wait_for(
                agent.collect_trajectory(prompt, env, obs_q, act_q), timeout=10
            )
        finally:
            server.cancel()

    return asyncio.run(main())


def test_multi_turn_succeeds_second_turn():
    agent = MathMultiTurnAgent(
        tokenizer=StubTokenizer(), num_turns=4, turn_level_discount=0.5,
        correct_reward=1.0, wrong_reward=-1.0, max_new_tokens=8,
    )
    env = StubEnv(succeed_at_turn=2)
    [traj] = run_episode(agent, env, make_prompt())

    seqlens = traj.seqlens["packed_input_ids"][0]
    assert len(seqlens) == 2  # stopped after the successful 2nd turn
    # Turn 1: prompt(3) + 2 generated. Turn 2: turn1 seq + feedback(2) + 2.
    assert seqlens == [5, 9]
    flat = np.asarray(traj.data["packed_input_ids"])
    turn2 = flat[5:]
    # turn-2 prompt = turn-1 sequence + feedback tokens
    np.testing.assert_array_equal(turn2[:5], flat[:5])
    np.testing.assert_array_equal(turn2[5:7], [90, 91])
    # rewards: turn2 = +1; turn1 = -1 + 0.5 * 1 = -0.5 (discounted return)
    np.testing.assert_allclose(
        np.asarray(traj.data["rewards"]), [-0.5, 1.0]
    )
    # prompt_mask covers everything before each turn's generation
    pm = np.asarray(traj.data["prompt_mask"])
    np.testing.assert_array_equal(pm[:5], [1, 1, 1, 0, 0])
    np.testing.assert_array_equal(pm[5:], [1] * 7 + [0, 0])
    # shifted logprob frame: generated lp at (gen_pos - 1)
    lp = np.asarray(traj.data["packed_logprobs"])
    np.testing.assert_allclose(lp[2:4], [-0.5, -0.7])
    assert traj.metadata["scores"] == [0.5]


def test_multi_turn_exhausts_turn_budget():
    agent = MathMultiTurnAgent(
        tokenizer=StubTokenizer(), num_turns=3, max_new_tokens=8,
    )
    env = StubEnv(succeed_at_turn=99)
    [traj] = run_episode(agent, env, make_prompt())
    assert len(traj.seqlens["packed_input_ids"][0]) == 3
    assert env.calls == 3


def test_rollout_worker_service_gen_loops():
    """ADVICE r1 (c): the worker's generation servicing must serve an
    arbitrary number of requests per episode (multi-turn agents), not
    exactly one."""
    from areal_tpu.system.rollout_worker import RolloutWorker

    pushed = []

    class StubPRM:
        async def generate_group(
            self, qid, prompt_ids, gconfig, continuation=False
        ):
            seq = list(prompt_ids) + [7, 8]
            return BundledGenerationOutputs(
                qid=qid, prompt_ids=list(prompt_ids), seqs=[seq],
                logprobs=[[0.0] * len(prompt_ids) + [-0.1, -0.2]],
                no_eos=[False], version_start=[0], version_end=[0],
            )

    class StubPusher:
        def push(self, payload, seq=None):
            pushed.append(payload)

    w = RolloutWorker.__new__(RolloutWorker)
    w.prm = StubPRM()
    w.pusher = StubPusher()
    w.env = StubEnv(succeed_at_turn=3)
    w.agent = MathMultiTurnAgent(
        tokenizer=StubTokenizer(), num_turns=3, max_new_tokens=8,
    )
    w._push_count = 0

    async def fake_finish(accepted):
        fake_finish.called = accepted

    w._finish = fake_finish

    asyncio.run(asyncio.wait_for(w.rollout_task(make_prompt()), timeout=10))
    assert len(pushed) == 1  # episode completed and was pushed
    assert fake_finish.called is True
    assert w.env.calls == 3  # three generation requests were serviced

"""Atomic result bank: per-phase JSON records with attestation.

Every phase pass (compile or measure) lands as ONE file in the bank
directory, written tmp+rename so a crash mid-write can never leave a
half record. Each record carries an attestation block — device kind,
topology, jax/jaxlib/libtpu versions, git sha, and a ``driver_verified``
bool — so a report assembled later can prove which numbers came from a
real accelerator driver and which are CPU/virtual-mesh proxies.

Record layout (``areal-bench-record/v1``)::

    {
      "schema": "areal-bench-record/v1",
      "phase": "train_tflops",
      "pass": "compile" | "measure",
      "status": "ok" | "failed" | "timeout",
      "value": {...} | null,          # phase metrics (ok only)
      "error": str | null,
      "tail": str | null,             # captured child stderr/stdout tail
      "started_at": float, "finished_at": float,
      "attestation": {
        "platform": "tpu" | "cpu" | ...,
        "device_kind": str | null, "n_devices": int | null,
        "topology": str | null,
        "jax_version": str | null, "jaxlib_version": str | null,
        "libtpu_version": str | null,
        "git_sha": str | null, "hostname": str,
        "python": "3.12.x",
        "driver_verified": bool,      # platform == "tpu", period.
      }
    }

The bank is resumable state *and* evidence: loading filters by platform
and age (a stale record from an old round must not be re-reported), and
``validate_record`` is the same checker ``scripts/validate_bench.py``
runs, so malformed evidence fails loudly in CI rather than silently in
a report.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional, Tuple

from areal_tpu.base import env_registry
from areal_tpu.base.wire_schemas import (
    BENCH_RECORD_V1 as RECORD_SCHEMA,
    BENCH_REPORT_V1 as REPORT_SCHEMA,
)
from areal_tpu.bench._util import repo_root

PASSES = ("compile", "measure")
STATUSES = ("ok", "failed", "timeout")

ATTESTATION_KEYS = (
    "platform", "device_kind", "n_devices", "topology",
    "jax_version", "jaxlib_version", "libtpu_version",
    "git_sha", "hostname", "python", "driver_verified",
)


def bank_dir(override: Optional[str] = None) -> str:
    return override or env_registry.get_str("AREAL_BENCH_BANK") or (
        os.path.join(tempfile.gettempdir(), "areal_bench_bank")
    )


def record_path(bank: str, phase: str, pass_: str,
                platform: Optional[str]) -> str:
    """One file per (phase, pass, platform): a CPU dev run sharing the
    bank dir must never overwrite a driver-verified TPU record banked
    mid-round — losing chip evidence to a smoke run is exactly the
    conflation this subsystem exists to prevent."""
    return os.path.join(bank, f"{phase}.{pass_}.{platform or 'unknown'}.json")


# ----------------------------------------------------------------------
# Attestation
# ----------------------------------------------------------------------


def _git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_root(), timeout=10,
            capture_output=True, text=True,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def attestation(devices=None, probe: bool = True) -> Dict:
    """Collect the attestation block for the CURRENT process.

    `devices` may be a pre-fetched jax device list; None probes lazily
    and degrades to nulls (a failed phase still attests versions + host,
    with driver_verified False). `probe=False` skips `jax.devices()`
    entirely — the runner PARENT uses it when banking a crash/timeout,
    because a device probe there could wedge on the very tunnel flap
    being recorded."""
    att = {k: None for k in ATTESTATION_KEYS}
    att["hostname"] = socket.gethostname()
    att["python"] = ".".join(map(str, sys.version_info[:3]))
    att["git_sha"] = _git_sha()
    att["driver_verified"] = False
    try:
        import jax  # safe without probe: no backend init on import

        att["jax_version"] = jax.__version__
        try:
            import jaxlib

            att["jaxlib_version"] = getattr(jaxlib, "__version__", None)
        except Exception:
            pass
        import importlib.metadata as _md

        for pkg in ("libtpu", "libtpu-nightly"):
            try:
                att["libtpu_version"] = _md.version(pkg)
                break
            except Exception:
                continue
        if devices is None:
            devices = jax.devices() if probe else []
        if devices:
            d0 = devices[0]
            att["platform"] = d0.platform
            att["device_kind"] = getattr(d0, "device_kind", None)
            att["n_devices"] = len(devices)
            coords = getattr(d0, "coords", None)
            att["topology"] = (
                f"{len(devices)}x{att['device_kind']}"
                + (f" coords0={tuple(coords)}" if coords is not None else "")
            )
            att["driver_verified"] = d0.platform == "tpu"
    except Exception:
        pass  # no usable backend: nulls + driver_verified False stand
    return att


# ----------------------------------------------------------------------
# Records
# ----------------------------------------------------------------------


def make_record(
    phase: str,
    pass_: str,
    status: str,
    value: Optional[Dict] = None,
    error: Optional[str] = None,
    tail: Optional[str] = None,
    started_at: Optional[float] = None,
    finished_at: Optional[float] = None,
    att: Optional[Dict] = None,
    probe: bool = True,
) -> Dict:
    now = time.time()
    return {
        "schema": RECORD_SCHEMA,
        "phase": phase,
        "pass": pass_,
        "status": status,
        "value": value if status == "ok" else None,
        "error": error,
        "tail": tail,
        "started_at": started_at if started_at is not None else now,
        "finished_at": finished_at if finished_at is not None else now,
        "attestation": att if att is not None else attestation(probe=probe),
    }


def validate_record(rec: Dict) -> None:
    """Raise ValueError naming every problem with `rec`."""
    problems = []
    if not isinstance(rec, dict):
        raise ValueError("record is not an object")
    if rec.get("schema") != RECORD_SCHEMA:
        problems.append(f"schema != {RECORD_SCHEMA!r}: {rec.get('schema')!r}")
    if not rec.get("phase") or not isinstance(rec.get("phase"), str):
        problems.append("missing/invalid 'phase'")
    if rec.get("pass") not in PASSES:
        problems.append(f"'pass' not in {PASSES}: {rec.get('pass')!r}")
    if rec.get("status") not in STATUSES:
        problems.append(f"'status' not in {STATUSES}: {rec.get('status')!r}")
    if rec.get("status") == "ok" and not isinstance(rec.get("value"), dict):
        problems.append("ok record must carry an object 'value'")
    att = rec.get("attestation")
    if not isinstance(att, dict):
        problems.append("missing attestation block")
    else:
        for k in ATTESTATION_KEYS:
            if k not in att:
                problems.append(f"attestation missing {k!r}")
        dv = att.get("driver_verified")
        if not isinstance(dv, bool):
            problems.append("attestation.driver_verified must be a bool")
        elif dv and att.get("platform") != "tpu":
            problems.append(
                "attestation claims driver_verified on platform "
                f"{att.get('platform')!r}"
            )
    for k in ("started_at", "finished_at"):
        if not isinstance(rec.get(k), (int, float)):
            problems.append(f"missing/invalid {k!r}")
    if problems:
        raise ValueError("; ".join(problems))


def write_record(rec: Dict, bank: Optional[str] = None) -> str:
    """Validate then flush `rec` atomically; returns the record path."""
    validate_record(rec)
    b = bank_dir(bank)
    os.makedirs(b, exist_ok=True)
    path = record_path(b, rec["phase"], rec["pass"],
                       rec["attestation"].get("platform"))
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _iter_records(bank: str):
    try:
        names = sorted(os.listdir(bank))
    except OSError:
        return
    for name in names:
        if not name.endswith(".json") or name.endswith(".tmp"):
            continue
        try:
            with open(os.path.join(bank, name)) as f:
                rec = json.load(f)
            validate_record(rec)
        except (OSError, ValueError):
            continue  # malformed files must never poison a report
        yield rec


def _preference(rec: Dict) -> Tuple:
    """Evidence order: driver-verified ok > any ok > newest anything."""
    return (
        rec["status"] == "ok" and bool(rec["attestation"].get("driver_verified")),
        rec["status"] == "ok",
        rec["finished_at"],
    )


def load_record(bank: str, phase: str, pass_: str,
                platform: Optional[str] = None) -> Optional[Dict]:
    """The record for (phase, pass) — exact platform file when given,
    otherwise the best evidence across platforms (see _preference)."""
    if platform is not None:
        try:
            with open(record_path(bank, phase, pass_, platform)) as f:
                rec = json.load(f)
            validate_record(rec)
            return rec
        except (OSError, ValueError):
            return None
    cands = [r for r in _iter_records(bank)
             if r["phase"] == phase and r["pass"] == pass_]
    return max(cands, key=_preference) if cands else None


def load_latest(bank: str, phase: str, pass_: str) -> Optional[Dict]:
    """Most recently finished record for (phase, pass), any platform —
    the runner parent uses this to see what THIS run's child banked."""
    cands = [r for r in _iter_records(bank)
             if r["phase"] == phase and r["pass"] == pass_]
    return max(cands, key=lambda r: r["finished_at"]) if cands else None


def load_bank(
    bank: Optional[str] = None, max_age_s: Optional[float] = None,
) -> Dict[Tuple[str, str], Dict]:
    """Best-evidence record per (phase, pass) (see _preference). The
    age filter applies BEFORE preference: a stale driver-verified record
    must not shadow (and thereby discard) fresh evidence from another
    platform."""
    out: Dict[Tuple[str, str], Dict] = {}
    now = time.time()
    for rec in _iter_records(bank_dir(bank)):
        if max_age_s is not None and now - float(rec["finished_at"]) > max_age_s:
            continue
        key = (rec["phase"], rec["pass"])
        if key not in out or _preference(rec) > _preference(out[key]):
            out[key] = rec
    return out


def is_banked(
    bank: Optional[str],
    phase: str,
    pass_: str,
    platform: Optional[str] = None,
    max_age_s: Optional[float] = None,
) -> bool:
    """True if an OK record for (phase, pass) exists, is fresh, and was
    measured on `platform` (stale or cross-platform records must not
    short-circuit a re-run)."""
    if max_age_s is None:
        max_age_s = env_registry.get_float("AREAL_BENCH_STATE_TTL_S")
    rec = load_record(bank_dir(bank), phase, pass_, platform)
    if rec is None or rec["status"] != "ok":
        return False
    if platform is not None and rec["attestation"].get("platform") != platform:
        return False
    if time.time() - float(rec["finished_at"]) > max_age_s:
        return False
    return True


def clear_bank(bank: Optional[str] = None) -> None:
    b = bank_dir(bank)
    try:
        names = os.listdir(b)
    except OSError:
        return
    for name in names:
        if name.endswith(".json") or name.endswith(".tmp"):
            try:
                os.remove(os.path.join(b, name))
            except OSError:
                pass

import numpy as np
import pytest

from areal_tpu.models.packing import pack_sequences


def test_pack_roundtrip():
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 100, size=l) for l in [5, 300, 17, 128, 64, 9]]
    b = pack_sequences(seqs, row_len_multiple=128)
    assert b.row_len % 128 == 0
    rec = b.gather_per_token(b.input_ids)
    for s, r in zip(seqs, rec):
        np.testing.assert_array_equal(s, r)
    # Segment ids: 0 only on padding; positions restart per sequence.
    for span in b.spans:
        seg = b.segment_ids[span.row, span.start : span.start + span.length]
        assert (seg == seg[0]).all() and seg[0] > 0
        pos = b.positions[span.row, span.start : span.start + span.length]
        np.testing.assert_array_equal(pos, np.arange(span.length))


def test_pack_rows_multiple():
    seqs = [np.arange(5)]
    b = pack_sequences(seqs, n_rows_multiple=4)
    assert b.n_rows == 4
    assert (b.segment_ids[1:] == 0).all()


def test_scatter_gather_per_token():
    seqs = [np.arange(4), np.arange(6)]
    b = pack_sequences(seqs, row_len=16)
    vals = [np.full(4, 1.5), np.full(6, 2.5)]
    rows = b.scatter_per_token(vals)
    back = b.gather_per_token(rows)
    np.testing.assert_array_equal(back[0], vals[0])
    np.testing.assert_array_equal(back[1], vals[1])
    flat = b.gather_flat(rows)
    assert flat.shape == (10,)


def test_oversized_raises():
    with pytest.raises(ValueError):
        pack_sequences([np.arange(100)], row_len=64)

"""Ulysses attention: all-to-all sequence parallelism.

The second context-parallel scheme next to ring attention
(ops/ring_attention.py), after DeepSpeed-Ulysses: instead of rotating KV
chunks S times around the `seq` axis, ONE all-to-all swaps the sharded
dimension from sequence to heads — each device then holds ALL tokens for
Hq/S of the heads, runs ordinary packed attention locally, and a second
all-to-all swaps back. Trade-offs vs ring:

- comm: 4 all-to-alls (q, k, v, out) + 2 tiny metadata all-gathers per
  layer, each moving O(T·hd/S) per device, vs ring's S ppermute steps
  pipelined behind compute — Ulysses usually wins at moderate T, ring
  at very long T where O(T/S) attention memory matters;
- memory: local attention sees the FULL sequence (O(T) KV per device,
  like megatron-SP; the splash local kernel keeps scores tiled) — ring
  keeps O(T/S);
- constraint: head counts must divide seq*tensor (ring only needs
  tensor).

Packed-varlen semantics are inherited from the local attention oracle
(same segment AND causal masking); GQA stays consistent because a
contiguous head split assigns each shard matching q/kv head runs
(q head j maps to kv head j // G, and Hq/S q-heads align with Hkv/S
kv-heads when Hkv % S == 0).

Differentiable end-to-end: all_to_all's transpose is the reverse
all-to-all, so autodiff derives the standard Ulysses backward.

Reference counterpart: none — the reference has no sequence/context
parallelism (megatron.py:94 TODO); both schemes exceed it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from areal_tpu.ops.attention import (
    cp_axes,
    reference_packed_attention,
    splash_packed_attention,
)


def ulysses_packed_attention(
    q: jnp.ndarray,  # [R, T, Hq, hd] (T sharded on `seq`)
    k: jnp.ndarray,  # [R, T, Hkv, hd]
    v: jnp.ndarray,  # [R, T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [R, T]
    positions: jnp.ndarray,  # [R, T]
    mesh,
    softmax_scale: Optional[float] = None,
    local_impl: str = "auto",
) -> jnp.ndarray:
    """Packed GQA attention with the seq shard swapped onto heads via
    all-to-all. Callers must check `ulysses_ok` first.

    `local_impl` selects the per-shard attention: 'splash' (the tiled
    TPU flash kernel — without it the dense oracle materializes [T, T]
    scores over the FULL gathered sequence, defeating CP exactly at the
    context lengths it exists for), 'reference', or 'auto' (splash on
    TPU when shapes allow)."""
    from areal_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    rows = ("data", "fsdp")
    T = q.shape[1]
    _, S, tensor = cp_axes(mesh)
    hq_l = q.shape[2] // tensor // S  # local heads after the swap
    hkv_l = k.shape[2] // tensor // S
    if local_impl == "auto":
        on_tpu = jax.default_backend() in ("tpu", "axon")
        splash_shapes = (
            T >= 128 and T % 128 == 0 and hq_l % max(hkv_l, 1) == 0
        )
        local_impl = "splash" if (on_tpu and splash_shapes) else "reference"

    def one_row(q1, k1, v1, s1, p1):
        if local_impl == "splash":
            return splash_packed_attention(
                q1, k1, v1, s1, p1, softmax_scale=softmax_scale
            )
        return reference_packed_attention(
            q1, k1, v1, s1, p1, softmax_scale=softmax_scale
        )

    def local(q, k, v, seg, pos):
        # per shard: q [R_l, C, Hq_t, hd] with C = T/S, Hq_t = Hq/tensor.
        # seq -> heads swap: [R_l, T, Hq_t/S, hd]
        q = jax.lax.all_to_all(q, "seq", split_axis=2, concat_axis=1, tiled=True)
        k = jax.lax.all_to_all(k, "seq", split_axis=2, concat_axis=1, tiled=True)
        v = jax.lax.all_to_all(v, "seq", split_axis=2, concat_axis=1, tiled=True)
        # mask metadata is tiny ([R_l, T] int32): gather it whole.
        seg_f = jax.lax.all_gather(seg, "seq", axis=1, tiled=True)
        pos_f = jax.lax.all_gather(pos, "seq", axis=1, tiled=True)
        out = jax.vmap(one_row)(q, k, v, seg_f, pos_f)
        # heads -> seq swap back: [R_l, C, Hq_t, hd]
        return jax.lax.all_to_all(
            out, "seq", split_axis=1, concat_axis=2, tiled=True
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(rows, "seq", "tensor", None),
            P(rows, "seq", "tensor", None),
            P(rows, "seq", "tensor", None),
            P(rows, "seq"),
            P(rows, "seq"),
        ),
        out_specs=P(rows, "seq", "tensor", None),
        check_vma=False,
    )(q, k, v, segment_ids, positions)


def ulysses_ok(mesh, r: int, t: int, hq: int, hkv: int) -> bool:
    """Shape/mesh divisibility for ulysses_packed_attention: the per-
    tensor-shard head counts must further divide the seq axis."""
    rows, seq, tensor = cp_axes(mesh)
    if seq <= 1 or r % rows or t % seq:
        return False
    if hq % tensor or hkv % tensor:
        return False
    hq_t, hkv_t = hq // tensor, hkv // tensor
    return (
        hq_t % seq == 0
        and hkv_t % seq == 0
        and (hq_t // seq) % (hkv_t // seq) == 0
    )

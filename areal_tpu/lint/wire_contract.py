"""Checker ``wire-contract``: every HTTP route on the fleet wire is
declared in ``areal_tpu.base.wire_routes``, every client call names a
declared route, and the deliberate status codes line up.

The fleet's correctness rests on ~25 hand-paired aiohttp routes
(servers <-> manager <-> clients <-> bench <-> tests), previously
string-matched with zero checking. Flags:

- ``app.router.add_get/add_post`` registering an undeclared
  (method, path);
- a client path reference — an f-string URL suffix
  (``f"{url}/drain"``), a ``url + "/path"`` concat, a
  ``_post(url, "/path")`` helper literal, or a ``path="/x"`` kwarg /
  default — naming a path no route declares, or using a verb no
  route for that path has;
- a server module emitting ``status=N`` for a deliberate code no
  route on that module declares (the shed-429 / drain-409 class);
- a client comparing ``resp.status`` / ``err.code`` against a code
  none of its referenced routes declare;
- declared routes never registered, deliberate statuses never
  emitted, and non-``operator`` routes with no client call site —
  the global pass, gated on the scan covering the registry module.

Path references are only harvested inside HTTP verb calls (session or
URL-ish receiver, or a known helper) or behind URL-ish receivers
(terminal name containing url/addr/host/endpoint/peer/source, or
``u``) so filesystem joins and name_resolve keys — dict-``.get`` with a
slash-bearing f-string included — never false-positive.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.lint.common import Finding, Module

CHECKER = "wire-contract"

REGISTRY_MODULE = "areal_tpu.base.wire_routes"
REGISTRY_REL = "areal_tpu/base/wire_routes.py"

_PATH_RE = re.compile(r"\A/[a-z][a-z0-9_/]*\Z")
_ADD_METHODS = {"add_get": "GET", "add_post": "POST"}
_GET_HELPERS = ("_get", "_get_json", "urlopen")
_POST_HELPERS = ("_post",)
_URLISH_SUBSTR = ("url", "addr", "host", "endpoint", "peer", "source")
_SESSIONISH_SUBSTR = ("sess", "client", "http")


@dataclasses.dataclass(frozen=True)
class RouteSpec:
    servers: Tuple[str, ...]
    statuses: Tuple[int, ...]
    operator: bool


@dataclasses.dataclass
class WireConfig:
    routes: Dict[Tuple[str, str], RouteSpec]
    implicit_statuses: Tuple[int, ...] = (200, 206, 500)
    registry_rel: str = REGISTRY_REL
    registry_module: str = REGISTRY_MODULE

    @property
    def paths(self) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        for (m, p) in self.routes:
            out.setdefault(p, set()).add(m)
        return out

    def server_modules(self) -> Set[str]:
        return {s for spec in self.routes.values() for s in spec.servers}


def default_config() -> WireConfig:
    # Import is deliberate: it validates the declarations execute, and
    # the module is stdlib-only so the no-jax gate is preserved.
    from areal_tpu.base import wire_routes

    return WireConfig(
        routes={
            key: RouteSpec(r.servers, r.statuses, r.operator)
            for key, r in wire_routes.REGISTRY.items()
        },
        implicit_statuses=tuple(wire_routes.IMPLICIT_STATUSES),
    )


@dataclasses.dataclass
class WireAcc:
    """Cross-file facts for the gated global pass."""
    registered: Dict[Tuple[str, str], List[str]] = dataclasses.field(
        default_factory=dict
    )
    # path -> HTTP methods clients were seen using (None = the call
    # site's verb was not spellable); the dead-route pass is
    # (method, path)-exact so a POST-only client cannot keep a dead
    # GET twin alive. Regression note: review find, PR 13.
    client_verbs: Dict[str, Set[Optional[str]]] = dataclasses.field(
        default_factory=dict
    )
    emitted_by_module: Dict[str, Set[int]] = dataclasses.field(
        default_factory=dict
    )


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _terminal(node.func)
    if isinstance(node, ast.Subscript):
        if isinstance(node.slice, ast.Constant) and isinstance(
            node.slice.value, str
        ):
            return node.slice.value
        return _terminal(node.value)
    if isinstance(node, ast.Await):
        return _terminal(node.value)
    return None


def _urlish(node: ast.AST) -> bool:
    name = _terminal(node)
    if name is None:
        return False
    n = name.lower()
    return n == "u" or any(t in n for t in _URLISH_SUBSTR)


def _norm_path(raw: str) -> Optional[str]:
    path = raw.split("?", 1)[0]
    return path if _PATH_RE.match(path) else None


def _http_verb_receiver(func: ast.AST) -> bool:
    """A bare ``.get``/``.post`` counts as an HTTP verb only when its
    receiver looks like a session or URL — ``mapping.get(f"{k}/x")`` or
    ``name_resolve.get(f"{root}/lease")`` carrying a slash-bearing
    f-string must not be harvested as a wire path (name_resolve keys
    ARE slash-separated). Regression note: review find, PR 13."""
    if not isinstance(func, ast.Attribute):
        return False
    recv = func.value
    name = _terminal(recv)
    n = (name or "").lower()
    return any(t in n for t in _SESSIONISH_SUBSTR) or _urlish(recv)


def _enclosing_http_method(mod: Module, node: ast.AST) -> Optional[str]:
    """HTTP verb of the nearest enclosing client call, if spellable."""
    cur: Optional[ast.AST] = node
    for _ in range(4):
        cur = mod.parent(cur) if cur is not None else None
        if cur is None:
            return None
        if isinstance(cur, ast.Call):
            name = _terminal(cur.func)
            if name in _POST_HELPERS:
                return "POST"
            if name in _GET_HELPERS:
                return "GET"
            if name in ("post", "get") and _http_verb_receiver(cur.func):
                return "POST" if name == "post" else "GET"
            return None
    return None


def _status_codes(node: ast.AST) -> List[int]:
    """Int literals inside a status expression (handles the
    ``200 if ok else 409`` idiom)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            out.append(n.value)
    return out


def check(mod: Module, cfg: WireConfig, acc: WireAcc) -> List[Finding]:
    if mod.rel == cfg.registry_rel:
        return []
    findings: List[Finding] = []
    paths = cfg.paths
    is_server = mod.rel in cfg.server_modules()
    declared_statuses = {
        s
        for spec in cfg.routes.values()
        if mod.rel in spec.servers
        for s in spec.statuses
    }
    mod_client_paths: Set[str] = set()
    client_status_sites: List[Tuple[int, int]] = []  # (line, code)

    def ref_path(raw: str, lineno: int, method: Optional[str]):
        path = _norm_path(raw)
        if path is None:
            return
        mod_client_paths.add(path)
        acc.client_verbs.setdefault(path, set()).add(method)
        if path not in paths:
            findings.append(Finding(
                mod.rel, lineno, CHECKER,
                f"client references path {path!r} no route declares: "
                f"declare it in {cfg.registry_module} or fix the path",
            ))
        elif method is not None and (method, path) not in cfg.routes:
            have = ", ".join(sorted(paths[path]))
            findings.append(Finding(
                mod.rel, lineno, CHECKER,
                f"client uses {method} {path} but the declared "
                f"method(s) are {have}",
            ))

    for node in mod.nodes:
        # -- server route registrations ----------------------------------
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in _ADD_METHODS:
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and node.args[0].value.startswith("/"):
                method = _ADD_METHODS[node.func.attr]
                path = node.args[0].value
                key = (method, path)
                acc.registered.setdefault(key, []).append(
                    f"{mod.rel}:{node.lineno}"
                )
                if key not in cfg.routes:
                    findings.append(Finding(
                        mod.rel, node.lineno, CHECKER,
                        f"registers undeclared route {method} {path}: "
                        f"declare it in {cfg.registry_module} (method, "
                        f"path, servers, statuses, doc)",
                    ))
            continue

        # -- client refs: f"{url}/path" ----------------------------------
        if isinstance(node, ast.JoinedStr):
            # Inside an HTTP verb call (sess.post(f"{target}/kv/accept"))
            # the string is a URL by construction; elsewhere the
            # receiver must look URL-ish so fs joins never match.
            method = _enclosing_http_method(mod, node)
            for i, part in enumerate(node.values):
                if (
                    i > 0
                    and isinstance(part, ast.Constant)
                    and isinstance(part.value, str)
                    and part.value.startswith("/")
                    and isinstance(node.values[i - 1], ast.FormattedValue)
                    and (method is not None
                         or _urlish(node.values[i - 1].value))
                ):
                    ref_path(part.value, node.lineno, method)
            continue

        # -- client refs: url + "/path" ----------------------------------
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            if (
                isinstance(node.right, ast.Constant)
                and isinstance(node.right.value, str)
                and node.right.value.startswith("/")
                and _urlish(node.left)
            ):
                ref_path(node.right.value, node.lineno,
                         _enclosing_http_method(mod, node))
            continue

        if isinstance(node, ast.Call):
            name = _terminal(node.func)
            # -- client refs: _post(url, "/path", ...) helpers -----------
            if name in _POST_HELPERS + _GET_HELPERS:
                method = "POST" if name in _POST_HELPERS else "GET"
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ) and arg.value.startswith("/"):
                        ref_path(arg.value, node.lineno, method)
            # -- client refs: path="/x" kwargs ---------------------------
            for kw in node.keywords:
                if kw.arg == "path" and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, str) \
                        and kw.value.value.startswith("/"):
                    ref_path(kw.value.value, node.lineno, None)
            # -- server-side deliberate statuses -------------------------
            if is_server:
                for kw in node.keywords:
                    if kw.arg == "status":
                        for code in _status_codes(kw.value):
                            acc.emitted_by_module.setdefault(
                                mod.rel, set()
                            ).add(code)
                            if code not in declared_statuses and \
                                    code not in cfg.implicit_statuses:
                                findings.append(Finding(
                                    mod.rel, kw.value.lineno, CHECKER,
                                    f"handler emits status {code} but "
                                    f"no route served by this module "
                                    f"declares it: add it to the "
                                    f"route's statuses in "
                                    f"{cfg.registry_module} (clients "
                                    f"must know deliberate codes)",
                                ))
            continue

        # -- path= defaults on client helper functions -------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            all_args = args.posonlyargs + args.args + args.kwonlyargs
            defaults = (
                [None] * (len(args.posonlyargs) + len(args.args)
                          - len(args.defaults))
                + list(args.defaults) + list(args.kw_defaults)
            )
            for a, d in zip(all_args, defaults):
                if (
                    a.arg == "path"
                    and isinstance(d, ast.Constant)
                    and isinstance(d.value, str)
                    and d.value.startswith("/")
                ):
                    ref_path(d.value, d.lineno, None)
            continue

        # -- client status handling --------------------------------------
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            has_status_attr = any(
                isinstance(s, ast.Attribute) and s.attr in ("status",
                                                            "code")
                for s in sides
            )
            if not has_status_attr:
                continue
            for s in sides:
                for code in _status_codes(s):
                    if 300 <= code < 600:
                        client_status_sites.append((node.lineno, code))

    # Client status codes are judged against ALL declared route
    # statuses (not just this module's refs: helper modules like
    # weight_client own the path while the caller owns the status
    # branch). A module touching no declared path is not a wire client
    # and is skipped.
    if mod_client_paths & set(paths):
        allowed = set(cfg.implicit_statuses)
        for spec in cfg.routes.values():
            allowed.update(spec.statuses)
        for lineno, code in client_status_sites:
            if code not in allowed:
                findings.append(Finding(
                    mod.rel, lineno, CHECKER,
                    f"client handles status {code} but no declared "
                    f"route emits it: the handler branch is dead (or "
                    f"the route's statuses in {cfg.registry_module} "
                    f"are stale)",
                ))
    return findings


def check_global(cfg: WireConfig, acc: WireAcc,
                 registry_lines: Dict[str, int]) -> List[Finding]:
    """Dead-declaration pass; the runner gates this on the scan
    covering the registry module (a single-file run must not misreport
    the whole wire dead)."""
    findings: List[Finding] = []
    for (method, path), spec in sorted(cfg.routes.items()):
        anchor = registry_lines.get(f"{method} {path}", 1)
        if (method, path) not in acc.registered:
            findings.append(Finding(
                cfg.registry_rel, anchor, CHECKER,
                f"route {method} {path} declared but never registered "
                f"by any scanned server: delete the Route or restore "
                f"the handler",
            ))
            continue
        verbs = acc.client_verbs.get(path, set())
        if not spec.operator and method not in verbs and None not in verbs:
            findings.append(Finding(
                cfg.registry_rel, anchor, CHECKER,
                f"dead route {method} {path}: no scanned client calls "
                f"it — delete it, wire a client, or mark it "
                f"operator=True with a doc saying who curls it",
            ))
        for code in spec.statuses:
            if not any(
                code in acc.emitted_by_module.get(srv, set())
                for srv in spec.servers
            ):
                findings.append(Finding(
                    cfg.registry_rel, anchor, CHECKER,
                    f"route {method} {path} declares status {code} "
                    f"but no serving module emits it: stale contract",
                ))
    return findings


def registry_decl_lines(mod: Module) -> Dict[str, int]:
    """Line of each ``_r("METHOD", "/path", ...)`` call in the
    registry module, keyed ``"METHOD /path"``."""
    lines: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname not in ("_r", "Route"):
            continue
        vals: List[Optional[str]] = [None, None]
        for i in (0, 1):
            if len(node.args) > i and isinstance(node.args[i],
                                                 ast.Constant):
                vals[i] = node.args[i].value
        for kw in node.keywords:
            if kw.arg == "method" and isinstance(kw.value, ast.Constant):
                vals[0] = kw.value.value
            if kw.arg == "path" and isinstance(kw.value, ast.Constant):
                vals[1] = kw.value.value
        if isinstance(vals[0], str) and isinstance(vals[1], str):
            lines[f"{vals[0]} {vals[1]}"] = node.lineno
    return lines

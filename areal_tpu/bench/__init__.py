"""Opportunistic benchmark banking.

The monolithic bench died three rounds in a row because a flapping TPU
tunnel only ever offered ~1-minute windows, and one wedged XLA compile
(or one PJRT crash) lost the whole run. This package decomposes the
bench into independently-banked *phases*:

- ``phases``    phase registry: priority, estimated compile/measure
                cost, minimal viable steady-state window
- ``runner``    one phase per subprocess with a hard deadline; a wedged
                compile kills one phase, not the run; compile (warm the
                persistent XLA cache) and measure are separate passes
- ``daemon``    opportunistic scheduler: polls device availability with
                backoff, classifies tunnel-down vs driver errors, and
                spends each observed window on the highest-value phase
                that fits it
- ``bank``      atomic per-phase JSON records (tmp+rename) carrying an
                attestation block (device/topology/versions/git sha and
                ``driver_verified``) so on-chip and CPU-proxy evidence
                can never be conflated
- ``report``    assembles a ``BENCH_rNN``-style report from the bank,
                folding in proxy evidence (pack density, prefetch
                overlap, multichip dryrun) explicitly labeled as
                non-driver-verified

``bench.py`` at the repo root is a thin CLI over this package.

No eager submodule imports here: the runner child executes as
``python -m areal_tpu.bench.runner`` and must not find itself already
half-imported by its own package init.
"""

#!/usr/bin/env python
"""Schema + attestation validator for bench evidence.

    python scripts/validate_bench.py BENCH_r06.json
    python scripts/validate_bench.py --bank /tmp/areal_bench_bank
    python scripts/validate_bench.py --require-driver-verified BENCH_r06.json

Nonzero exit when:
- any record is malformed (schema tag, pass/status enums, missing or
  inconsistent attestation block — e.g. ``driver_verified: true`` on a
  non-TPU platform);
- a headline number is presented WITHOUT ``driver_verified: true`` and
  without the explicit ``"evidence": "proxy"`` label (the round-6
  mandate: chip numbers and CPU smoke numbers must never be conflated);
- the report claims top-level ``driver_verified: true`` that its own
  records do not back;
- with ``--require-driver-verified``: any headline entry is not
  driver-verified at all (the gate for publishing a BENCH round as chip
  evidence).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.bench import bank  # noqa: E402

# Per-phase value schemas: an ok MEASURE record for these phases must
# carry every listed numeric key. Catches a phase body drifting away
# from what the report/readers consume without anything failing loudly.
PHASE_VALUE_KEYS: Dict[str, tuple] = {
    # Mesh shape + device count ride the VALUES (not just record
    # attestation) so scaling curves assemble across bench rounds;
    # train_tflops stays the per-chip headline number.
    "train_tflops": ("train_tflops", "n_devices"),
    # Sharded-training evidence without its parity/high-water/roundtrip
    # fields is not evidence: a record could bank mesh step times off a
    # run whose sharded math silently diverged.
    "train_sharded": (
        "fsdp2_parity_ok", "tp2_parity_ok", "loss_parity_max_rel_err",
        "dump_highwater_frac", "dump_roundtrip_ok", "n_devices",
    ),
    "train_tflops_scaling": ("n_devices_max", "scaling_efficiency"),
    "weight_update": (
        "weight_update_ms", "weight_transfer_ms", "weight_cutover_ms",
        "origin_full_payloads",
    ),
    # The hedging A/B is only evidence as a PAIR with its win/cancel
    # accounting: a low hedged p99 without hedge_wins could just mean
    # the injected tail never landed.
    "rpc_resilience": (
        "n_chunks", "injected_delay_ms", "hedge_delay_ms",
        "unhedged_p50_ms", "unhedged_p99_ms",
        "hedged_p50_ms", "hedged_p99_ms",
        "hedge_wins", "hedge_cancelled", "hedge_failures",
    ),
    # Durable-plane evidence is only evidence when nothing was lost OR
    # double-trained along the way and the async arm actually bought
    # its stall reduction: a fast MTTR next to a nonzero loss counter
    # is a broken plane with a good-looking timing.
    "recovery_slo": (
        "state_mb", "n_ckpt_saves",
        "sync_stall_ms_mean", "async_stall_ms_mean",
        "async_stall_saved_frac", "mttr_ms",
        "wal_records", "wal_replayed", "redelivered",
        "samples_lost", "samples_duplicated",
    ),
    # Quantized-wire evidence without its dequant-parity check field is
    # not evidence: a record could bank a great ingress number off a
    # stream that assembles to garbage weights.
    "weight_plane_sharded": (
        "full_payload_bytes", "tp1_ingress_frac", "tp2_ingress_frac",
        "tp2_int8_ingress_frac", "origin_full_payloads",
        "replica_bytes_from_origin",
        "dequant_parity_ok", "dequant_max_abs_err",
    ),
    "serving_openloop": (
        "capacity_rps",
        "overload_offered_rps",
        "overload_admission_p99_ttft_ms",
        "overload_admission_goodput_rps",
        "overload_baseline_p99_ttft_ms",
        "overload_baseline_goodput_rps",
    ),
    # The tiered-KV probe is only evidence as a PAIR (tier vs full-
    # re-prefill baseline) WITH its per-tier hit accounting and loss
    # counter: a fast TTFT number without those could just mean the
    # sweep never exceeded HBM.
    "sessions_resident": (
        "n_resident_max",
        "tier_ttft_p99_ms",
        "baseline_ttft_p99_ms",
        "hit_rate_hbm",
        "hit_rate_host",
        "hit_rate_peer",
        "miss_rate",
        "kv_spill_total",
        "kv_prefix_lost",
        "int8_spill_bytes_ratio",
    ),
    # Elastic-fleet evidence is only evidence when nothing was lost
    # along the way: a record with ANY failed rollout, a "peer" join
    # that actually read origin bytes, or drained prefixes that did not
    # migrate is a broken control plane with good-looking timings.
    "fleet_elastic": (
        "join_peer_ms",
        "join_origin_ms",
        "join_peer_origin_bytes",
        "killover_recovery_ms",
        "killover_epoch",
        "failed_rollouts",
        "drain_migrated",
        "drain_lost",
        "kv_prefix_lost",
        "n_servers_max",
        "autoscale_out_actions",
        "autoscale_launched",
        "autoscale_n_after",
        "autoscale_load_failed",
    ),
    # Multi-model evidence is only evidence with its isolation and
    # independence accounting next to the latency pair: a clean B-side
    # p99 with a contaminated parity row, a cross-model route/KV hit,
    # or a steady pool whose version (or outputs) moved during the
    # other model's cutover is the exact failure the phase refuses.
    "multi_model_serving": (
        "n_models", "families_distinct",
        "parity_mismatches", "cross_model_routes", "cross_model_kv_hits",
        "unknown_model_rejected", "unknown_model_routed",
        "cutover_version_before", "cutover_version_after",
        "steady_version_after", "steady_outputs_stable",
        "cutover_outputs_changed",
        "b_completed", "b_failed",
        "b_p99_ttft_base_ms", "b_p99_ttft_cutover_ms",
        "kv_prefix_lost",
    ),
    # Gateway fairness evidence is only evidence as the full A/B/C
    # triple with its shed and queue accounting: a good-looking fair-arm
    # p99 without aggressor sheds (the flood never saturated), without
    # DRR picks (the queue never arbitrated), or without the FIFO arm's
    # collapse next to it proves nothing about fair share.
    "tenant_fairness": (
        "solo_p99_ttft_ms", "fair_p99_ttft_ms", "unfair_p99_ttft_ms",
        "fair_over_solo", "unfair_over_fair",
        "aggressor_sheds", "fairshare_picks", "victim_failed",
    ),
    # MoE fast-path evidence is only evidence with its parity, drop, and
    # ingress accounting: a fast EP2 step time next to a diverged loss
    # trajectory, a "dropless" arm that realized drops, or an
    # expert-sliced stream that did not shrink ingress is the exact
    # failure the phase exists to catch.
    "moe_scaling": (
        "n_devices", "dense_step_s", "moe_ep1_step_s", "moe_ep2_step_s",
        "capacity_step_s", "ep_parity_ok", "capacity_parity_ok",
        "ep_loss_max_rel_err", "dropless_drop_rate", "ep_degree",
        "ep_ingress_frac_max", "origin_full_payloads",
    ),
    # Agentic-rollout evidence is only evidence when every episode
    # finished, the continuation path measurably beat the session-blind
    # baseline, the affinity/prefix path actually engaged, and the
    # executor sweep shed under load WITHOUT starving a single job.
    "agentic_rollout": (
        "episodes", "failed_episodes", "episodes_per_s",
        "turn_ttft_p50_ms", "baseline_turn_ttft_p50_ms",
        "tool_calls", "tool_failures", "tool_call_ms_p50",
        "reprefill_tokens", "full_prefill_tokens", "reprefill_ratio",
        "affinity_prefix_hits",
        "exec_jobs_total", "exec_warm_hits", "exec_workers_alive",
        "sat_peak_jobs_per_s", "sat_failed", "sat_shed_total",
    ),
    # kernel_micro family: per-kernel timing is only evidence NEXT TO
    # its parity number, and a CPU round must label itself proxy
    # (enforced against the record's own attestation below).
    "kernel_micro_gae": ("n_cases", "best_speedup", "cpu_proxy"),
    "kernel_micro_paged_decode": ("n_cases", "best_speedup", "cpu_proxy"),
    "kernel_micro_splash": ("n_cases", "best_speedup", "cpu_proxy"),
    "kernel_micro_decode_state": (
        "token_parity_ok",
        "h2d_per_block_resident",
        "h2d_per_block_legacy",
        "h2d_bytes_per_block_resident",
        "h2d_bytes_per_block_legacy",
        "gen_tps_resident",
        "gen_tps_legacy",
        "cpu_proxy",
    ),
    # The disaggregation A/B is only evidence as a PAIR: a record
    # carrying one arm's tail latency without the other cannot show the
    # interference delta the phase exists to measure.
    "serving_disagg": (
        "offered_rate_rps",
        "unified_itl_p99_ms",
        "unified_ttft_p99_ms",
        "disagg_itl_p99_ms",
        "disagg_ttft_p99_ms",
        "kv_handoffs",
        "kv_handoff_bytes",
    ),
}

# Phases whose records may carry a p99-TTFT SLO stamp; key = the value
# field holding the headline p99 the stamp judges.
SLO_HEADLINE_KEYS = {
    "serving_openloop": "headline_ttft_p99_ms",
    "serving_disagg": "disagg_ttft_p99_ms",
}


def _validate_ttft_slo(name: str, val: Dict) -> List[str]:
    """A record carrying an SLO limit must stamp itself honestly: p99
    over the limit without ttft_slo_violated=true is exactly the silent
    headline-eligibility the satellite forbids."""
    slo = val.get("ttft_slo_ms")
    if not isinstance(slo, (int, float)) or isinstance(slo, bool):
        return []
    headline_key = SLO_HEADLINE_KEYS.get(name)
    p99 = val.get(headline_key) if headline_key else None
    problems: List[str] = []
    if not isinstance(p99, (int, float)) or isinstance(p99, bool):
        problems.append(
            f"{name}: carries ttft_slo_ms but no numeric "
            f"{headline_key!r} to judge it against"
        )
        return problems
    violated = bool(val.get("ttft_slo_violated"))
    if p99 > float(slo) and not violated:
        problems.append(
            f"{name}: p99 TTFT {p99:.0f}ms exceeds the {slo:.0f}ms SLO "
            f"but the record is not stamped ttft_slo_violated — "
            f"refusing silent headline eligibility"
        )
    if p99 <= float(slo) and violated:
        problems.append(
            f"{name}: stamped ttft_slo_violated but p99 {p99:.0f}ms is "
            f"within the {slo:.0f}ms SLO"
        )
    return problems

# Numeric keys every serving_openloop arrival-rate sweep point must
# carry: a record without the sweep (or with points missing p99 TTFT)
# is not tail-latency evidence.
OPENLOOP_POINT_KEYS = (
    "offered_rps", "goodput_rps", "p50_ttft_ms", "p99_ttft_ms",
)


def _validate_openloop_sweep(val: Dict) -> List[str]:
    problems: List[str] = []
    sweep = val.get("sweep")
    if not isinstance(sweep, list) or len(sweep) < 2:
        return [
            "serving_openloop: measure value must carry an arrival-rate "
            "'sweep' list with >= 2 points"
        ]
    for i, pt in enumerate(sweep):
        if not isinstance(pt, dict):
            problems.append(f"serving_openloop: sweep[{i}] is not an object")
            continue
        for k in OPENLOOP_POINT_KEYS:
            if not isinstance(pt.get(k), (int, float)) or isinstance(
                pt.get(k), bool
            ):
                problems.append(
                    f"serving_openloop: sweep[{i}] missing numeric {k!r}"
                )
        off, good = pt.get("offered_rps"), pt.get("goodput_rps")
        if (
            isinstance(off, (int, float))
            and isinstance(good, (int, float))
            and good > off * 1.001
        ):
            # Physically impossible: completions can't outrun arrivals.
            problems.append(
                f"serving_openloop: sweep[{i}] goodput {good:.2f} rps "
                f"exceeds offered load {off:.2f} rps"
            )
    return problems


def _num(val: Dict, key: str):
    v = val.get(key)
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) else None


def _validate_sharded_plane(val: Dict) -> List[str]:
    """The sharded-plane phase exists to show ingress SHRINKING: with
    the TP degree (each server fetches only its slice) and again with
    the quantized wire. A record where it doesn't — or whose quantized
    stream failed the dequant-parity check, or whose same-shard replica
    leaned on the origin — is refused, not published."""
    problems: List[str] = []
    tp1, tp2 = _num(val, "tp1_ingress_frac"), _num(val, "tp2_ingress_frac")
    tpq = _num(val, "tp2_int8_ingress_frac")
    if tp1 is not None and tp2 is not None and tp2 >= tp1 * 0.75:
        problems.append(
            f"weight_plane_sharded: per-server ingress does not shrink "
            f"with TP degree (tp1 {tp1:.3f} -> tp2 {tp2:.3f})"
        )
    if tp2 is not None and tpq is not None and tpq >= tp2 * 0.75:
        problems.append(
            f"weight_plane_sharded: quantized wire does not shrink "
            f"ingress (tp2 {tp2:.3f} -> int8 {tpq:.3f})"
        )
    if _num(val, "dequant_parity_ok") != 1:
        problems.append(
            "weight_plane_sharded: quantized-wire record failed (or "
            "lacks) the dequant-parity check"
        )
    rep = _num(val, "replica_bytes_from_origin")
    if rep is not None and rep > 0:
        problems.append(
            f"weight_plane_sharded: same-shard replica pulled "
            f"{rep:.0f} bytes from the origin — peer serving degraded"
        )
    if (
        _num(val, "decode_parity_checked") == 1
        and _num(val, "decode_parity_ok") != 1
    ):
        problems.append(
            "weight_plane_sharded: sharded-cutover greedy decode "
            "diverged from the unsharded baseline"
        )
    return problems


def _validate_train_sharded(val: Dict) -> List[str]:
    """The sharded-training phase exists to show the mesh paths
    MATCHING the single-device trajectory and the shard-local dump
    actually shrinking the host high-water while round-tripping
    byte-identically — a record failing any of those is refused."""
    problems: List[str] = []
    for k in ("fsdp2_parity_ok", "tp2_parity_ok"):
        if _num(val, k) is not None and _num(val, k) != 1:
            problems.append(
                f"train_sharded: {k.split('_')[0]} loss trajectory "
                f"diverged from the single-device engine"
            )
    if _num(val, "dump_roundtrip_ok") != 1:
        problems.append(
            "train_sharded: shard-local dump did not round-trip "
            "byte-identically through the weight plane"
        )
    frac = _num(val, "dump_highwater_frac")
    if frac is not None and not (0.0 < frac <= 0.75):
        problems.append(
            f"train_sharded: dump host high-water frac {frac:.3f} does "
            f"not show the ~1/mesh_size reduction (expected <= 0.75 on "
            f"a 2-device mesh)"
        )
    return problems


# Numeric keys every train_tflops_scaling curve point must carry: a
# record without per-point per-chip throughput is not a scaling curve.
SCALING_POINT_KEYS = ("n_devices", "step_s", "train_tflops_per_chip")


def _validate_scaling_points(val: Dict) -> List[str]:
    problems: List[str] = []
    points = val.get("points")
    if not isinstance(points, list) or not points:
        return [
            "train_tflops_scaling: measure value must carry a "
            "non-empty 'points' curve"
        ]
    prev_n = 0.0
    for i, pt in enumerate(points):
        if not isinstance(pt, dict):
            problems.append(
                f"train_tflops_scaling: points[{i}] is not an object"
            )
            continue
        for k in SCALING_POINT_KEYS:
            if not isinstance(pt.get(k), (int, float)) or isinstance(
                pt.get(k), bool
            ):
                problems.append(
                    f"train_tflops_scaling: points[{i}] missing "
                    f"numeric {k!r}"
                )
        n = pt.get("n_devices")
        if isinstance(n, (int, float)):
            if n <= prev_n:
                problems.append(
                    f"train_tflops_scaling: points[{i}] n_devices "
                    f"{n} not increasing (curve must run 1 -> N)"
                )
            prev_n = float(n)
    first_n = (points[0] or {}).get("n_devices")
    if isinstance(first_n, (int, float)) and first_n != 1:
        problems.append(
            "train_tflops_scaling: curve must start at n_devices == 1 "
            "(the per-chip baseline every other point is judged against)"
        )
    return problems


def _validate_sessions_resident(val: Dict) -> List[str]:
    """The tiered-KV phase exists to show a returning session's TTFT
    measurably below the full re-prefill baseline once residency
    exceeds HBM, with the tier actually engaged (spills happened, host
    restores happened, nothing truly lost) and the int8 spill wire at
    least halving tier bytes. Records not showing that are refused."""
    problems: List[str] = []
    tier = _num(val, "tier_ttft_p99_ms")
    base = _num(val, "baseline_ttft_p99_ms")
    if tier is not None and base is not None and tier > 0.75 * base:
        problems.append(
            f"sessions_resident: tier-hit returning p99 TTFT "
            f"{tier:.0f}ms is not measurably below the full-re-prefill "
            f"baseline {base:.0f}ms"
        )
    lost = _num(val, "kv_prefix_lost")
    if lost is None or lost > 0:
        problems.append(
            f"sessions_resident: {lost} true prefix losses under "
            f"pressure — spill-not-loss is the phase's contract"
        )
    if (_num(val, "kv_spill_total") or 0) < 1:
        problems.append(
            "sessions_resident: no spills recorded — residency never "
            "exceeded the HBM budget, nothing was measured"
        )
    if (_num(val, "hit_rate_host") or 0) <= 0:
        problems.append(
            "sessions_resident: zero host-tier restores — the tier "
            "never engaged"
        )
    if (_num(val, "hit_rate_peer") or 0) <= 0:
        problems.append(
            "sessions_resident: zero peer pulls — the global prefix "
            "index path never engaged"
        )
    for k in ("hit_rate_hbm", "hit_rate_host", "hit_rate_disk",
              "hit_rate_peer", "miss_rate"):
        v = _num(val, k)
        if v is not None and not (0.0 <= v <= 1.0):
            problems.append(f"sessions_resident: {k} {v} outside [0, 1]")
    ratio = _num(val, "int8_spill_bytes_ratio")
    if ratio is not None and not (0.1 <= ratio <= 0.62):
        problems.append(
            f"sessions_resident: int8 spill wire is {ratio:.2f}x the "
            f"float wire — expected <= 0.62 (halved or better) and a "
            f"sane floor"
        )
    sweep = val.get("sweep")
    if not isinstance(sweep, list) or len(sweep) < 2:
        problems.append(
            "sessions_resident: measure value must carry a residency "
            "'sweep' list with >= 2 points"
        )
    else:
        for i, pt in enumerate(sweep):
            if not isinstance(pt, dict):
                problems.append(
                    f"sessions_resident: sweep[{i}] is not an object"
                )
                continue
            for k in ("n_resident", "ttft_p99_ms", "hit_rate"):
                if not isinstance(pt.get(k), (int, float)) or isinstance(
                    pt.get(k), bool
                ):
                    problems.append(
                        f"sessions_resident: sweep[{i}] missing "
                        f"numeric {k!r}"
                    )
    return problems


def _validate_fleet_elastic(val: Dict) -> List[str]:
    """The elastic control plane's contract: joins bootstrap from
    peers (the 'peer' arm must read ZERO origin bytes — a fallback to
    origin broadcast is the regression the phase exists to catch), the
    manager killover costs zero rollouts, and a drain migrates every
    live prefix instead of losing it."""
    problems: List[str] = []
    failed = _num(val, "failed_rollouts")
    if failed is None or failed > 0:
        problems.append(
            f"fleet_elastic: {failed} failed rollout(s) — the elastic "
            f"control plane's contract is zero across join, killover, "
            f"and drain"
        )
    if val.get("join_peer_source") != "peer":
        problems.append(
            f"fleet_elastic: peer-arm join source is "
            f"{val.get('join_peer_source')!r}, not 'peer' — the join "
            f"fell back to the origin broadcast"
        )
    if (_num(val, "join_peer_origin_bytes") or 0) > 0:
        problems.append(
            "fleet_elastic: the 'peer' join read bytes from the origin "
            "— origin egress is no longer O(1) under joins"
        )
    if (_num(val, "join_peer_peer_bytes") or 0) <= 0:
        problems.append(
            "fleet_elastic: the peer join transferred zero peer bytes "
            "— the bootstrap path never engaged"
        )
    for k in ("drain_lost", "kv_prefix_lost"):
        v = _num(val, k)
        if v is None or v > 0:
            problems.append(
                f"fleet_elastic: {k} = {v} — drained prefixes must "
                f"migrate, never be lost"
            )
    if (_num(val, "drain_migrated") or 0) < 1:
        problems.append(
            "fleet_elastic: zero migrated prefixes — the drain path "
            "never exercised the KV wire"
        )
    if (_num(val, "killover_epoch") or 0) < 2:
        problems.append(
            "fleet_elastic: killover epoch < 2 — no successor manager "
            "ever took the lease"
        )
    if (_num(val, "n_servers_max") or 0) < 3:
        problems.append(
            "fleet_elastic: fleet never grew past its launch size — "
            "no runtime join was measured"
        )
    # The autoscale arm's growth must be AUTOSCALER-driven: the
    # WatermarkAutoscaler issues the launch through its attached
    # launcher. Growth the launcher cannot account for means the
    # harness grew the fleet and the record proves nothing about the
    # control loop.
    if (_num(val, "autoscale_out_actions") or 0) < 1:
        problems.append(
            "fleet_elastic: the autoscaler never issued a scale-out — "
            "the watermark control loop was not exercised"
        )
    if (_num(val, "autoscale_launched") or 0) < 1:
        problems.append(
            "fleet_elastic: the autoscaler's launcher launched nothing "
            "— any growth was harness-driven"
        )
    n_before = _num(val, "autoscale_n_before") or 1
    n_after = _num(val, "autoscale_n_after") or 0
    if n_after <= n_before:
        problems.append(
            f"fleet_elastic: autoscale pool never grew "
            f"({n_before:.0f} -> {n_after:.0f})"
        )
    if n_after - n_before > (_num(val, "autoscale_launched") or 0):
        problems.append(
            "fleet_elastic: autoscale pool grew beyond what the "
            "launcher launched — harness-driven growth is not "
            "autoscaler evidence"
        )
    auto_failed = _num(val, "autoscale_load_failed")
    if auto_failed is None or auto_failed > 0:
        problems.append(
            f"fleet_elastic: {auto_failed} failed request(s) under the "
            f"autoscale arm's pressure load — scale-out must be "
            f"loss-free"
        )
    return problems


def _validate_multi_model_serving(val: Dict) -> List[str]:
    """The multi-model serving plane's contract (ISSUE 20): pools are
    ISOLATED (parity per pool vs single-model baselines, zero
    cross-model routes or KV hits, unknown models refused) and weight
    lifecycles are INDEPENDENT (one family cuts over while the other's
    version, outputs, and tail latency hold, loss-free)."""
    problems: List[str] = []
    if (_num(val, "families_distinct") or 0) != 1:
        problems.append(
            "multi_model_serving: the two families share a config hash "
            "— contamination would be token-invisible"
        )
    for k, what in (
        ("parity_mismatches",
         "pool outputs diverged from the single-model baseline"),
        ("cross_model_routes",
         "a request routed outside its model's pool"),
        ("cross_model_kv_hits",
         "a KV source crossed a model boundary"),
        ("unknown_model_routed",
         "an unregistered model was routed instead of refused"),
    ):
        v = _num(val, k)
        if v is None or v > 0:
            problems.append(f"multi_model_serving: {k} = {v} — {what}")
    if (_num(val, "unknown_model_rejected") or 0) < 1:
        problems.append(
            "multi_model_serving: the unknown-model refusal was never "
            "observed — the negative arm did not run"
        )
    before = _num(val, "cutover_version_before") or 0
    if (_num(val, "cutover_version_after") or 0) <= before:
        problems.append(
            "multi_model_serving: the cutover family's version never "
            "advanced — no independent cutover was measured"
        )
    if (_num(val, "steady_version_after") or 0) != before:
        problems.append(
            f"multi_model_serving: steady_version_after = "
            f"{val.get('steady_version_after')} — the OTHER family's "
            f"cutover moved the steady pool's version"
        )
    if (_num(val, "steady_outputs_stable") or 0) != 1:
        problems.append(
            "multi_model_serving: the steady family's greedy outputs "
            "changed across the other family's cutover — cross-model "
            "weight contamination"
        )
    if (_num(val, "cutover_outputs_changed") or 0) != 1:
        problems.append(
            "multi_model_serving: the cutover family's outputs did not "
            "change at v2 — the 'cutover' never actually swapped "
            "weights"
        )
    b_failed = _num(val, "b_failed")
    if b_failed is None or b_failed > 0:
        problems.append(
            f"multi_model_serving: {b_failed} failed steady-family "
            f"request(s) during the cutover — the independent-"
            f"lifecycle claim requires zero"
        )
    if (_num(val, "b_completed") or 0) < 1:
        problems.append(
            "multi_model_serving: zero steady-family completions "
            "during the cutover window — nothing was measured"
        )
    b_base = _num(val, "b_p99_ttft_base_ms") or 0.0
    b_cut = _num(val, "b_p99_ttft_cutover_ms")
    if b_cut is None or b_cut > 5.0 * b_base + 500.0:
        problems.append(
            f"multi_model_serving: steady-family p99 TTFT went "
            f"{b_base:.0f}ms -> {b_cut}ms across the cutover — the "
            f"other family's fanout stalled this pool"
        )
    lost = _num(val, "kv_prefix_lost")
    if lost is None or lost > 0:
        problems.append(
            f"multi_model_serving: kv_prefix_lost = {lost} — the "
            f"cutover must never cost a prefix"
        )
    return problems


def _validate_agentic_rollout(val: Dict) -> List[str]:
    """The agentic-rollout contract (ISSUE 18 acceptance): episodes are
    loss-free, the session-continuation path measurably beats full
    re-prefill (ratio strictly below 1 AND prefix affinity actually
    engaged — a good ratio with zero prefix hits means the accounting
    lied), tool calls all landed, and the executor sweep proves
    BACKPRESSURE (sheds happened, nothing starved)."""
    problems: List[str] = []
    failed = _num(val, "failed_episodes")
    if failed is None or failed > 0:
        problems.append(
            f"agentic_rollout: {failed} failed episode(s) — multi-turn "
            f"rollout evidence must be loss-free"
        )
    ratio = _num(val, "reprefill_ratio")
    if ratio is None or ratio >= 1.0:
        problems.append(
            f"agentic_rollout: re-prefill ratio {ratio} not below 1.0 "
            f"— continuation turns paid the session-blind full "
            f"re-prefill, the path never engaged"
        )
    if (_num(val, "reprefill_tokens") or 0) <= 0:
        problems.append(
            "agentic_rollout: zero re-prefill tokens — either no "
            "continuation turn ran or the client accounting is dead"
        )
    if (_num(val, "affinity_prefix_hits") or 0) < 1:
        problems.append(
            "agentic_rollout: zero prefix-cache hits during the "
            "continuation arm — sticky-qid affinity never engaged, so "
            "the delta re-prefills hit servers without the parked KV"
        )
    if (_num(val, "tool_failures") or 0) > 0:
        problems.append(
            f"agentic_rollout: {val.get('tool_failures')} failed tool "
            f"call(s) — the pooled executor starved mid-episode"
        )
    if (_num(val, "exec_warm_hits") or 0) < 1:
        problems.append(
            "agentic_rollout: zero warm-worker hits — every job paid a "
            "cold spawn, the pool's whole point"
        )
    if (_num(val, "exec_workers_alive") or 0) < 1:
        problems.append(
            "agentic_rollout: no executor worker alive at the end of "
            "the episode arms"
        )
    if (_num(val, "sat_shed_total") or 0) < 1:
        problems.append(
            "agentic_rollout: saturation sweep never shed — the "
            "bounded queue's 429 backpressure was not exercised"
        )
    if (_num(val, "sat_failed") or 0) > 0:
        problems.append(
            f"agentic_rollout: {val.get('sat_failed')} job(s) failed "
            f"in the saturation sweep — sheds must back clients off, "
            f"never starve them"
        )
    return problems


def _validate_tenant_fairness(val: Dict) -> List[str]:
    """The tenant gateway's fairness contract (ISSUE 19 acceptance):
    under the aggressor flood, weighted fair share must hold the
    victim's p99 TTFT below the FIFO arm's, the aggressor must be shed
    against its OWN limits (a flood that never saturated proves
    nothing), the DRR queue must have actually arbitrated, and not one
    victim request may fail — fairness by starving no one."""
    problems: List[str] = []
    fair = _num(val, "fair_p99_ttft_ms")
    unfair = _num(val, "unfair_p99_ttft_ms")
    if fair is None or unfair is None or fair >= unfair:
        problems.append(
            f"tenant_fairness: fair-share victim p99 {fair}ms is not "
            f"below the FIFO arm's {unfair}ms — the weighted queue "
            f"bought the victim nothing"
        )
    if (_num(val, "solo_p99_ttft_ms") or 0) <= 0:
        problems.append(
            "tenant_fairness: no solo baseline p99 — the record cannot "
            "anchor the flood arms to an idle-fleet floor"
        )
    if (_num(val, "aggressor_sheds") or 0) < 1:
        problems.append(
            "tenant_fairness: zero aggressor sheds — the flood never "
            "exceeded its stream cap, so the arms measured an idle "
            "gateway"
        )
    if (_num(val, "fairshare_picks") or 0) < 1:
        problems.append(
            "tenant_fairness: zero DRR picks in the fair arm — "
            "admitted requests never contended in the gateway queue, "
            "so fair share was never exercised"
        )
    victim_failed = _num(val, "victim_failed")
    if victim_failed is None or victim_failed > 0:
        problems.append(
            f"tenant_fairness: {victim_failed} failed victim "
            f"request(s) — fair share must protect the victim, not "
            f"starve it"
        )
    return problems


def _validate_rpc_resilience(val: Dict) -> List[str]:
    """The hedging contract (ISSUE 14 acceptance): under the injected
    delay tail, the hedged arm's p99 must be MEASURABLY lower than the
    unhedged arm's — sitting below the injected tail, which the
    unhedged arm must actually have eaten (otherwise the A/B measured
    nothing) — and the win/cancel accounting must prove hedges ran,
    won, and cancelled their losers instead of double-counting."""
    problems: List[str] = []
    injected = _num(val, "injected_delay_ms") or 0.0
    unhedged = _num(val, "unhedged_p99_ms")
    hedged = _num(val, "hedged_p99_ms")
    if injected <= 0:
        problems.append(
            "rpc_resilience: no injected delay — the A/B has no tail "
            "to escape"
        )
    if unhedged is None or unhedged < injected:
        problems.append(
            f"rpc_resilience: unhedged p99 {unhedged} ms below the "
            f"injected {injected} ms tail — the slow peer never "
            f"landed, so the hedged number proves nothing"
        )
    if hedged is None or unhedged is None or hedged >= unhedged:
        problems.append(
            f"rpc_resilience: hedged p99 {hedged} ms not below "
            f"unhedged {unhedged} ms — hedging bought nothing"
        )
    if hedged is not None and injected > 0 and hedged >= injected:
        problems.append(
            f"rpc_resilience: hedged p99 {hedged} ms still at/above "
            f"the injected {injected} ms tail — the hedge never "
            f"escaped the slow holder"
        )
    if (_num(val, "hedge_wins") or 0) < 1:
        problems.append(
            "rpc_resilience: zero hedge wins — a low hedged p99 "
            "without wins just means the tail never landed on the "
            "hedged arm"
        )
    if (_num(val, "hedge_cancelled") or 0) < 1:
        problems.append(
            "rpc_resilience: zero cancelled losers — every win must "
            "abandon its loser or bytes get double-counted"
        )
    if (_num(val, "hedge_failures") or 0) > 0:
        problems.append(
            f"rpc_resilience: {val.get('hedge_failures')} hedged pull "
            f"failure(s) — both holders serve the same verified bytes, "
            f"a failure means the substrate dropped a request"
        )
    return problems


def _validate_recovery_slo(val: Dict) -> List[str]:
    """The durable-plane contract (ISSUE 16 acceptance): the recovery
    path must have a measured MTTR, the exactly-once ledger must show
    ZERO lost and ZERO duplicated samples even though redelivery and
    WAL replay were actually exercised, and the async checkpoint arm's
    caller stall must be measurably below the sync arm's — otherwise
    the background writer bought nothing."""
    problems: List[str] = []
    mttr = _num(val, "mttr_ms")
    if mttr is None or mttr <= 0:
        problems.append(
            f"recovery_slo: mttr_ms = {mttr} — no measured recovery "
            f"path, the SLO record is empty"
        )
    for k in ("samples_lost", "samples_duplicated"):
        v = _num(val, k)
        if v is None or v > 0:
            problems.append(
                f"recovery_slo: {k} = {v} — exactly-once means zero, "
                f"a durable plane that loses or double-trains samples "
                f"is broken regardless of its timings"
            )
    if (_num(val, "wal_replayed") or 0) < 1:
        problems.append(
            "recovery_slo: zero WAL records replayed — the MTTR number "
            "never exercised the journal"
        )
    if (_num(val, "redelivered") or 0) < 1:
        problems.append(
            "recovery_slo: zero redeliveries — the exactly-once "
            "counters were never put under stress"
        )
    sync_ms = _num(val, "sync_stall_ms_mean")
    async_ms = _num(val, "async_stall_ms_mean")
    if sync_ms is None or async_ms is None or async_ms >= sync_ms:
        problems.append(
            f"recovery_slo: async stall {async_ms} ms not below sync "
            f"stall {sync_ms} ms — the background writer bought nothing"
        )
    return problems


# Parity ceiling for kernel_micro cases: impls reassociate float32
# sums, so agreement is ~1e-7..1e-6 relative (ops/gae docstring); a
# case past this diverged, it didn't round.
KMICRO_PARITY_MAX = 1e-4
# Noise allowance on the optimized-not-slower tooth. When 'auto'
# resolves to the baseline impl the phase banks the SAME measurement
# for both arms (speedup exactly 1.0), so this margin only ever absorbs
# genuine run-to-run jitter of a genuinely different kernel.
KMICRO_SLOWDOWN_MAX = 1.10

KMICRO_CASE_PHASES = (
    "kernel_micro_gae", "kernel_micro_paged_decode", "kernel_micro_splash",
)


def _validate_kmicro_cases(name: str, val: Dict) -> List[str]:
    """The kernel_micro contract: every case carries its parity number,
    and a case timed as evidence must not show the optimized path
    SLOWER than its baseline — that record is a regression, not
    evidence (the tooth the tentpole issue mandates)."""
    problems: List[str] = []
    cases = val.get("cases")
    if not isinstance(cases, list) or not cases:
        return [f"{name}: measure value must carry a non-empty 'cases' list"]
    for i, c in enumerate(cases):
        if not isinstance(c, dict):
            problems.append(f"{name}: cases[{i}] is not an object")
            continue
        tag = c.get("name", f"cases[{i}]")
        for k in ("baseline_impl", "optimized_impl"):
            if not isinstance(c.get(k), str):
                problems.append(f"{name}: {tag} missing {k!r}")
        par = _num(c, "parity_max_rel")
        if par is None:
            problems.append(
                f"{name}: {tag} lacks numeric parity_max_rel — a timing "
                f"without its parity check is not kernel evidence"
            )
        elif par > KMICRO_PARITY_MAX:
            problems.append(
                f"{name}: {tag} parity_max_rel {par:.2e} exceeds "
                f"{KMICRO_PARITY_MAX:.0e} — the optimized kernel diverged"
            )
        timed = _num(c, "timed")
        if timed is None:
            problems.append(f"{name}: {tag} missing numeric 'timed' flag")
            continue
        if timed:
            base, opt = _num(c, "baseline_ms"), _num(c, "optimized_ms")
            if base is None or opt is None or _num(c, "speedup") is None:
                problems.append(
                    f"{name}: {tag} is timed but lacks "
                    f"baseline_ms/optimized_ms/speedup"
                )
            elif opt > base * KMICRO_SLOWDOWN_MAX:
                problems.append(
                    f"{name}: {tag} optimized path ({opt:.3f} ms) is "
                    f"slower than its baseline ({base:.3f} ms) — refusing "
                    f"a regression as evidence"
                )
    return problems


def _validate_kmicro_labeling(name: str, rec: Dict) -> List[str]:
    """CPU-proxy labeling, cross-checked against the record's OWN
    attestation: a non-driver-verified kernel_micro record must stamp
    itself cpu_proxy/evidence=proxy, and a driver-verified one must
    not — the round-6 conflation mandate applied per record."""
    att = rec.get("attestation")
    if not isinstance(att, dict):
        return []  # bare value dicts (unit tests); bank records always attest
    val = rec.get("value") or {}
    dv = bool(att.get("driver_verified"))
    proxy = _num(val, "cpu_proxy")
    problems: List[str] = []
    if not dv:
        if proxy != 1:
            problems.append(
                f"{name}: non-driver-verified record lacks cpu_proxy=1"
            )
        if val.get("evidence") != "proxy":
            problems.append(
                f"{name}: non-driver-verified record is not labeled "
                f"evidence: proxy"
            )
    else:
        if proxy not in (None, 0):
            problems.append(
                f"{name}: driver-verified record claims cpu_proxy"
            )
        if val.get("evidence") == "proxy":
            problems.append(
                f"{name}: driver-verified record mislabeled evidence: proxy"
            )
    return problems


def _validate_decode_state(val: Dict) -> List[str]:
    """The decode-state A/B contract: token parity is non-negotiable
    (a faster engine emitting different tokens is a broken engine), and
    the resident arm must actually reduce per-block host staging —
    that reduction IS the phase's claim."""
    problems: List[str] = []
    if _num(val, "token_parity_ok") != 1:
        problems.append(
            "kernel_micro_decode_state: resident/legacy greedy tokens "
            "diverged (or parity missing) — refusing"
        )
    res = _num(val, "h2d_per_block_resident")
    leg = _num(val, "h2d_per_block_legacy")
    if res is not None and leg is not None and res >= leg:
        problems.append(
            f"kernel_micro_decode_state: resident arm stages "
            f"{res:.2f} transfers/block, not below the legacy "
            f"{leg:.2f} — the optimization is not engaged"
        )
    bres = _num(val, "h2d_bytes_per_block_resident")
    bleg = _num(val, "h2d_bytes_per_block_legacy")
    if bres is not None and bleg is not None and bres > bleg * 1.10:
        problems.append(
            f"kernel_micro_decode_state: resident arm stages "
            f"{bres:.0f} bytes/block vs legacy {bleg:.0f} — the delta "
            f"path is moving MORE data than the full restage"
        )
    return problems


def _validate_moe_scaling(val: Dict) -> List[str]:
    """The MoE fast-path contract: EP and no-drop-capacity loss
    trajectories must MATCH dropless-EP1 (parity-missing records are
    refused by the key schema), a 'dropless' arm that realized drops is
    a broken dispatcher, and the expert-sliced stream must actually
    shrink per-rank ingress toward 1/EP."""
    problems: List[str] = []
    for k, arm in (("ep_parity_ok", "dropless-EP2"),
                   ("capacity_parity_ok", "no-drop capacity")):
        if _num(val, k) != 1:
            problems.append(
                f"moe_scaling: {arm} loss trajectory diverged from "
                f"dropless-EP1 (or parity missing) — refusing"
            )
    for k in ("dropless_drop_rate", "ep2_drop_rate"):
        dr = _num(val, k)
        if dr is not None and dr > 0:
            problems.append(
                f"moe_scaling: {k} = {dr:.4f} — a dropless dispatch "
                f"that drops tokens is a broken dispatcher"
            )
    ep = _num(val, "ep_degree")
    frac = _num(val, "ep_ingress_frac_max")
    if ep and frac is not None and frac > 1.0 / ep + 0.25:
        problems.append(
            f"moe_scaling: per-rank ingress frac {frac:.3f} does not "
            f"shrink toward 1/{ep:.0f} — the expert-sliced stream is "
            f"not engaged"
        )
    sweep = val.get("capacity_sweep")
    if not isinstance(sweep, list) or not sweep:
        problems.append(
            "moe_scaling: measure value must carry a non-empty "
            "'capacity_sweep'"
        )
    else:
        prev = None
        for i, pt in enumerate(sweep):
            cf = pt.get("capacity_factor") if isinstance(pt, dict) else None
            dr = pt.get("drop_rate") if isinstance(pt, dict) else None
            if not isinstance(cf, (int, float)) or not isinstance(
                dr, (int, float)
            ):
                problems.append(
                    f"moe_scaling: capacity_sweep[{i}] missing numeric "
                    f"capacity_factor/drop_rate"
                )
                continue
            if prev is not None and (cf <= prev[0] or dr > prev[1] + 1e-9):
                problems.append(
                    f"moe_scaling: capacity_sweep[{i}] drop rate must "
                    f"be non-increasing in capacity_factor"
                )
            prev = (cf, dr)
    return problems


def validate_phase_value(name: str, rec: Dict) -> List[str]:
    """Schema problems for one banked record's value dict (measure/ok
    records of phases with a declared schema only)."""
    keys = PHASE_VALUE_KEYS.get(name)
    if not keys or rec.get("status") != "ok" or rec.get("pass") != "measure":
        return []
    problems = []
    val = rec.get("value") or {}
    for k in keys:
        if not isinstance(val.get(k), (int, float)) or isinstance(
            val.get(k), bool
        ):
            problems.append(f"{name}: measure value missing numeric {k!r}")
    ofp = val.get("origin_full_payloads")
    if isinstance(ofp, (int, float)) and ofp > 1.05:
        # The plane's whole point: each byte leaves the origin once.
        problems.append(
            f"{name}: origin served {ofp:.2f} full payloads — peer "
            f"fanout silently degraded to an origin broadcast"
        )
    if name == "train_sharded":
        problems.extend(_validate_train_sharded(val))
    if name == "train_tflops_scaling":
        problems.extend(_validate_scaling_points(val))
    if name == "moe_scaling":
        problems.extend(_validate_moe_scaling(val))
    if name == "train_tflops" and not isinstance(
        val.get("mesh_shape"), dict
    ):
        problems.append(
            "train_tflops: measure value missing the 'mesh_shape' dict"
        )
    if name == "weight_plane_sharded":
        problems.extend(_validate_sharded_plane(val))
    if name == "serving_openloop":
        problems.extend(_validate_openloop_sweep(val))
    if name == "sessions_resident":
        problems.extend(_validate_sessions_resident(val))
    if name == "fleet_elastic":
        problems.extend(_validate_fleet_elastic(val))
    if name == "multi_model_serving":
        problems.extend(_validate_multi_model_serving(val))
    if name == "rpc_resilience":
        problems.extend(_validate_rpc_resilience(val))
    if name == "tenant_fairness":
        problems.extend(_validate_tenant_fairness(val))
    if name == "agentic_rollout":
        problems.extend(_validate_agentic_rollout(val))
    if name == "recovery_slo":
        problems.extend(_validate_recovery_slo(val))
    if name in KMICRO_CASE_PHASES:
        problems.extend(_validate_kmicro_cases(name, val))
    if name == "kernel_micro_decode_state":
        problems.extend(_validate_decode_state(val))
    if name.startswith("kernel_micro_"):
        problems.extend(_validate_kmicro_labeling(name, rec))
    if name == "serving_disagg":
        failed = val.get("disagg_failed")
        if isinstance(failed, (int, float)) and failed > 0:
            problems.append(
                f"{name}: {failed:.0f} failed request(s) in the "
                f"disaggregated arm — handoff evidence must be loss-free"
            )
    problems.extend(_validate_ttft_slo(name, rec.get("value") or {}))
    return problems


def validate_report(rep: Dict, require_driver: bool = False) -> List[str]:
    problems: List[str] = []
    if rep.get("schema") != bank.REPORT_SCHEMA:
        problems.append(
            f"report schema != {bank.REPORT_SCHEMA!r}: {rep.get('schema')!r}"
        )
        return problems

    # Keyed per section: a phase's compile record must never shadow (or
    # be shadowed by) its measure record — the driver_verified backing
    # check below must see the MEASURE evidence, nothing else.
    measures = {}
    for section in ("phases", "compiled", "proxy"):
        for name, rec in (rep.get(section) or {}).items():
            if name == "multichip_dryrun":
                if rec.get("driver_verified") is not False:
                    problems.append(
                        "multichip_dryrun passthrough must be labeled "
                        "driver_verified: false"
                    )
                continue
            try:
                bank.validate_record(rec)
            except ValueError as e:
                problems.append(f"{section}/{name}: {e}")
                continue
            problems.extend(
                f"{section}/{p}" for p in validate_phase_value(name, rec)
            )
            if section == "phases":
                measures[name] = rec
            if section == "proxy" and rec["attestation"].get("driver_verified"):
                problems.append(
                    f"proxy/{name}: proxy evidence cannot be driver_verified"
                )

    # Report-level SLO gating consistency: a record stamped
    # ttft_slo_violated must surface in the report's slo_violations —
    # the stamp exists so a breach is never silently headline-eligible.
    stamped = set()
    for section in ("phases", "proxy"):
        for name, rec in (rep.get(section) or {}).items():
            if ((rec or {}).get("value") or {}).get("ttft_slo_violated"):
                stamped.add(name)
    surfaced = set(rep.get("slo_violations") or {})
    for name in sorted(stamped - surfaced):
        problems.append(
            f"{name}: record is stamped ttft_slo_violated but the "
            f"report's slo_violations does not surface it"
        )

    headline = rep.get("headline") or {}
    any_unverified_headline = False
    for key, entry in headline.items():
        dv = entry.get("driver_verified")
        if not isinstance(dv, bool):
            problems.append(f"headline/{key}: missing driver_verified bool")
            continue
        if not dv:
            any_unverified_headline = True
            if entry.get("evidence") != "proxy":
                problems.append(
                    f"headline/{key}: number lacks driver_verified: true and "
                    f"is not labeled evidence: proxy — refusing to conflate"
                )
        if require_driver and not dv:
            problems.append(
                f"headline/{key}: --require-driver-verified set but the "
                f"number is not driver-verified"
            )

    if rep.get("driver_verified") and any_unverified_headline:
        problems.append(
            "report claims driver_verified: true but carries non-verified "
            "headline numbers"
        )
    if rep.get("driver_verified"):
        tr = measures.get("train_tflops")
        if tr is None or not tr["attestation"].get("driver_verified"):
            problems.append(
                "report claims driver_verified: true but the train_tflops "
                "record does not back it"
            )
    return problems


def validate_bank_dir(path: str) -> List[str]:
    problems: List[str] = []
    seen = 0
    try:
        names = sorted(os.listdir(path))
    except OSError as e:
        return [f"cannot read bank dir {path!r}: {e}"]
    for name in names:
        if not name.endswith(".json"):
            continue
        seen += 1
        full = os.path.join(path, name)
        try:
            with open(full) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{name}: unreadable ({e})")
            continue
        try:
            bank.validate_record(rec)
        except ValueError as e:
            problems.append(f"{name}: {e}")
            continue
        problems.extend(
            f"{name}: {p}"
            for p in validate_phase_value(str(rec.get("phase")), rec)
        )
    if seen == 0:
        problems.append(f"bank dir {path!r} holds no records")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("report", nargs="?", default=None,
                        help="report JSON to validate")
    parser.add_argument("--bank", default=None,
                        help="validate every record in a bank directory")
    parser.add_argument("--require-driver-verified", action="store_true")
    args = parser.parse_args(argv)
    if (args.report is None) == (args.bank is None):
        parser.error("pass exactly one of a report path or --bank")

    if args.bank:
        problems = validate_bank_dir(args.bank)
        target = args.bank
    else:
        try:
            with open(args.report) as f:
                rep = json.load(f)
        except (OSError, ValueError) as e:
            print(f"INVALID {args.report}: unreadable ({e})", file=sys.stderr)
            return 1
        problems = validate_report(
            rep, require_driver=args.require_driver_verified
        )
        target = args.report

    if problems:
        for p in problems:
            print(f"INVALID {target}: {p}", file=sys.stderr)
        return 1
    print(f"OK {target}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

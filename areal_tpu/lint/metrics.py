"""Checker ``metrics-registry``: every cross-process metric name is
declared in ``areal_tpu.base.metrics_registry`` and alive.

The /metrics text surface (``areal:*`` lines) and the stats_tracker
scalar keys (``perf/*``) are string-matched across process boundaries
— emitter and parser can drift silently (``perf/overlap_events`` was
parsed by the prefetch-overlap bench but never emitted; this checker's
founding find). Flags, per module:

- an ``areal:*`` / ``perf/*`` string literal (emission line head,
  startswith-parse prefix, dict key) naming an undeclared metric;
- an f-string that BUILDS a metric name (``f"perf/{k}"``) — the
  registry cannot verify it; route through a declared helper like
  ``metrics_registry.perf_mem_stats``;
- a ``.startswith("areal:x")`` parse whose prefix (without a trailing
  space) matches more than one declared name — whether or not the
  probe is itself a declared name — an ambiguous parse that reads the
  wrong line (append a space, migrate to
  ``metrics_registry.parse_line``, or declare a deliberate family
  probe in ``metrics_registry.FAMILY_PREFIXES``);
- a ``metrics_registry.<ATTR>`` reference that does not resolve
  (constants are generated from the registry, so a typo'd constant
  must fail the gate, not return a stale name);
- registry entries nothing references (dead metric) — only when the
  scan covers the registry module itself.

The registry module is exempt: declarations are not uses (else the
dead-entry check could never fire).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.lint.common import Finding, Module

CHECKER = "metrics-registry"

REGISTRY_MODULE = "areal_tpu.base.metrics_registry"
REGISTRY_REL = "areal_tpu/base/metrics_registry.py"

# A complete name never ends in '_' — trailing-underscore strings are
# prefixes under construction (startswith probes, f-string heads).
_NAME_RE = re.compile(
    r"\A(areal:[a-z0-9_]*[a-z0-9]|perf/[a-z0-9_]*[a-z0-9])( ?)\Z"
)
_HEAD_RE = re.compile(
    r"\A(areal:[a-z0-9_]*[a-z0-9]|perf/[a-z0-9_]*[a-z0-9]) "
)
# An f-string head that stops mid-name (next part is interpolated):
# "areal:", "areal:kv_", "perf/" ... with no terminating space.
_DANGLING_RE = re.compile(r"\A(?:areal:|perf/)[a-z0-9_]*\Z")


@dataclasses.dataclass
class MetricsConfig:
    declared: Set[str]
    constants: Dict[str, str]  # CONST_NAME -> metric name
    # non-constant module attributes that are legal to reference
    exported: Set[str]
    # prefixes that deliberately match a whole family (filter loops,
    # not single-line parses) — declared in the registry
    family_prefixes: Tuple[str, ...] = ("areal:", "perf/")
    registry_rel: str = REGISTRY_REL
    registry_module: str = REGISTRY_MODULE


def default_config() -> MetricsConfig:
    # Import is deliberate (not AST-parsing the registry): it validates
    # the declarations execute, and the module is stdlib-only so the
    # no-jax gate is preserved.
    from areal_tpu.base import metrics_registry

    return MetricsConfig(
        declared=set(metrics_registry.REGISTRY),
        constants=dict(metrics_registry.CONSTANTS),
        exported={
            "REGISTRY", "CONSTANTS", "Metric", "const_name",
            "parse_line", "perf_mem_stats", "render_docs",
            "AREAL_PREFIX", "PERF_PREFIX", "FAMILY_PREFIXES",
        },
        family_prefixes=tuple(metrics_registry.FAMILY_PREFIXES),
    )


def _record(name: str, mod: Module, lineno: int, cfg: MetricsConfig,
            uses: Dict[str, int], findings: List[Finding]):
    uses[name] = uses.get(name, 0) + 1
    if name not in cfg.declared:
        findings.append(Finding(
            mod.rel, lineno, CHECKER,
            f"undeclared metric name {name}: declare it in "
            f"{cfg.registry_module} (name, kind, emitter, doc)",
        ))


def check(mod: Module, cfg: MetricsConfig,
          uses: Dict[str, int]) -> List[Finding]:
    """Per-module pass; records metric uses into ``uses`` for the
    cross-module dead-entry check."""
    if mod.rel == cfg.registry_rel:
        return []
    findings: List[Finding] = []

    for node in mod.nodes:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            # Skip constants living inside an f-string: JoinedStr parts
            # are handled below with interpolation context.
            parent = mod.parent(node)
            if isinstance(parent, (ast.JoinedStr, ast.FormattedValue)):
                continue
            m = _NAME_RE.match(node.value) or _HEAD_RE.match(node.value)
            if m:
                _record(m.group(1), mod, node.lineno, cfg, uses, findings)
            continue

        if isinstance(node, ast.JoinedStr):
            for i, part in enumerate(node.values):
                if not (isinstance(part, ast.Constant)
                        and isinstance(part.value, str)):
                    continue
                # Only the part that STARTS the string can start a
                # metric name; later constants follow interpolations.
                if i != 0:
                    continue
                if _DANGLING_RE.match(part.value) and i + 1 < len(
                    node.values
                ):
                    findings.append(Finding(
                        mod.rel, node.lineno, CHECKER,
                        f"f-string-built metric name "
                        f"({part.value!r}...): the registry cannot "
                        f"verify it; use a declared name or a registry "
                        f"helper (e.g. perf_mem_stats)",
                    ))
                    continue
                m = _NAME_RE.match(part.value) or _HEAD_RE.match(
                    part.value
                )
                if m:
                    _record(m.group(1), mod, node.lineno, cfg, uses,
                            findings)
            continue

        if isinstance(node, ast.Call):
            # startswith-parse prefix ambiguity.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "startswith"
                and node.args
            ):
                s = mod.resolve_str(node.args[0])
                if s is not None and (
                    s.startswith("areal:") or s.startswith("perf/")
                ):
                    bare = s.rstrip(" ")
                    # A trailing space pins the probe to one exact line;
                    # a declared family prefix matches many by design.
                    # Otherwise ANY probe matching two or more declared
                    # names reads whichever line comes first — the
                    # probe being a declared name itself is not
                    # required ("areal:kv_spill_" is just as wrong).
                    # Regression note: review find, PR 13.
                    if s == bare and bare not in cfg.family_prefixes:
                        clash = sorted(
                            o for o in cfg.declared
                            if o != bare and o.startswith(bare)
                        )
                        if clash and (bare in cfg.declared
                                      or len(clash) >= 2):
                            findings.append(Finding(
                                mod.rel, node.lineno, CHECKER,
                                f"ambiguous startswith parse {bare!r}: "
                                f"also matches {', '.join(clash)} — "
                                f"append ' ' or use "
                                f"metrics_registry.parse_line",
                            ))

    # Registry attribute references: both `metrics_registry.X` and
    # `from ...metrics_registry import X` forms must resolve.
    for node in mod.nodes:
        if not isinstance(node, ast.Attribute):
            continue
        dotted = mod.dotted_name(node)
        if dotted is None:
            continue
        head, _, attr = dotted.rpartition(".")
        if head != cfg.registry_module and not head.endswith(
            ".metrics_registry"
        ):
            continue
        if attr in cfg.constants:
            name = cfg.constants[attr]
            uses[name] = uses.get(name, 0) + 1
        elif attr == "perf_mem_stats":
            # The one declared dynamic builder: a call site keeps every
            # perf/mem_* entry alive (the helper validates each key
            # against the registry at runtime).
            for name in cfg.declared:
                if name.startswith("perf/mem_"):
                    uses[name] = uses.get(name, 0) + 1
        elif attr not in cfg.exported and not attr.startswith("__"):
            findings.append(Finding(
                mod.rel, node.lineno, CHECKER,
                f"metrics_registry.{attr} does not resolve: constants "
                f"are generated from the registry — declare the metric "
                f"or fix the constant name",
            ))
    for local, target in mod.imports.items():
        prefix = cfg.registry_module + "."
        if not target.startswith(prefix):
            continue
        attr = target[len(prefix):]
        if attr in cfg.constants:
            name = cfg.constants[attr]
            uses[name] = uses.get(name, 0) + 1
        elif attr not in cfg.exported and "." not in attr:
            findings.append(Finding(
                mod.rel, 1, CHECKER,
                f"import of unknown metrics_registry attr {attr}",
            ))
    return findings


def check_dead(cfg: MetricsConfig, uses: Dict[str, int],
               registry_lines: Dict[str, int]) -> List[Finding]:
    """Registry entries nothing references (emitter or parser)."""
    findings: List[Finding] = []
    for name in sorted(cfg.declared):
        if not uses.get(name):
            findings.append(Finding(
                cfg.registry_rel, registry_lines.get(name, 1), CHECKER,
                f"dead registry entry {name}: no scanned module emits "
                f"or parses it — delete the Metric or the feature that "
                f"grew past it",
            ))
    return findings


def registry_decl_lines(mod: Module) -> Dict[str, int]:
    """Line of each ``_m("name", ...)`` / ``Metric(name=...)`` call in
    the registry module, for anchoring dead-entry findings."""
    lines: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname not in ("_m", "Metric"):
            continue
        name: Optional[str] = None
        if node.args and isinstance(node.args[0], ast.Constant):
            name = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
        if isinstance(name, str):
            lines[name] = node.lineno
    return lines

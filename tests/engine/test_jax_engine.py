"""JaxTrainEngine: train_batch/forward/generate on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.base.topology import MeshSpec
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.ops.loss import sft_loss_from_logprobs
from areal_tpu.parallel.mesh import make_mesh


def small_cfg(**kw):
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32", **kw,
    )


def make_batch(n=8, seed=0, vocab=64):
    rng = np.random.RandomState(seed)
    seqlens = rng.randint(5, 30, size=n).tolist()
    total = sum(seqlens)
    # prompt_mask: 1.0 on response positions (loss positions), 0 on prompt.
    masks = []
    for l in seqlens:
        m = np.zeros(l, np.float32)
        m[l // 2 :] = 1.0
        masks.append(m)
    return SequenceSample.from_default(
        ids=[f"s{seed}-{i}" for i in range(n)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, vocab, size=total),
            "loss_mask": np.concatenate(masks),
        },
    )


def sft_packed_loss(lp, rows):
    # `lp` = engine-fused next-token logprobs [R, T].
    total, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
    return total, {"n_valid_tokens": n}


def loss_weight(mb):
    return float(np.sum(mb.data["loss_mask"]))


# d1f2s2t2 is the exact mesh __graft_entry__._mesh_spec_for(8) builds (the
# round-1 dryrun crash); d2s2t2 exercises data+seq+tensor together.
@pytest.mark.parametrize(
    "mesh_spec", [None, "d2f2t2", "d1f2s2t2", "d2s2t2"]
)
def test_train_batch_reduces_loss(mesh_spec):
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshSpec.parse(mesh_spec)) if mesh_spec else None
    eng = JaxTrainEngine(
        cfg, params, mesh=mesh,
        optimizer_config=OptimizerConfig(lr=2e-3, warmup_steps_proportion=0.0),
        total_train_steps=50, row_len_multiple=32,
    )
    batch = make_batch(n=8)
    losses = []
    for step in range(8):
        stats = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=2), sft_packed_loss, loss_weight,
            version_steps=step, loss_name="sft",
        )
        losses.append(stats["sft/loss"])
        assert np.isfinite(stats["sft/grad_norm"])
    assert losses[-1] < losses[0] * 0.9, losses


def test_version_steps_positions_lr_schedule():
    """`version_steps` is HONORED as the LR-schedule position (PR 9
    satellite; it was previously accepted and silently ignored): under a
    decaying schedule, the same batch trained at version 0 vs a late
    version must move the params by visibly different amounts, and the
    applied LR is reported as `<loss>/lr` at exactly the schedule's
    value for that position. Budget: <5 s (two tiny engines, warm XLA
    cache; tier-1 headroom note per PR 7's discipline)."""
    from areal_tpu.engine.optimizer import make_lr_schedule

    cfg = small_cfg()
    opt = OptimizerConfig(
        lr=1e-2, min_lr_ratio=0.0, lr_scheduler_type="linear",
        warmup_steps_proportion=0.0,
    )
    sched = make_lr_schedule(opt, 10)
    params = init_params(cfg, jax.random.PRNGKey(7))
    batch = make_batch(n=6, seed=7)
    deltas = []
    for pos in (0, 9):
        eng = JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params),
            optimizer_config=opt, total_train_steps=10,
            row_len_multiple=32,
        )
        st = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=1), sft_packed_loss, loss_weight,
            version_steps=pos, loss_name="t",
        )
        np.testing.assert_allclose(st["t/lr"], float(sched(pos)), rtol=1e-6)
        before = jax.tree_util.tree_leaves(params)
        after = jax.tree_util.tree_leaves(jax.device_get(eng.params))
        deltas.append(
            max(
                float(np.max(np.abs(np.asarray(a, np.float32)
                                    - np.asarray(b, np.float32))))
                for a, b in zip(after, before)
            )
        )
    # Position 9 of a 10-step linear decay trains at ~1/10 the LR of
    # position 0; the update magnitudes must reflect it.
    assert deltas[1] < deltas[0] * 0.5, deltas


def test_version_steps_default_uses_internal_count():
    """Callers that never pass version_steps keep the old semantics: the
    schedule advances with the engine's own train_batch count (reported
    via `<loss>/lr`). Budget: <5 s."""
    from areal_tpu.engine.optimizer import make_lr_schedule

    cfg = small_cfg()
    opt = OptimizerConfig(
        lr=1e-2, min_lr_ratio=0.0, lr_scheduler_type="linear",
        warmup_steps_proportion=0.0,
    )
    sched = make_lr_schedule(opt, 10)
    eng = JaxTrainEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(8)),
        optimizer_config=opt, total_train_steps=10, row_len_multiple=32,
    )
    batch = make_batch(n=4, seed=8)
    for i in range(3):
        st = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=1), sft_packed_loss, loss_weight,
            loss_name="t",
        )
        np.testing.assert_allclose(st["t/lr"], float(sched(i)), rtol=1e-6)


def test_microbatching_invariance():
    # Same data, different mb splits -> same gradient step (same next loss).
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(1))
    results = []
    for n_mbs in (1, 3):
        eng = JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params),
            optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            total_train_steps=10, row_len_multiple=32,
        )
        batch = make_batch(n=6, seed=3)
        s1 = eng.train_batch(batch, MicroBatchSpec(n_mbs=n_mbs), sft_packed_loss,
                             loss_weight, loss_name="sft")
        s2 = eng.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                             loss_weight, loss_name="sft")
        results.append((s1["sft/loss"], s2["sft/loss"]))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-4)
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-3)


def dp_scaled_sft_loss(lp, rows):
    """Test loss honoring the engine-injected dp_loss_scale (the contract
    every interface loss follows for token_normalize_scope='dp')."""
    mask = rows["loss_mask"]
    if "dp_loss_scale" in rows:
        mask = mask * rows["dp_loss_scale"]
    total, n = sft_loss_from_logprobs(lp, mask)
    return total, {}


def test_dp_token_normalize_scope():
    """token_normalize_scope='dp' reproduces the reference's per-rank
    normalization (ppo_interface.py:253): loss = mean over dp shards of
    (shard loss sum / shard token count), and it differs from 'global'
    when shards carry unequal token counts."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(11))
    # Two sequences of 24 and 20 tokens -> one row each (max_row_len=32),
    # row0 -> dp shard 0, row1 -> dp shard 1: unequal denominators.
    seqlens = [24, 20]
    rng = np.random.RandomState(11)
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=["a", "b"],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )
    # Expected per-shard-normalized loss from the same params' logprobs.
    inf = JaxTrainEngine(
        cfg, jax.tree_util.tree_map(jnp.copy, params),
        row_len_multiple=32, max_row_len=32,
    )
    lp = np.asarray(
        inf.forward(batch, MicroBatchSpec(n_mbs=1), output_key="logprobs")
        .data["logprobs"]
    )
    nll0 = -lp[:24].sum() / 24
    nll1 = -lp[24:].sum() / 20
    expected_dp = 0.5 * (nll0 + nll1)
    expected_global = -lp.sum() / total

    stats = {}
    for scope in ("dp", "global"):
        eng = JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params),
            mesh=make_mesh(MeshSpec.parse("d2"), devices=jax.devices()[:2]),
            optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            total_train_steps=10, row_len_multiple=32, max_row_len=32,
        )
        stats[scope] = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=1), dp_scaled_sft_loss, loss_weight,
            token_normalize_scope=scope, loss_name="sft",
        )
    np.testing.assert_allclose(stats["dp"]["sft/loss"], expected_dp, rtol=1e-4)
    np.testing.assert_allclose(
        stats["global"]["sft/loss"], expected_global, rtol=1e-4
    )
    assert abs(expected_dp - expected_global) > 1e-6  # scopes genuinely differ


def test_dp_scope_requires_token_weights():
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(12))
    eng = JaxTrainEngine(
        cfg, params,
        mesh=make_mesh(MeshSpec.parse("d2"), devices=jax.devices()[:2]),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32,
    )
    rng = np.random.RandomState(13)
    batch = SequenceSample.from_default(
        ids=["x", "y"], seqlens=[12, 12],
        data={"packed_input_ids": rng.randint(0, 64, size=24)},
    )
    with pytest.raises(ValueError, match="loss weights"):
        eng.train_batch(
            batch, MicroBatchSpec(n_mbs=1),
            lambda lp, rows: (jnp.sum(-lp), {}), lambda mb: 24.0,
            token_normalize_scope="dp",
        )


def test_dp_scope_with_sft_interface_loss():
    """The REAL SFT loss path (prompt_mask rows, sft_row_loss) under
    'dp' on a 2-shard mesh: weights derive from the response mask, no
    loss_mask key needed (the review-found crash)."""
    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss

    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(14))
    seqlens = [24, 20]
    rng = np.random.RandomState(14)
    total = sum(seqlens)
    pm = np.zeros(total, np.int32)
    pm[:8] = 1  # seq a: 8 prompt tokens
    pm[24:24 + 4] = 1  # seq b: 4 prompt tokens
    batch = SequenceSample.from_default(
        ids=["a", "b"], seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "prompt_mask": pm,
        },
    )
    eng = JaxTrainEngine(
        cfg, params,
        mesh=make_mesh(MeshSpec.parse("d2"), devices=jax.devices()[:2]),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32, max_row_len=32,
    )
    st = eng.train_batch(
        batch, MicroBatchSpec(n_mbs=1), sft_row_loss, sft_loss_weight,
        token_normalize_scope="dp", loss_name="sft",
    )
    assert np.isfinite(st["sft/loss"]) and np.isfinite(st["sft/grad_norm"])


@pytest.mark.parametrize("mesh_spec", ["d1f2s2t2", "d2f2t2"])
def test_forward_parity_across_meshes(mesh_spec):
    """forward() on a sharded mesh matches the single-device result."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(7))
    batch = make_batch(n=8, seed=9)
    ref_eng = JaxTrainEngine(
        cfg, jax.tree_util.tree_map(jnp.copy, params), row_len_multiple=32
    )
    ref = ref_eng.forward(batch, MicroBatchSpec(n_mbs=1), output_key="logprobs")
    eng = JaxTrainEngine(
        cfg, jax.tree_util.tree_map(jnp.copy, params),
        mesh=make_mesh(MeshSpec.parse(mesh_spec)), row_len_multiple=32,
    )
    out = eng.forward(batch, MicroBatchSpec(n_mbs=1), output_key="logprobs")
    np.testing.assert_allclose(
        out.data["logprobs"], ref.data["logprobs"], rtol=1e-4, atol=1e-5
    )


def test_forward_logprobs_and_values():
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(2))
    eng = JaxTrainEngine(cfg, params, row_len_multiple=32)
    batch = make_batch(n=5, seed=5)
    out = eng.forward(batch, MicroBatchSpec(n_mbs=2), output_key="logprobs")
    assert out.keys == {"logprobs"}
    assert out.data["logprobs"].shape[0] == batch.total_seqlen()
    assert out.ids == batch.ids

    ccfg = small_cfg(is_critic=True)
    cparams = init_params(ccfg, jax.random.PRNGKey(3))
    ceng = JaxTrainEngine(ccfg, cparams, row_len_multiple=32)
    vals = ceng.forward(batch, MicroBatchSpec(n_mbs=1), output_key="values")
    assert vals.data["values"].shape[0] == batch.total_seqlen()


def test_engine_generate():
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(4))
    eng = JaxTrainEngine(cfg, params, row_len_multiple=32)
    prompts = SequenceSample.from_default(
        ids=["p0", "p1"],
        seqlens=[4, 6],
        data={"packed_prompts": np.arange(10) % 64},
    )
    g = GenerationHyperparameters(n=2, max_new_tokens=8, greedy=True)
    outs = eng.generate(prompts, MicroBatchSpec(), None, g)
    assert len(outs) == 4  # 2 prompts x n=2
    assert all(len(o["output_ids"]) <= 8 for o in outs)


def test_train_batch_sharded_splash_attention():
    """d1f2s2t2 mesh with the flash (splash) path forced: the pallas
    kernel runs per shard under shard_map (interpret mode on CPU) inside
    the full fused train step — the program that ships to real
    multi-chip TPUs (VERDICT r2 weak #2)."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshSpec.parse("d1f2s2t2"))
    eng = JaxTrainEngine(
        cfg, params, mesh=mesh,
        optimizer_config=OptimizerConfig(lr=2e-3, warmup_steps_proportion=0.0),
        total_train_steps=50, row_len_multiple=128, max_row_len=128,
        attn_impl="splash",
    )
    rng = np.random.RandomState(5)
    seqlens = rng.randint(64, 128, size=8).tolist()
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[f"sp{i}" for i in range(8)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )
    losses = []
    for step in range(6):
        stats = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=1), sft_packed_loss, loss_weight,
            version_steps=step, loss_name="sft",
        )
        losses.append(stats["sft/loss"])
        assert np.isfinite(stats["sft/grad_norm"])
    assert losses[-1] < losses[0], losses


def test_sharded_splash_forward_matches_reference_impl():
    """Same mesh, same inputs: splash-under-shard_map logprobs equal the
    einsum path's within tolerance."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(3))
    mesh = make_mesh(MeshSpec.parse("d1f2s2t2"))
    rng = np.random.RandomState(6)
    seqlens = rng.randint(64, 128, size=8).tolist()
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[f"pp{i}" for i in range(8)],
        seqlens=seqlens,
        data={"packed_input_ids": rng.randint(0, 64, size=total)},
    )
    outs = []
    for impl in ("reference", "splash"):
        eng = JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params), mesh=mesh,
            row_len_multiple=128, max_row_len=128, attn_impl=impl,
        )
        out = eng.forward(batch, MicroBatchSpec(n_mbs=1), output_key="logprobs")
        outs.append(np.asarray(out.data["logprobs"]))
    np.testing.assert_allclose(outs[0], outs[1], atol=5e-3, rtol=1e-3)


def test_sharded_splash_grads_match_reference_impl():
    """One optimizer step on the d1f2s2t2 mesh with splash vs the einsum
    impl must produce the same updated parameters (catches wrong cotangent
    scaling over the unmentioned seq axis — check_vma is off)."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(8))
    mesh = make_mesh(MeshSpec.parse("d1f2s2t2"))
    rng = np.random.RandomState(9)
    seqlens = rng.randint(64, 128, size=8).tolist()
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[f"gp{i}" for i in range(8)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )
    updated = {}
    for impl in ("reference", "splash"):
        eng = JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params), mesh=mesh,
            optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            total_train_steps=10, row_len_multiple=128, max_row_len=128,
            attn_impl=impl,
        )
        eng.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                        loss_weight, loss_name="sft")
        updated[impl] = jax.device_get(eng.params)
    leaves_r = jax.tree_util.tree_leaves(updated["reference"])
    leaves_s = jax.tree_util.tree_leaves(updated["splash"])
    for a, b in zip(leaves_r, leaves_s):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=2e-4, rtol=2e-3,
        )


def test_serial_dispatch_guard_and_overlap():
    """VERDICT r2 weak #4: the CPU-platform collective-serialization guard.

    XLA's in-process CPU collectives mismatch rendezvous when two
    collective-bearing executables are in flight, so the engine
    serializes dispatch on CPU meshes (real TPUs order collectives per
    stream). This pins the guard's activation conditions and exercises
    back-to-back collective-bearing dispatches (train step + sharded
    forward) under it — the overlap pattern that flaked in round 1."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(21))
    mesh = make_mesh(MeshSpec.parse("d2f2t2"))
    eng = JaxTrainEngine(
        cfg, params, mesh=mesh,
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32,
    )
    assert eng._serial_dispatch  # multi-device CPU mesh -> guard on
    single = JaxTrainEngine(cfg, init_params(cfg, jax.random.PRNGKey(22)),
                            row_len_multiple=32)
    assert not single._serial_dispatch  # 1 device -> no sync needed

    batch = make_batch(n=8, seed=21)
    for step in range(3):
        st = eng.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                             loss_weight, version_steps=step, loss_name="sft")
        out = eng.forward(batch, MicroBatchSpec(n_mbs=1), output_key="logprobs")
        assert np.isfinite(st["sft/loss"])
        assert np.all(np.isfinite(out.data["logprobs"]))


def test_offload_roundtrip_preserves_training():
    """offload() frees device state; the next engine call transparently
    restores params + optimizer state, and training continues bit-for-bit
    identically to a never-offloaded twin (reference async_offload)."""
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(30))
    batch = make_batch(n=6, seed=30)

    def mk():
        return JaxTrainEngine(
            cfg, jax.tree_util.tree_map(jnp.copy, params),
            optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            total_train_steps=10, row_len_multiple=32,
        )

    eng_a, eng_b = mk(), mk()
    for eng in (eng_a, eng_b):
        eng.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                        loss_weight, loss_name="sft")
    eng_a.offload()
    assert eng_a.params is None and eng_a.opt_state is None
    assert eng_a._host_params is not None
    sa = eng_a.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                           loss_weight, loss_name="sft")
    sb = eng_b.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                           loss_weight, loss_name="sft")
    np.testing.assert_allclose(sa["sft/loss"], sb["sft/loss"], rtol=1e-6)
    np.testing.assert_allclose(sa["sft/grad_norm"], sb["sft/grad_norm"], rtol=1e-6)
    # get_params while offloaded returns the HOST copy without restoring
    # to device (restoring could OOM the colocated model).
    eng_a.offload()
    assert eng_a.get_params() is not None and eng_a._offloaded
    assert eng_a.get_opt_state() is not None and eng_a._offloaded


def test_offload_checkpoint_roundtrip(tmp_path):
    """Saving while offloaded must write the real weights (not None), and
    loading restores a usable engine (the review-found silent-None save)."""
    from areal_tpu.engine.checkpoint import load_engine_state, save_engine_state

    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(31))
    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32,
    )
    batch = make_batch(n=4, seed=31)
    eng.train_batch(batch, MicroBatchSpec(n_mbs=1), sft_packed_loss,
                    loss_weight, loss_name="sft")
    eng.offload()
    save_engine_state(eng, str(tmp_path))

    eng2 = JaxTrainEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(99)),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32,
    )
    load_engine_state(eng2, str(tmp_path))
    a = jax.tree_util.tree_leaves(eng.get_params())
    b = jax.tree_util.tree_leaves(eng2.get_params())
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

"""Single registry of every cross-process metric name.

Two namespaces, both string-matched across process boundaries and both
previously undeclared anywhere:

- ``areal:*`` — the /metrics text surface every generation server
  emits (``generation_server._h_metrics``) and four independent
  consumers regex/startswith-parse: the gserver manager's poll loop,
  ``fleet_controller.rebuild_fleet_state`` (manager-HA takeover),
  the bench fleet harness, and the system tests. A renamed line used
  to turn a consumer into a silent zero (the PR 7 "different random
  weights per server" class: contract drift found the hard way).
- ``perf/*`` — stats_tracker scalar keys shipped worker -> master in
  MFC stats payloads and read back by ``master_worker`` (perf history,
  tflops headline) and the bench workloads. ``perf/overlap_events``
  was parsed by the prefetch-overlap bench but never emitted — the
  checker class this registry exists for.

Every name is declared ONCE here (name, kind, emitter, doc); the
``metrics-registry`` checker in ``areal_tpu/lint`` flags any
``areal:*``/``perf/*`` literal not declared here, any f-string-built
name (unverifiable), any ``startswith`` parse whose prefix is
ambiguous against the registry, and any dead entry nothing references.

Parse call sites reference the generated CONSTANTS (e.g.
``metrics_registry.NUM_USED_TOKENS``) instead of raw literals — same
pattern as the PR 10 env-knob migration. ``docs/metrics.md`` is
GENERATED from this registry
(``python scripts/areal_lint.py --emit-metrics-docs docs/metrics.md``)
and drift-gated in tier-1.

This module must stay stdlib-only: it is imported by the no-jax lint
gate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

AREAL_PREFIX = "areal:"
PERF_PREFIX = "perf/"

# Deliberate family probes: a startswith() on exactly one of these
# matches a whole name family by design (filtering, iteration) and is
# not an ambiguous single-line parse. Any other prefix probe matching
# two or more declared names fails the metrics-registry lint gate.
FAMILY_PREFIXES = (AREAL_PREFIX, PERF_PREFIX, "perf/mem_")

# kind vocabulary:
#   counter — monotonically increasing since process start (consumers
#             must diff, never reset: /metrics counters never reset)
#   gauge   — point-in-time value
#   hist    — sparse latency bucket counts (base/latency.py encoding;
#             '-' when empty); fleet aggregation merges raw counts
#   string  — non-numeric surface (role, wire tag, 'r/d' shard)
#   scalar  — stats_tracker scalar (perf/*); ``reduce`` says how DP
#             workers merge (avg/sum/max) or 'derived' for keys
#             computed at aggregation time


@dataclasses.dataclass(frozen=True)
class Metric:
    name: str  # full wire name: "areal:x" or "perf/x"
    kind: str  # counter | gauge | hist | string | scalar
    emitter: str  # repo-rel module (under areal_tpu/) that emits it
    doc: str
    reduce: str = ""  # perf/* only: avg | sum | max | derived


def _m(name: str, kind: str, emitter: str, doc: str, *,
       reduce: str = "") -> Metric:
    return Metric(name=name, kind=kind, emitter=emitter, doc=doc,
                  reduce=reduce)


_GS = "system/generation_server.py"

_METRICS: List[Metric] = [
    # -- serving load (admission, routing) -------------------------------
    _m("areal:num_running_reqs", "gauge", _GS,
       "In-flight requests on the engine loop; manager load estimate."),
    _m("areal:num_used_tokens", "gauge", _GS,
       "KV tokens resident in the paged pool; the manager's "
       "least_token_usage routing signal (poll + in-flight fold)."),
    _m("areal:queue_depth", "gauge", _GS,
       "Requests queued behind admission on this server."),
    _m("areal:queued_prompt_tokens", "gauge", _GS,
       "Prompt tokens queued behind admission; the 429 watermark and "
       "re-role sizer input."),
    _m("areal:load_shed_total", "counter", _GS,
       "Requests shed with 429 + Retry-After. Deliberate backpressure, "
       "NOT failures — the manager must never count these toward "
       "eviction."),
    _m("areal:total_requests", "counter", _GS,
       "All /generate requests admitted; fleet hit-rate denominator "
       "(manager aggregates ratio of sums, never averages rates)."),
    _m("areal:total_generated_tokens", "counter", _GS,
       "Tokens generated since start; fleet throughput numerator."),
    _m("areal:num_interrupted_reqs", "counter", _GS,
       "Generations interrupted by weight cutover (resubmitted by "
       "partial_rollout with the accumulated prefix)."),
    _m("areal:num_preempted_reqs", "counter", _GS,
       "Requests preempted by the scheduler for page pressure."),
    # -- MoE decode router telemetry -------------------------------------
    _m("areal:moe_drop_rate", "gauge", _GS,
       "Decode-time realized MoE token-drop rate, layer-mean over the "
       "last decode block (0 for dense models and dropless dispatch)."),
    _m("areal:moe_router_entropy", "gauge", _GS,
       "Decode-time MoE router entropy (nats), layer-mean over the "
       "last decode block; collapse detector for serving-side drift."),
    # -- latency SLOs ----------------------------------------------------
    _m("areal:ttft_p50_ms", "gauge", _GS,
       "Per-server TTFT p50 (humans; fleet math uses the hist)."),
    _m("areal:ttft_p99_ms", "gauge", _GS,
       "Per-server TTFT p99 (humans; SLO gate uses the hist)."),
    _m("areal:itl_p50_ms", "gauge", _GS,
       "Per-server inter-token latency p50."),
    _m("areal:itl_p99_ms", "gauge", _GS,
       "Per-server inter-token latency p99."),
    _m("areal:ttft_hist", "hist", _GS,
       "Raw TTFT bucket counts (base/latency.py edges, sparse "
       "i:count) — percentiles cannot be averaged, so the manager and "
       "bench merge counts."),
    _m("areal:itl_hist", "hist", _GS,
       "Raw ITL bucket counts; fleet ratio-of-sums aggregation."),
    # -- weights ---------------------------------------------------------
    _m("areal:weight_version", "gauge", _GS,
       "Engine weight version; staleness control + HA rebuild input."),
    _m("areal:last_weight_swap_s", "gauge", _GS,
       "Seconds the last on-device weight swap took."),
    _m("areal:last_weight_stage_s", "gauge", _GS,
       "Seconds the last host-side weight staging took."),
    _m("areal:last_weight_load_s", "gauge", _GS,
       "Seconds the last full weight load took (disk or plane)."),
    _m("areal:weight_load_fast_path", "gauge", _GS,
       "1.0 when the last load came from the shm_raw fast path."),
    _m("areal:weight_transfer_ms", "gauge", _GS,
       "Weight-plane network transfer ms (overlaps serving — "
       "deliberately separate from cutover)."),
    _m("areal:weight_cutover_ms", "gauge", _GS,
       "Weight cutover interrupt+swap window ms (budget-bounded)."),
    _m("areal:weight_verify_ms", "gauge", _GS,
       "Per-chunk hash verification ms for the last plane transfer."),
    _m("areal:weight_bytes_from_origin", "counter", _GS,
       "Plane bytes fetched from the origin; the peer-fanout benches "
       "pin this (zero origin bytes per peer join)."),
    _m("areal:weight_bytes_from_peers", "counter", _GS,
       "Plane bytes fetched from peer servers."),
    _m("areal:weight_chunks_served", "counter", _GS,
       "Plane chunks this server served to peers."),
    _m("areal:weight_bytes_served", "counter", _GS,
       "Plane bytes this server served to peers."),
    _m("areal:weight_expected_bytes", "gauge", _GS,
       "THIS server's chunk-stream size (shard slice and/or quantized "
       "wire) — ingress/expected reads 1.0 for a complete sliced "
       "fetch, never 'incomplete' against the full payload."),
    _m("areal:weight_ingress_payload_equivalents", "gauge", _GS,
       "Ingress bytes / expected bytes for the last transfer "
       "(attested 1.0 -> 0.50 -> 0.25 across TP1/TP2/TP2+int8)."),
    _m("areal:weight_wire", "string", _GS,
       "Wire encoding of the last plane transfer (float/int8)."),
    _m("areal:weight_shard", "string", _GS,
       "'rank/degree' TP shard this server holds, '-' unsharded; "
       "second source besides the heartbeat so a fanout racing a "
       "server's first beat never plans it into the unsharded group."),
    # -- disaggregated serving / roles -----------------------------------
    _m("areal:role", "string", _GS,
       "Live pool role (prefill/decode/unified) as the server sees "
       "it; the sizer's view wins until this surface catches up."),
    _m("areal:model_id", "string", _GS,
       "Registered model family this server hosts (multi-model "
       "serving plane, system/model_registry.py); second source "
       "besides the heartbeat so a manager-HA rebuild pools the "
       "fleet per model without waiting a beat."),
    _m("areal:elastic", "gauge", _GS,
       "1.0 when the CONFIGURED role is unified (re-role pool "
       "eligibility), independent of the live role."),
    _m("areal:kv_pages_free", "gauge", _GS,
       "Free paged-pool pages; autoscaler low-watermark input."),
    _m("areal:kv_pages_total", "gauge", _GS,
       "Total paged-pool pages."),
    # -- KV handoff (prefill -> decode wire) -----------------------------
    _m("areal:kv_export_total", "counter", _GS,
       "KV handoffs exported (prefill side)."),
    _m("areal:kv_export_bytes", "counter", _GS,
       "KV handoff bytes exported."),
    _m("areal:last_kv_export_ms", "gauge", _GS,
       "Duration of the last KV export."),
    _m("areal:kv_import_total", "counter", _GS,
       "KV handoffs imported (decode side)."),
    _m("areal:kv_import_bytes", "counter", _GS,
       "KV handoff bytes imported."),
    _m("areal:last_kv_import_ms", "gauge", _GS,
       "Duration of the last KV import."),
    _m("areal:last_kv_transfer_ms", "gauge", _GS,
       "End-to-end duration of the last KV handoff transfer."),
    _m("areal:kv_handoff_ok", "counter", _GS,
       "Handoffs completed on the disagg wire."),
    _m("areal:kv_handoff_failed", "counter", _GS,
       "Handoffs that failed outright (after retries)."),
    _m("areal:kv_handoff_fallback", "counter", _GS,
       "Handoffs that fell back to local-serve (the A/B bench pins "
       "this to zero on the disagg arm)."),
    # -- tiered KV plane (spill/restore, docs/serving.md) ----------------
    _m("areal:kv_spill_total", "counter", _GS,
       "Prefix evictions spilled to the host tier instead of freed."),
    _m("areal:kv_spill_bytes", "counter", _GS,
       "Bytes spilled to the KV tier (int8 wire ~0.31x float)."),
    _m("areal:kv_spill_tokens", "counter", _GS,
       "Tokens covered by spilled prefixes."),
    _m("areal:kv_restore_total", "counter", _GS,
       "Prefix restores from any tier (delta prefill instead of full "
       "re-prefill)."),
    _m("areal:kv_restore_host", "counter", _GS,
       "Restores served from the host-RAM tier."),
    _m("areal:kv_restore_disk", "counter", _GS,
       "Restores served from the disk tier."),
    _m("areal:kv_restore_tokens", "counter", _GS,
       "Tokens restored from tiers (re-prefill work avoided)."),
    _m("areal:last_kv_restore_ms", "gauge", _GS,
       "Duration of the last tier restore."),
    _m("areal:kv_prefix_lost_total", "counter", _GS,
       "Prefixes the tier FAILED to preserve — the residual true-loss "
       "count the tier exists to zero (chaos bench asserts 0)."),
    _m("areal:kv_tier_host_bytes", "gauge", _GS,
       "Bytes resident in the host-RAM tier."),
    _m("areal:kv_tier_disk_bytes", "gauge", _GS,
       "Bytes resident in the disk tier."),
    _m("areal:kv_tier_host_entries", "gauge", _GS,
       "Entries resident in the host-RAM tier."),
    _m("areal:kv_tier_disk_entries", "gauge", _GS,
       "Entries resident in the disk tier."),
    _m("areal:kv_tier_misses", "counter", _GS,
       "Tier lookups that found nothing (full re-prefill)."),
    _m("areal:kv_tier_corrupt_dropped", "counter", _GS,
       "Tier entries dropped on hash-verify failure at read-back."),
    _m("areal:kv_tier_peer_hits", "counter", _GS,
       "Restores served from a PEER's tier via the global prefix "
       "index (kv_source routing hint)."),
    _m("areal:kv_tier_peer_bytes", "counter", _GS,
       "Bytes fetched from peer tiers."),
    _m("areal:kv_tier_peer_failed", "counter", _GS,
       "Peer-tier fetches that failed (fell back to re-prefill)."),
    # -- elastic fleet (drain-then-leave, docs/fault_tolerance.md) -------
    _m("areal:draining", "gauge", _GS,
       "1.0 while drain-then-leave is quiescing this server."),
    _m("areal:kv_migrated_out", "counter", _GS,
       "Parked prefixes migrated to survivors during drain."),
    _m("areal:kv_drain_lost", "counter", _GS,
       "Prefixes lost during drain — the drain analogue of "
       "kv_prefix_lost_total; the elastic e2e pins it to 0."),
    _m("areal:kv_accepted", "counter", _GS,
       "Migrated prefixes this server accepted from a drainer."),
    _m("areal:kv_accept_bytes", "counter", _GS,
       "Bytes accepted from draining peers."),
    _m("areal:kv_manifests_served", "counter", _GS,
       "KV tier manifests served to peers (/kv/manifest)."),
    _m("areal:kv_chunks_served", "counter", _GS,
       "KV tier chunks served to peers (/kv/chunk)."),
    # -- prefix cache ----------------------------------------------------
    _m("areal:prefix_cache_hits", "counter", _GS,
       "Prefix-cache hits; affinity-routing numerator (fleet "
       "ratio-of-sums with total_requests)."),
    _m("areal:prefix_tokens_reused", "counter", _GS,
       "Prompt tokens served from cached prefixes."),
    _m("areal:prefix_cached_tokens", "counter", _GS,
       "Tokens currently parked in cached prefixes."),
    # -- speculative decoding --------------------------------------------
    _m("areal:spec_tokens_per_step", "gauge", _GS,
       "Mean emitted tokens per spec-decode step (per-server ratio; "
       "humans — fleet math uses the raw sums below)."),
    _m("areal:spec_emitted_tokens", "counter", _GS,
       "Raw spec-decode emitted-token sum (fleet yield numerator)."),
    _m("areal:spec_active_steps", "counter", _GS,
       "Raw spec-decode active-step sum (fleet yield denominator)."),
    # -- RPC substrate (base/rpc.py, docs/fault_tolerance.md) ------------
    _m("areal:rpc_attempts", "counter", _GS,
       "Outbound RPC attempts this process made through base/rpc.py "
       "(retries included)."),
    _m("areal:rpc_retries", "counter", _GS,
       "Attempts that were retries of a failed/shed predecessor."),
    _m("areal:rpc_failures", "counter", _GS,
       "Calls that exhausted their retry budget (includes each "
       "exhausted hedge LEG; see rpc_hedge_failures for whole races "
       "lost)."),
    _m("areal:rpc_hedges", "counter", _GS,
       "Secondary (hedge) requests launched after the primary went "
       "AREAL_RPC_HEDGE_DELAY_S without answering."),
    _m("areal:rpc_hedge_wins", "counter", _GS,
       "Races a hedge won — the rpc_resilience bench's proof that "
       "hedging, not luck, cut the tail."),
    _m("areal:rpc_hedge_cancelled", "counter", _GS,
       "Losing hedge legs cancelled/abandoned; their bytes are "
       "dropped, never double-counted into ingress/egress."),
    _m("areal:rpc_hedge_failures", "counter", _GS,
       "Whole hedged races lost (every leg failed), counted once per "
       "race — a transient leg failure inside a race the hedge won "
       "does NOT land here."),
    _m("areal:rpc_deadline_expired", "counter", _GS,
       "Calls short-circuited because the propagated X-Areal-Deadline "
       "budget was already spent (includes refusals before attempt "
       "1)."),
    _m("areal:rpc_breaker_rejections", "counter", _GS,
       "Attempts refused locally by an OPEN per-peer circuit "
       "breaker — budget saved, not failures."),
    _m("areal:rpc_breaker_opens", "counter", _GS,
       "closed->open (and failed-probe re-open) breaker transitions."),
    # -- pooled reward executor (system/reward_executor.py) --------------
    _m("areal:rexec_jobs_total", "counter",
       "system/reward_executor.py",
       "Sandboxed jobs completed (ok or failed) by this executor's "
       "warm worker pool; saturation-sweep throughput numerator."),
    _m("areal:rexec_job_failures", "counter",
       "system/reward_executor.py",
       "Jobs that returned failed (guarded exec raised, nonzero "
       "exit, rlimit kill) — episode-level failures, distinct from "
       "sheds."),
    _m("areal:rexec_timeouts", "counter",
       "system/reward_executor.py",
       "Jobs killed at their wall timeout (the one worker running "
       "the job is killed + respawned; the pool survives)."),
    _m("areal:rexec_shed_total", "counter",
       "system/reward_executor.py",
       "Submits shed with 429 + Retry-After past the bounded queue "
       "watermark. Deliberate backpressure, NOT failures — clients "
       "fail over or back off."),
    _m("areal:rexec_queue_depth", "gauge",
       "system/reward_executor.py",
       "Jobs pending or running on the pool right now; the "
       "saturation sweep's load signal."),
    _m("areal:rexec_workers_alive", "gauge",
       "system/reward_executor.py",
       "Warm sandbox workers currently alive in the pool."),
    _m("areal:rexec_worker_respawns", "counter",
       "system/reward_executor.py",
       "Worker respawns (timeout kill, crash, preventive recycle) "
       "since start; the warm-reuse test pins this at 0 under clean "
       "load."),
    _m("areal:rexec_warm_hits", "counter",
       "system/reward_executor.py",
       "Jobs served by an already-warm worker (no spawn on the job's "
       "critical path) — the pooled service's whole point; the bench "
       "asserts warm_hits/jobs ~ 1 after warmup."),
    # -- multi-tenant gateway (system/gateway.py, docs/serving.md) -------
    _m("areal:gw_requests_total", "counter", "system/gateway.py",
       "/v1 requests ADMITTED through auth + bucket + fair-share "
       "(completed or failed upstream); the tenant_fairness bench's "
       "throughput denominator."),
    _m("areal:gw_auth_failures_total", "counter", "system/gateway.py",
       "Requests refused 401 (missing/unknown API key, or the gw.auth "
       "chaos point firing in the key lookup)."),
    _m("areal:gw_shed_total", "counter", "system/gateway.py",
       "Requests shed 429 by a tenant's OWN token bucket or stream "
       "cap, Retry-After from that bucket. Deliberate per-tenant "
       "backpressure, NOT failures — the fleet never sees these."),
    _m("areal:gw_prompt_tokens_total", "counter", "system/gateway.py",
       "Prompt tokens metered across tenants (ledger grand total; "
       "/v1/usage carries the per-tenant split)."),
    _m("areal:gw_completion_tokens_total", "counter",
       "system/gateway.py",
       "Completion tokens metered across tenants, billed as emitted "
       "— a mid-stream failover resumes from the billed prefix, so "
       "retried chunks never double-count."),
    _m("areal:gw_active_streams", "gauge", "system/gateway.py",
       "Upstream SSE streams running right now (bounded by "
       "AREAL_GW_MAX_INFLIGHT)."),
    _m("areal:gw_queue_depth", "gauge", "system/gateway.py",
       "Admitted requests waiting in tenant fair-share queues."),
    _m("areal:gw_fairshare_picks_total", "counter",
       "system/gateway.py",
       "DRR dispatch decisions taken while 2+ tenant queues were "
       "nonempty — proof the fair-share queue actually arbitrated "
       "(validate_bench refuses tenant_fairness records where this "
       "never moved)."),
    _m("areal:gw_ttft_hist", "hist", "system/gateway.py",
       "Gateway-observed TTFT bucket counts across tenants "
       "(base/latency.py edges; per-tenant hists ride /v1/usage)."),
    _m("areal:gw_itl_hist", "hist", "system/gateway.py",
       "Gateway-observed inter-token latency bucket counts across "
       "tenants."),
    _m("areal:gw_upstream_failovers_total", "counter",
       "system/gateway.py",
       "Mid-stream server deaths survived by rerouting through the "
       "manager with the accumulated prefix (PR 14 discipline on the "
       "gateway->server hop)."),
    _m("areal:gw_usage_replayed_total", "counter",
       "system/gateway.py",
       "Usage-WAL records replayed into the ledger at gateway "
       "restart."),
    _m("areal:gw_usage_dup_dropped_total", "counter",
       "system/gateway.py",
       "Usage records dropped at replay/append because their request "
       "id was already accounted — the exactly-once ledger doing its "
       "job across restarts."),
    _m("areal:gw_model_rejections_total", "counter",
       "system/gateway.py",
       "Requests refused at model resolution: 404 (model unknown to "
       "the registry) or 403 (tenant not entitled to it). Neither "
       "reaches the fleet; distinct from auth failures and sheds."),
    _m("areal:gw_usage_compactions_total", "counter",
       "system/gateway.py",
       "Usage-WAL compactions: every AREAL_GW_USAGE_COMPACT_EVERY "
       "billing records the journal folds into one aggregated "
       "per-tenant record, bounding disk, replay time, and the "
       "request-id dedup set for long-lived gateways."),
    # ====================================================================
    # perf/* — stats_tracker scalar keys (worker -> master MFC stats
    # payloads; master_worker perf history + bench workloads).
    # ====================================================================
    _m("perf/sec", "scalar", "system/model_worker.py",
       "Wall seconds of the MFC on this worker.", reduce="max"),
    _m("perf/elapsed", "scalar", "system/model_function_call.py",
       "Aggregated MFC wall seconds (slowest worker) — becomes "
       "timeperf/<mfc> in the master's history.", reduce="max"),
    _m("perf/flops", "scalar", "system/model_worker.py",
       "Analytic FLOP count of the MFC (monitor.mfc_flops).",
       reduce="sum"),
    _m("perf/tflops", "scalar", "system/model_function_call.py",
       "flops/elapsed/1e12, computed at aggregation.",
       reduce="derived"),
    _m("perf/gen_tokens", "scalar", "system/model_worker.py",
       "New tokens generated by a generate MFC (group-sampling "
       "replicas subtracted).", reduce="sum"),
    _m("perf/gen_tokens_per_sec", "scalar",
       "system/model_function_call.py",
       "gen_tokens/elapsed, computed at aggregation.",
       reduce="derived"),
    _m("perf/packing_efficiency", "scalar", "engine/jax_engine.py",
       "Realized token/cell density of what shipped to HBM (FFD "
       "fallback for non-packed paths).", reduce="avg"),
    _m("perf/h2d_wait_ms", "scalar", "engine/jax_engine.py",
       "Host-to-device staging wait per step; MAX across DP workers "
       "— the step blocks on the slowest, averaging understates.",
       reduce="max"),
    _m("perf/dispatch_gap_ms", "scalar", "engine/jax_engine.py",
       "Gap between microbatch dispatches (prefetch pipeline bubble).",
       reduce="max"),
    _m("perf/overlap_events", "scalar", "engine/jax_engine.py",
       "Microbatches staged during a previous step's compute (the "
       "prefetch-overlap bench's engagement proof).", reduce="sum"),
    # MoE router telemetry (engine/jax_engine._record_moe_stats; per-MFC
    # fold in master_worker perf_summary, bench JSON passthrough).
    _m("perf/moe_drop_rate", "scalar", "engine/jax_engine.py",
       "Realized fraction of routed (token, expert) assignments dropped "
       "by capacity buckets this step; exactly 0 on dropless arms.",
       reduce="avg"),
    _m("perf/moe_router_entropy", "scalar", "engine/jax_engine.py",
       "Mean per-token router-softmax entropy (nats). Collapse toward "
       "0 means the router funnels everything to few experts.",
       reduce="avg"),
    _m("perf/moe_expert_overload", "scalar", "engine/jax_engine.py",
       "max_e(f_e) * E — hottest expert's token share relative to the "
       "uniform ideal (1.0 = perfectly balanced). MAX across DP "
       "workers: the hottest shard bounds the step.", reduce="max"),
    _m("perf/moe_a2a_bytes", "scalar", "engine/jax_engine.py",
       "Trace-time estimate of bytes exchanged by the expert-parallel "
       "dispatch per step (0 at EP1); SUM accumulates the window "
       "total.", reduce="sum"),
    _m("perf/rollout_e2e_p50_ms", "scalar",
       "system/model_function_call.py",
       "Rollout end-to-end p50 from RL spans.", reduce="max"),
    _m("perf/rollout_e2e_p95_ms", "scalar",
       "system/model_function_call.py",
       "Rollout end-to-end p95 from RL spans.", reduce="max"),
    _m("perf/reprefill_tokens", "scalar",
       "system/model_function_call.py",
       "Tokens re-prefilled after interrupts this MFC.", reduce="sum"),
    # Multi-turn episode telemetry (trajectory metadata stamped by the
    # agents, folded at MFC aggregation like rollout_e2e above).
    _m("perf/episode_turns", "scalar",
       "system/model_function_call.py",
       "Agent turns across the episodes consumed by this train MFC.",
       reduce="sum"),
    _m("perf/episode_tool_calls", "scalar",
       "system/model_function_call.py",
       "Tool invocations (executor-pool python exec, calculator, "
       "search) across the consumed episodes.", reduce="sum"),
    _m("perf/task_staleness_math", "scalar",
       "system/model_function_call.py",
       "Mean version lag (train step - version_end) of consumed "
       "samples tagged task=math — the tight per-task window.",
       reduce="max"),
    _m("perf/task_staleness_agentic", "scalar",
       "system/model_function_call.py",
       "Mean version lag of consumed samples tagged task=agentic — "
       "the loose window (multi-turn episodes live longer).",
       reduce="max"),
    _m("perf/task_stale_dropped_math", "scalar",
       "system/model_function_call.py",
       "Samples tagged task=math dropped at buffer admission by the "
       "math staleness window since the last train step — the "
       "per-task split of areal:train_stale_dropped_total.",
       reduce="sum"),
    _m("perf/task_stale_dropped_agentic", "scalar",
       "system/model_function_call.py",
       "Samples tagged task=agentic dropped at buffer admission by "
       "the agentic staleness window since the last train step.",
       reduce="sum"),
    # HBM telemetry (monitor.device_memory_stats, shipped per MFC by
    # model_worker through perf_mem_stats below).
    _m("perf/mem_bytes_in_use", "scalar", "base/monitor.py",
       "Device bytes in use, summed over local devices.",
       reduce="max"),
    _m("perf/mem_bytes_limit", "scalar", "base/monitor.py",
       "Device byte limit, summed over local devices.", reduce="max"),
    _m("perf/mem_peak_bytes_in_use", "scalar", "base/monitor.py",
       "Peak device bytes in use.", reduce="max"),
    _m("perf/mem_frac_in_use", "scalar", "base/monitor.py",
       "in_use/limit fraction (the OOM-guard input).", reduce="max"),
    _m("perf/mem_devices_reporting", "scalar", "base/monitor.py",
       "Local devices that reported memory stats.", reduce="max"),
    # Durable training plane (rollout WAL + exactly-once ledger +
    # async checkpoint). The two headline invariant counters are
    # expected to read 0 — the kill-anywhere e2e asserts exactly that.
    _m("areal:train_samples_lost_total", "counter",
       "system/push_pull_stream.py",
       "Pushed samples dropped after exhausting the redelivery budget "
       "(AREAL_WAL_REDELIVER_MAX). 0 under the default unbounded "
       "budget — the exactly-once invariant."),
    _m("areal:train_samples_duplicated_total", "counter",
       "system/buffer.py",
       "Samples DETECTED entering training twice (a sequence id "
       "consumed again after the ledger marked it). A defensive "
       "invariant detector, not a dedup count: redeliveries/replays "
       "the ledger filters at admission are counted separately "
       "(areal:train_wal_dup_dropped_total). Expected 0."),
    _m("areal:train_wal_replayed_total", "counter",
       "system/stream_dataset.py",
       "WAL records replayed into the stream dataset at restart "
       "(in-flight rollouts that survived a trainer kill)."),
    _m("areal:train_wal_dup_dropped_total", "counter",
       "system/stream_dataset.py",
       "Redelivered/replayed samples dropped at admission because "
       "their sequence id was already journaled or consumed — the "
       "ledger doing its job (each drop is a prevented duplicate)."),
    _m("areal:train_stale_dropped_total", "counter",
       "system/buffer.py",
       "Samples dropped at buffer admission because their task's "
       "staleness window (AREAL_TASK_STALENESS_WINDOWS) was exceeded "
       "— per-task admission on top of the gserver manager's global "
       "allocation gate."),
    _m("areal:train_ckpt_stall_ms", "gauge", "engine/checkpoint.py",
       "Step-loop stall of the most recent engine checkpoint: full "
       "save duration when synchronous, reference-snapshot handoff "
       "only when AREAL_CKPT_ASYNC routes the write off-thread (the "
       "recovery_slo bench A/Bs the two)."),
]

REGISTRY: Dict[str, Metric] = {m.name: m for m in _METRICS}
assert len(REGISTRY) == len(_METRICS), "duplicate metric declaration"


def const_name(name: str) -> str:
    """Deterministic constant identifier for a metric name:
    ``areal:num_used_tokens`` -> ``NUM_USED_TOKENS``,
    ``perf/h2d_wait_ms`` -> ``PERF_H2D_WAIT_MS``."""
    if name.startswith(AREAL_PREFIX):
        return name[len(AREAL_PREFIX):].upper()
    if name.startswith(PERF_PREFIX):
        return "PERF_" + name[len(PERF_PREFIX):].upper()
    raise ValueError(f"metric {name!r} outside both namespaces")


# Bind one module constant per entry (NUM_USED_TOKENS = "areal:...").
# Parse sites reference these instead of literals; the metrics-registry
# checker verifies `metrics_registry.X` attributes resolve here.
CONSTANTS: Dict[str, str] = {}
for _metric in _METRICS:
    _c = const_name(_metric.name)
    assert _c not in CONSTANTS, f"constant collision: {_c}"
    CONSTANTS[_c] = _metric.name
    globals()[_c] = _metric.name
del _metric, _c


def parse_line(line: str) -> Optional[Tuple[str, str]]:
    """Split one ``/metrics`` text line into (declared name, value
    text). Returns None for blank/unknown lines. Exact name match —
    immune to the startswith prefix-ambiguity class the lint checker
    flags."""
    name, _, value = line.strip().partition(" ")
    if name in REGISTRY:
        return name, value
    return None


def perf_mem_stats(mem: Dict[str, float]) -> Dict[str, float]:
    """Prefix monitor.device_memory_stats() keys into declared
    ``perf/mem_*`` scalars. The one legal dynamic build of a perf key
    — anywhere else the metrics-registry checker flags f-string-built
    names; here every output key is validated against the registry."""
    out = {}
    for k, v in mem.items():
        name = f"{PERF_PREFIX}{k}"
        if name not in REGISTRY:
            raise KeyError(
                f"{name} is not declared in "
                f"areal_tpu.base.metrics_registry; declare it (name, "
                f"kind, emitter, doc) — the metrics-registry lint "
                f"checker enforces this"
            )
        out[name] = v
    return out


def render_docs() -> str:
    """Markdown for docs/metrics.md — generated, drift-gated; never
    hand-edit the output file."""
    lines = [
        "# Cross-process metric names",
        "",
        "<!-- GENERATED FILE — do not edit. Source of truth: "
        "areal_tpu/base/metrics_registry.py. Regenerate with: "
        "python scripts/areal_lint.py --emit-metrics-docs "
        "docs/metrics.md -->",
        "",
        "Every `areal:*` /metrics line and every `perf/*` "
        "stats_tracker scalar key that crosses a process boundary, "
        "generated from the registry the `metrics-registry` lint "
        "checker enforces. Counters are monotonic since process start "
        "(consumers diff; they never reset). `hist` lines carry "
        "sparse `i:count` buckets over base/latency.py edges — fleet "
        "aggregation merges counts, never averages percentiles.",
        "",
        "## `areal:*` — generation-server /metrics lines",
        "",
        "| Name | Kind | Description |",
        "|---|---|---|",
    ]
    areal = [m for m in _METRICS if m.name.startswith(AREAL_PREFIX)]
    perf = [m for m in _METRICS if m.name.startswith(PERF_PREFIX)]
    for m in sorted(areal, key=lambda m: m.name):
        doc = m.doc.replace("|", "\\|")
        lines.append(f"| `{m.name}` | {m.kind} | {doc} |")
    lines += [
        "",
        "## `perf/*` — stats_tracker scalar keys (worker → master)",
        "",
        "| Name | Reduce | Emitter | Description |",
        "|---|---|---|---|",
    ]
    for m in sorted(perf, key=lambda m: m.name):
        doc = m.doc.replace("|", "\\|")
        lines.append(
            f"| `{m.name}` | {m.reduce} | `{m.emitter}` | {doc} |"
        )
    lines.append("")
    return "\n".join(lines)

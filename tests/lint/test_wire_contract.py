"""wire-contract checker fixtures: seeded violations prove each rule
fires; exempt-pattern negatives prove the harvest heuristics don't
swallow filesystem joins or non-wire modules. AST-only, no aiohttp."""

import textwrap

from areal_tpu.lint.runner import LintConfig, run_lint
from areal_tpu.lint.wire_contract import RouteSpec, WireConfig

SRV = "srv.py"

_CFG_ROUTES = {
    ("POST", "/generate"): RouteSpec((SRV,), (429,), False),
    ("GET", "/metrics"): RouteSpec((SRV,), (), False),
    ("GET", "/health"): RouteSpec((SRV,), (), True),  # operator
}


def _cfg(registry_rel="wire_routes.py"):
    return WireConfig(routes=dict(_CFG_ROUTES), registry_rel=registry_rel)


def _lint(tmp_path, source, *, name=SRV, cfg=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    lint_cfg = LintConfig(
        root=str(tmp_path), wire_cfg=cfg or _cfg(),
        checkers={"wire-contract"},
    )
    return run_lint([str(p)], lint_cfg)


def test_undeclared_route_registration_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def routes(app, h):
            app.router.add_post("/generate", h)
            app.router.add_get("/totally_new", h)
    """)
    assert len(findings) == 1
    assert "GET /totally_new" in findings[0].message


def test_unknown_client_path_flagged(tmp_path):
    findings = _lint(tmp_path, """
        async def go(sess, url):
            async with sess.post(f"{url}/genrate", json={}) as r:
                pass
    """)
    assert len(findings) == 1
    assert "/genrate" in findings[0].message


def test_method_mismatch_flagged(tmp_path):
    findings = _lint(tmp_path, """
        async def go(sess, url):
            async with sess.get(f"{url}/generate") as r:
                pass
    """)
    assert len(findings) == 1
    assert "GET /generate" in findings[0].message
    assert "POST" in findings[0].message


def test_fs_join_not_harvested(tmp_path):
    # Neither a URL-ish receiver nor an HTTP call: must not be treated
    # as a wire path even though it looks like one.
    findings = _lint(tmp_path, """
        def save(base_dir, name):
            return f"{base_dir}/checkpoints/{name}"
    """)
    assert findings == []


def test_dict_get_with_slash_fstring_not_harvested(tmp_path):
    # ``.get``/``.post`` on a non-session, non-URL receiver is not an
    # HTTP verb: dict lookups and name_resolve keys (which ARE
    # slash-separated) must not trip the wire gate.
    findings = _lint(tmp_path, """
        from areal_tpu.base import name_resolve

        def look(mapping, root, key):
            a = mapping.get(f"{key}/lease")
            b = name_resolve.get(f"{root}/lease")
            return a, b
    """)
    assert findings == []


def test_concat_and_helper_refs_clean(tmp_path):
    findings = _lint(tmp_path, """
        import urllib.request

        def _post(url, path, payload):
            return (url, path, payload)

        def go(url):
            urllib.request.urlopen(url + "/metrics")
            _post(url, "/generate", {})
    """)
    assert findings == []


def test_client_unknown_status_flagged(tmp_path):
    findings = _lint(tmp_path, """
        async def go(sess, url):
            async with sess.post(f"{url}/generate", json={}) as r:
                if r.status == 429:
                    pass  # declared: clean
                if r.status == 418:
                    pass  # no route declares 418
    """)
    assert len(findings) == 1
    assert "418" in findings[0].message


def test_status_check_skipped_off_wire(tmp_path):
    # A module referencing no declared path is not a wire client; its
    # status comparisons (e.g. subprocess returncodes) are none of our
    # business.
    findings = _lint(tmp_path, """
        def check(proc):
            return proc.status == 418
    """)
    assert findings == []


def test_server_undeclared_status_flagged(tmp_path):
    findings = _lint(tmp_path, """
        from aiohttp import web

        def routes(app, h):
            app.router.add_post("/generate", h)

        async def h(request):
            return web.json_response({}, status=409)
    """)
    assert len(findings) == 1
    assert "status 409" in findings[0].message


def test_server_declared_and_implicit_statuses_clean(tmp_path):
    findings = _lint(tmp_path, """
        from aiohttp import web

        def routes(app, h):
            app.router.add_post("/generate", h)

        async def h(request):
            if bad(request):
                return web.json_response({}, status=429)
            return web.json_response({}, status=200 if ok(request) else 500)
    """)
    assert findings == []


_REGISTRY_FIXTURE = """
    def _r(method, path):
        return (method, path)

    _ROUTES = [
        _r("POST", "/generate"),
        _r("GET", "/metrics"),
        _r("GET", "/health"),
    ]
"""


def _global_lint(tmp_path, server_src, client_src=None):
    (tmp_path / "wire_routes.py").write_text(
        textwrap.dedent(_REGISTRY_FIXTURE)
    )
    (tmp_path / SRV).write_text(textwrap.dedent(server_src))
    if client_src is not None:
        (tmp_path / "client.py").write_text(textwrap.dedent(client_src))
    lint_cfg = LintConfig(
        root=str(tmp_path), wire_cfg=_cfg(), checkers={"wire-contract"},
    )
    return run_lint([str(tmp_path)], lint_cfg)


def test_global_dead_and_unregistered_routes(tmp_path):
    # /generate registered + called; /metrics registered, never called
    # (dead); /health never called but operator (exempt); 429 declared
    # but never emitted (stale status). The registry fixture anchors
    # finding lines.
    findings = _global_lint(tmp_path, """
        def routes(app, h):
            app.router.add_post("/generate", h)
            app.router.add_get("/metrics", h)
            app.router.add_get("/health", h)
    """, """
        async def go(sess, url):
            async with sess.post(f"{url}/generate", json={}) as r:
                pass
    """)
    msgs = [f.message for f in findings]
    assert any("dead route GET /metrics" in m for m in msgs)
    assert any("declares status 429" in m for m in msgs)
    assert not any("/health" in m for m in msgs)  # operator exempt
    assert len(findings) == 2


def test_global_never_registered(tmp_path):
    findings = _global_lint(tmp_path, """
        from aiohttp import web

        def routes(app, h):
            app.router.add_post("/generate", h)
            app.router.add_get("/health", h)

        async def h(request):
            return web.json_response({}, status=429)
    """, """
        async def go(sess, url):
            async with sess.post(f"{url}/generate", json={}) as r:
                pass
            async with sess.get(f"{url}/metrics") as r:
                pass
    """)
    msgs = [f.message for f in findings]
    assert any(
        "GET /metrics declared but never registered" in m for m in msgs
    )
    assert len(findings) == 1


def test_dead_route_is_method_exact(tmp_path):
    # A POST-only client must not keep a clientless GET twin of the
    # same path alive; a verb-unknown ref (path= kwarg) keeps both.
    dual = {
        ("POST", "/flip"): RouteSpec((SRV,), (), False),
        ("GET", "/flip"): RouteSpec((SRV,), (), False),
    }
    (tmp_path / "wire_routes.py").write_text(
        textwrap.dedent(_REGISTRY_FIXTURE)
    )
    (tmp_path / SRV).write_text(textwrap.dedent("""
        def routes(app, h):
            app.router.add_post("/flip", h)
            app.router.add_get("/flip", h)
    """))
    (tmp_path / "client.py").write_text(textwrap.dedent("""
        async def go(sess, url):
            async with sess.post(f"{url}/flip", json={}) as r:
                pass
    """))
    cfg = WireConfig(routes=dual, registry_rel="wire_routes.py")
    lint_cfg = LintConfig(
        root=str(tmp_path), wire_cfg=cfg, checkers={"wire-contract"},
    )
    findings = run_lint([str(tmp_path)], lint_cfg)
    msgs = [f.message for f in findings]
    assert any("dead route GET /flip" in m for m in msgs)
    assert not any("dead route POST /flip" in m for m in msgs)

    # Same tree plus a verb-unknown path= ref: both verbs stay alive.
    (tmp_path / "client.py").write_text(textwrap.dedent("""
        async def go(sess, url):
            async with sess.post(f"{url}/flip", json={}) as r:
                pass

        def probe(fetch):
            return fetch(path="/flip")
    """))
    cfg = WireConfig(routes=dict(dual), registry_rel="wire_routes.py")
    lint_cfg = LintConfig(
        root=str(tmp_path), wire_cfg=cfg, checkers={"wire-contract"},
    )
    assert run_lint([str(tmp_path)], lint_cfg) == []


def test_subset_scan_skips_global_pass(tmp_path):
    # Without the registry module in the scan, no dead-route noise.
    findings = _lint(tmp_path, """
        def routes(app, h):
            app.router.add_post("/generate", h)
    """)
    assert findings == []

"""Segment-aware blocked flash attention for packed varlen batches (Pallas/TPU).

TPU-native replacement for the reference's flash-attn varlen kernels
(realhf/impl/model/modules/attn.py:272-289): instead of cu_seqlens, a packed
token stream carries *segment ids* (0 = padding) and within-sequence
positions. The kernel computes online-softmax attention over (block_q,
block_k) tiles with two kinds of tile skipping:

- causal skip: tile (i, j) is skipped when every kv index in j exceeds every
  q index in i (valid because sequences are packed contiguously with
  ascending positions, so position-causality implies stream-causality);
- masking inside live tiles uses (same segment) & (q_pos >= kv_pos).

GQA is handled by gridding over q heads and indexing the shared kv head
(h // group) in the BlockSpec index map; the dkv backward grids over kv
heads and accumulates the whole group in scratch so dk/dv HBM traffic is
[Hkv, T, d], not [Hq, T, d]. head_dim is zero-padded to a lane multiple (128).

Forward saves the logsumexp rows; backward recomputes probabilities per
tile (standard flash backward) with two kernels: dq (grid over q tiles,
inner loop kv) and dkv (grid over kv tiles, inner loop q).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.utils.jax_compat import tpu_compiler_params

NEG_INF = -1e30
LANES = 128


def _block_size(t: int, preferred: int = 512) -> int:
    b = preferred
    while b >= LANES:
        if t % b == 0:
            return b
        b //= 2
    raise ValueError(f"sequence length {t} is not a multiple of {LANES}")


def _pad_head_dim(x: jnp.ndarray) -> jnp.ndarray:
    d = x.shape[-1]
    dp = ((d + LANES - 1) // LANES) * LANES
    if dp == d:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, dp - d)]
    return jnp.pad(x, pad)


def _tile_mask(qseg, kseg, qpos, kpos):
    """[bq, bk] boolean validity mask from (1, b)-shaped ref reads."""
    qs = qseg.reshape(-1, 1)
    ks = kseg.reshape(1, -1)
    qp = qpos.reshape(-1, 1)
    kp = kpos.reshape(1, -1)
    return (qs == ks) & (qp >= kp) & (qs > 0)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    qseg_ref, kseg_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
    out_ref, lse_ref, m_s, l_s, acc_s, *, scale, bq, bk,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    j_last = ((i + 1) * bq - 1) // bk

    @pl.when(j <= j_last)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_mask(qseg_ref[:], kseg_ref[:], qpos_ref[:], kpos_ref[:])
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_s[:, :1]  # [bq, 1]
        row_max = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)  # [bq, bk] f32
        l_new = alpha * l_s[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_s[:] = acc_s[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(j == j_last)
    def _finalize():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_s[:] / safe_l).astype(out_ref.dtype)
        lse_ref[0, 0] = (m_s[:, :1] + jnp.log(safe_l))[:, 0]


def _fwd(scale, interpret, group, q, k, v, seg, pos):
    """q: [Hq, T, dp], k/v: [Hkv, T, dp], seg/pos: [1, T] -> (out, lse)."""
    hq, t, dp = q.shape
    bq = _block_size(t)
    bk = _block_size(t)
    grid = (hq, t // bq, t // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq), lambda h, i, j: (0, i)),
            pl.BlockSpec((1, bk), lambda h, i, j: (0, j)),
            pl.BlockSpec((1, bq), lambda h, i, j: (0, i)),
            pl.BlockSpec((1, bk), lambda h, i, j: (0, j)),
            pl.BlockSpec((1, bq, dp), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, dp), lambda h, i, j: (h // group, j, 0)),
            pl.BlockSpec((1, bk, dp), lambda h, i, j: (h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda h, i, j: (h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((hq, t, dp), q.dtype),
            jax.ShapeDtypeStruct((hq, 1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seg, seg, pos, pos, q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    qseg_ref, kseg_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
    dout_ref, lse_ref, delta_ref, dq_ref, dq_s, *, scale, bq, bk,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    j_last = ((i + 1) * bq - 1) // bk

    @pl.when(j <= j_last)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_mask(qseg_ref[:], kseg_ref[:], qpos_ref[:], kpos_ref[:])
        lse = lse_ref[0].reshape(-1, 1)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dout = dout_ref[0]
        dp = jax.lax.dot_general(
            dout, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0].reshape(-1, 1)
        ds = p * (dp - delta) * scale  # [bq, bk] f32
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == j_last)
    def _finalize():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(
    qseg_ref, kseg_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
    dout_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s,
    *, scale, bq, bk, nq,
):
    # Grid: (Hkv, kv tiles, group * q tiles). The inner dimension walks
    # (g, i) pairs so dk/dv accumulate over the whole GQA group in scratch
    # and are written once per kv head — [Hkv, T, dp] HBM traffic, not
    # [Hq, T, dp].
    j = pl.program_id(1)  # kv tile
    c = pl.program_id(2)  # g * nq + i
    nc = pl.num_programs(2)
    i = c % nq

    i_first = (j * bk) // bq

    @pl.when(c == i_first)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    @pl.when(i >= i_first)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_mask(qseg_ref[:], kseg_ref[:], qpos_ref[:], kpos_ref[:])
        lse = lse_ref[0].reshape(-1, 1)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dout = dout_ref[0]
        # dv += p^T @ dout
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(dout.dtype), dout, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dout, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0].reshape(-1, 1)
        ds = p * (dp - delta) * scale  # [bq, bk]
        # dk += ds^T @ q
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(c == nc - 1)
    def _finalize():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _bwd(scale, interpret, group, q, k, v, seg, pos, out, lse, dout):
    hq, t, dp = q.shape
    bq = _block_size(t)
    bk = _block_size(t)
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[:, None, :]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk),
        grid=(hq, t // bq, t // bk),
        in_specs=[
            pl.BlockSpec((1, bq), lambda h, i, j: (0, i)),
            pl.BlockSpec((1, bk), lambda h, i, j: (0, j)),
            pl.BlockSpec((1, bq), lambda h, i, j: (0, i)),
            pl.BlockSpec((1, bk), lambda h, i, j: (0, j)),
            pl.BlockSpec((1, bq, dp), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, dp), lambda h, i, j: (h // group, j, 0)),
            pl.BlockSpec((1, bk, dp), lambda h, i, j: (h // group, j, 0)),
            pl.BlockSpec((1, bq, dp), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda h, i, j: (h, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda h, i, j: (h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, dp), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((hq, t, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dp), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seg, seg, pos, pos, q, k, v, dout, lse, delta)

    # dk/dv accumulated over the GQA group inside the kernel (grid walks
    # (g, i) pairs in its inner dimension); outputs are [Hkv, T, dp].
    nq = t // bq
    hkv = hq // group
    qh = lambda hk, c: hk * group + c // nq
    qi = lambda c: c % nq
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk, nq=nq),
        grid=(hkv, t // bk, group * nq),
        in_specs=[
            pl.BlockSpec((1, bq), lambda hk, j, c: (0, qi(c))),
            pl.BlockSpec((1, bk), lambda hk, j, c: (0, j)),
            pl.BlockSpec((1, bq), lambda hk, j, c: (0, qi(c))),
            pl.BlockSpec((1, bk), lambda hk, j, c: (0, j)),
            pl.BlockSpec((1, bq, dp), lambda hk, j, c: (qh(hk, c), qi(c), 0)),
            pl.BlockSpec((1, bk, dp), lambda hk, j, c: (hk, j, 0)),
            pl.BlockSpec((1, bk, dp), lambda hk, j, c: (hk, j, 0)),
            pl.BlockSpec((1, bq, dp), lambda hk, j, c: (qh(hk, c), qi(c), 0)),
            pl.BlockSpec((1, 1, bq), lambda hk, j, c: (qh(hk, c), 0, qi(c))),
            pl.BlockSpec((1, 1, bq), lambda hk, j, c: (qh(hk, c), 0, qi(c))),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dp), lambda hk, j, c: (hk, j, 0)),
            pl.BlockSpec((1, bk, dp), lambda hk, j, c: (hk, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((hkv, t, dp), q.dtype),
            jax.ShapeDtypeStruct((hkv, t, dp), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dp), jnp.float32),
            pltpu.VMEM((bk, dp), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seg, seg, pos, pos, q, k, v, dout, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_core(scale, interpret, group, q, k, v, seg, pos):
    out, _ = _fwd(scale, interpret, group, q, k, v, seg, pos)
    return out


def _flash_core_fwd(scale, interpret, group, q, k, v, seg, pos):
    out, lse = _fwd(scale, interpret, group, q, k, v, seg, pos)
    return out, (q, k, v, seg, pos, out, lse)


def _flash_core_bwd(scale, interpret, group, res, dout):
    q, k, v, seg, pos, out, lse = res
    dq, dk, dv = _bwd(scale, interpret, group, q, k, v, seg, pos, out, lse, dout)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_packed_attention(
    q: jnp.ndarray,  # [T, Hq, hd]
    k: jnp.ndarray,  # [T, Hkv, hd]
    v: jnp.ndarray,  # [T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [T] int32, 0 = padding
    positions: jnp.ndarray,  # [T] int32
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    t, hq, hd = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    scale = float(softmax_scale) if softmax_scale is not None else hd**-0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    qt = _pad_head_dim(q.transpose(1, 0, 2))
    kt = _pad_head_dim(k.transpose(1, 0, 2))
    vt = _pad_head_dim(v.transpose(1, 0, 2))
    seg = segment_ids.reshape(1, t).astype(jnp.int32)
    pos = positions.reshape(1, t).astype(jnp.int32)

    out = _flash_core(scale, bool(interpret), group, qt, kt, vt, seg, pos)
    return out[..., :hd].transpose(1, 0, 2)

"""Worker runtime: streams, buffer, workers, data plane.

Counterpart of the reference's system layer (realhf/system/). The worker
roles and the metadata-only control plane are kept; the GPU data plane is
replaced by host-side numpy transfer + on-device resharding inside the
JAX engines (reference: realhf/system/__init__.py:17-23).
"""

import importlib

# worker type -> (module, class); grown as worker roles are implemented.
_WORKER_CLASSES = {
    "master_worker": ("areal_tpu.system.master_worker", "MasterWorker"),
    "model_worker": ("areal_tpu.system.model_worker", "ModelWorker"),
    "rollout_worker": ("areal_tpu.system.rollout_worker", "RolloutWorker"),
    "gserver_manager": ("areal_tpu.system.gserver_manager", "GserverManager"),
    "generation_server": ("areal_tpu.system.generation_server", "GenerationServer"),
}

WORKER_TYPES = sorted(_WORKER_CLASSES)


def load_worker(worker_type: str):
    """Resolve a worker type name to its class (lazy import).

    Accepts either a registered role name or a fully-qualified
    "module.path:ClassName" spec — the latter lets harnesses (e.g. the
    chaos suite) run custom Worker subclasses under the real controller
    without registering a production role."""
    if ":" in worker_type:
        module, cls = worker_type.split(":", 1)
        return getattr(importlib.import_module(module), cls)
    if worker_type not in _WORKER_CLASSES:
        raise ValueError(
            f"unknown worker type {worker_type!r}; available: {WORKER_TYPES}"
        )
    module, cls = _WORKER_CLASSES[worker_type]
    return getattr(importlib.import_module(module), cls)

"""Experiment runner with a fault-tolerant relaunch loop.

Counterpart of the reference's launcher (realhf/apps/main.py:77-289 +
training/utils.py): run the experiment via the LocalController; on
worker/master failure, relaunch with recover_mode=auto up to
`recover_retries` times, resuming from the last recover checkpoint.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from typing import Optional, Type

from areal_tpu.api.cli_args import apply_overrides
from areal_tpu.base import constants, logging, name_resolve
from areal_tpu.experiments import make_experiment
from areal_tpu.system.controller import LocalController

logger = logging.getLogger("launcher")


def parse_args(cfg_cls: Type, argv=None):
    parser = argparse.ArgumentParser(
        description=f"areal_tpu launcher ({cfg_cls.__name__}). "
        "Overrides: dotted key=value pairs, e.g. actor.path=/ckpt lr=1e-5",
    )
    parser.add_argument("overrides", nargs="*", help="a.b.c=value overrides")
    args = parser.parse_args(argv)
    cfg = cfg_cls()
    apply_overrides(cfg, args.overrides)
    return cfg


def run_experiment(experiment_type: str, cfg, worker_env: Optional[dict] = None) -> dict:
    """Build + run, relaunching with recovery on failure
    (reference apps/main.py:236-289)."""
    name_resolve_cfg = {"backend": cfg.name_resolve_backend}
    if cfg.name_resolve_root:
        name_resolve_cfg["record_root"] = cfg.name_resolve_root
    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)

    # Propagate a JAX platform override into the worker bootstrap: env
    # vars alone don't stick in spawned children (this environment's
    # sitecustomize imports jax before user env takes effect), so the
    # controller must jax.config.update in each worker — which it only
    # does for platforms named in worker_env.
    worker_env = dict(worker_env or {})
    import os as _os

    if _os.environ.get("JAX_PLATFORMS") and "JAX_PLATFORMS" not in worker_env:
        worker_env["JAX_PLATFORMS"] = _os.environ["JAX_PLATFORMS"]

    attempt = 0
    while True:
        exp_cfg = make_experiment(experiment_type, cfg)
        ctl = LocalController(
            exp_cfg, name_resolve_cfg=name_resolve_cfg, worker_env=worker_env
        )
        try:
            return ctl.run()
        except Exception:
            attempt += 1
            if cfg.recover_mode == "disabled" or attempt > cfg.recover_retries:
                raise
            logger.exception(
                f"experiment failed; relaunching with recovery "
                f"(attempt {attempt}/{cfg.recover_retries})"
            )
            cfg.recover_mode = "auto"
            time.sleep(2)


def main(experiment_type: str, cfg_cls: Type, argv=None):
    cfg = parse_args(cfg_cls, argv)
    result = run_experiment(experiment_type, cfg)
    logger.info(f"experiment finished: {result}")
    return result

"""Single-step math/code RL agent.

Counterpart of the reference's math single-step agent
(realhf/impl/agent/math_single_step_agent.py:44-248): one prompt -> one
group of generations -> verifier rewards -> one trajectory sample. The
obs/act queue protocol is kept: the agent never talks HTTP itself.
Degenerate groups (success rate outside [lb, ub]) are dropped
(reference :95-103).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import numpy as np

from areal_tpu.api.agent_api import Agent, register_agent
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.env_api import EnvironmentService
from areal_tpu.api.model_api import BundledGenerationOutputs, GenerationHyperparameters
from areal_tpu.base import logging

logger = logging.getLogger("math_agent")


class MathSingleStepAgent(Agent):
    def __init__(
        self,
        gconfig: Optional[GenerationHyperparameters] = None,
        tokenizer: Any = None,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
        correct_reward: float = 5.0,
        wrong_reward: float = -5.0,
        success_rate_lb: float = 0.0,
        success_rate_ub: float = 1.0,
        **gconfig_kwargs,
    ):
        if gconfig is None:
            gconfig = GenerationHyperparameters(**gconfig_kwargs)
        elif isinstance(gconfig, dict):
            gconfig = GenerationHyperparameters(**gconfig)
        self.gconfig = gconfig
        self.tokenizer = tokenizer
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias
        self.correct_reward = correct_reward
        self.wrong_reward = wrong_reward
        self.success_rate_lb = success_rate_lb
        self.success_rate_ub = success_rate_ub

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        assert prompt.bs == 1
        qid = prompt.ids[0]
        prompt_ids = np.asarray(prompt.data["packed_prompts"]).tolist()
        await obs_queue.put((qid, prompt_ids, self.gconfig))
        bundle: BundledGenerationOutputs = await act_queue.get()

        task = (prompt.metadata.get("tasks") or ["math"])[0]
        answer_info = (prompt.metadata.get("solutions") or [None])[0]
        answers = [
            self.tokenizer.decode(seq[bundle.prompt_len:])
            for seq in bundle.seqs
        ]
        successes, *_ = await env.step((qid, answers, task, answer_info))

        sr = float(np.mean(successes)) if successes else 0.0
        if not (self.success_rate_lb <= sr <= self.success_rate_ub):
            logger.debug(f"{qid}: degenerate group (sr={sr:.2f}), dropped")
            return []

        rewards = np.asarray(
            [
                (self.correct_reward if ok else self.wrong_reward)
                * self.reward_scaling
                + self.reward_bias
                for ok in successes
            ],
            np.float32,
        )
        n = len(bundle.seqs)
        seq_lens = [len(s) for s in bundle.seqs]
        plen = bundle.prompt_len
        pmask = np.concatenate(
            [
                np.concatenate(
                    [np.ones(plen, np.int64), np.zeros(l - plen, np.int64)]
                )
                for l in seq_lens
            ]
        )
        # Shifted frame (PPO convention, reference ppo generate): the
        # logprob of generated token at abs position p is stored at p-1.
        shifted_lps = []
        for seq, lp in zip(bundle.seqs, bundle.logprobs):
            out_lp = np.asarray(lp[plen:], np.float32)  # behind-prompt lps
            full = np.zeros(len(seq), np.float32)
            full[plen - 1 : len(seq) - 1] = out_lp
            shifted_lps.append(full)
        sample = SequenceSample(
            ids=[qid],
            keys={
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask", "rewards",
            },
            data={
                "packed_input_ids": np.concatenate(
                    [np.asarray(s, np.int32) for s in bundle.seqs]
                ),
                "prompt_mask": pmask,
                "packed_logprobs": np.concatenate(shifted_lps),
                "seq_no_eos_mask": np.asarray(
                    [1.0 if x else 0.0 for x in bundle.no_eos], np.float32
                ),
                "rewards": rewards,
            },
            seqlens={
                "packed_input_ids": [seq_lens],
                "prompt_mask": [seq_lens],
                "packed_logprobs": [seq_lens],
                "seq_no_eos_mask": [[1] * n],
                "rewards": [[1] * n],
            },
            metadata={
                "version_start": [min(bundle.version_start)],
                "version_end": [max(bundle.version_end)],
                "scores": [sr],
                "birth_time": [0],
            },
        )
        return [sample]


register_agent("math-single-step", MathSingleStepAgent)

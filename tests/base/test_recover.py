"""Recovery metadata round-trip + latest-checkpoint discovery."""

import os

import pytest

from areal_tpu.base import constants, recover
from areal_tpu.base.recover import RecoverInfo, StepInfo


@pytest.fixture()
def recover_root(tmp_path, monkeypatch):
    monkeypatch.setattr(constants, "RECOVER_ROOT", str(tmp_path / "recover"))
    yield tmp_path


EXP, TRIAL = "recover-test", "t0"


def test_dump_load_roundtrip(recover_root):
    info = RecoverInfo(
        recover_start=StepInfo(epoch=1, epoch_step=2, global_step=12),
        last_step_info=StepInfo(epoch=1, epoch_step=3, global_step=13),
        save_ctl_info={"freq_sec": 60, "last": 123.0},
        ckpt_ctl_info={"freq_step": 5},
        eval_ctl_info={},
        data_loading_dp_idx=3,
        hash_vals_to_ignore=[11, 7, 5],
    )
    recover.dump(info, EXP, TRIAL)
    loaded = recover.load(EXP, TRIAL)
    assert loaded == info
    # Atomic write: no .tmp litter left behind.
    d = os.path.dirname(recover.dump_path(EXP, TRIAL))
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]


def test_dump_load_roundtrip_durable_plane_fields(recover_root):
    """ISSUE 16 pins: the exactly-once ledger snapshot and per-dataset
    cursors ride the recover record and round-trip exactly."""
    info = RecoverInfo(
        last_step_info=StepInfo(epoch=0, epoch_step=7, global_step=7),
        consumed_seqs={"water": {"w0": 4, "w1": 1}, "extras": {"w0": [7]}},
        dataset_cursors={"model_worker/0": {"epoch": 0, "offset": 64}},
    )
    recover.dump(info, EXP, TRIAL)
    loaded = recover.load(EXP, TRIAL)
    assert loaded.consumed_seqs == info.consumed_seqs
    assert loaded.dataset_cursors == info.dataset_cursors
    assert loaded == info


def test_dump_is_schema_versioned(recover_root):
    import pickle

    recover.dump(RecoverInfo(), EXP, TRIAL)
    with open(recover.dump_path(EXP, TRIAL), "rb") as f:
        payload = pickle.load(f)
    assert payload["schema"] == "areal-recover-info/v1"
    assert isinstance(payload["info"], RecoverInfo)


def test_load_accepts_legacy_raw_record(recover_root):
    """Pre-schema records (a bare pickled RecoverInfo) still load — a
    rolling upgrade must not strand an older trial's recover state."""
    import pickle

    info = RecoverInfo(data_loading_dp_idx=2)
    path = recover.dump_path(EXP, TRIAL)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(info, f)
    assert recover.load(EXP, TRIAL) == info


def test_load_rejects_unknown_schema(recover_root):
    import pickle

    path = recover.dump_path(EXP, TRIAL)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump({"schema": "areal-recover-info/v999", "info": None}, f)
    with pytest.raises(ValueError, match="unsupported recover-info schema"):
        recover.load(EXP, TRIAL)


def test_dump_leaves_no_tmp_litter(recover_root):
    recover.dump(RecoverInfo(), EXP, TRIAL)
    d = os.path.dirname(recover.dump_path(EXP, TRIAL))
    assert not [f for f in os.listdir(d) if ".tmp." in f]


def test_load_without_dump_raises(recover_root):
    with pytest.raises(FileNotFoundError):
        recover.load(EXP, "no-such-trial")


def test_step_info_next():
    s = StepInfo(epoch=2, epoch_step=4, global_step=9)
    n = s.next()
    assert (n.epoch, n.epoch_step, n.global_step) == (2, 5, 10)


def test_discover_ckpt_picks_latest_step(recover_root):
    root = os.path.join(constants.get_recover_path(EXP, TRIAL), "ckpt", "actor")
    # Numeric ordering, not lexicographic: 100 > 99 > 9.
    for step in ("9", "99", "100"):
        os.makedirs(os.path.join(root, step))
    # Non-numeric entries are ignored.
    os.makedirs(os.path.join(root, "tmp-partial"))
    assert recover.discover_ckpt("actor", EXP, TRIAL) == os.path.join(root, "100")


def test_discover_ckpt_empty_cases(recover_root):
    assert recover.discover_ckpt("nonexistent-role", EXP, TRIAL) is None
    root = os.path.join(constants.get_recover_path(EXP, TRIAL), "ckpt", "critic")
    os.makedirs(root)
    assert recover.discover_ckpt("critic", EXP, TRIAL) is None

"""Multi-host SPMD training: the pod-scale launch path.

On TPU pods the right architecture is NOT the single-host master/worker
dance scaled up — it is one identical SPMD process per host over a global
mesh: `jax.distributed` forms the world (coordinator elected through the
name_resolve rendezvous, areal_tpu/parallel/distributed.py), every host
builds the same global mesh, iterates the same deterministic dataloader,
and dispatches the same jitted train step; GSPMD inserts every cross-host
collective over ICI/DCN.

Reference counterpart: realhf/training/utils.py:62-226 +
realhf/scheduler/slurm/utils.py (816 LoC of srun/NCCL group wiring). The
reference must explicitly construct NCCL subgroups per parallelism
dimension; on TPU the runtime owns the fabric, so multi-host launch
reduces to (1) rendezvous, (2) same program everywhere — which is what
this module does.

`launch_multihost` starts one process per host through the scheduler
client: LocalSchedulerClient simulates a pod on one machine (each "host"
gets its own process with a slice of CPU devices — the test topology);
a cluster scheduler registered under `make_scheduler` submits the same
per-host commands to real pods.

Usage (single-machine simulation of 2 hosts):
    python -m training.multihost n_hosts=2 mesh_spec=d2f2 \
        experiment_name=mh trial_name=t0 dataset.path=/data/sft.jsonl \
        model.config='{"n_layers":2,...}' steps=4 out=/tmp/mh.json
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.api.cli_args import SFTExpConfig, apply_overrides
from areal_tpu.base import logging, name_resolve

logger = logging.getLogger("multihost")

_HOST_ENV = "AREAL_TPU_HOST_RANK"


def host_main(
    cfg: SFTExpConfig,
    host_rank: int,
    n_hosts: int,
    mesh_spec: str,
    steps: int,
    out_path: Optional[str] = None,
) -> Dict:
    """The per-host SPMD program: rendezvous, global mesh, lockstep SFT.

    Every host runs this exact function with only `host_rank` differing;
    determinism of the dataloader (same seed, same files) keeps the hosts
    dispatching identical programs, which is the SPMD contract.
    """
    from areal_tpu.utils.jaxenv import apply_jax_platform_override

    apply_jax_platform_override()

    from areal_tpu.parallel.distributed import setup_host_group

    if cfg.name_resolve_root:
        name_resolve.reconfigure("nfs", record_root=cfg.name_resolve_root)
    else:
        name_resolve.reconfigure("nfs")
    group = setup_host_group(
        cfg.experiment_name, cfg.trial_name, "trainer", host_rank, n_hosts
    )

    import jax
    import numpy as np

    from areal_tpu.api import data_api
    from areal_tpu.api.data_api import DatasetUtility, MicroBatchSpec
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.models.hf import load_hf_model
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params
    from areal_tpu.parallel.mesh import make_mesh
    import areal_tpu.datasets  # noqa: F401  (registry)
    from areal_tpu.experiments import common as C

    mesh = make_mesh(MeshSpec.parse(mesh_spec), jax.devices())
    logger.info(
        f"host {host_rank}/{n_hosts}: world={jax.process_count()} procs, "
        f"{jax.device_count()} devices, mesh={dict(mesh.shape)}"
    )

    m = cfg.model
    if m.path is not None:
        model_cfg, params = load_hf_model(m.path)
        tokenizer_path = cfg.tokenizer_path or m.path
    else:
        model_cfg = TransformerConfig(**(m.config or {}))
        params = init_params(model_cfg, jax.random.PRNGKey(cfg.seed))
        tokenizer_path = cfg.tokenizer_path
    tokenizer = (
        data_api.load_hf_tokenizer(tokenizer_path) if tokenizer_path else None
    )

    # Same dataset + same shuffle seed on every host => lockstep batches.
    ds = data_api.make_dataset(
        C.dataset_abstraction(cfg.dataset),
        DatasetUtility(seed=cfg.seed, dp_rank=0, world_size=1,
                       tokenizer=tokenizer),
    )
    loader = data_api.PackedDataLoader(
        ds, batch_size=cfg.train_batch_size, shuffle=True, seed=cfg.seed
    )

    eng = JaxTrainEngine(
        model_cfg, params, mesh=mesh,
        optimizer_config=m.optimizer,
        total_train_steps=max(steps, 1),
        remat=m.remat,
        row_len_multiple=m.row_len_multiple,
        max_row_len=m.max_row_len,
    )

    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss

    losses: List[float] = []
    for step in range(steps):
        batch, _ = loader.next_batch()
        st = eng.train_batch(
            batch, MicroBatchSpec(n_mbs=cfg.mb_spec_n_mbs), sft_row_loss,
            sft_loss_weight, version_steps=step, loss_name="sft",
        )
        losses.append(st["sft/loss"])
        logger.info(f"host {host_rank} step {step}: loss={st['sft/loss']:.4f}")

    result = {
        "host_rank": host_rank,
        "n_processes": jax.process_count(),
        "n_devices": jax.device_count(),
        "mesh": dict(mesh.shape),
        "losses": losses,
    }
    if out_path and jax.process_index() == 0:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f)
    return result


def launch_multihost(
    n_hosts: int,
    overrides: List[str],
    mesh_spec: str,
    steps: int,
    out_path: str,
    host_env: Optional[Dict[str, str]] = None,
    scheduler_mode: str = "local",
    timeout: float = 900.0,
):
    """Spawn one `training.multihost` process per host and wait.

    With scheduler_mode="local", hosts are subprocesses of this machine
    (pod simulation / tests); cluster schedulers registered under
    make_scheduler receive identical per-host submissions."""
    from areal_tpu.scheduler.client import make_scheduler

    sched = make_scheduler(scheduler_mode)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = []
    for rank in range(n_hosts):
        env = dict(host_env or {})
        env[_HOST_ENV] = str(rank)
        cmd = [
            sys.executable, "-m", "training.multihost",
            f"n_hosts={n_hosts}", f"mesh_spec={mesh_spec}",
            f"steps={steps}", f"out={out_path}",
        ] + list(overrides)
        names.append(sched.submit(f"host{rank}", cmd, env=env, cwd=repo_root))
    try:
        sched.wait(names, timeout=timeout)
    finally:
        sched.stop_all()
    if not out_path:
        return None  # hosts ran fine; nothing was asked to be collected
    with open(out_path) as f:
        return json.load(f)


def _parse_argv(argv: List[str]):
    meta = {"n_hosts": 1, "mesh_spec": "d1", "steps": 2, "out": ""}
    overrides = []
    for arg in argv:
        k, _, v = arg.partition("=")
        if k in ("n_hosts", "steps"):
            meta[k] = int(v)
        elif k in ("mesh_spec", "out"):
            meta[k] = v
        else:
            overrides.append(arg)
    cfg = SFTExpConfig()
    apply_overrides(cfg, overrides)
    return meta, cfg, overrides


if __name__ == "__main__":
    meta, cfg, overrides = _parse_argv(sys.argv[1:])
    rank_env = os.environ.get(_HOST_ENV)
    if rank_env is None:
        # Launcher role: fan out one process per host.
        launch_multihost(
            meta["n_hosts"], overrides, meta["mesh_spec"], meta["steps"],
            meta["out"],
        )
    else:
        host_main(
            cfg, int(rank_env), meta["n_hosts"], meta["mesh_spec"],
            meta["steps"], meta["out"],
        )

"""Budget-aware RPC substrate for every cross-process HTTP call.

Until this module the fleet's five wire planes (routing, weight, KV,
handoff, fleet-lease) each grew a private retry loop with hand-picked
timeouts and no shared deadline: a slow peer was indistinguishable from
a dead one, and a rollout with 2 s of budget left could still wait 30 s
on a chunk pull. Everything here exists to make those calls share ONE
discipline:

- **Deadline propagation.** The outermost caller mints a
  :class:`Deadline`; every outbound hop stamps the *remaining* seconds
  into the ``X-Areal-Deadline`` header (:data:`DEADLINE_HEADER`, wire
  rule declared in ``base/wire_routes.py``) and every server parses it
  back with :meth:`Deadline.from_headers`. Budgets therefore decrement
  across hops — the KV pull a decode server makes on behalf of a
  rollout inherits the rollout's remaining budget, not a fresh 30 s.

- **Unified retry policy.** :class:`RetryPolicy` carries the attempt
  count, jittered exponential backoff (Retry-After floors the wait),
  and the per-attempt timeout *derived from the remaining budget*.
  :func:`retry_sync` / :func:`retry_async` are the only two retry
  loops the tree needs; the ``rpc-discipline`` lint checker flags any
  other HTTP-call-plus-sleep loop outside this module.

- **Hedged reads** (:func:`hedged_sync` / :func:`hedged_async`) for
  idempotent, hash-verified GETs where several holders can serve the
  same bytes (weight ``/weights/chunk``, KV ``/kv/chunk``): the
  secondary launches after ``hedge_delay_s`` of primary silence, first
  success wins, losers are cancelled and their bytes never reach the
  caller — so egress/ingress accounting cannot double-count.

- **Per-peer circuit breakers** (:class:`CircuitBreaker`,
  closed -> open -> half-open) pooled in a :class:`BreakerBoard`. The
  gserver manager feeds its board into routing/health so a flapping
  peer stops eating every caller's budget; servers keep a process
  board for their own peer pulls.

All counters land in the process-global :data:`stats` and surface as
``areal:rpc_*`` /metrics lines (``base/metrics_registry.py``) and the
manager's ``/status`` rpc section.

Import discipline: stdlib-only at import time (the no-jax lint gate
imports this for the rpc-discipline registry); aiohttp is imported
lazily inside the async helpers.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import threading
import time
from typing import (
    Any,
    Awaitable,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
    TypeVar,
)

from areal_tpu.base import env_registry, logging
from areal_tpu.base.wire_routes import DEADLINE_HEADER

logger = logging.getLogger("rpc")

T = TypeVar("T")

# Default retryable failures. asyncio.TimeoutError is spelled out
# because on Python < 3.11 it is NOT a subclass of builtin
# TimeoutError — and it is exactly what an aiohttp total-timeout
# raises, the single most retryable failure the substrate sees.
RETRYABLE_DEFAULT = (OSError, TimeoutError, asyncio.TimeoutError, ValueError)

# Below this many seconds of remaining budget an attempt cannot
# plausibly complete; the call short-circuits with RpcDeadlineExceeded
# instead of burning a socket on a doomed request.
MIN_ATTEMPT_S = 0.01


class RpcError(RuntimeError):
    """Base class for substrate failures."""


class RpcDeadlineExceeded(RpcError):
    """The propagated deadline expired (possibly before attempt 1)."""


class BreakerOpen(RpcError):
    """The peer's circuit breaker is open; no attempt was made."""

    def __init__(self, peer: str, detail: str = ""):
        super().__init__(f"circuit open for {peer}{': ' if detail else ''}{detail}")
        self.peer = peer


class RpcShed(RpcError):
    """The peer shed the request (429). Deliberate backpressure, not a
    failure: carries the server's Retry-After so callers (or the retry
    loop itself) can floor their backoff on it."""

    def __init__(self, peer: str, retry_after: float):
        super().__init__(f"{peer} shed request (retry after {retry_after:.2f}s)")
        self.peer = peer
        self.retry_after = float(retry_after)


# ----------------------------------------------------------------------
# Deadline propagation
# ----------------------------------------------------------------------


class Deadline:
    """A monotonic-clock budget minted once at the outermost caller and
    decremented implicitly as time passes. Serialized on the wire as
    REMAINING seconds (``X-Areal-Deadline: 12.345``) so clocks never
    need to agree across hosts — each hop re-anchors against its own
    monotonic clock, losing only the network latency of the hop."""

    __slots__ = ("_expires",)

    def __init__(self, expires_monotonic: Optional[float]):
        self._expires = expires_monotonic

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(time.monotonic() + float(budget_s))

    @classmethod
    def unbounded(cls) -> "Deadline":
        return cls(None)

    @classmethod
    def from_header_value(cls, value: Optional[str]) -> Optional["Deadline"]:
        if not value:
            return None
        try:
            return cls.after(float(value))
        except ValueError:
            return None

    @classmethod
    def from_headers(cls, headers) -> Optional["Deadline"]:
        """Parse the propagated deadline out of a request's headers
        (any mapping with .get). None when the caller sent none."""
        try:
            return cls.from_header_value(headers.get(DEADLINE_HEADER))
        except Exception:
            return None

    def remaining(self) -> float:
        if self._expires is None:
            return float("inf")
        return self._expires - time.monotonic()

    def expired(self) -> bool:
        return self._expires is not None and self.remaining() <= 0.0

    def bounded(self) -> bool:
        return self._expires is not None

    def header_value(self) -> Optional[str]:
        if self._expires is None:
            return None
        return f"{max(0.0, self.remaining()):.3f}"

    def headers(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """``base`` plus the deadline header (omitted when unbounded)."""
        out = dict(base or {})
        v = self.header_value()
        if v is not None:
            out[DEADLINE_HEADER] = v
        return out

    def cap(self, budget_s: float) -> "Deadline":
        """The tighter of this deadline and a fresh ``budget_s`` window
        — the standard way a hop bounds its own work without ever
        EXTENDING the caller's budget."""
        capped = time.monotonic() + float(budget_s)
        if self._expires is None or capped < self._expires:
            return Deadline(capped)
        return Deadline(self._expires)


def ensure_deadline(
    deadline: Optional[Deadline], default_budget_s: float
) -> Deadline:
    """The caller's deadline, or a freshly minted one — used at the
    outermost edges (client entry points) so every call below them is
    always budgeted."""
    if deadline is not None:
        return deadline
    return Deadline.after(default_budget_s)


# ----------------------------------------------------------------------
# Retry policy
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """One declared retry discipline: how many attempts, how long each
    may take, how long to wait between them. Per-attempt timeouts are
    derived from the remaining budget at attempt time, never a fixed
    constant — the deadline always wins."""

    attempts: int = 4
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    attempt_timeout_s: float = 30.0
    jitter: float = 0.5  # +-fraction of the computed backoff

    def attempt_timeout(self, deadline: Optional[Deadline]) -> float:
        """Timeout for the next attempt: the policy cap clipped to the
        remaining budget. Raises RpcDeadlineExceeded (and counts the
        short-circuit) when the budget cannot fit an attempt."""
        if deadline is None:
            return self.attempt_timeout_s
        rem = deadline.remaining()
        if rem <= MIN_ATTEMPT_S:
            stats.incr("deadline_expired")
            raise RpcDeadlineExceeded(
                f"deadline expired ({rem:.3f}s remaining)"
            )
        return min(self.attempt_timeout_s, rem)

    def backoff(
        self,
        consecutive_failures: int,
        retry_after: Optional[float] = None,
        deadline: Optional[Deadline] = None,
    ) -> float:
        """Jittered exponential backoff after the k-th consecutive
        failure (k >= 1); a server's Retry-After floors it; the
        remaining budget caps it (no point sleeping past the
        deadline)."""
        k = max(1, int(consecutive_failures))
        delay = min(self.backoff_max_s, self.backoff_base_s * (2 ** (k - 1)))
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        if retry_after is not None:
            delay = max(delay, float(retry_after))
        if deadline is not None and deadline.bounded():
            delay = min(delay, max(0.0, deadline.remaining()))
        return delay


def default_policy(**overrides) -> RetryPolicy:
    """The fleet-wide declared policy, tuned by AREAL_RPC_* knobs."""
    kw: Dict[str, Any] = dict(
        attempts=env_registry.get_int("AREAL_RPC_ATTEMPTS"),
        backoff_base_s=env_registry.get_float("AREAL_RPC_BACKOFF_S"),
        backoff_max_s=env_registry.get_float("AREAL_RPC_BACKOFF_MAX_S"),
        attempt_timeout_s=env_registry.get_float("AREAL_RPC_TIMEOUT_S"),
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def rediscovery_policy(**overrides) -> RetryPolicy:
    """The manager-blip policy shared by partial_rollout and the
    rollout worker: a control-plane restart costs seconds and every
    client sees it at once, so the budget is generous and the backoff
    ceiling high enough to not hammer the successor."""
    kw: Dict[str, Any] = dict(
        attempts=env_registry.get_int("AREAL_RPC_REDISCOVERY_ATTEMPTS"),
        backoff_base_s=env_registry.get_float("AREAL_RPC_BACKOFF_S"),
        backoff_max_s=env_registry.get_float(
            "AREAL_RPC_REDISCOVERY_BACKOFF_MAX_S"
        ),
        attempt_timeout_s=env_registry.get_float("AREAL_RPC_TIMEOUT_S"),
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def shed_backoff(
    consecutive_sheds: int, retry_after: float, cap: float = 10.0
) -> float:
    """THE client-side 429 discipline: a jittered wait around the
    server's Retry-After hint with a mild exponential ramp on
    consecutive sheds — synchronized retries from many workers would
    re-create the very burst that tripped the admission watermark.
    Sheds are deliberate backpressure: they never touch breakers or
    failure budgets."""
    k = max(1, int(consecutive_sheds))
    delay = min(cap, float(retry_after) * (2 ** min(k - 1, 3)))
    return delay * (0.5 + random.random())


def hedge_delay_s() -> float:
    return env_registry.get_float("AREAL_RPC_HEDGE_DELAY_S")


def hedging_enabled() -> bool:
    return env_registry.get_bool("AREAL_RPC_HEDGE")


# ----------------------------------------------------------------------
# Circuit breakers
# ----------------------------------------------------------------------

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-peer closed -> open -> half-open breaker.

    ``fail_threshold`` consecutive failures open the circuit; after
    ``cooldown_s`` ONE probe is allowed through (half-open); its
    success closes the circuit, its failure re-opens it for another
    cooldown. Thread-safe: the manager's poll thread and HTTP loop
    both touch the board."""

    __slots__ = (
        "peer", "fail_threshold", "cooldown_s", "_lock",
        "_consecutive", "_opened_at", "_probing", "opens", "rejections",
    )

    def __init__(self, peer: str, fail_threshold: int, cooldown_s: float):
        self.peer = peer
        self.fail_threshold = max(1, int(fail_threshold))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.opens = 0
        self.rejections = 0

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return STATE_CLOSED
        if self._probing or (
            time.monotonic() - self._opened_at >= self.cooldown_s
        ):
            return STATE_HALF_OPEN
        return STATE_OPEN

    def allow(self) -> bool:
        """May a call proceed right now? In half-open exactly one
        caller wins the probe slot; everyone else is rejected until
        the probe resolves."""
        with self._lock:
            st = self._state_locked()
            if st == STATE_CLOSED:
                return True
            if st == STATE_HALF_OPEN and not self._probing:
                self._probing = True
                return True
            self.rejections += 1
            stats.incr("breaker_rejections")
            return False

    def record_success(self):
        with self._lock:
            self._consecutive = 0
            self._opened_at = None
            self._probing = False

    def release_probe(self):
        """Give an allow()-granted probe slot back without an outcome
        (the attempt ended in something that is neither success nor a
        peer failure — e.g. a non-retryable application error). The
        slot MUST be resolved one way or another: a leaked slot makes
        _state_locked() report half-open forever and every future
        allow() reject, wedging the peer out permanently."""
        with self._lock:
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._consecutive += 1
            if self._probing:
                # Failed half-open probe: re-open for a fresh cooldown.
                self._probing = False
                self._opened_at = time.monotonic()
                self.opens += 1
                stats.incr("breaker_opens")
                return
            if self._opened_at is not None:
                # record()-fed boards (the manager never calls allow();
                # failures arrive as client reports / its own polls):
                # once the cooldown has elapsed the breaker is
                # half-open by time, and this failure IS the failed
                # probe — re-open for a fresh cooldown, or the peer
                # would sit half-open forever and re-enter rotation
                # while still failing. A failure landing inside the
                # cooldown leaves the open window untouched.
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    self._opened_at = time.monotonic()
                    self.opens += 1
                    stats.incr("breaker_opens")
                return
            if self._consecutive >= self.fail_threshold:
                self._opened_at = time.monotonic()
                self.opens += 1
                stats.incr("breaker_opens")

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state_locked(),
                "consecutive_failures": self._consecutive,
                "opens": self.opens,
                "rejections": self.rejections,
            }


class BreakerBoard:
    """All of one process's per-peer breakers. The gserver manager
    folds its board into routing (an open peer is unroutable, like a
    shedding one — never evicted for it) and surfaces it on /status."""

    def __init__(
        self,
        fail_threshold: Optional[int] = None,
        cooldown_s: Optional[float] = None,
    ):
        self.fail_threshold = (
            int(fail_threshold)
            if fail_threshold is not None
            else env_registry.get_int("AREAL_RPC_BREAKER_FAILS")
        )
        self.cooldown_s = (
            float(cooldown_s)
            if cooldown_s is not None
            else env_registry.get_float("AREAL_RPC_BREAKER_COOLDOWN_S")
        )
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def breaker(self, peer: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(peer)
            if br is None:
                br = CircuitBreaker(
                    peer, self.fail_threshold, self.cooldown_s
                )
                self._breakers[peer] = br
            return br

    def allow(self, peer: str) -> bool:
        return self.breaker(peer).allow()

    def record(self, peer: str, ok: bool):
        br = self.breaker(peer)
        if ok:
            br.record_success()
        else:
            br.record_failure()

    def drop(self, peer: str):
        """Forget a departed peer (manager _forget_server hook)."""
        with self._lock:
            self._breakers.pop(peer, None)

    def open_peers(self) -> List[str]:
        with self._lock:
            items = list(self._breakers.items())
        return sorted(p for p, b in items if b.state() == STATE_OPEN)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            items = list(self._breakers.items())
        return {p: b.snapshot() for p, b in items}


# ----------------------------------------------------------------------
# Stats (areal:rpc_* surface)
# ----------------------------------------------------------------------


class RpcStats:
    """Process-global substrate counters, emitted as areal:rpc_* lines
    by generation_server._h_metrics and the manager /status rpc
    section. Monotonic since process start, like every /metrics
    counter."""

    FIELDS = (
        "attempts", "retries", "failures",
        "hedges", "hedge_wins", "hedge_cancelled", "hedge_failures",
        "deadline_expired", "breaker_rejections", "breaker_opens",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {f: 0 for f in self.FIELDS}

    def incr(self, field: str, n: int = 1):
        with self._lock:
            self._c[field] += n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)

    def reset(self):
        """Test/bench hook only — production counters never reset."""
        with self._lock:
            for f in self.FIELDS:
                self._c[f] = 0


stats = RpcStats()


# ----------------------------------------------------------------------
# Sync substrate (urllib; executor/poll threads only, never an event
# loop — the blocking-async lint contract)
# ----------------------------------------------------------------------


def retry_sync(
    fn: Callable[[float], T],
    *,
    policy: RetryPolicy,
    deadline: Optional[Deadline] = None,
    peer: Optional[str] = None,
    board: Optional[BreakerBoard] = None,
    retryable: Tuple[type, ...] = RETRYABLE_DEFAULT,
    what: str = "rpc",
) -> T:
    """THE sync retry loop. ``fn(timeout_s)`` runs up to
    ``policy.attempts`` times with budget-derived per-attempt timeouts;
    ``retryable`` failures back off (jittered, Retry-After-floored via
    :class:`RpcShed`) and retry; anything else propagates. The breaker
    (when given) gates every attempt and records the outcome."""
    last: Optional[BaseException] = None
    br = board.breaker(peer) if (board is not None and peer) else None
    for attempt in range(1, policy.attempts + 1):
        timeout = policy.attempt_timeout(deadline)  # raises when expired
        if br is not None and not br.allow():
            raise BreakerOpen(peer or "?", what)
        stats.incr("attempts")
        try:
            out = fn(timeout)
        except RpcShed as e:
            # Shed is deliberate backpressure, and PROOF the peer is
            # alive and answering: a success for breaker purposes
            # (also resolves a held half-open probe slot — a leaked
            # slot would reject the peer forever).
            last = e
            if br is not None:
                br.record_success()
            if attempt >= policy.attempts:
                break
            stats.incr("retries")
            time.sleep(policy.backoff(attempt, retry_after=e.retry_after,
                                      deadline=deadline))
            continue
        except retryable as e:
            last = e
            if br is not None:
                br.record_failure()
            if attempt >= policy.attempts:
                break
            stats.incr("retries")
            logger.debug(f"{what}: attempt {attempt} failed: {e!r}")
            time.sleep(policy.backoff(attempt, deadline=deadline))
            continue
        except BaseException:
            # Non-retryable application error: neither a peer failure
            # nor a success — but the probe slot must not leak.
            if br is not None:
                br.release_probe()
            raise
        if br is not None:
            br.record_success()
        return out
    stats.incr("failures")
    raise RpcError(
        f"{what}: failed after {policy.attempts} attempt(s): {last!r}"
    ) from last


def get_bytes_sync(
    url: str,
    *,
    policy: Optional[RetryPolicy] = None,
    deadline: Optional[Deadline] = None,
    headers: Optional[Dict[str, str]] = None,
    peer: Optional[str] = None,
    board: Optional[BreakerBoard] = None,
    what: str = "GET",
) -> bytes:
    """Budget-aware GET returning the body bytes. 429s raise
    :class:`RpcShed` internally so the loop floors its backoff on the
    server's Retry-After."""
    import urllib.error
    import urllib.request

    policy = policy or default_policy()

    def attempt(timeout: float) -> bytes:
        dl = deadline or Deadline.after(timeout)
        req = urllib.request.Request(url, headers=dl.headers(headers))
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 429:
                ra = e.headers.get("Retry-After") if e.headers else None
                raise RpcShed(url, float(ra or 1.0)) from e
            if e.code >= 500:
                raise OSError(f"{url}: server error {e.code}") from e
            # Deliberate non-retryable status (404/416/...): re-wrap —
            # HTTPError subclasses OSError via URLError, so a bare
            # `raise` would be swallowed by RETRYABLE_DEFAULT and
            # burned against the budget attempts-1 more times.
            raise RpcError(f"{url}: HTTP {e.code}") from e
        except urllib.error.URLError as e:
            raise OSError(f"{url}: {e.reason}") from e

    return retry_sync(
        attempt, policy=policy, deadline=deadline, peer=peer,
        board=board, what=f"{what} {url}",
    )


def get_json_sync(url: str, **kw) -> Any:
    import json

    return json.loads(get_bytes_sync(url, **kw))


def hedged_sync(
    fns: Sequence[Callable[[], T]],
    *,
    hedge_delay: Optional[float] = None,
    deadline: Optional[Deadline] = None,
    what: str = "hedged",
) -> Tuple[T, int]:
    """Hedged execution of idempotent fetchers: ``fns[0]`` starts
    immediately; each time the race has gone ``hedge_delay`` seconds
    without a winner the next fn launches. First SUCCESS wins and is
    returned with its index; losers are abandoned (their results are
    dropped on the floor, never returned — callers therefore cannot
    double-count loser bytes) and counted in ``hedge_cancelled``.

    Sync variant runs hedges on daemon threads (urllib cannot be
    cancelled mid-read; the abandoned socket dies with the thread).
    Raises the primary's error once every launched fn has failed."""
    if not fns:
        raise ValueError("hedged_sync: no fetchers")
    if hedge_delay is None:
        hedge_delay = hedge_delay_s()
    done = threading.Event()
    lock = threading.Lock()
    results: Dict[int, Tuple[bool, Any]] = {}

    def run(i: int):
        try:
            out = fns[i]()
            ok = True
        except BaseException as e:  # noqa: BLE001 — race bookkeeping
            out = e
            ok = False
        with lock:
            results[i] = (ok, out)
        done.set()

    launched = 0

    def launch():
        nonlocal launched
        i = launched
        launched += 1
        if i > 0:
            stats.incr("hedges")
        threading.Thread(
            target=run, args=(i,), daemon=True, name=f"rpc-hedge-{i}"
        ).start()

    launch()
    while True:
        rem = deadline.remaining() if deadline is not None else float("inf")
        if rem <= 0:
            stats.incr("deadline_expired")
            raise RpcDeadlineExceeded(f"{what}: deadline expired mid-race")
        wait = min(hedge_delay, rem) if launched < len(fns) else min(rem, 60.0)
        fired = done.wait(wait)
        with lock:
            done.clear()
            winner = next(
                (i for i, (ok, _) in sorted(results.items()) if ok), None
            )
            failures = sum(1 for ok, _ in results.values() if not ok)
            if winner is not None:
                out = results[winner][1]
                # Everything else launched loses: abandoned threads and
                # late results alike are dropped, never returned.
                losers = launched - 1 - failures
                if losers > 0:
                    stats.incr("hedge_cancelled", losers)
                if winner > 0:
                    stats.incr("hedge_wins")
                return out, winner
        if failures >= len(fns):
            # hedge_failures counts WHOLE races lost, exactly once —
            # the per-leg retry exhaustion already landed in
            # "failures", and a transient leg failure inside a race
            # the hedge still WON must not read as a hedge failure.
            stats.incr("failures")
            stats.incr("hedge_failures")
            err0 = results[0][1]
            raise RpcError(f"{what}: every hedge failed") from (
                err0 if isinstance(err0, BaseException) else None
            )
        # Launch the next hedge on silence, or immediately when every
        # launched attempt has already failed.
        if launched < len(fns) and (not fired or failures >= launched):
            launch()


# ----------------------------------------------------------------------
# Async substrate (aiohttp; event-loop callers)
# ----------------------------------------------------------------------


async def retry_async(
    fn: Callable[[float], Awaitable[T]],
    *,
    policy: RetryPolicy,
    deadline: Optional[Deadline] = None,
    peer: Optional[str] = None,
    board: Optional[BreakerBoard] = None,
    retryable: Tuple[type, ...] = RETRYABLE_DEFAULT,
    what: str = "rpc",
) -> T:
    """Async twin of :func:`retry_sync`: same policy semantics, same
    breaker/deadline/shed handling, sleeps on the event loop."""
    import asyncio

    last: Optional[BaseException] = None
    br = board.breaker(peer) if (board is not None and peer) else None
    for attempt in range(1, policy.attempts + 1):
        timeout = policy.attempt_timeout(deadline)  # raises when expired
        if br is not None and not br.allow():
            raise BreakerOpen(peer or "?", what)
        stats.incr("attempts")
        try:
            out = await fn(timeout)
        except RpcShed as e:
            # Alive-and-answering: a breaker success (and probe-slot
            # resolution), same as the sync twin.
            last = e
            if br is not None:
                br.record_success()
            if attempt >= policy.attempts:
                break
            stats.incr("retries")
            await asyncio.sleep(policy.backoff(
                attempt, retry_after=e.retry_after, deadline=deadline
            ))
            continue
        except asyncio.CancelledError:
            if br is not None:
                br.release_probe()
            raise
        except retryable as e:
            last = e
            if br is not None:
                br.record_failure()
            if attempt >= policy.attempts:
                break
            stats.incr("retries")
            logger.debug(f"{what}: attempt {attempt} failed: {e!r}")
            await asyncio.sleep(policy.backoff(attempt, deadline=deadline))
            continue
        except BaseException:
            if br is not None:
                br.release_probe()
            raise
        if br is not None:
            br.record_success()
        return out
    stats.incr("failures")
    raise RpcError(
        f"{what}: failed after {policy.attempts} attempt(s): {last!r}"
    ) from last


async def hedged_async(
    fns: Sequence[Callable[[], Awaitable[T]]],
    *,
    hedge_delay: Optional[float] = None,
    deadline: Optional[Deadline] = None,
    what: str = "hedged",
) -> Tuple[T, int]:
    """Async hedged execution with REAL loser cancellation: the first
    success wins (returned with its launch index), every other
    in-flight task is cancelled — the socket is torn down, the bytes
    never arrive, so callers cannot double-count loser traffic — and
    counted in ``hedge_cancelled``. A new hedge launches after each
    ``hedge_delay`` of silence, or immediately when every in-flight
    attempt has already failed. Raises once all fns have failed."""
    import asyncio

    if not fns:
        raise ValueError("hedged_async: no fetchers")
    if hedge_delay is None:
        hedge_delay = hedge_delay_s()

    async def indexed(i: int) -> Tuple[int, T]:
        return i, await fns[i]()

    inflight: List[asyncio.Task] = [asyncio.ensure_future(indexed(0))]
    launched = 1
    failed = 0
    first_err: Optional[BaseException] = None
    try:
        while True:
            rem = (
                deadline.remaining() if deadline is not None else float("inf")
            )
            if rem <= 0:
                stats.incr("deadline_expired")
                raise RpcDeadlineExceeded(f"{what}: deadline expired mid-race")
            can_launch = launched < len(fns)
            wait = min(hedge_delay, rem) if can_launch else min(rem, 60.0)
            done, pending = await asyncio.wait(
                inflight, timeout=wait, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                inflight.remove(t)
                if t.cancelled():
                    failed += 1
                    continue
                if t.exception() is not None:
                    failed += 1
                    if first_err is None:
                        first_err = t.exception()
                    continue
                winner_idx, out = t.result()
                if pending:
                    for p in pending:
                        p.cancel()
                    stats.incr("hedge_cancelled", len(pending))
                    await asyncio.gather(*pending, return_exceptions=True)
                if winner_idx > 0:
                    stats.incr("hedge_wins")
                return out, winner_idx
            if failed >= len(fns):
                stats.incr("failures")
                stats.incr("hedge_failures")
                raise RpcError(f"{what}: every hedge failed") from first_err
            # Launch the next hedge on silence (timeout) or immediately
            # when everything in flight has already failed.
            if can_launch and (not done or not inflight):
                stats.incr("hedges")
                inflight.append(asyncio.ensure_future(indexed(launched)))
                launched += 1
    finally:
        for t in inflight:
            if not t.done():
                t.cancel()


# ----------------------------------------------------------------------
# rpc-discipline lint registry
# ----------------------------------------------------------------------

# The ONE module allowed to hold raw HTTP retry loops. The
# rpc-discipline checker (areal_tpu/lint/rpc_discipline.py) flags
# HTTP-call-plus-sleep loops and numeric-literal per-call timeouts in
# any module not named here. Deliberately a one-entry tuple: new
# entries need a justification comment AND the checker's tests keep
# the contract honest. (repo-relative paths)
LINT_RPC_MODULES = ("areal_tpu/base/rpc.py",)

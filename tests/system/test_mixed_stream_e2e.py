"""Mixed-stream RUN e2e (ISSUE 19 satellite): a math stream AND an
agentic tool-use stream feed ONE buffer through the same trainer, with
per-task staleness windows gating admission independently (math tight,
agentic loose) and per-task attribution surfaced as master scalars —
zero failed episodes on either stream."""

import uuid

import pytest

from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
)
from areal_tpu.api.system_api import (
    ExperimentConfig,
    GenerationServerConfig,
    GserverManagerConfig,
    RolloutWorkerConfig,
)
from areal_tpu.base import name_resolve
from areal_tpu.system.controller import LocalController
from tests import fixtures
from tests.system.test_async_e2e import _deflaked_env, _trainer_parts
from tests.system.test_e2e_experiments import _mk_tokenizer_files
from tests.system.test_reward_executor import _spawn_executor

pytestmark = pytest.mark.serial


@pytest.mark.slow
def test_mixed_math_and_agentic_streams_share_one_buffer(
    tmp_path, monkeypatch
):
    exp, trial = f"e2e-mixed-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    mc_rows = [
        r for r in fixtures.make_math_code_rows(16, seed=17)
        if r["task"] == "math"
    ]
    data_path = fixtures.write_jsonl(mc_rows, tmp_path / "mc.jsonl")
    nr_root = str(tmp_path / "name_resolve")

    worker_env = _deflaked_env(tmp_path, monkeypatch)
    # The point of the run: per-task windows, admitted/dropped
    # independently per stream (math tight, agentic loose).
    worker_env["AREAL_TASK_STALENESS_WINDOWS"] = "math:2,agentic:8"

    # One real reward executor for the tool-use stream's tool calls.
    name_resolve.reconfigure("nfs", record_root=nr_root)
    procs = [_spawn_executor(0, exp, trial, nr_root)]

    # n_seqs=4 so every train batch has room for BOTH streams — the
    # buffer is FIFO and a 2-seq batch can fill from one stream alone.
    model_args, mw, master = _trainer_parts(exp, trial, tok_dir, n_seqs=4)
    gen_server = GenerationServerConfig(
        experiment_name=exp,
        trial_name=trial,
        server_index=0,
        model=ModelAbstraction("tpu_transformer", args=model_args),
        tokenizer_path=tok_dir,
        max_concurrent_requests=8,
        max_seq_len=256,
        decode_block_steps=4,
        # Tool-turn continuations re-enter on sticky-qid routes.
        prefix_cache_tokens=2048,
    )
    gserver_mgr = GserverManagerConfig(
        experiment_name=exp,
        trial_name=trial,
        model_name="actor",
        n_servers=1,
        train_batch_size=4,
        max_head_offpolicyness=100,  # the BUFFER's windows gate, not this
    )
    # Worker 0: the fast math stream, throttled (1 in flight, chunked
    # decode) so it cannot starve the slower agentic stream out of
    # every FIFO batch.
    math_worker = RolloutWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        n_rollout_workers=2,
        n_pullers=1,
        agent=AgentAbstraction(
            "math-single-step",
            args=dict(gconfig=dict(n=1, max_new_tokens=8)),
        ),
        env=EnvServiceAbstraction("math-code-single-step"),
        datasets=[
            DatasetAbstraction(
                "math_code_prompt", args=dict(dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        max_concurrent_rollouts=1,
        new_tokens_per_chunk=4,
    )
    # Worker 1: the agentic stream — multi-turn tool-use episodes
    # through the real executor.
    tool_worker = RolloutWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=1,
        n_rollout_workers=2,
        n_pullers=1,
        agent=AgentAbstraction(
            "tool-use",
            args=dict(
                gconfig=dict(max_new_tokens=8),
                num_turns=2,
                scripted_tool_turns=1,
            ),
        ),
        env=EnvServiceAbstraction("tool-use"),
        datasets=[
            DatasetAbstraction(
                "math_code_prompt", args=dict(dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        max_concurrent_rollouts=4,
    )
    cfg = ExperimentConfig(
        experiment_name=exp,
        trial_name=trial,
        master=master,
        model_workers=[mw],
        rollout_workers=[math_worker, tool_worker],
        gserver_manager=gserver_mgr,
        generation_servers=[gen_server],
    )
    ctl = LocalController(
        cfg,
        name_resolve_cfg={"backend": "nfs", "record_root": nr_root},
        worker_env=worker_env,
    )
    try:
        result = ctl.run()
        assert result["global_step"] == 2

        overlap = result["perf_summary"]["overlap"]
        # BOTH task tags survived rollout -> shared buffer -> train
        # batch -> master scalars: the streams were genuinely mixed.
        assert "task_staleness_math" in overlap, overlap
        assert "task_staleness_agentic" in overlap, overlap
        # Zero failed episodes on the agentic stream: episode_turns /
        # episode_tool_calls are stamped ONLY by tool-use episodes, so
        # the means are exact — every trained agentic episode ran its
        # full 2 turns and executed its scripted tool call.
        assert overlap.get("episode_turns") == 2.0, overlap
        assert overlap.get("episode_tool_calls") == 1.0, overlap
        # The executor that served the tool calls stayed alive.
        assert procs[0].poll() is None
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        from areal_tpu.base import tracing

        tracing.reconfigure()

"""Automatic evaluator: eval every new checkpoint as it appears.

Counterpart of the reference's AutomaticEvaluator
(realhf/scheduler/evaluator.py:160-348): watch the save directory for
new `step{N}` checkpoints, submit one eval job per checkpoint through
the scheduler client (capped concurrency), parse each results.json, and
log the accuracy curve.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional

from areal_tpu.base import constants, logging
from areal_tpu.scheduler.client import JobState, SchedulerClient, make_scheduler

logger = logging.getLogger("evaluator")


@dataclasses.dataclass
class EvaluationStep:
    global_step: int
    ckpt_dir: str
    job_name: Optional[str] = None
    output_path: str = ""
    done: bool = False
    result: Optional[dict] = None


class AutomaticEvaluator:
    def __init__(
        self,
        save_root: str,  # .../save/<role>/ containing step{N}/dp0 dirs
        data_path: str,
        output_root: str,
        scheduler: Optional[SchedulerClient] = None,
        max_concurrent_jobs: int = 1,
        eval_args: Optional[Dict] = None,
        task: str = "math",  # math | code: picks the eval harness
        job_env: Optional[Dict[str, str]] = None,  # extra env for eval jobs
    ):
        if task not in ("math", "code"):
            raise ValueError(f"unknown eval task {task!r}")
        self.job_env = job_env
        self.save_root = save_root
        self.data_path = data_path
        self.output_root = output_root
        self.task = task
        self.scheduler = scheduler or make_scheduler("local")
        self.max_concurrent_jobs = max_concurrent_jobs
        self.eval_args = eval_args or {}
        self.steps: Dict[int, EvaluationStep] = {}

    def discover_new_ckpts(self) -> List[EvaluationStep]:
        if not os.path.isdir(self.save_root):
            return []
        new = []
        for name in sorted(os.listdir(self.save_root)):
            m = re.fullmatch(r"step(\d+)", name)
            if not m:
                continue
            step = int(m.group(1))
            if step in self.steps:
                continue
            d = os.path.join(self.save_root, name)
            # saved per DP rank; rank 0 is the canonical copy
            dp0 = os.path.join(d, "dp0")
            ckpt = dp0 if os.path.isdir(dp0) else d
            if not os.path.exists(os.path.join(ckpt, "config.json")):
                continue  # still being written
            es = EvaluationStep(
                global_step=step,
                ckpt_dir=ckpt,
                output_path=os.path.join(self.output_root, f"step{step}.json"),
            )
            self.steps[step] = es
            new.append(es)
        return new

    def _n_running(self) -> int:
        return sum(
            1
            for es in self.steps.values()
            if es.job_name and not es.done
            and self.scheduler.find(es.job_name).state == JobState.RUNNING
        )

    def _maybe_submit(self):
        for step in sorted(self.steps):
            es = self.steps[step]
            if es.job_name is not None or es.done:
                continue
            if self._n_running() >= self.max_concurrent_jobs:
                return
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            cmd = [
                sys.executable,
                os.path.join(repo_root, "evaluation", f"{self.task}_eval.py"),
                f"ckpt={es.ckpt_dir}",
                f"data={self.data_path}",
                f"output={es.output_path}",
            ] + [f"{k}={v}" for k, v in self.eval_args.items()]
            es.job_name = self.scheduler.submit(
                f"eval_step{step}", cmd, env=self.job_env
            )

    def _collect(self):
        for es in self.steps.values():
            if es.done or es.job_name is None:
                continue
            info = self.scheduler.find(es.job_name)
            if info.state == JobState.COMPLETED and os.path.exists(es.output_path):
                with open(es.output_path) as f:
                    es.result = json.load(f)
                es.done = True
                logger.info(
                    f"eval step {es.global_step}: "
                    f"accuracy={es.result['accuracy']:.4f}"
                )
            elif info.state in (JobState.FAILED, JobState.CANCELLED):
                es.done = True
                logger.warning(f"eval job for step {es.global_step} failed")

    def step(self):
        """One poll: discover, submit, collect."""
        self.discover_new_ckpts()
        self._maybe_submit()
        self._collect()

    def run_until_idle(self, timeout: float = 3600):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.step()
            pending = [
                es for es in self.steps.values() if not es.done
            ]
            if not pending:
                return
            time.sleep(1.0)
        raise TimeoutError("evaluator still has pending jobs")

    def results(self) -> Dict[int, float]:
        return {
            s: es.result["accuracy"]
            for s, es in self.steps.items()
            if es.done and es.result
        }

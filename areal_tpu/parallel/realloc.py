"""Parameter reallocation: reshard live weights between model deployments.

Counterpart of the reference's param_realloc subsystem
(realhf/impl/model/comm/param_realloc.py — sender/receiver step plans,
interval scatter/gather CUDA kernels, NCCL groups between disjoint GPU
sets). On TPU the entire mechanism collapses:

- same process set, different mesh/sharding: `jax.device_put(params,
  target_shardings)` — XLA plans the all-to-all over ICI itself.
- disjoint process sets (trainer pod -> generation pod over DCN, the
  reference's DISK default, model_worker.py:1055): checkpoint-mediated
  through a shared filesystem, with versioned directories and GC.

The disk format is a flat .npz (fast, numpy-native) plus a JSON meta; HF
safetensors export stays separate (models/hf.save_hf_model) for
user-facing checkpoints.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from areal_tpu.parallel.sharding import param_shardings

Params = Dict[str, Any]


def reshard_params(params: Params, target_mesh) -> Params:
    """Live resharding onto a different mesh/sharding (same process set)."""
    return jax.device_put(params, param_shardings(params, target_mesh))


# ---------------------------------------------------------------------------
# Disk-mediated weight sync (trainer -> generation servers)
# ---------------------------------------------------------------------------


def _flatten(params: Params, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Params:
    out: Params = {}
    for k, v in flat.items():
        parts = k.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def save_param_version(params: Params, root: str, version: int, meta: Optional[dict] = None):
    """Write a versioned weight snapshot atomically (dir rename commit)."""
    final = os.path.join(root, f"v{version}")
    tmp = final + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    flat = _flatten(jax.device_get(params))
    np.savez(os.path.join(tmp, "params.npz"), **flat)
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"version": version, **(meta or {})}, f)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    return final


def load_param_version(root: str, version: int) -> Params:
    path = os.path.join(root, f"v{version}", "params.npz")
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    return _unflatten(flat)


def latest_param_version(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    versions = [
        int(d[1:])
        for d in os.listdir(root)
        if d.startswith("v") and d[1:].isdigit()
        and os.path.isfile(os.path.join(root, d, "meta.json"))
    ]
    return max(versions) if versions else None


def gc_param_versions(root: str, keep_latest: int = 2):
    """Remove old weight snapshots (counterpart of gserver_manager GC,
    realhf/system/gserver_manager.py:287-304)."""
    if not os.path.isdir(root):
        return
    versions = sorted(
        int(d[1:]) for d in os.listdir(root) if d.startswith("v") and d[1:].isdigit()
    )
    for v in versions[:-keep_latest] if keep_latest else versions:
        shutil.rmtree(os.path.join(root, f"v{v}"), ignore_errors=True)

"""Token-level loss/logprob primitives over packed rows.

Replaces the reference's vocab-parallel cross entropy and packed logprob
gathering (realhf/impl/model/parallelism/tensor_parallel/modules.py:1180,
realhf/impl/model/utils/functional.py): under GSPMD the vocab dimension is
just a sharded axis, so a plain log_softmax + gather compiles to the same
collectives the hand-written vocab-parallel CE performs.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def gather_logprobs(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """log P(labels) under logits along the last axis. fp32."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return picked - lse


def next_token_logprobs(
    logits: jnp.ndarray,  # [R, T, V] fp32
    input_ids: jnp.ndarray,  # [R, T]
    segment_ids: jnp.ndarray,  # [R, T], 0 = pad
) -> jnp.ndarray:
    """logprob[t] = log P(token[t+1] | prefix) when t+1 continues the same
    segment; 0 elsewhere (sequence-final tokens, padding). Shape [R, T].

    Matches the reference convention where packed logprobs are shifted so
    position t scores the token emitted *at* t+1.
    """
    next_ids = jnp.concatenate(
        [input_ids[:, 1:], jnp.zeros_like(input_ids[:, :1])], axis=1
    )
    next_seg = jnp.concatenate(
        [segment_ids[:, 1:], jnp.zeros_like(segment_ids[:, :1])], axis=1
    )
    valid = (segment_ids > 0) & (next_seg == segment_ids)
    logp = gather_logprobs(logits, next_ids)
    return jnp.where(valid, logp, 0.0)


def next_token_entropy(
    logits: jnp.ndarray, segment_ids: jnp.ndarray
) -> jnp.ndarray:
    """Per-position predictive entropy, masked like next_token_logprobs."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ent = -jnp.sum(jnp.exp(logp) * logp, axis=-1)
    return jnp.where(segment_ids > 0, ent, 0.0)


def sft_loss(
    logits: jnp.ndarray,  # [R, T, V]
    input_ids: jnp.ndarray,  # [R, T]
    segment_ids: jnp.ndarray,  # [R, T]
    loss_mask: jnp.ndarray,  # [R, T] 1.0 where the *target* token (t+1) counts
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Next-token cross entropy over masked positions.

    loss_mask is given per-position in the shifted frame: mask[t] = 1 means
    the prediction made at t (of token t+1) contributes. Returns
    (sum_loss, n_tokens); callers normalize globally so DP shards with
    different token counts average correctly.
    """
    logp = next_token_logprobs(logits, input_ids, segment_ids)
    mask = loss_mask.astype(jnp.float32)
    return -jnp.sum(logp * mask), jnp.sum(mask)


def masked_normalization(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-5,
    unbiased: bool = True,
) -> jnp.ndarray:
    """Whiten x over masked elements (advantage normalization).

    Under pjit the batch is global, so the mean/std are global without any
    explicit collective (reference: realhf/impl/model/utils/functional.py
    masked_normalization with its dist.all_reduce).
    """
    mask = mask.astype(jnp.float32)
    x32 = x.astype(jnp.float32)
    n = jnp.maximum(mask.sum(), 1.0)
    mean = jnp.sum(x32 * mask) / n
    var = jnp.sum(((x32 - mean) ** 2) * mask) / jnp.maximum(
        n - (1.0 if unbiased else 0.0), 1.0
    )
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return jnp.where(mask > 0, out, 0.0).astype(x.dtype)

"""Stream dataset: makes async rollouts look like a dataset to the trainer.

Counterpart of the reference's PullerStreamDataset
(realhf/system/stream_dataset.py:23-106): a background thread pulls JSON
trajectories from the rollout workers' push stream into a queue; the
model worker's "fetch" handler drains it into `SequenceSample` batches.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

from areal_tpu.api import data_api
from areal_tpu.base import logging, tracing
from areal_tpu.system.push_pull_stream import NameResolvingZmqPuller

logger = logging.getLogger("stream_dataset")


class PullerStreamDataset:
    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        puller_index: int = 0,
        max_queue_size: int = 4096,
        pull_timeout_ms: int = 100,
    ):
        self.puller = NameResolvingZmqPuller(
            experiment_name, trial_name, puller_index=puller_index
        )
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue_size)
        self._stop = threading.Event()
        self._pull_timeout_ms = pull_timeout_ms
        self._thread = threading.Thread(target=self._pull_worker, daemon=True)
        self._thread.start()
        self.n_pulled = 0

    def _pull_worker(self):
        while not self._stop.is_set():
            try:
                d = self.puller.pull(timeout_ms=self._pull_timeout_ms)
            except TimeoutError:
                continue
            except Exception:
                logger.exception("puller error")
                continue
            try:
                sample = data_api.sample_from_json(d)
            except Exception:
                logger.exception("bad trajectory json dropped")
                continue
            self.n_pulled += 1
            # Queue residency is traced per sample: span from arrival on
            # this host to the fetch that drains it, parented under the
            # rollout's episode span (trace ctx rides the sample
            # metadata; 0 when tracing is off — never allocated).
            recv_ns = tracing.now_ns() if tracing.enabled() else 0
            # Block (with stop checks) rather than drop: the manager already
            # counted this trajectory as submitted, so dropping it would
            # desync the staleness accounting. Blocking applies backpressure
            # through the ZMQ high-water mark to the rollout workers.
            while not self._stop.is_set():
                try:
                    self._queue.put((recv_ns, sample), timeout=1)
                    break
                except queue.Full:
                    continue

    def qsize(self) -> int:
        return self._queue.qsize()

    def poll_batch(self, max_samples: int = 64) -> Optional["data_api.SequenceSample"]:
        """Drain up to max_samples pulled trajectories into one batch."""
        samples: List[data_api.SequenceSample] = []
        while len(samples) < max_samples:
            try:
                recv_ns, sample = self._queue.get_nowait()
            except queue.Empty:
                break
            if tracing.enabled() and recv_ns:
                ctx = (sample.metadata.get("trace_ctx") or [None])[0]
                tracing.record_span(
                    "stream.recv", recv_ns,
                    ctx=tracing.extract(ctx),
                    qid=str(sample.ids[0]) if sample.ids else "",
                )
            samples.append(sample)
        if not samples:
            return None
        return data_api.SequenceSample.gather(samples)

    def __len__(self):
        # Unknown a priori; reference returns the configured dataset size.
        return self.qsize()

    def close(self):
        self._stop.set()
        self._thread.join(timeout=3)
        self.puller.close()

"""Paged KV-cache machinery for the serving engine.

TPU-native counterpart of SGLang/vLLM's paged attention memory manager
(the reference serves through patched SGLang — realhf/impl/model/backend/
sglang.py:192-500 — whose RadixAttention allocates KV in fixed-size pages
from a token pool). Here:

- KV lives in a global page pool `[L, Hkv, n_pages, page_size, hd]`
  shared by every slot; a host-side `PageAllocator` hands out pages and a
  per-slot page table `[B, pages_per_seq]` maps sequence position ->
  pool page. Memory scales with *tokens in flight*, not
  `batch * max_seq_len`, which is what makes 31k-token generation
  (benchmark/verl_v0_3_0_post1_76084d3/README.md:38-44) servable.
- Decode attention dispatches to jax's TPU Pallas paged-attention kernel
  (jax.experimental.pallas.ops.tpu.paged_attention) on TPU backends and
  to a gather + masked-softmax XLA fallback elsewhere (the CPU oracle).
- Page 0 is a reserved trash page: writes for inactive slots and
  prompt-padding overflow are routed there so a freed-and-reused page can
  never be corrupted by a stale slot.

Everything here is shape-static: the pool, the page table width, and the
decode block are compiled once per engine lifetime.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp

from areal_tpu.base import env_registry
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import _mlp, _norm
from areal_tpu.ops.wquant import qmat
from areal_tpu.ops.norms import rms_norm
from areal_tpu.ops.rotary import apply_rotary, rotary_cos_sin, rotary_inv_freq
from areal_tpu.ops.sampling import NEG_INF

TRASH_PAGE = 0  # reserved sink page, never allocated
# top-k requests at or below this threshold sample through lax.top_k
# instead of a full-vocab sort (warp_sample tier 1).
TOPK_FAST_MAX = 128


def pages_needed(n_tokens: int, page_size: int) -> int:
    return max(1, -(-n_tokens // page_size))


# ----------------------------------------------------------------------
# int8 KV pools
# ----------------------------------------------------------------------
#
# With kv_cache_dtype="int8" a pool is a (data, scales) pair instead of a
# bare array: data [L, Hkv, N, pg, hd] int8, scales [L, Hkv, N, pg] f32 —
# per-token-per-head absmax over the head dim, stored WITHOUT a trailing
# size-1 dim (TPU tiled layouts pad the minor dim to 128 lanes, so a
# [.., pg, 1] f32 array can physically occupy 128x its logical bytes;
# squeezed, pg=128 IS the lane dim). Decode is HBM-bandwidth-bound
# streaming KV pages, so int8 halves the pool's resident bytes — double
# the tokens-in-flight a pool budget holds (fewer preempt/resubmit
# cycles at 16-32k contexts) — and halves the gathered bytes on the XLA
# attention path. NOTE the stock Pallas kernel is NOT the fast path for
# int8: it broadcasts the scales to full head_dim in f32 before
# pallas_call (paged_attention_kernel.py:421-431), materializing 2x the
# bf16 pool per call, so 'auto' keeps quantized pools on the XLA path
# (see paged_decode_attention). A from-scratch kernel streaming
# [.., pg, 1] scales is the follow-up. The reference's serving backend
# has no KV quantization (realhf/impl/model/backend/sglang.py). Pools
# stay plain arrays when not quantized; every helper accepts both.

# Dequant convention: x ~= int8 * scale / 127.5. ONE source of truth
# (ops/quant_const — dependency-free, so importing this module still
# doesn't pull the Pallas stack; all kernel imports here stay lazy, at
# the branches that dispatch to them). The structural identity of this
# re-export with the kernel's is pinned in tests/engine/test_kv_int8.py.
from areal_tpu.ops.quant_const import KV_INT8_MAX  # noqa: F401  (re-export)


def kv_pool_data(pool) -> jnp.ndarray:
    """The data leaf of a pool (bare array, or (data, scales) pair)."""
    return pool[0] if isinstance(pool, tuple) else pool


def quantize_kv(x: jnp.ndarray):
    """[..., hd] float -> (int8 [..., hd], f32 scales [..., 1]).

    Matches the kernel's from_int8 dequant (w * s / 127.5). The exact-max
    element clips to 127 (~0.4% error on that single element) instead of
    wrapping at rint(127.5) = 128."""
    x32 = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True), 1e-6)
    w = jnp.clip(jnp.rint(x32 * (KV_INT8_MAX / s)), -127, 127)
    return w.astype(jnp.int8), s


def dequantize_kv(w: jnp.ndarray, s: jnp.ndarray, dtype) -> jnp.ndarray:
    return (w.astype(jnp.float32) * (s / KV_INT8_MAX)).astype(dtype)


def gather_kv_tokens(pool, page_ids, n_tokens: int):
    """Gather one sequence's KV out of the pool in token-major order
    (the disaggregated-serving handoff export, engine/kv_handoff.py).

    ``page_ids`` are the sequence's pages in order; tokens beyond
    ``n_tokens`` (final-page padding) are dropped. Plain pools return
    ``[L, Hkv, n_tokens, hd]``; int8 pools return the
    ``(data, scales [L, Hkv, n_tokens])`` pair. Dispatch-only — the
    caller device_gets the (small) result off the serve loop."""
    idx = jnp.asarray(page_ids, jnp.int32)

    def g(arr, has_hd: bool):
        x = arr[:, :, idx]  # [L, Hkv, P, pg, (hd)]
        L, H = x.shape[0], x.shape[1]
        if has_hd:
            return x.reshape(L, H, -1, x.shape[-1])[:, :, :n_tokens]
        return x.reshape(L, H, -1)[:, :, :n_tokens]

    if isinstance(pool, tuple):
        return g(pool[0], True), g(pool[1], False)
    return g(pool, True)


class PageAllocator:
    """Host-side free-list allocator over the pool's page indices.

    Page 0 (TRASH_PAGE) is reserved. Same role as SGLang's
    TokenToKVPool allocator; transparently simple because the device
    side only ever sees the page-table indices."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError("need at least 2 pages (one is the trash page)")
        self.n_pages = n_pages
        self._free: List[int] = list(range(n_pages - 1, 0, -1))

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages, or None (and no state change) if unavailable."""
        if n > len(self._free):
            return None
        got = self._free[-n:][::-1]
        del self._free[len(self._free) - n:]
        return got

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == TRASH_PAGE:
                raise ValueError("freeing the trash page")
            self._free.append(p)


# ----------------------------------------------------------------------
# Paged decode attention
# ----------------------------------------------------------------------


def paged_attention_kernel_ok(page_size: int, head_dim: int, pages_per_seq: int) -> bool:
    """Shape gate for jax's TPU paged-attention Pallas kernel: the kernel
    tiles (page, hd) blocks into VMEM, so lanes (hd) must be 128-aligned
    and sublanes (page) 8-aligned."""
    return head_dim % 128 == 0 and page_size % 8 == 0 and pages_per_seq >= 1


def _pages_per_compute_block(pages_per_seq: int, cap: int = 8) -> int:
    d = min(cap, pages_per_seq)
    while pages_per_seq % d:
        d -= 1
    return d


def _paged_attention_xla(q, k_pages, v_pages, lengths, page_indices, scale):
    """Gather + masked softmax oracle/fallback.

    q: [B, Hq, hd]; k/v_pages: [Hkv, N, pg, hd] (or int8 (data, scales)
    pairs — gathered quantized, dequantized after the gather so the bytes
    moved stay halved); lengths: [B] valid tokens (INCLUDING the one
    written this step); page_indices: [B, P]."""
    B, Hq, hd = q.shape
    Hkv, _, pg, _ = kv_pool_data(k_pages).shape
    P = page_indices.shape[1]
    group = Hq // Hkv

    def gather(pool):
        # [Hkv, B, P, pg, hd] -> [B, P*pg, Hkv, hd]
        if isinstance(pool, tuple):
            d, s = pool  # s: [Hkv, N, pg] squeezed
            g = dequantize_kv(d[:, page_indices],
                              s[:, page_indices][..., None], jnp.float32)
        else:
            g = pool[:, page_indices]
        return g.transpose(1, 2, 3, 0, 4).reshape(B, P * pg, Hkv, hd)

    k = gather(k_pages)
    v = gather(v_pages)
    qg = q.reshape(B, Hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bshd->bhgs", qg, k.astype(jnp.float32)) * scale
    pos = jnp.arange(P * pg)[None, :]
    mask = pos < lengths[:, None]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bshd->bhgd", probs, v.astype(jnp.float32))
    return out.reshape(B, Hq, hd).astype(q.dtype)


def resolve_paged_decode_impl(
    impl: str,
    quantized: bool,
    page_size: int,
    head_dim: int,
    pages_per_seq: int,
    tp_ok: bool = True,
) -> str:
    """Resolve 'auto' to a concrete paged-decode impl (trace-time static
    decision, mirroring ops/attention.resolve_attn_impl — and the
    dispatch table kernel_micro_paged_decode measures case by case).
    Explicit impls pass through untouched.

    int8 pools use OUR kernel (ops/pallas/paged_decode_int8) on TPU:
    the stock kernel broadcasts the scales to full head_dim in f32
    before pallas_call (jax .../paged_attention_kernel.py:421-431),
    materializing 2x the bf16 pool per call. impl='kernel' stays
    available for an explicit A/B. Off-TPU (and whenever shapes or the
    TP head split disqualify a kernel) everything resolves to the XLA
    gather path."""
    if impl != "auto":
        return impl
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if quantized:
        if on_tpu and tp_ok:
            # Import inside the on_tpu arm: keeps the Pallas stack off
            # CPU-only import paths.
            from areal_tpu.ops.pallas.paged_decode_int8 import (
                int8_paged_kernel_ok,
            )

            if int8_paged_kernel_ok(page_size, head_dim):
                return "int8_kernel"
        return "xla"
    return (
        "kernel"
        if on_tpu
        and paged_attention_kernel_ok(page_size, head_dim, pages_per_seq)
        and tp_ok
        else "xla"
    )


def paged_decode_attention(
    q,  # [B, Hq, hd]
    k_pages,  # [Hkv, N, pg, hd]
    v_pages,
    lengths,  # [B] int32, incl. the token written this step
    page_indices,  # [B, P] int32
    softmax_scale: Optional[float] = None,
    mesh=None,
    impl: str = "auto",
):
    """Single-step decode attention over the paged pool.

    impl: 'kernel' (Pallas), 'xla', or 'auto' (kernel on TPU when shapes
    allow). With a mesh whose `tensor` axis is >1, the Pallas kernel runs
    under shard_map with heads sharded on `tensor` (pallas_call is opaque
    to the SPMD partitioner — same treatment as sharded_splash_attention,
    ops/attention.py). int8 (data, scales) pools flow to the kernel as
    QuantizedTensor (fused dequant in VMEM) and to the XLA path as a
    gather-then-dequantize."""
    B, Hq, hd = q.shape
    quantized = isinstance(k_pages, tuple)
    Hkv, _, pg, _ = kv_pool_data(k_pages).shape
    P = page_indices.shape[1]
    scale = float(softmax_scale) if softmax_scale is not None else hd**-0.5
    tensor_size = mesh.shape.get("tensor", 1) if mesh is not None else 1
    # Under tensor parallelism the kernel runs per shard with heads split
    # on `tensor` — impossible when the head counts don't divide (the
    # pool then replicates, ServingEngine._ensure_pool); the GSPMD-
    # partitionable einsum path handles that layout instead.
    tp_ok = Hkv % tensor_size == 0 and Hq % tensor_size == 0
    if impl == "auto":
        impl = resolve_paged_decode_impl(impl, quantized, pg, hd, P, tp_ok)
    elif impl in ("kernel", "int8_kernel") and not tp_ok:
        raise ValueError(
            f"paged-attention kernel under tensor={tensor_size} needs head "
            f"counts divisible by it (Hq={Hq}, Hkv={Hkv}); use impl='xla'"
        )
    if impl == "xla":
        return _paged_attention_xla(q, k_pages, v_pages, lengths, page_indices, scale)
    if impl == "int8_kernel":
        if not quantized:
            raise ValueError("impl='int8_kernel' needs an int8 (data, "
                             "scales) pool; got a plain array")
        from areal_tpu.ops.pallas.paged_decode_int8 import (
            int8_paged_decode_attention,
        )

        qs = q * jnp.asarray(scale, q.dtype)
        interp = jax.default_backend() not in ("tpu", "axon")
        if tensor_size > 1:
            from areal_tpu.utils.jax_compat import shard_map
            from jax.sharding import PartitionSpec as Pt

            pool_spec = (Pt("tensor", None, None, None),
                         Pt("tensor", None, None))
            out = shard_map(
                functools.partial(int8_paged_decode_attention,
                                  interpret=interp),
                mesh=mesh,
                in_specs=(Pt(None, "tensor", None), pool_spec, pool_spec,
                          Pt(None), Pt(None, None)),
                out_specs=Pt(None, "tensor", None),
                check_vma=False,
            )(qs, k_pages, v_pages, lengths, page_indices)
        else:
            out = int8_paged_decode_attention(
                qs, k_pages, v_pages, lengths, page_indices,
                interpret=interp,
            )
        return out.astype(q.dtype)

    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention_kernel as pak,
        quantization_utils as pqu,
    )

    ppcb = _pages_per_compute_block(P)
    # int8 pools: q stays in its float dtype (the kernel dequantizes KV
    # to bf16 in VMEM); otherwise match the pool dtype as before.
    qs = q * jnp.asarray(scale, q.dtype)
    if not quantized:
        qs = qs.astype(k_pages.dtype)

    def kernel(qq, kk, vv, ll, pi):
        if isinstance(kk, tuple):
            # Stock kernel wants [.., pg, 1] scales; ours are squeezed.
            kk = pqu.QuantizedTensor(kk[0], kk[1][..., None])
            vv = pqu.QuantizedTensor(vv[0], vv[1][..., None])
        return pak.paged_attention(
            qq, kk, vv, ll, pi, pages_per_compute_block=ppcb
        )

    tensor = mesh.shape.get("tensor", 1) if mesh is not None else 1
    if tensor > 1:
        from jax.sharding import PartitionSpec as Pt
        from areal_tpu.utils.jax_compat import shard_map

        pool_spec = Pt("tensor", None, None, None)
        if quantized:  # spec subtree mirrors (data 4-D, scales 3-D)
            pool_spec = (pool_spec, Pt("tensor", None, None))
        out = shard_map(
            kernel,
            mesh=mesh,
            in_specs=(
                Pt(None, "tensor", None),
                pool_spec,
                pool_spec,
                Pt(None),
                Pt(None, None),
            ),
            out_specs=Pt(None, "tensor", None),
            check_vma=False,
        )(qs, k_pages, v_pages, lengths, page_indices)
    else:
        out = kernel(qs, k_pages, v_pages, lengths, page_indices)
    return out.astype(q.dtype)


# ----------------------------------------------------------------------
# Paged decode step (one token per slot through all layers)
# ----------------------------------------------------------------------


def _paged_decode_layer(
    x, lp, cfg, cos, sin, kp_l, vp_l, w_pidx, w_off, page_indices, lengths,
    cdt, mesh, attn_impl,
):
    """One layer for one new token per slot against the paged pool.

    x: [B, D]; kp_l/vp_l: [Hkv, N, pg, hd]; w_pidx/w_off: [B] write page +
    offset (already trash-routed for inactive slots); lengths: [B] fill
    count BEFORE this token. Mirrors models/generation._decode_layer."""
    B, _ = x.shape
    h = _norm(x, lp["ln1"], cfg)
    a = lp["attn"]
    q = qmat(h, a["wq"], cdt)
    k = qmat(h, a["wk"], cdt)
    v = qmat(h, a["wv"], cdt)
    if "bq" in a:
        q = q + a["bq"].astype(cdt)
        k = k + a["bk"].astype(cdt)
        v = v + a["bv"].astype(cdt)
    q = q.reshape(B, cfg.n_q_heads, cfg.head_dim)
    k = k.reshape(B, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, a["q_norm"], cfg.norm_eps)
        k = rms_norm(k, a["k_norm"], cfg.norm_eps)
    if cos is not None:
        q = apply_rotary(q, cos, sin, cfg.rotary_interleaved)
        k = apply_rotary(k, cos, sin, cfg.rotary_interleaved)
    # Scatter the new token's K/V into its page. [Hkv, B, hd] values at
    # (page w_pidx[b], offset w_off[b]) per slot; allocator guarantees
    # active slots' pages are distinct, trash collisions are harmless.
    def scatter(pool, val_t):  # val_t: [Hkv, B, hd]
        if isinstance(pool, tuple):
            w, s = quantize_kv(val_t)
            return (pool[0].at[:, w_pidx, w_off].set(w),
                    pool[1].at[:, w_pidx, w_off].set(s[..., 0]))
        return pool.at[:, w_pidx, w_off].set(val_t.astype(pool.dtype))

    kp_l = scatter(kp_l, k.transpose(1, 0, 2))
    vp_l = scatter(vp_l, v.transpose(1, 0, 2))
    out = paged_decode_attention(
        q, kp_l, vp_l, lengths + 1, page_indices, mesh=mesh, impl=attn_impl
    )
    attn_out = qmat(out.reshape(B, cfg.q_dim), a["wo"], cdt)
    if "bo" in a:
        attn_out = attn_out + a["bo"].astype(cdt)
    x = x + attn_out
    h = _norm(x, lp["ln2"], cfg)
    if cfg.moe is not None:
        from areal_tpu.models.moe import decode_moe_overrides, moe_mlp

        # Decode-time dispatch/capacity differ from training: the
        # capacity formula quantizes badly at decode row counts (C=1
        # drops on any router skew), so decode defaults to dropless —
        # see decode_moe_overrides.
        d_dispatch, d_cap = decode_moe_overrides(cfg)
        m, moe_aux = moe_mlp(
            h, lp["mlp"], cfg, cdt,
            capacity_factor=d_cap, dispatch=d_dispatch,
        )
        aux = {
            "moe_drop_rate": moe_aux["drop_rate"].astype(jnp.float32),
            "moe_router_entropy":
                moe_aux["router_entropy"].astype(jnp.float32),
        }
    else:
        m = _mlp(h, lp["mlp"], cfg, cdt)
        aux = {}
    x = x + m
    return x, kp_l, vp_l, aux


def paged_decode_step(
    params, cfg: TransformerConfig, tokens, k_pages, v_pages, page_indices,
    lengths, active, mesh=None, attn_impl: str = "auto",
    return_moe_stats: bool = False,
):
    """One decode step for all slots. tokens: [B] just-sampled inputs;
    lengths: [B] fill BEFORE this token; active: [B] bool (inactive slots'
    writes are routed to the trash page). Returns (logits, pools); with
    return_moe_stats, also a dict of layer-mean router scalars
    (moe_drop_rate / moe_router_entropy; empty for dense models)."""
    cdt = jnp.dtype(cfg.compute_dtype)
    pg = kv_pool_data(k_pages).shape[3]
    B = tokens.shape[0]
    w_pidx = jnp.where(
        active,
        page_indices[jnp.arange(B), lengths // pg],
        TRASH_PAGE,
    ).astype(jnp.int32)
    w_off = jnp.where(active, lengths % pg, 0).astype(jnp.int32)

    x = params["embedding"]["weight"][tokens].astype(cdt)
    if cfg.embedding_multiplier:
        x = x * jnp.asarray(cfg.embedding_multiplier, cdt)
    if cfg.pos_emb == "learned":
        x = x + params["pos_embedding"]["weight"][lengths].astype(cdt)
        cos = sin = None
    else:
        inv_freq = jnp.asarray(
            rotary_inv_freq(
                cfg.head_dim, cfg.rotary_base, cfg.rotary_scaling,
                cfg.rotary_scaling_type, cfg.rotary_scaling_params,
            )
        )
        cos, sin = rotary_cos_sin(lengths, inv_freq)

    def body(x, layer):
        lp, kp, vp = layer
        x, kp, vp, aux = _paged_decode_layer(
            x, lp, cfg, cos, sin, kp, vp, w_pidx, w_off, page_indices,
            lengths, cdt, mesh, attn_impl,
        )
        return x, (kp, vp, aux)

    x, (k_pages, v_pages, aux) = jax.lax.scan(
        body, x, (params["layers"], k_pages, v_pages)
    )
    moe_stats = {k: v.mean() for k, v in aux.items()}  # mean over layers
    x = _norm(x, params["final_norm"], cfg)
    if "head_q" in params:  # int8 decode weights (ops/wquant.py)
        logits = qmat(x, params["head_q"], cdt).astype(jnp.float32)
    else:
        head_w = (
            params["embedding"]["weight"].T
            if cfg.tied_embeddings
            else params["head"]["weight"]
        )
        logits = (x @ head_w.astype(cdt)).astype(jnp.float32)
    if return_moe_stats:
        return logits, k_pages, v_pages, moe_stats
    return logits, k_pages, v_pages


# ----------------------------------------------------------------------
# Chunked prefill (long prompts)
# ----------------------------------------------------------------------


def _chunk_prefill_body(
    params,
    cfg: TransformerConfig,
    tokens,  # [C] chunk token ids, right-padded to the chunk size
    k_pages,
    v_pages,
    page_row,  # [P] the request's page-table row
    start,  # scalar int32: absolute position of tokens[0]
    valid_len,  # scalar int32: valid tokens in this chunk
    attn_impl: str = "auto",
    mesh=None,
):
    """One chunk of ONE long prompt through the paged pool.

    A chunk of C tokens at positions start..start+C-1 is exactly C decode
    rows of the same request with staggered lengths sharing one
    page-table row: every row's K/V scatters into its (page, offset)
    first, then row i's attention masks gathered keys at flat positions
    < start+i+1 — full prefix (earlier chunks, already in the pool) plus
    intra-chunk causal. So this reuses paged_decode_step verbatim, which
    keeps ONE compiled program for any prompt length (the batched
    prefill path compiles per length bucket — ruinous for 16-32k prompts
    with varied lengths; the reference's serving backend chunk-prefills
    long prompts for the same reason).

    Returns (last_logits [V] — the final valid row's, for first-token
    sampling; meaningful only on the prompt's last chunk — k_pages,
    v_pages).

    The C rows run through paged_decode_step in sub-chunks: the TPU
    paged-attention kernel prefetches its [rows, P] page_indices operand
    into SMEM (~1 MB), so rows*P*4 bytes must stay well under that — at
    C=2048 and a 16k-context pool (P~138) a single call is a guaranteed
    compile-time SMEM overflow (measured on v5e: 1,130,496 B > 1,048,576).
    Sub-chunks also keep logits at [sub, V] instead of [C, V] (268 MB at
    C=2048, V=32k): only the selected last-valid row's logits leave the
    scan."""
    C = tokens.shape[0]
    P = page_row.shape[0]
    # Half the 1 MB SMEM for the page-index operand; the rest holds the
    # kernel's other prefetched scalars. AREAL_CHUNK_SMEM_BUDGET overrides
    # for tests (forcing n_sub > 1 on CPU pools too small to need it);
    # read at trace time, so set it before the first call in a process.
    smem_budget = env_registry.get_int("AREAL_CHUNK_SMEM_BUDGET")
    rows_cap = max(8, smem_budget // (P * 4))
    # Balanced ceil-division with a padded tail, NOT a divisor search:
    # any chunk size (prime included) splits into n_sub equal sub-chunks;
    # pad rows sit past valid_len, so `active` masks them like any ragged
    # tail. Balancing (n_sub first, then sub) minimizes the padding —
    # sub=min(C,rows_cap) at C=2048/cap=949 would pad 799 wasted rows.
    n_sub = -(-C // min(C, rows_cap))
    sub = -(-C // n_sub)
    pad = n_sub * sub - C
    tokens = jnp.pad(tokens, (0, pad)) if pad else tokens
    target = jnp.maximum(valid_len - 1, 0)

    def body(carry, xs):
        k_pages, v_pages, acc = carry
        toks_s, base = xs
        rows = base + jnp.arange(sub, dtype=jnp.int32)
        lengths = start + rows
        active = rows < valid_len
        page_indices = jnp.broadcast_to(page_row, (sub, P))
        logits, k_pages, v_pages = paged_decode_step(
            params, cfg, toks_s, k_pages, v_pages, page_indices, lengths,
            active, mesh=mesh, attn_impl=attn_impl,
        )
        sel = (rows == target).astype(logits.dtype)
        acc = acc + jnp.einsum("r,rv->v", sel, logits)
        return (k_pages, v_pages, acc), None

    acc0 = jnp.zeros((cfg.vocab_size,), jnp.float32)
    bases = (jnp.arange(n_sub, dtype=jnp.int32) * sub)
    (k_pages, v_pages, last), _ = jax.lax.scan(
        body, (k_pages, v_pages, acc0), (tokens.reshape(n_sub, sub), bases)
    )
    return last, k_pages, v_pages


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "mesh"),
    donate_argnames=("k_pages", "v_pages"),
)
def paged_chunk_prefill(
    params,
    cfg: TransformerConfig,
    tokens,
    k_pages,
    v_pages,
    page_row,
    start,
    valid_len,
    attn_impl: str = "auto",
    mesh=None,
):
    """Legacy 3-transfer entry point (tokens + start + valid_len staged
    separately): see ``_chunk_prefill_body`` for the semantics. Kept as
    the AREAL_DECODE_RESIDENT=0 arm of the decode-state A/B."""
    return _chunk_prefill_body(
        params, cfg, tokens, k_pages, v_pages, page_row, start, valid_len,
        attn_impl=attn_impl, mesh=mesh,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "mesh"),
    donate_argnames=("k_pages", "v_pages"),
)
def paged_chunk_prefill_packed(
    params,
    cfg: TransformerConfig,
    ctl,  # [C + 2] int32: tokens[0:C] | start | valid_len
    k_pages,
    v_pages,
    page_row,
    attn_impl: str = "auto",
    mesh=None,
):
    """``_chunk_prefill_body`` with the per-chunk control — token ids,
    absolute start position, valid length — packed into ONE staged int32
    array. The legacy entry point pays three H2D transfers per chunk
    (tokens + two scalars); each transfer is a separate dispatch (and on
    remote-tunneled devices a separate round trip), so a 16k prompt at
    C=512 paid ~96 stagings where this pays ~32. Scalars are sliced out
    on device — trace-identical math, pinned by the decode-state parity
    tests."""
    C = ctl.shape[0] - 2
    return _chunk_prefill_body(
        params, cfg, ctl[:C], k_pages, v_pages, page_row, ctl[C],
        ctl[C + 1], attn_impl=attn_impl, mesh=mesh,
    )


# ----------------------------------------------------------------------
# Prefill scatter
# ----------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnames=("k_pages", "v_pages"))
def scatter_prefill(k_pages, v_pages, k_pref, v_pref, flat_page_ids):
    """Write batched-prefill KV into the pool.

    k_pref/v_pref: [L, n, pad, Hkv, hd] from the packed forward;
    flat_page_ids: [n * pad//pg] pool pages in row-major (row, chunk)
    order, TRASH_PAGE for chunks past a row's allocation. int8 pools
    quantize each token's head vector before the scatter."""
    L, n, pad, Hkv, hd = k_pref.shape
    pg = kv_pool_data(k_pages).shape[3]
    n_chunks = pad // pg

    def to_chunks(pref):
        # [L, n, pad, Hkv, x] -> [L, Hkv, n*chunks, pg, x]
        x = pref.shape[-1]
        out = pref.transpose(0, 3, 1, 2, 4).reshape(
            L, Hkv, n, n_chunks, pg, x
        )
        return out.reshape(L, Hkv, n * n_chunks, pg, x)

    def write(pool, pref):
        if isinstance(pool, tuple):
            w, s = quantize_kv(pref)
            return (pool[0].at[:, :, flat_page_ids].set(to_chunks(w)),
                    pool[1].at[:, :, flat_page_ids].set(
                        to_chunks(s)[..., 0]))
        return pool.at[:, :, flat_page_ids].set(
            to_chunks(pref).astype(pool.dtype)
        )

    return write(k_pages, k_pref), write(v_pages, v_pref)


@functools.partial(jax.jit, donate_argnames=("k_pages", "v_pages"))
def scatter_prefill_int8(k_pages, v_pages, k_data, k_scales, v_data,
                         v_scales, page_ids):
    """Write an int8-wire KV prefix straight into an int8 pool — the
    tier-restore fast path (ISSUE 11 satellite): the wire's (data,
    scales) pairs ARE the pool encoding, so a spill + restore round
    trip is bit-exact and never pays dequantize→re-quantize (nor the
    4x float staging bytes).

    k_data/v_data: [L, Hkv, pad, hd] int8 token-major (padded to whole
    pages); k_scales/v_scales: [L, Hkv, pad] f32; page_ids: [pad//pg]
    pool pages in order. Pools must be (data, scales) pairs."""
    L, Hkv, pad, hd = k_data.shape
    pg = k_pages[0].shape[3]
    n_chunks = pad // pg

    def write(pool, data, scales):
        d = data.reshape(L, Hkv, n_chunks, pg, hd)
        s = scales.reshape(L, Hkv, n_chunks, pg)
        return (pool[0].at[:, :, page_ids].set(d),
                pool[1].at[:, :, page_ids].set(s))

    return (write(k_pages, k_data, k_scales),
            write(v_pages, v_data, v_scales))


# ----------------------------------------------------------------------
# Per-slot sampling (shared by the decode block and batched prefill)
# ----------------------------------------------------------------------


def warp_logits(logits, temps, top_ps, top_ks, forbid_rows, eos_mask,
                active_rows=None):
    """The warping half of warp_sample: per-row temperature / top-k /
    top-p / EOS-forbid applied to [B, V] logits. Returns (warped [B, V],
    base_logp [B, V] — log-softmax of the UNWARPED, forbid-masked
    logits, the distribution PPO logprobs are reported under). Shared by
    the decode block's sampling and speculative verification (which
    needs the whole warped distribution, not just a sample).

    Three tiers, picked at runtime by the active rows' settings:
    temperature-only skips warping entirely; top-k-only (all active k <=
    TOPK_FAST_MAX, no top-p) thresholds via `lax.top_k` — far cheaper
    than sorting 32k+ vocab; any top-p (or huge k) pays the full [B, V]
    descending sort (one sort serves both warps). The tiers produce
    identical warped logits for the rows they share, so the sampled
    token for a given rng is tier-invariant.
    """
    logits = logits.astype(jnp.float32)
    em = eos_mask if eos_mask.ndim == 2 else eos_mask[None, :]
    forbid = forbid_rows[:, None] & em
    logits = jnp.where(forbid, NEG_INF, logits)
    base_logp = jax.nn.log_softmax(logits, axis=-1)
    warped = logits / jnp.maximum(temps[:, None], 1e-6)

    def with_cutoffs(warped):
        V = warped.shape[-1]
        # ONE descending sort serves both warps (top-k threshold + top-p
        # nucleus cutoff); two sorts would double the per-step cost.
        sorted_desc = jnp.sort(warped, axis=-1)[:, ::-1]
        k_eff = jnp.where(top_ks <= 0, V, jnp.minimum(top_ks, V))
        kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_ps[:, None]
        cutoff_idx = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
        p_cut = jnp.take_along_axis(sorted_desc, cutoff_idx, axis=-1)
        return jnp.where(warped < jnp.maximum(kth, p_cut), NEG_INF, warped)

    kmax = min(TOPK_FAST_MAX, logits.shape[-1])

    def with_topk_only(warped):
        # k-th largest via lax.top_k: same threshold the sort path
        # gathers at sorted[k-1], without ordering the other V-k logits.
        vals = jax.lax.top_k(warped, kmax)[0]  # [B, kmax] desc
        k_eff = jnp.clip(top_ks, 1, kmax)
        kth = jnp.take_along_axis(vals, (k_eff - 1)[:, None], axis=-1)
        kth = jnp.where((top_ks > 0)[:, None], kth, NEG_INF)
        return jnp.where(warped < kth, NEG_INF, warped)

    # Only ACTIVE rows count: finished slots keep their stale top-k/top-p
    # until the next admission overwrites them, and must not re-enable
    # the sort for temperature-only batches.
    row_topk = top_ks > 0
    row_topp = top_ps < 1.0 - 1e-6
    if active_rows is not None:
        row_topk = row_topk & active_rows
        row_topp = row_topp & active_rows
    any_warp = jnp.any(row_topk | row_topp)
    need_sort = jnp.any(row_topp) | jnp.any(
        jnp.where(row_topk, top_ks, 0) > kmax
    )
    warped = jax.lax.cond(
        any_warp,
        lambda w: jax.lax.cond(need_sort, with_cutoffs, with_topk_only, w),
        lambda w: w,
        warped,
    )
    return warped, base_logp


def warp_sample(logits, rng, temps, top_ps, top_ks, greedy_mask, forbid_rows,
                eos_mask, active_rows=None):
    """Per-row warped sampling: temperature, top-k, top-p, greedy rows,
    and EOS-forbid rows — all as [B] arrays so one compiled program serves
    every mix of per-request params. Returns (tokens [B], logprobs [B] of
    the unwarped distribution, PPO convention — ops/sampling.sample_token).
    Warping tiers documented on warp_logits."""
    warped, base_logp = warp_logits(
        logits, temps, top_ps, top_ks, forbid_rows, eos_mask,
        active_rows=active_rows,
    )
    sampled = jax.random.categorical(rng, warped, axis=-1)
    argmax = jnp.argmax(base_logp, axis=-1)
    tokens = jnp.where(greedy_mask, argmax, sampled).astype(jnp.int32)
    logprobs = jnp.take_along_axis(base_logp, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs


# ----------------------------------------------------------------------
# The decode block
# ----------------------------------------------------------------------


@functools.partial(
    jax.jit,
    donate_argnames=("state",),
    static_argnames=("n_slots",),
)
def apply_admits(
    state,  # tuple of [B] control arrays (see ServingEngine._dstate order)
    slots,  # [m] int32 slot indices (admitted)
    valid,  # [m] bool — False rows are bucket padding, must not write
    plens,  # [m] int32
    toks,  # [m] int32 first sampled tokens
    budgets,  # [m] int32 remaining budget after the first token
    minrs,  # [m] int32 min_remaining
    temps_new,  # [m] f32
    tps_new,  # [m] f32
    tks_new,  # [m] int32
    greedy_new,  # [m] bool
    n_slots: int,
):
    """One fused device update activating admitted slots.

    Keeps ALL per-slot control state device-resident between decode
    blocks — per-slot host writes would each be a host->device round trip,
    which dominates end-to-end latency on remote-tunneled TPUs. Invalid
    (padding) rows are routed to a scratch row beyond the real slots."""
    (lengths, next_input, active, remaining, min_remaining,
     temps, top_ps, top_ks, greedy) = state
    # Route padding rows to index B (one past the end): scatter drops
    # out-of-bounds indices on TPU/XLA's clip semantics would corrupt slot
    # B-1, so extend by one scratch row and slice back.
    idx = jnp.where(valid, slots, n_slots).astype(jnp.int32)

    def upd(arr, new):
        ext = jnp.concatenate([arr, arr[:1]], axis=0)
        ext = ext.at[idx].set(new.astype(arr.dtype))
        return ext[:n_slots]

    lengths = upd(lengths, plens)
    next_input = upd(next_input, toks)
    active = upd(active, jnp.ones_like(slots, bool))
    remaining = upd(remaining, budgets)
    min_remaining = upd(min_remaining, minrs)
    temps = upd(temps, temps_new)
    top_ps = upd(top_ps, tps_new)
    top_ks = upd(top_ks, tks_new)
    greedy = upd(greedy, greedy_new)
    return (lengths, next_input, active, remaining, min_remaining,
            temps, top_ps, top_ks, greedy)


@functools.partial(jax.jit, donate_argnames=("active",))
def apply_deactivations(active, deact_mask):
    """Host-initiated stops (extra stop-token trims, preemptions) must
    land on the device active mask BEFORE the next block, or the dead
    slot would keep writing KV into pages the allocator already freed."""
    return active & ~deact_mask


@functools.partial(
    jax.jit, donate_argnames=("pt_dev",), static_argnames=("n_slots",)
)
def update_page_rows(
    pt_dev,  # [B, P] int32 device page table (donated)
    packed_rows,  # [m, P + 1] int32: col 0 = slot index (< 0 padding),
    #               cols 1: = that slot's replacement page row
    n_slots: int,
):
    """Scatter only the CHANGED page-table rows into the device table.

    The device-resident half of the decode-state contract
    (AREAL_DECODE_RESIDENT): the legacy path re-staged the whole
    [B, max_pages] host mirror every time any slot's row changed — at
    B=64 slots x a 16k-context table that is ~35 KB of H2D per admit/
    finish/page-growth lap for a one-row edit. Here only the dirty rows
    cross the host boundary, fused with their slot indices into ONE
    staged array (each transfer is its own dispatch — and on
    remote-tunneled devices its own round trip — so splitting control
    into slots/valid/rows arrays would triple the count the A/B
    measures); the table itself stays device-resident (donated, like
    apply_admits). Padding rows (slot < 0) route to the scratch row
    past the real slots — same clip-semantics guard as apply_admits."""
    slots = packed_rows[:, 0]
    rows = packed_rows[:, 1:]
    idx = jnp.where(slots >= 0, slots, n_slots).astype(jnp.int32)
    ext = jnp.concatenate([pt_dev, pt_dev[:1]], axis=0)
    ext = ext.at[idx].set(rows.astype(pt_dev.dtype))
    return ext[:n_slots]


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "attn_impl", "mesh"),
    donate_argnames=(
        "k_pages", "v_pages", "lengths", "next_input", "active",
        "remaining", "min_remaining", "rng",
    ),
)
def paged_decode_block(
    params,
    cfg: TransformerConfig,
    k_pages,
    v_pages,
    page_indices,  # [B, P]
    lengths,  # [B] cache fill per slot (excl. the pending next_input token)
    next_input,  # [B] last sampled token, to feed
    active,  # [B] bool
    remaining,  # [B] int32 budget left
    min_remaining,  # [B] int32 forbid-EOS countdown
    temps,
    top_ps,
    top_ks,
    greedy_mask,
    eos_mask,  # [V] bool
    rng,
    n_steps: int,
    attn_impl: str = "auto",
    mesh=None,
):
    """Run up to n_steps decode steps for every active slot over the paged
    pool. The host guarantees each active slot has pages allocated for
    lengths + n_steps tokens before calling.

    Returns (packed, k_pages, v_pages, lengths, next_input, active,
    remaining, min_remaining, rng) where `packed` is ONE [B, 2n+4] f32
    array — [tokens | logprobs | n_emitted, hit_eos, active, lengths] —
    so the host needs exactly one device fetch per block (per-array
    fetches are serial round trips; ruinous on remote-tunneled TPUs).
    Emission is prefix-contiguous per slot (active only ever falls within
    a block), so tokens[:n_emitted] is the emitted sequence.

    MoE models get TWO extra packed columns — [B, 2n+6] instead of
    [B, 2n+4] — broadcasting the block-mean decode router stats
    (moe_drop_rate, moe_router_entropy) so the serving /metrics surface
    sees them without a second device fetch."""
    B = lengths.shape[0]
    is_moe = cfg.moe is not None

    def body(i, carry):
        (kp, vp, lengths, next_input, active, remaining, min_remaining,
         rng, out_t, out_lp, out_m, hit_eos, moe_acc) = carry
        logits, kp, vp, moe_stats = paged_decode_step(
            params, cfg, next_input, kp, vp, page_indices, lengths, active,
            mesh=mesh, attn_impl=attn_impl, return_moe_stats=True,
        )
        if is_moe:
            moe_acc = (
                moe_acc[0] + moe_stats["moe_drop_rate"],
                moe_acc[1] + moe_stats["moe_router_entropy"],
            )
        rng, sub = jax.random.split(rng)
        tokens, logprobs = warp_sample(
            logits, sub, temps, top_ps, top_ks, greedy_mask,
            min_remaining > 0, eos_mask, active_rows=active,
        )
        emit = active
        tokens = jnp.where(emit, tokens, 0)
        logprobs = jnp.where(emit, logprobs, 0.0)
        out_t = out_t.at[:, i].set(tokens)
        out_lp = out_lp.at[:, i].set(logprobs)
        out_m = out_m.at[:, i].set(emit)

        is_eos = eos_mask[tokens] & emit
        remaining = remaining - emit.astype(jnp.int32)
        min_remaining = jnp.maximum(min_remaining - emit.astype(jnp.int32), 0)
        exhausted = (remaining <= 0) & emit
        hit_eos = hit_eos | is_eos
        active = active & ~is_eos & ~exhausted
        lengths = lengths + emit.astype(lengths.dtype)
        next_input = tokens
        return (kp, vp, lengths, next_input, active, remaining, min_remaining,
                rng, out_t, out_lp, out_m, hit_eos, moe_acc)

    out_t = jnp.zeros((B, n_steps), jnp.int32)
    out_lp = jnp.zeros((B, n_steps), jnp.float32)
    out_m = jnp.zeros((B, n_steps), bool)
    hit_eos = jnp.zeros((B,), bool)
    moe_acc = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    carry = (k_pages, v_pages, lengths, next_input, active, remaining,
             min_remaining, rng, out_t, out_lp, out_m, hit_eos, moe_acc)
    carry = jax.lax.fori_loop(0, n_steps, body, carry)
    (k_pages, v_pages, lengths, next_input, active, remaining, min_remaining,
     rng, out_t, out_lp, out_m, hit_eos, moe_acc) = carry
    cols = [
        out_t.astype(jnp.float32),
        out_lp,
        jnp.sum(out_m, axis=1, keepdims=True).astype(jnp.float32),
        hit_eos[:, None].astype(jnp.float32),
        active[:, None].astype(jnp.float32),
        lengths[:, None].astype(jnp.float32),
    ]
    if is_moe:
        inv = 1.0 / float(n_steps)
        cols.append(jnp.broadcast_to(moe_acc[0] * inv, (B,))[:, None])
        cols.append(jnp.broadcast_to(moe_acc[1] * inv, (B,))[:, None])
    packed = jnp.concatenate(cols, axis=1)
    return (packed, k_pages, v_pages, lengths, next_input, active,
            remaining, min_remaining, rng)

"""Pallas flash attention vs the dense reference oracle (forward + grads).

Runs the kernel in interpreter mode on the CPU test platform; the same
code path compiles on TPU (dispatched by areal_tpu/ops/attention.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.ops.attention import reference_packed_attention
from areal_tpu.ops.pallas.flash_attn import flash_packed_attention


def make_packed(T, n_seqs, hq, hkv, hd, seed=0):
    rng = np.random.RandomState(seed)
    # Random cut points -> n_seqs contiguous segments + tail padding.
    cuts = np.sort(rng.choice(np.arange(1, T - 1), size=n_seqs - 1, replace=False))
    bounds = [0, *cuts.tolist(), T - rng.randint(0, T // 8)]
    seg = np.zeros(T, np.int32)
    pos = np.zeros(T, np.int32)
    for s in range(n_seqs):
        lo, hi = bounds[s], bounds[s + 1]
        seg[lo:hi] = s + 1
        pos[lo:hi] = np.arange(hi - lo)
    q = rng.randn(T, hq, hd).astype(np.float32)
    k = rng.randn(T, hkv, hd).astype(np.float32)
    v = rng.randn(T, hkv, hd).astype(np.float32)
    return q, k, v, seg, pos


@pytest.mark.parametrize("hq,hkv,hd", [(4, 4, 64), (4, 2, 64), (8, 2, 32)])
def test_flash_forward_matches_reference(hq, hkv, hd):
    T = 256
    q, k, v, seg, pos = make_packed(T, n_seqs=3, hq=hq, hkv=hkv, hd=hd)
    ref = reference_packed_attention(q, k, v, seg, pos)
    got = flash_packed_attention(q, k, v, seg, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_padding_rows_zero():
    T = 128
    q, k, v, seg, pos = make_packed(T, n_seqs=2, hq=4, hkv=2, hd=32, seed=3)
    seg[100:] = 0  # force a padded tail
    got = np.asarray(flash_packed_attention(q, k, v, seg, pos, interpret=True))
    np.testing.assert_allclose(got[100:], 0.0, atol=1e-6)


def test_flash_grads_match_reference():
    T = 256
    q, k, v, seg, pos = make_packed(T, n_seqs=3, hq=4, hkv=2, hd=32, seed=7)
    dout = np.random.RandomState(9).randn(T, 4, 32).astype(np.float32)

    def loss_ref(q, k, v):
        return jnp.vdot(reference_packed_attention(q, k, v, seg, pos), dout)

    def loss_flash(q, k, v):
        return jnp.vdot(
            flash_packed_attention(q, k, v, seg, pos, interpret=True), dout
        )

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4, err_msg=name
        )


def test_flash_vmap_rows():
    # The model vmaps attention over packed rows; exercise the batching rule.
    R, T = 2, 128
    packs = [make_packed(T, 2, 4, 2, 32, seed=10 + r) for r in range(R)]
    q = np.stack([p[0] for p in packs])
    k = np.stack([p[1] for p in packs])
    v = np.stack([p[2] for p in packs])
    seg = np.stack([p[3] for p in packs])
    pos = np.stack([p[4] for p in packs])
    got = jax.vmap(
        lambda q1, k1, v1, s1, p1: flash_packed_attention(
            q1, k1, v1, s1, p1, interpret=True
        )
    )(q, k, v, seg, pos)
    for r in range(R):
        ref = reference_packed_attention(q[r], k[r], v[r], seg[r], pos[r])
        np.testing.assert_allclose(
            np.asarray(got[r]), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


# ---------------------------------------------------------------------------
# splash attention (jax's TPU kernel, auto-dispatched on TPU backends)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("hq,hkv,hd", [(4, 4, 64), (4, 2, 64), (8, 2, 32)])
def test_splash_forward_matches_reference(hq, hkv, hd):
    from areal_tpu.ops.attention import splash_packed_attention

    T = 256
    q, k, v, seg, pos = make_packed(T, 3, hq, hkv, hd, seed=11)
    ref = reference_packed_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(seg), jnp.asarray(pos),
    )
    got = splash_packed_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(seg), jnp.asarray(pos), interpret=True,
    )
    valid = seg > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(ref)[valid], atol=2e-2, rtol=2e-2
    )


def test_splash_grads_match_reference():
    from areal_tpu.ops.attention import splash_packed_attention

    T, hq, hkv, hd = 256, 4, 2, 32
    q, k, v, seg, pos = make_packed(T, 2, hq, hkv, hd, seed=12)
    qj, kj, vj = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    segj, posj = jnp.asarray(seg), jnp.asarray(pos)
    rng = np.random.RandomState(0)
    dout = jnp.asarray(rng.randn(T, hq, hd).astype(np.float32))
    dout = dout * jnp.asarray((seg > 0)[:, None, None], jnp.float32)

    def loss_splash(q, k, v):
        return jnp.sum(
            splash_packed_attention(q, k, v, segj, posj, interpret=True) * dout
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_packed_attention(q, k, v, segj, posj) * dout)

    g1 = jax.grad(loss_splash, argnums=(0, 1, 2))(qj, kj, vj)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(qj, kj, vj)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2
        )


def test_splash_block_sizes_divide_odd_row_lengths():
    """Packed rows are padded to multiples of 128 (e.g. T=640, 1536);
    block-size selection must produce dividing blocks for all of them."""
    from areal_tpu.ops.attention import splash_packed_attention

    for T in (128, 384, 640, 896):
        q, k, v, seg, pos = make_packed(T, 2, 4, 2, 32, seed=13)
        out = splash_packed_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(seg), jnp.asarray(pos), interpret=True,
        )
        assert out.shape == (T, 4, 32)


@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="real-TPU compiled-kernel parity (CPU runs interpret mode above)",
)
def test_splash_compiled_matches_reference_on_tpu():
    from areal_tpu.ops.attention import splash_packed_attention

    T, hq, hkv, hd = 512, 4, 2, 64
    q, k, v, seg, pos = make_packed(T, 3, hq, hkv, hd, seed=21)
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(k, jnp.bfloat16)
    vb = jnp.asarray(v, jnp.bfloat16)
    ref = reference_packed_attention(
        qb, kb, vb, jnp.asarray(seg), jnp.asarray(pos)
    )
    got = splash_packed_attention(
        qb, kb, vb, jnp.asarray(seg), jnp.asarray(pos), interpret=False
    )
    valid = seg > 0
    np.testing.assert_allclose(
        np.asarray(got, np.float32)[valid],
        np.asarray(ref, np.float32)[valid],
        atol=5e-2, rtol=5e-2,
    )

"""GAE implementation parity + edge cases (ISSUE 15 tentpole/satellite).

The serial ``gae_rows`` scan is the oracle; the associative scan and
the blocked Pallas kernel (interpret mode on CPU) must match it on the
case families the reference ships three CUDA variants for: packed
multi-segment rows, misaligned starts, zero-length (all-padding) rows,
truncation bootstraps at segment boundaries, and the lam in {0, 1}
closed forms.

Parity tolerance: the impls reassociate float32 sums, so comparisons
are NORMALIZED by the advantage scale (<= 1e-6 relative — absolute
1e-6 at O(20) magnitudes would be below float32 eps, unattainable by
any reassociated sum). lam = 0 accumulates nothing and is one-ulp
tight (XLA's FMA fusion still moves the last bit vs numpy).

Time budget: pure CPU jit of tiny shapes — the whole module runs in
well under 30 s warm (each case is a [R<=8, T<=256] program).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.ops.gae import (
    gae_rows,
    gae_rows_assoc,
    gae_rows_pallas,
    packed_gae,
    resolve_gae_impl,
)

IMPLS = {
    "assoc": gae_rows_assoc,
    "pallas": gae_rows_pallas,
}


def _pack(R, T, seed=0, max_len=40, gap=True):
    """Misaligned packed rows: segments start at random offsets, padding
    gaps between them, bootstrap at every segment's final token."""
    rng = np.random.RandomState(seed)
    seg = np.zeros((R, T), np.int32)
    boot = np.zeros((R, T), np.float32)
    for r in range(R):
        t = int(rng.randint(0, 5))
        s = 1
        while t < T - 4:
            length = int(rng.randint(3, max_len))
            end = min(t + length, T)
            seg[r, t:end] = s
            boot[r, end - 1] = rng.randn()
            s += 1
            t = end + (int(rng.randint(0, 3)) if gap else 0)
    rew = (rng.randn(R, T) * (seg > 0)).astype(np.float32)
    val = (rng.randn(R, T) * (seg > 0)).astype(np.float32)
    return tuple(
        jnp.asarray(x) for x in (rew, val, seg, boot)
    ), (rew, val, seg, boot)


def _assert_close(got, want, rel=1e-6):
    g, w = np.asarray(got, np.float64), np.asarray(want, np.float64)
    scale = max(1.0, float(np.max(np.abs(w))))
    np.testing.assert_allclose(g, w, atol=rel * scale, rtol=0)


@pytest.mark.parametrize("impl", sorted(IMPLS))
@pytest.mark.parametrize("gamma,lam", [(1.0, 1.0), (0.97, 0.95)])
def test_impl_parity_packed_misaligned(impl, gamma, lam):
    args, _ = _pack(8, 256, seed=1)
    adv0, ret0 = gae_rows(*args, gamma=gamma, lam=lam)
    adv1, ret1 = IMPLS[impl](*args, gamma=gamma, lam=lam)
    _assert_close(adv1, adv0)
    _assert_close(ret1, ret0)


@pytest.mark.parametrize("impl", sorted(IMPLS))
def test_zero_length_rows(impl):
    """All-padding rows (and an empty batch half) must come back exact
    zeros — padding never leaks into the recursion."""
    args, (rew, val, seg, boot) = _pack(8, 128, seed=2)
    seg2 = seg.copy()
    seg2[1] = 0  # row 1 entirely padding
    seg2[3] = 0
    args2 = (jnp.asarray(rew), jnp.asarray(val), jnp.asarray(seg2),
             jnp.asarray(boot))
    adv0, ret0 = gae_rows(*args2, gamma=0.97, lam=0.95)
    adv1, ret1 = IMPLS[impl](*args2, gamma=0.97, lam=0.95)
    assert np.all(np.asarray(adv1)[1] == 0.0)
    assert np.all(np.asarray(ret1)[3] == 0.0)
    _assert_close(adv1, adv0)
    _assert_close(ret1, ret0)


@pytest.mark.parametrize("impl", sorted(IMPLS))
def test_truncation_bootstrap_at_segment_boundary(impl):
    """A truncated (no-EOS) segment bootstraps V(s_{T+1}) at its final
    token; its right NEIGHBOR segment must not see that value. A
    hand-checkable segment pair, tiled to 8 rows for the Pallas
    sublane gate."""
    T = 128
    seg = np.zeros((8, T), np.int32)
    seg[:, 2:6] = 1  # segment 1: positions 2..5
    seg[:, 6:9] = 2  # segment 2 abuts it immediately (misaligned pair)
    rew = np.zeros((8, T), np.float32)
    val = np.zeros((8, T), np.float32)
    boot = np.zeros((8, T), np.float32)
    rew[:, 2:9] = 1.0
    boot[:, 5] = 10.0  # segment 1 truncated, V(s_T+1) = 10
    gamma, lam = 0.9, 0.8
    args = tuple(jnp.asarray(x) for x in (rew, val, seg, boot))
    adv, _ = IMPLS[impl](*args, gamma=gamma, lam=lam)
    adv = np.asarray(adv)
    # Last token of segment 1: delta = r + gamma * boot = 1 + 9 = 10.
    np.testing.assert_allclose(adv[0, 5], 1.0 + gamma * 10.0, rtol=1e-6)
    # Last token of segment 2: NO bootstrap (boot=0 there) — the
    # neighbor's bootstrap must not cross the boundary.
    np.testing.assert_allclose(adv[0, 8], 1.0, rtol=1e-6)
    # And the whole thing matches the serial oracle.
    adv0, _ = gae_rows(*args, gamma=gamma, lam=lam)
    _assert_close(adv, adv0)


@pytest.mark.parametrize("impl", ["scan"] + sorted(IMPLS))
def test_lam_zero_closed_form(impl):
    """lam = 0: A_t = delta_t (one-step TD error), nothing accumulates.
    Checked per element against the numpy closed form at one-ulp
    tightness (1e-7 relative: XLA fuses r + g*v - v into FMA forms
    numpy does not, so the LAST BIT can legitimately differ — anything
    beyond that is a real leak across tokens). Padding is exact zero."""
    args, (rew, val, seg, boot) = _pack(8, 128, seed=3)
    fn = gae_rows if impl == "scan" else IMPLS[impl]
    adv, ret = fn(*args, gamma=0.9, lam=0.0)
    # Closed form, vectorized: delta_t = r + gamma*V(s_{t+1}) - V(s_t).
    seg_next = np.concatenate([seg[:, 1:], np.zeros_like(seg[:, :1])], 1)
    v_next = np.concatenate([val[:, 1:], np.zeros_like(val[:, :1])], 1)
    same = (seg == seg_next) & (seg > 0)
    v_tp1 = np.where(same, v_next, boot).astype(np.float32)
    delta = np.where(
        seg > 0, rew + np.float32(0.9) * v_tp1 - val, np.float32(0.0)
    )
    _assert_close(adv, delta, rel=1e-7)
    _assert_close(ret, np.where(seg > 0, delta + val, np.float32(0.0)),
                  rel=1e-7)
    assert np.all(np.asarray(adv)[seg == 0] == 0.0)


@pytest.mark.parametrize("impl", sorted(IMPLS))
def test_lam_one_closed_form(impl):
    """lam = 1: A_t = sum_k gamma^(k-t) delta_k over the remaining
    segment (pure discounted delta sum) — checked against a float64
    numpy suffix sum per segment."""
    gamma = 0.95
    args, (rew, val, seg, boot) = _pack(8, 128, seed=4, max_len=20)
    adv, _ = IMPLS[impl](*args, gamma=gamma, lam=1.0)
    adv = np.asarray(adv, np.float64)
    for r in range(seg.shape[0]):
        for s in np.unique(seg[r])[1:] if seg[r].any() else []:
            idx = np.where(seg[r] == s)[0]
            v_n = np.append(val[r, idx[1:]], boot[r, idx[-1]])
            delta = rew[r, idx] + gamma * v_n - val[r, idx]
            want = np.zeros(len(idx))
            acc = 0.0
            for j in range(len(idx) - 1, -1, -1):
                acc = delta[j] + gamma * acc
                want[j] = acc
            scale = max(1.0, np.max(np.abs(want)))
            np.testing.assert_allclose(
                adv[r, idx], want, atol=2e-6 * scale, rtol=0
            )


def test_pallas_shape_gate():
    """Unaligned shapes must be refused loudly, not miscomputed."""
    args, _ = _pack(3, 100, seed=5)  # 3 rows, T=100: both misaligned
    with pytest.raises(ValueError, match="pallas"):
        gae_rows_pallas(*args)


def test_dispatcher_resolution_and_knob_default():
    """'auto' resolves to the associative scan (the measured default;
    kernel_micro_gae banks the ongoing evidence), explicit impls pass
    through, unknown ones are refused, and the registered knob default
    is 'auto' so the PPO interface dispatches without env plumbing."""
    from areal_tpu.base import env_registry

    assert resolve_gae_impl("auto", 8, 256) == "assoc"
    assert resolve_gae_impl("scan", 8, 256) == "scan"
    assert resolve_gae_impl("pallas", 8, 256) == "pallas"
    assert env_registry.REGISTRY["AREAL_GAE_IMPL"].default == "auto"

    args, _ = _pack(8, 128, seed=6)
    a_auto, _ = packed_gae(*args, gamma=0.97, lam=0.95)
    a_assoc, _ = gae_rows_assoc(*args, gamma=0.97, lam=0.95)
    np.testing.assert_array_equal(np.asarray(a_auto), np.asarray(a_assoc))
    with pytest.raises(ValueError, match="unknown gae impl"):
        packed_gae(*args, impl="cuda")

#!/usr/bin/env python3
"""Merge RL-trace shards into one Perfetto timeline + derived reports.

Usage:
  python scripts/merge_rl_trace.py <trace_dir> [-o merged.json] [--report]
  python scripts/merge_rl_trace.py /tmp/areal_tpu/rl_trace -o /tmp/rl.json

<trace_dir> is the AREAL_RL_TRACE_DIR a traced run (AREAL_RL_TRACE=1)
wrote its per-worker *.jsonl shards into. The merged JSON opens in
Perfetto (ui.perfetto.dev) or chrome://tracing: one track per worker,
flow arrows following each rollout across processes into the train step
that consumed it.

Validation runs first and is strict by default: malformed shard lines,
spans that end before they start, missing headers, and DANGLING SPAN
REFERENCES (a parent id no span in the trace defines, in any shard) all
exit nonzero — a broken emitter fails CI, not a debugging session.
Use --lenient to emit anyway (problems still print to stderr).

See docs/observability.md for the span model and how to read the
overlap score / staleness histogram.
"""

import argparse
import json
import os
import sys

# Runnable as `python scripts/merge_rl_trace.py` from anywhere: the repo
# root may not be on sys.path when invoked by path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.utils import rl_trace  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace_dir", help="AREAL_RL_TRACE_DIR with *.jsonl shards")
    p.add_argument(
        "-o", "--output", default=None,
        help="write merged Chrome-trace JSON here (default: "
        "<trace_dir>/merged_trace.json)",
    )
    p.add_argument(
        "--report", action="store_true",
        help="print the derived report (staleness histogram, per-phase "
        "latency, overlap score)",
    )
    p.add_argument(
        "--json-report", action="store_true",
        help="print the derived report as machine-readable JSON",
    )
    p.add_argument(
        "--lenient", action="store_true",
        help="emit the merged trace even when validation finds problems",
    )
    args = p.parse_args(argv)

    try:
        shards = rl_trace.load_shards(args.trace_dir)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    problems = rl_trace.validate(shards)
    for prob in problems:
        print(f"VALIDATION: {prob}", file=sys.stderr)
    # Waived findings (dangling parents explained by recorded ring
    # overflow) are reported but never fatal — a long healthy run must
    # not fail CI for dropping its oldest spans by design.
    fatal = [p for p in problems if not p.startswith(rl_trace.WAIVED_PREFIX)]
    if fatal and not args.lenient:
        print(
            f"{len(fatal)} validation problem(s); refusing to merge "
            f"(--lenient overrides)",
            file=sys.stderr,
        )
        return 1

    out_path = args.output or f"{args.trace_dir.rstrip('/')}/merged_trace.json"
    merged = rl_trace.merge_to_chrome(shards)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    print(
        f"merged {sum(len(s.spans) for s in shards)} spans from "
        f"{len(shards)} shard(s) -> {out_path}",
        file=sys.stderr,
    )

    if args.json_report:
        print(json.dumps(rl_trace.summarize_shards(shards), indent=2))
    elif args.report:
        print(rl_trace.format_report(shards))
    return 0


if __name__ == "__main__":
    sys.exit(main())

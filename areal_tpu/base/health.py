"""Lease-based worker health registry on top of name_resolve.

The fault-domain isolation layer's discovery primitive: every worker
(and generation server) periodically rewrites a small JSON record under
``names.health(exp, trial, member)`` carrying its own wall-clock
timestamp and TTL. Consumers read the subtree and classify members as
alive (fresh timestamp) or dead (stale by more than ``STALE_FACTOR``
TTLs), with alive->dead / dead->alive transition callbacks.

Liveness is encoded in the record VALUE, not in backend TTL machinery,
for two reasons:

- it works identically across every name_resolve backend (the memory
  backend has no TTL at all; the NFS backend's keepalive toucher is a
  daemon thread that keeps touching even when the worker's poll loop is
  wedged — exactly the hang this registry must detect);
- a beat is one atomic ``add(replace=True)``, so a hung worker stops
  beating the moment its loop stops, and readmission is just the next
  beat.

Records are written with ``delete_on_exit=False``: a clean worker exit
calls ``Heartbeat.stop()`` (which deletes the record), while a killed
worker leaves a stale record behind — that staleness IS the death
signal consumers key off.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Optional

from areal_tpu.base import env_registry, logging, name_resolve, names

logger = logging.getLogger("health")

# A member is dead once its last beat is older than STALE_FACTOR * ttl.
# 3x tolerates one missed beat + clock jitter without flapping, matching
# the NFS backend's own expiry slack (name_resolve.py:_is_expired).
STALE_FACTOR = 3.0


def default_ttl() -> float:
    """Heartbeat TTL (seconds). AREAL_HEALTH_TTL overrides for tests and
    chaos drills that need sub-second failure detection."""
    return env_registry.get_float("AREAL_HEALTH_TTL")


class Heartbeat:
    """Producer side: one member's periodic lease renewal.

    ``beat()`` is cheap and rate-limited (ttl/3), so callers just invoke
    it from their poll loop every iteration. There is deliberately NO
    background thread: a beat only happens while the owning loop is
    actually making progress, which is what makes hung-worker detection
    possible.
    """

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        member: str,
        payload: Optional[Dict] = None,
        ttl: Optional[float] = None,
    ):
        self.member = member
        self.ttl = ttl if ttl is not None else default_ttl()
        self._key = names.health(experiment_name, trial_name, member)
        self._payload = dict(payload or {})
        self._last_beat = 0.0
        self._stopped = False
        self.beat(force=True)

    def update_payload(self, **kwargs):
        self._payload.update(kwargs)
        self.beat(force=True)

    def beat(self, force: bool = False):
        """Renew the lease (no-op within ttl/3 of the previous beat)."""
        if self._stopped:
            return
        now = time.monotonic()
        if not force and now - self._last_beat < self.ttl / 3:
            return
        record = dict(self._payload)
        record["ts"] = time.time()
        record["ttl"] = self.ttl
        try:
            name_resolve.add(
                self._key,
                json.dumps(record, separators=(",", ":")),
                delete_on_exit=False,
                replace=True,
            )
            self._last_beat = now
        except Exception:
            # A flaky KV write must never take down the worker it is
            # supposed to protect; the next beat retries.
            logger.warning(f"heartbeat write failed for {self.member}",
                           exc_info=True)

    def stop(self):
        """Clean shutdown: rewrite the record with a `stopped` marker so
        consumers can tell a graceful departure (leaves the live set, no
        death handling) from a crash/hang (stale record, death
        handling)."""
        if self._stopped:
            return
        self._stopped = True
        record = dict(self._payload)
        record["ts"] = time.time()
        record["ttl"] = self.ttl
        record["stopped"] = True
        try:
            name_resolve.add(
                self._key,
                json.dumps(record, separators=(",", ":")),
                delete_on_exit=False,
                replace=True,
            )
        except Exception:
            try:
                name_resolve.delete(self._key)
            except Exception:
                pass


class HealthRegistry:
    """Consumer side: live-set view + alive/dead transition callbacks.

    ``poll()`` is pull-based so consumers fold it into their own loops
    (the gserver manager and controller both already have one);
    ``start_watch()`` wraps it in a daemon thread for callers that
    don't.
    """

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        prefix: str = "",
        on_dead: Optional[Callable[[str, Dict], None]] = None,
        on_alive: Optional[Callable[[str, Dict], None]] = None,
    ):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.prefix = prefix
        self.on_dead = on_dead
        self.on_alive = on_alive
        self._known_alive: Dict[str, Dict] = {}
        self._lock = threading.Lock()
        self._watch_stop: Optional[threading.Event] = None

    def _root(self) -> str:
        root = names.health_root(self.experiment_name, self.trial_name)
        return root.rstrip("/") + ("/" + self.prefix if self.prefix else "")

    def _records(self) -> Dict[str, Dict]:
        root = self._root().rstrip("/")
        out: Dict[str, Dict] = {}
        for key in name_resolve.find_subtree(root):
            try:
                record = json.loads(name_resolve.get(key))
            except (name_resolve.NameEntryNotFoundError, ValueError):
                continue
            member = key[len(root):].strip("/")
            if self.prefix:
                member = f"{self.prefix}/{member}" if member else self.prefix
            out[member] = record
        return out

    def snapshot(self) -> Dict[str, Dict]:
        """member -> record for every member whose last beat is fresh and
        that has not gracefully stopped. Members with stale beats are
        omitted (they show up via poll()'s dead-transition callback
        instead)."""
        now = time.time()
        return {
            m: r for m, r in self._records().items()
            if not r.get("stopped")
            and now - float(r.get("ts", 0))
            <= float(r.get("ttl", default_ttl())) * STALE_FACTOR
        }

    def classified(self) -> "tuple[Dict[str, Dict], Dict[str, Dict]]":
        """(alive, stopped) from ONE subtree walk. Consumers folding
        both views every poll (the gserver manager's health fold) must
        not pay two full scans — each record read is file I/O, NFS in
        production."""
        now = time.time()
        alive: Dict[str, Dict] = {}
        stopped: Dict[str, Dict] = {}
        for m, r in self._records().items():
            if r.get("stopped"):
                stopped[m] = r
            elif now - float(r.get("ts", 0)) <= float(
                r.get("ttl", default_ttl())
            ) * STALE_FACTOR:
                alive[m] = r
        return alive, stopped

    def stopped_members(self) -> Dict[str, Dict]:
        """Members that announced a graceful shutdown (Heartbeat.stop).
        Consumers treat these as departed, NOT dead — no failure
        handling."""
        return {
            m: r for m, r in self._records().items() if r.get("stopped")
        }

    def alive(self) -> Dict[str, Dict]:
        return self.snapshot()

    def poll(self):
        """Recompute the live set; fire on_dead for members that were
        alive and are now stale/deleted, on_alive for new or returning
        members. Callbacks run on the caller's thread."""
        now_alive = self.snapshot()
        with self._lock:
            appeared = {
                m: r for m, r in now_alive.items()
                if m not in self._known_alive
            }
            died = {
                m: r for m, r in self._known_alive.items()
                if m not in now_alive
            }
            self._known_alive = now_alive
        for member, record in died.items():
            logger.warning(f"health: {member} went dead")
            if self.on_dead is not None:
                self.on_dead(member, record)
        for member, record in appeared.items():
            logger.info(f"health: {member} alive")
            if self.on_alive is not None:
                self.on_alive(member, record)
        return now_alive

    def start_watch(self, interval: float = 1.0) -> threading.Thread:
        """Run poll() on a daemon thread every `interval` seconds."""
        self._watch_stop = threading.Event()
        stop = self._watch_stop

        def _loop():
            while not stop.wait(interval):
                try:
                    self.poll()
                except Exception:
                    logger.warning("health watch poll failed", exc_info=True)

        t = threading.Thread(target=_loop, daemon=True)
        t.start()
        return t

    def stop_watch(self):
        if self._watch_stop is not None:
            self._watch_stop.set()
            self._watch_stop = None

"""Async-vs-sync PPO speedup benchmark — the reference's headline metric.

AReaL's pitch is asynchronous RL beating synchronous PPO by >2.5x on
effective-token throughput at equal quality (reference README.md:23,
blog/AReaL_v0_3.md:107-119; methodology: effective trained tokens /
end-to-end seconds, benchmark/verl_v0_3_0_post1_76084d3/README.md:26-36).
This script runs the SAME math workload through BOTH experiment shapes
and reports the ratio:

  sync:  in-mesh generate -> reward -> train, generation blocking every
         step (the ppo_math_exp DFG).
  async: generation server(s) + gserver manager + rollout workers
         (math agent + verifier env) feeding a stream-dataset trainer
         (the async_ppo_math_exp topology) — generation and verification
         overlap training.

Modes:
  --mode tiny (default): self-contained CPU run — synthetic math prompts,
    a freshly-trained WordPiece tokenizer, a 2-layer model. Proves the
    harness end-to-end and is pinned in CI
    (tests/system/test_async_speedup_bench.py). The printed ratio on CPU
    miniatures is a harness artifact, not the headline number.
  --mode chip: flagship-shaped config staged for real TPU hardware
    (R1-Distill-Qwen-1.5B shape, real tokenizer/dataset paths required).

Output: ONE JSON line
  {"sync_tokens_per_s": ..., "async_tokens_per_s": ..., "speedup": ...,
   "target": 2.5, ...}
plus optional --out file. Warmup steps (XLA compiles) are dropped from
the rate via the master's per-step history.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import uuid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TINY_CFG = dict(
    vocab_size=128,
    hidden_dim=32,
    n_layers=2,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    max_position_embeddings=256,
    compute_dtype="float32",
)

# The round-3 flagship bench shape (docs/perf_notes.md): what the
# reference's own headline benchmark trains, sized for one v5e.
FLAGSHIP_CFG = dict(
    vocab_size=32768,
    hidden_dim=1536,
    n_layers=16,
    n_q_heads=12,
    n_kv_heads=2,
    head_dim=128,
    intermediate_dim=8960,
    max_position_embeddings=32768,
    compute_dtype="bfloat16",
)


def _make_synthetic_workload(root: str, n_rows: int = 64, seed: int = 17):
    """Tiny tokenizer + \\boxed math prompts, self-contained (no tests/
    import): the same workload shape the e2e suites drive."""
    import random

    from tokenizers import Tokenizer
    from tokenizers.models import WordPiece
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.trainers import WordPieceTrainer
    from transformers import PreTrainedTokenizerFast

    rng = random.Random(seed)
    words = [
        "prove", "that", "the", "sum", "of", "two", "odd", "numbers",
        "is", "even", "find", "x", "such", "integral", "matrix", "prime",
        "graph", "vertex", "angle", "triangle", "circle", "radius",
    ]
    rows = []
    texts = []
    for _ in range(n_rows):
        prompt = " ".join(rng.choice(words) for _ in range(rng.randint(6, 14)))
        rows.append(
            dict(
                query_id=str(uuid.uuid4()),
                task="math",
                prompt=prompt,
                solutions=["\\boxed{42}"],
            )
        )
        texts.append(prompt)

    tok = Tokenizer(WordPiece(unk_token="[UNK]"))
    tok.pre_tokenizer = Whitespace()
    trainer = WordPieceTrainer(
        vocab_size=TINY_CFG["vocab_size"] - 2,
        min_frequency=0,
        special_tokens=["[UNK]", "[EOS]"],
    )
    tok.train_from_iterator(texts, trainer)
    tok_file = os.path.join(root, "tokenizer.json")
    tok.save(tok_file)
    tok_dir = os.path.join(root, "tokenizer")
    PreTrainedTokenizerFast(
        tokenizer_file=tok_file, eos_token="[EOS]", pad_token="[EOS]",
        unk_token="[UNK]",
    ).save_pretrained(tok_dir)

    data_path = os.path.join(root, "math.jsonl")
    with open(data_path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return tok_dir, data_path


def build_sync_cfg(*, exp, trial, model_cfg, tok_dir, data_path, n_seqs,
                   steps, gconfig, remat):
    """Sync PPO DFG: actor_gen -> rew_inf -> actor_train on one worker
    (areal_tpu/experiments/ppo_math_exp.py shape). Generation runs
    in-mesh and blocks every step — the baseline being beaten."""
    from areal_tpu.api.config import (
        DatasetAbstraction, ModelAbstraction, ModelBackendAbstraction,
        ModelInterfaceAbstraction, ModelName, ModelShardID,
    )
    from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
    from areal_tpu.api.system_api import (
        ExperimentConfig, ExperimentSaveEvalControl, MasterWorkerConfig,
        ModelShardSpec, ModelWorkerConfig,
    )

    actor = ModelName("actor", 0)
    rew = ModelName("reward", 0)
    rpcs = [
        MFCDef(
            name="actor_gen",
            model_name=actor,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=("packed_prompts",),
            output_keys=(
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask",
            ),
        ),
        MFCDef(
            name="rew_inf",
            model_name=rew,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=("packed_input_ids", "prompt_mask"),
            output_keys=("rewards",),
        ),
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=None,
            n_seqs=n_seqs,
            input_keys=(
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "rewards", "seq_no_eos_mask",
            ),
        ),
    ]
    model_args = dict(config=model_cfg, tokenizer_path=tok_dir,
                      dtype=model_cfg.get("compute_dtype", "float32"))
    shards = [
        ModelShardSpec(
            id=ModelShardID(actor),
            model=ModelAbstraction("tpu_transformer", args=model_args),
            backend=ModelBackendAbstraction(
                "jax_train",
                args=dict(optimizer=dict(lr=1e-5), remat=remat,
                          row_len_multiple=8),
            ),
            interface=ModelInterfaceAbstraction(
                "ppo_actor", args=dict(gconfig=gconfig, kl_ctl=0.0)
            ),
        ),
        ModelShardSpec(
            id=ModelShardID(rew),
            model=ModelAbstraction("tpu_transformer", args=model_args),
            backend=ModelBackendAbstraction("mock_inference"),
            interface=ModelInterfaceAbstraction("rw-math-code"),
        ),
    ]
    mw = ModelWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        shards=shards,
        datasets=[
            DatasetAbstraction("math_code_prompt",
                               args=dict(dataset_path=data_path))
        ],
        tokenizer_path=tok_dir,
        train_batch_size=n_seqs,
        total_train_epochs=1000,
    )
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(
            total_train_epochs=1000, benchmark_steps=steps
        ),
        rpcs=rpcs,
        model_topos={str(actor): ["model_worker/0"],
                     str(rew): ["model_worker/0"]},
        data_hosts=["model_worker/0"],
        n_model_workers=1,
        train_batch_size=n_seqs,
    )
    return ExperimentConfig(
        experiment_name=exp, trial_name=trial, master=master,
        model_workers=[mw],
    )


def build_async_cfg(*, exp, trial, model_cfg, tok_dir, data_path, n_seqs,
                    steps, gconfig, remat, max_seq_len,
                    max_concurrent_rollouts, offpolicyness):
    """Async PPO topology: generation server + manager + rollout worker
    (math agent + verifier env) + stream-dataset trainer
    (areal_tpu/experiments/async_ppo_math_exp.py shape)."""
    from areal_tpu.api.config import (
        AgentAbstraction, DatasetAbstraction, EnvServiceAbstraction,
        ModelAbstraction, ModelBackendAbstraction,
        ModelInterfaceAbstraction, ModelName, ModelShardID,
    )
    from areal_tpu.api.dfg import (
        MFCDef, ModelInterfaceType, ParamReallocHook,
    )
    from areal_tpu.api.system_api import (
        ExperimentConfig, ExperimentSaveEvalControl,
        GenerationServerConfig, GserverManagerConfig, MasterWorkerConfig,
        ModelShardSpec, ModelWorkerConfig, RolloutWorkerConfig,
    )

    actor = ModelName("actor", 0)
    train = MFCDef(
        name="actor_train",
        model_name=actor,
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=n_seqs,
        input_keys=(
            "packed_input_ids", "prompt_mask", "packed_logprobs",
            "rewards", "seq_no_eos_mask",
        ),
        post_hooks=[ParamReallocHook(source=str(actor))],
    )
    model_args = dict(config=model_cfg, tokenizer_path=tok_dir,
                      dtype=model_cfg.get("compute_dtype", "float32"))
    mw = ModelWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        shards=[
            ModelShardSpec(
                id=ModelShardID(actor),
                model=ModelAbstraction("tpu_transformer", args=model_args),
                backend=ModelBackendAbstraction(
                    "jax_train",
                    args=dict(optimizer=dict(lr=1e-5), remat=remat,
                              row_len_multiple=8),
                ),
                interface=ModelInterfaceAbstraction(
                    "ppo_actor", args=dict(kl_ctl=0.0)
                ),
            )
        ],
        tokenizer_path=tok_dir,
        train_batch_size=n_seqs,
        total_train_epochs=1000,
        stream_dataset=True,
        n_pullers=1,
    )
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(
            total_train_epochs=1000, benchmark_steps=steps
        ),
        rpcs=[train],
        model_topos={str(actor): ["model_worker/0"]},
        data_hosts=["model_worker/0"],
        n_model_workers=1,
        train_batch_size=n_seqs,
    )
    gen_server = GenerationServerConfig(
        experiment_name=exp,
        trial_name=trial,
        server_index=0,
        model=ModelAbstraction("tpu_transformer", args=model_args),
        tokenizer_path=tok_dir,
        max_concurrent_requests=max_concurrent_rollouts,
        max_seq_len=max_seq_len,
        decode_block_steps=4,
    )
    gserver_mgr = GserverManagerConfig(
        experiment_name=exp,
        trial_name=trial,
        model_name="actor",
        n_servers=1,
        train_batch_size=n_seqs,
        max_head_offpolicyness=offpolicyness,
    )
    rollout = RolloutWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        n_rollout_workers=1,
        n_pullers=1,
        agent=AgentAbstraction(
            "math-single-step", args=dict(gconfig=gconfig)
        ),
        env=EnvServiceAbstraction("math-code-single-step"),
        datasets=[
            DatasetAbstraction("math_code_prompt",
                               args=dict(dataset_path=data_path))
        ],
        tokenizer_path=tok_dir,
        max_concurrent_rollouts=max_concurrent_rollouts,
    )
    return ExperimentConfig(
        experiment_name=exp,
        trial_name=trial,
        master=master,
        model_workers=[mw],
        rollout_workers=[rollout],
        gserver_manager=gserver_mgr,
        generation_servers=[gen_server],
    )


def _rate(perf_summary: dict, warmup: int):
    """Effective tokens/s over post-warmup steps (reference methodology:
    tokens / e2e seconds; warmup steps carry the XLA compiles). Returns
    (rate, tokens, secs, warmup_dropped): when the run is too short to
    drop warmup the FULL history is used and warmup_dropped is False —
    the report flags that the rate is compile-contaminated."""
    hist = perf_summary.get("history") or []
    dropped = len(hist) > warmup
    eff = hist[warmup:] if dropped else hist
    secs = sum(h[0] for h in eff)
    toks = sum(h[1] for h in eff)
    return (toks / secs if secs > 0 else 0.0), toks, secs, dropped


def run_one(cfg, *, workdir: str, warmup: int, worker_env: dict):
    from areal_tpu.system.controller import LocalController

    env = dict(worker_env)
    env["AREAL_FILEROOT"] = os.path.join(workdir, "fileroot")
    ctl = LocalController(
        cfg,
        name_resolve_cfg={
            "backend": "nfs",
            "record_root": os.path.join(workdir, "name_resolve"),
        },
        worker_env=env,
    )
    result = ctl.run()
    rate, toks, secs, warmup_dropped = _rate(result["perf_summary"], warmup)
    return dict(
        global_step=result["global_step"],
        tokens_per_s=rate,
        measured_tokens=toks,
        measured_secs=secs,
        warmup_dropped=warmup_dropped,
        perf_summary=result["perf_summary"],
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["tiny", "chip"], default="tiny")
    ap.add_argument("--steps", type=int, default=4,
                    help="train steps per experiment (incl. warmup)")
    ap.add_argument("--warmup-steps", type=int, default=1,
                    help="leading steps dropped from the rate (compiles)")
    ap.add_argument("--n-seqs", type=int, default=4,
                    help="train batch size in sequences")
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--group-size", type=int, default=2,
                    help="samples per prompt (gconfig.n)")
    ap.add_argument("--offpolicyness", type=int, default=4,
                    help="async max_head_offpolicyness staleness gate")
    ap.add_argument("--tokenizer", default=None,
                    help="tokenizer dir (chip mode; tiny synthesizes one)")
    ap.add_argument("--dataset", default=None,
                    help="math jsonl path (chip mode; tiny synthesizes one)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, help="also write the JSON here")
    args = ap.parse_args(argv)

    import tempfile

    workdir = args.workdir or tempfile.mkdtemp(prefix="async_speedup_")
    os.makedirs(workdir, exist_ok=True)

    # The master runs inline in THIS process and is control-plane only —
    # pin it to CPU so the (possibly axon-preloaded) jax runtime never
    # touches a device here. Workers get their platform via worker_env.
    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.mode == "tiny":
        model_cfg = TINY_CFG
        remat = False
        max_seq_len = 256
        tok_dir, data_path = _make_synthetic_workload(workdir)
        worker_env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": os.environ.get(
                "XLA_FLAGS", "--xla_force_host_platform_device_count=2"
            ),
        }
    else:
        if not (args.tokenizer and args.dataset):
            ap.error("--mode chip requires --tokenizer and --dataset")
        model_cfg = FLAGSHIP_CFG
        remat = "save_attn"
        max_seq_len = 4096
        tok_dir, data_path = args.tokenizer, args.dataset
        worker_env = {}  # workers use the real device platform

    gconfig = dict(
        n=args.group_size, max_new_tokens=args.max_new_tokens,
        greedy=False, temperature=1.0,
    )
    shared = dict(
        model_cfg=model_cfg, tok_dir=tok_dir, data_path=data_path,
        n_seqs=args.n_seqs, steps=args.steps, gconfig=gconfig, remat=remat,
    )
    run_id = uuid.uuid4().hex[:6]

    sync_cfg = build_sync_cfg(
        exp=f"spdup-sync-{run_id}", trial="t0", **shared
    )
    sync = run_one(sync_cfg, workdir=os.path.join(workdir, "sync"),
                   warmup=args.warmup_steps, worker_env=worker_env)

    async_cfg = build_async_cfg(
        exp=f"spdup-async-{run_id}", trial="t0", **shared,
        max_seq_len=max_seq_len,
        max_concurrent_rollouts=max(8, 2 * args.n_seqs),
        offpolicyness=args.offpolicyness,
    )
    asy = run_one(async_cfg, workdir=os.path.join(workdir, "async"),
                  warmup=args.warmup_steps, worker_env=worker_env)

    speedup = (
        asy["tokens_per_s"] / sync["tokens_per_s"]
        if sync["tokens_per_s"] > 0 else 0.0
    )
    report = {
        "metric": "async_over_sync_speedup",
        "mode": args.mode,
        "sync_tokens_per_s": round(sync["tokens_per_s"], 2),
        "async_tokens_per_s": round(asy["tokens_per_s"], 2),
        "speedup": round(speedup, 3),
        "target": 2.5,
        "steps": args.steps,
        "warmup_steps": args.warmup_steps,
        # False = runs were too short to drop warmup; the rates include
        # XLA compile time and the ratio is not citable.
        "warmup_dropped": bool(
            sync["warmup_dropped"] and asy["warmup_dropped"]
        ),
        "n_seqs": args.n_seqs,
        "max_new_tokens": args.max_new_tokens,
        "sync_steps_done": sync["global_step"],
        "async_steps_done": asy["global_step"],
    }
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return report


if __name__ == "__main__":
    main()

"""Quantization wire/pool constants shared across import domains.

``KV_INT8_MAX`` is the int8 KV dequant convention (``x ~= int8 * scale
/ 127.5``) consumed by BOTH ``engine/paged.py`` (host-side quantize /
dequantize + the XLA gather path) and
``ops/pallas/paged_decode_int8.py`` (in-VMEM dequant inside the Pallas
kernel). It used to live as a numeric duplicate in each module — paged
must not import the Pallas stack, and the kernel must not import the
engine — pinned equal only by a test. This module is the one importable
source of truth: dependency-free (no jax, no Pallas), so either side
can import it without pulling the other's stack, and the pin test is
now structural (both modules re-export THIS object) instead of
comparing two literals that could drift to a third value together.

The exact-max element clips to 127 (~0.4% error on that one element)
instead of wrapping at rint(127.5) = 128 — see
``engine/paged.quantize_kv``.
"""

from __future__ import annotations

KV_INT8_MAX = 127.5

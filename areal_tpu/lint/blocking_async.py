"""Checker ``blocking-async``: no blocking work on an asyncio loop.

The tail-latency incidents this encodes: the model-sized staging
buffer allocated on the serving loop (PR 5, fixed by pushing
``ChunkStore`` construction to an executor) and cold-compile stalls
misread as queueing (PR 7). One blocking call in an ``async def``
handler stalls every in-flight response on that loop.

A call is flagged when its *nearest* enclosing function is an
``async def``. Work inside a nested sync ``def``/``lambda`` is exempt —
that is exactly the ``run_in_executor`` / loop-door shape
(``await loop.run_in_executor(None, _fetch)``); passing a blocking
function as an executor *argument* is not a Call node, so the wrapped
pattern never trips the checker. Two indirection holes are also
covered, same-module only:

- a nested sync helper defined in the async function and then called
  directly from async code;
- ``self._x()`` / bare ``helper()`` calls from async code where the
  same-class method / module-level function (transitively) performs a
  blocking call outside any nested def of its own.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from areal_tpu.lint.common import Finding, Module

CHECKER = "blocking-async"

# Exact dotted calls (post import-alias resolution).
BLOCKING_CALLS: Set[str] = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen", "os.makedirs", "os.replace", "os.rename",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen", "urllib.request.urlretrieve",
    "shutil.copy", "shutil.copy2", "shutil.copytree", "shutil.rmtree",
    "jax.device_get", "jax.device_put", "jax.block_until_ready",
    "jax.make_array_from_single_device_arrays",
    # Repo-specific CPU-bound helpers: sha256 over multi-MB chunks.
    # ~10ms+ per call on the 2-core host — a decode stream's ITL budget.
    "areal_tpu.base.chunking.verify_chunk",
    "areal_tpu.base.chunking.build_chunk_index",
    # name_resolve's default backend is files under AREAL_FILEROOT —
    # NFS in production deployments, so a read is tens of ms of I/O.
    "areal_tpu.base.name_resolve.get",
    "areal_tpu.base.name_resolve.get_subtree",
    "areal_tpu.base.name_resolve.add",
    "areal_tpu.base.name_resolve.add_subentry",
    "areal_tpu.base.name_resolve.delete",
}

# Any call rooted at these modules blocks (sync HTTP clients).
BLOCKING_ROOTS: Set[str] = {"requests", "urllib3", "http.client"}

# Builtins.
BLOCKING_BUILTINS: Set[str] = {"open", "input"}

# Method names that block regardless of receiver type. Deliberately
# conservative: names here must be unambiguous enough that a false
# positive is unlikely (``.read()``/``.join()`` are NOT listed).
# The ServingEngine entries block on the engine-loop door (up to its
# 60s timeout) or on device transfers — exactly the PR 7 class of
# event-loop stall when called from an aiohttp handler.
BLOCKING_METHODS: Set[str] = {
    "read_text", "write_text", "read_bytes", "write_bytes",
    "block_until_ready",
    "export_kv_handoff", "import_kv_handoff", "update_params",
    "cutover_params", "stage_shard_leaves", "cutover_shard_leaves",
    "run_until_complete",
}


def _called_name(mod: Module, call: ast.Call) -> Optional[str]:
    return mod.dotted_name(call.func)


def _is_blocking_dotted(dotted: Optional[str]) -> bool:
    return bool(dotted) and (
        dotted in BLOCKING_CALLS
        or dotted.split(".")[0] in BLOCKING_ROOTS
        or dotted in BLOCKING_BUILTINS
    )


def _direct_blocking_line(mod: Module, fn: ast.FunctionDef) -> Optional[int]:
    """Line of the first blocking call whose nearest enclosing function
    is ``fn`` itself (blocking work inside a nested def is the executor
    pattern and doesn't count)."""
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and _is_blocking_dotted(_called_name(mod, node))
            and mod.enclosing_function(node) is fn
        ):
            return node.lineno
    return None


def _blocking_sync_callables(mod: Module):
    """Same-module transitive blocking sets.

    Returns ``(module_fns, methods_by_class)``: module-level sync
    function names, and per-class sync method names, that (transitively
    within the module/class) perform a blocking call in their own
    bodies. Each maps name -> human-readable reason."""
    tree = mod.tree
    module_fns: dict = {}
    fn_nodes: dict = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.FunctionDef):
            fn_nodes[node.name] = node
            line = _direct_blocking_line(mod, node)
            if line is not None:
                module_fns[node.name] = f"blocks at {mod.rel}:{line}"
    # one transitive hop set at a time, to fixpoint
    changed = True
    while changed:
        changed = False
        for name, fn in fn_nodes.items():
            if name in module_fns:
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in module_fns
                    and mod.enclosing_function(node) is fn
                ):
                    module_fns[name] = (
                        f"calls {node.func.id}() "
                        f"({module_fns[node.func.id]})"
                    )
                    changed = True
                    break

    methods_by_class: dict = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {
            n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)
        }
        blocking: dict = {}
        for name, m in methods.items():
            line = _direct_blocking_line(mod, m)
            if line is not None:
                blocking[name] = f"blocks at {mod.rel}:{line}"
            else:
                # module-level blocking helpers called from the method
                for node in ast.walk(m):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in module_fns
                        and mod.enclosing_function(node) is m
                    ):
                        blocking[name] = (
                            f"calls {node.func.id}() "
                            f"({module_fns[node.func.id]})"
                        )
                        break
        changed = True
        while changed:
            changed = False
            for name, m in methods.items():
                if name in blocking:
                    continue
                for node in ast.walk(m):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in blocking
                        and mod.enclosing_function(node) is m
                    ):
                        blocking[name] = (
                            f"calls self.{node.func.attr}() "
                            f"({blocking[node.func.attr]})"
                        )
                        changed = True
                        break
        if blocking:
            methods_by_class[cls.name] = (methods, blocking)
    return module_fns, methods_by_class


def check(mod: Module) -> List[Finding]:
    findings: List[Finding] = []

    for node in mod.nodes:
        if not isinstance(node, ast.Call):
            continue
        enclosing = mod.enclosing_function(node)
        if not isinstance(enclosing, ast.AsyncFunctionDef):
            continue

        reason = None
        dotted = _called_name(mod, node)
        if dotted is not None:
            if dotted in BLOCKING_CALLS:
                reason = f"blocking call {dotted}()"
            elif dotted.split(".")[0] in BLOCKING_ROOTS:
                reason = f"synchronous {dotted.split('.')[0]} call {dotted}()"
            elif dotted in BLOCKING_BUILTINS:
                reason = f"blocking builtin {dotted}()"
        if (
            reason is None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in BLOCKING_METHODS
            # jnp arrays etc. are fine: only flag when the receiver is
            # not itself awaited (awaited => asyncio object).
            and not isinstance(mod.parent(node), ast.Await)
        ):
            reason = f"blocking method .{node.func.attr}()"
        # threading.Event.wait lookalikes: a .wait() that is NOT awaited
        # inside async code blocks the loop (asyncio .wait() must be
        # awaited anyway, so an un-awaited one is a bug either way).
        if (
            reason is None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "wait"
            and not isinstance(mod.parent(node), ast.Await)
        ):
            reason = "un-awaited .wait() (threading.Event.wait blocks " \
                     "the loop; asyncio waits must be awaited)"

        if reason is not None:
            findings.append(Finding(
                mod.rel, node.lineno, CHECKER,
                f"{reason} inside async def {enclosing.name!r}: move to "
                f"run_in_executor (or the loop-door helper) so the event "
                f"loop keeps serving",
            ))

    # Indirection hole #2: sync same-class methods / module functions
    # that (transitively) block, invoked synchronously from async code.
    module_fns, methods_by_class = _blocking_sync_callables(mod)
    class_of_fn = {}
    for cls in mod.nodes:
        if isinstance(cls, ast.ClassDef):
            for n in cls.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of_fn[n] = cls.name
    for node in mod.nodes:
        if not isinstance(node, ast.Call):
            continue
        enclosing = mod.enclosing_function(node)
        if not isinstance(enclosing, ast.AsyncFunctionDef):
            continue
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in module_fns
        ):
            findings.append(Finding(
                mod.rel, node.lineno, CHECKER,
                f"sync call of {node.func.id}() from async def "
                f"{enclosing.name!r}, and it {module_fns[node.func.id]}: "
                f"hand it to run_in_executor",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            cls_name = class_of_fn.get(enclosing)
            if cls_name in methods_by_class:
                _, blocking = methods_by_class[cls_name]
                m = node.func.attr
                if m in blocking:
                    findings.append(Finding(
                        mod.rel, node.lineno, CHECKER,
                        f"sync call of self.{m}() from async def "
                        f"{enclosing.name!r}, and it {blocking[m]}: "
                        f"hand it to run_in_executor",
                    ))

    # Residual hole: nested sync def containing blocking calls, invoked
    # DIRECTLY from async code in the same function.
    for fn in mod.nodes:
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        nested_blocking: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.FunctionDef) and mod.enclosing_function(sub) is fn:
                for c in ast.walk(sub):
                    if isinstance(c, ast.Call):
                        d = _called_name(mod, c)
                        if d and (d in BLOCKING_CALLS
                                  or d.split(".")[0] in BLOCKING_ROOTS
                                  or d in BLOCKING_BUILTINS):
                            nested_blocking.add(sub.name)
                            break
        if not nested_blocking:
            continue
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in nested_blocking
                and mod.enclosing_function(node) is fn
            ):
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"direct call of {node.func.id}() (which blocks) from "
                    f"async def {fn.name!r}: hand it to run_in_executor "
                    f"instead",
                ))
    return findings

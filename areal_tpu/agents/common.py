"""Shared trajectory assembly for rollout agents."""

from __future__ import annotations

from typing import Optional

import numpy as np

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.model_api import BundledGenerationOutputs


def bundle_to_sample(
    qid: str, bundle: BundledGenerationOutputs, rewards: np.ndarray,
    score: float, task: Optional[str] = None,
) -> SequenceSample:
    """Assemble one grouped trajectory SequenceSample from a generation
    bundle (the packed-keys layout every RL interface consumes; logprobs
    in the PPO shifted frame — the generated token at abs position p is
    scored at p-1)."""
    n = len(bundle.seqs)
    seq_lens = [len(s) for s in bundle.seqs]
    plen = bundle.prompt_len
    pmask = np.concatenate(
        [
            np.concatenate(
                [np.ones(plen, np.int64), np.zeros(l - plen, np.int64)]
            )
            for l in seq_lens
        ]
    )
    shifted_lps = []
    for seq, lp in zip(bundle.seqs, bundle.logprobs):
        out_lp = np.asarray(lp[plen:], np.float32)
        full = np.zeros(len(seq), np.float32)
        full[plen - 1 : len(seq) - 1] = out_lp
        shifted_lps.append(full)
    return SequenceSample(
        ids=[qid],
        keys={
            "packed_input_ids", "prompt_mask", "packed_logprobs",
            "seq_no_eos_mask", "rewards",
        },
        data={
            "packed_input_ids": np.concatenate(
                [np.asarray(s, np.int32) for s in bundle.seqs]
            ),
            "prompt_mask": pmask,
            "packed_logprobs": np.concatenate(shifted_lps),
            "seq_no_eos_mask": np.asarray(
                [1.0 if x else 0.0 for x in bundle.no_eos], np.float32
            ),
            "rewards": rewards,
        },
        seqlens={
            "packed_input_ids": [seq_lens],
            "prompt_mask": [seq_lens],
            "packed_logprobs": [seq_lens],
            "seq_no_eos_mask": [[1] * n],
            "rewards": [[1] * n],
        },
        metadata={
            "version_start": [min(bundle.version_start)],
            "version_end": [max(bundle.version_end)],
            "scores": [score],
            "birth_time": [0],
            # Per-task staleness tag (buffer admission windows +
            # per-task master scalars); None -> untagged, global gate
            # only.
            **({"task": [task]} if task is not None else {}),
        },
    )

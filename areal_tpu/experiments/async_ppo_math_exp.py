"""Async PPO math experiment (reference experiments/async_exp/
async_ppo_math_exp.py): decoupled generation servers + rollout workers
stream trajectories to stream-dataset trainers; the train-side DFG is
{ref_inf?} -> actor_train with a post-hook param-realloc dump that the
gserver manager fans out to the servers."""

from __future__ import annotations

import dataclasses

from areal_tpu.api.cli_args import AsyncPPOMATHExpConfig
from areal_tpu.api.config import (
    AgentAbstraction,
    EnvServiceAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType, ParamReallocHook
from areal_tpu.api.system_api import (
    ExperimentConfig,
    GenerationServerConfig,
    GserverManagerConfig,
    ModelShardSpec,
    RolloutWorkerConfig,
)
from areal_tpu.experiments import register_experiment
from areal_tpu.experiments import common as C
from areal_tpu.experiments.ppo_math_exp import actor_interface_args


def _agent_abstraction(cfg: AsyncPPOMATHExpConfig) -> AgentAbstraction:
    """Rollout agent from config: `agent_type` picks "math-single-step"
    (default; one group per prompt), "math-multi-turn" (feedback loop,
    reference math_multi_turn_agent.py), or "tool-use" (multi-turn tool
    calls through the pooled reward executor, agents/tool_use.py)."""
    if cfg.agent_type == "tool-use":
        return AgentAbstraction(
            "tool-use",
            args=dict(
                gconfig=dataclasses.asdict(cfg.ppo.gconfig.new(n=1)),
                num_turns=cfg.agent_num_turns,
                turn_level_discount=cfg.agent_turn_discount,
                reward_scaling=cfg.ppo.reward_output_scaling,
                reward_bias=cfg.ppo.reward_output_bias,
                scripted_tool_turns=cfg.agent_scripted_tool_turns,
            ),
        )
    if cfg.agent_type == "math-multi-turn":
        return AgentAbstraction(
            "math-multi-turn",
            args=dict(
                gconfig=dataclasses.asdict(cfg.ppo.gconfig.new(n=1)),
                num_turns=cfg.agent_num_turns,
                turn_level_discount=cfg.agent_turn_discount,
                reward_scaling=cfg.ppo.reward_output_scaling,
                reward_bias=cfg.ppo.reward_output_bias,
            ),
        )
    return AgentAbstraction(
        "math-single-step",
        args=dict(
            gconfig=dataclasses.asdict(
                cfg.ppo.gconfig.new(n=cfg.ppo.group_size)
            ),
            success_rate_lb=cfg.ppo.success_rate_lb,
            success_rate_ub=cfg.ppo.success_rate_ub,
            reward_scaling=cfg.ppo.reward_output_scaling,
            reward_bias=cfg.ppo.reward_output_bias,
        ),
    )


def build_async_ppo_math_experiment(cfg: AsyncPPOMATHExpConfig) -> ExperimentConfig:
    n_workers = C.resolve_n_workers(cfg)
    actor = ModelName("actor", 0)
    ref = ModelName("ref", 0)
    use_ref = cfg.ref is not None or (
        cfg.actor.path is not None and cfg.ppo.kl_ctl != 0.0
    )
    n_seqs = cfg.train_batch_size
    iface_args = actor_interface_args(cfg)

    train_input_keys = [
        "packed_input_ids", "prompt_mask", "packed_logprobs",
        "rewards", "seq_no_eos_mask",
    ]
    rpcs = []
    if use_ref:
        rpcs.append(
            MFCDef(
                name="ref_inf",
                model_name=ref,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("ppo_actor"),
                n_seqs=n_seqs,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=("logprobs",),
                output_key_remap={"logprobs": "ref_logprobs"},
                mb_spec=C.mb_spec(cfg, cfg.ref_inf),
            )
        )
        train_input_keys.append("ref_logprobs")
    rpcs.append(
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            n_seqs=n_seqs,
            input_keys=tuple(train_input_keys),
            mb_spec=C.mb_spec(cfg, cfg.actor_train),
            post_hooks=[ParamReallocHook(source=str(actor))],
        )
    )

    workers = []
    for i in range(n_workers):
        # The decoupled allocation's TRAIN partition (devices after the
        # gen partition) drives the trainer mesh: fsdp/tensor axes from
        # allocation_mode now reach the engine instead of being dropped.
        t_mesh, t_devs = C.train_mesh_for_worker(cfg, i, n_workers)
        shards = [
            ModelShardSpec(
                id=ModelShardID(actor, host_rank=i, n_hosts=n_workers),
                model=C.model_abstraction(
                    cfg.actor, cfg.tokenizer_path,
                    mesh_spec=t_mesh, device_ids=t_devs,
                ),
                backend=C.backend_abstraction(cfg.actor, train=True),
                interface=ModelInterfaceAbstraction("ppo_actor", args=iface_args),
            )
        ]
        if use_ref:
            ref_cfg = cfg.ref or cfg.actor
            shards.append(
                ModelShardSpec(
                    id=ModelShardID(ref, host_rank=i, n_hosts=n_workers),
                    model=C.model_abstraction(
                        ref_cfg, cfg.tokenizer_path,
                        mesh_spec=t_mesh, device_ids=t_devs,
                    ),
                    backend=C.backend_abstraction(ref_cfg, train=False),
                    interface=ModelInterfaceAbstraction("ppo_actor", args=iface_args),
                )
            )
        workers.append(
            C.base_model_worker(
                cfg, i, n_workers, shards, with_dataset=False, stream_dataset=True
            )
        )

    names_ = C.worker_names(n_workers)
    model_topos = {str(actor): names_}
    if use_ref:
        model_topos[str(ref)] = names_
    master = C.base_master(cfg, rpcs, model_topos, n_workers)
    # The prompt dataset lives in the rollout workers, so the master's
    # stream dataset never reports epoch boundaries; give it the prompt
    # count so it can derive steps-per-epoch (and terminate on
    # total_train_epochs without benchmark_steps).
    master.dataset_size = C.dataset_line_count(cfg.dataset)

    # Disaggregated prefill/decode: per-index roles from the
    # comma-separated knob, padded with "unified" (the elastic pool).
    roles = [
        r.strip() or "unified"
        for r in (cfg.gen_server_roles or "").split(",")
    ]
    roles += ["unified"] * (cfg.n_generation_servers - len(roles))
    # Shard-aware weight plane: per-server (rank, degree) fleet-TP
    # coordinates (validated at config parse).
    from areal_tpu.api.cli_args import parse_weight_shards

    shards = parse_weight_shards(
        cfg.gen_weight_shards, cfg.n_generation_servers
    )
    gen_servers = [
        GenerationServerConfig(
            experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name,
            server_index=i,
            model=C.model_abstraction(cfg.actor, cfg.tokenizer_path),
            tokenizer_path=cfg.tokenizer_path or cfg.actor.path,
            max_concurrent_requests=cfg.gen_max_concurrent_requests,
            max_seq_len=cfg.gen_max_seq_len,
            decode_block_steps=cfg.gen_decode_block_steps,
            kv_page_size=cfg.gen_kv_page_size,
            kv_pool_tokens=cfg.gen_kv_pool_tokens,
            prompt_bucket=cfg.gen_prompt_bucket,
            prefill_max_batch=cfg.gen_prefill_max_batch,
            prefill_chunk=cfg.gen_prefill_chunk,
            chunked_prefill_per_lap=cfg.gen_chunked_prefill_per_lap,
            prefix_cache_tokens=cfg.gen_prefix_cache_tokens,
            kv_cache_dtype=cfg.gen_kv_cache_dtype,
            speculative_draft_len=cfg.gen_speculative_draft_len,
            speculative_ngram=cfg.gen_speculative_ngram,
            speculative_window=cfg.gen_speculative_window,
            decode_weight_dtype=cfg.gen_decode_weight_dtype,
            tensor_parallel=cfg.gen_tensor_parallel,
            role=roles[i],
            kv_handoff_compress=cfg.gen_kv_handoff_compress,
            kv_tier_bytes=(
                cfg.gen_kv_tier_mb << 20
                if cfg.gen_kv_tier_mb is not None else None
            ),
            kv_tier_disk_dir=cfg.gen_kv_tier_disk_dir,
            kv_spill_dtype=cfg.gen_kv_spill_dtype,
            weight_shard_rank=shards[i][0] if shards[i] else None,
            weight_shard_degree=shards[i][1] if shards[i] else None,
            seed=cfg.seed,
        )
        for i in range(cfg.n_generation_servers)
    ]
    manager = GserverManagerConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        model_name=actor.role,
        n_servers=cfg.n_generation_servers,
        schedule_policy=cfg.schedule_policy,
        max_head_offpolicyness=cfg.ppo.max_head_offpolicyness,
        train_batch_size=cfg.train_batch_size,
        max_concurrent_rollouts=cfg.ppo.max_concurrent_rollouts,
        weight_plane=cfg.gen_weight_plane,
        weight_chunk_bytes=cfg.gen_weight_chunk_mb << 20,
        weight_fanout_degree=cfg.gen_weight_fanout,
        weight_cutover_budget_s=cfg.gen_weight_cutover_budget_s,
        weight_wire_dtype=cfg.gen_weight_wire_dtype,
        kv_index_size=cfg.gen_kv_index_size,
        elastic_pools=cfg.gen_elastic_pools,
        prefill_queue_high_tokens=cfg.gen_prefill_queue_high_tokens,
        prefill_queue_low_tokens=cfg.gen_prefill_queue_low_tokens,
        decode_free_page_min_frac=cfg.gen_decode_free_page_min_frac,
        elastic_fleet=cfg.gen_elastic_fleet,
        autoscale=cfg.gen_autoscale,
        scale_out_queued_tokens=cfg.gen_scale_out_queued_tokens,
        scale_in_queued_tokens=cfg.gen_scale_in_queued_tokens,
        pool_min_servers=cfg.gen_pool_min_servers,
        pool_max_servers=cfg.gen_pool_max_servers,
    )
    rollouts = [
        RolloutWorkerConfig(
            experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name,
            worker_index=i,
            n_rollout_workers=cfg.n_rollout_workers,
            n_pullers=n_workers,
            model_name=actor.role,
            agent=_agent_abstraction(cfg),
            env=EnvServiceAbstraction(
                "tool-use" if cfg.agent_type == "tool-use"
                else "math-code-single-step"
            ),
            datasets=[C.dataset_abstraction(cfg.dataset)],
            tokenizer_path=cfg.tokenizer_path or cfg.actor.path,
            new_tokens_per_chunk=cfg.ppo.new_tokens_per_chunk,
            max_concurrent_rollouts=max(
                1, cfg.ppo.max_concurrent_rollouts // cfg.n_rollout_workers
            ),
            seed=cfg.seed,
        )
        for i in range(cfg.n_rollout_workers)
    ]
    return ExperimentConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        master=master,
        model_workers=workers,
        rollout_workers=rollouts,
        gserver_manager=manager,
        generation_servers=gen_servers,
    )


register_experiment("async-ppo-math", build_async_ppo_math_experiment)

"""On-chip MFU sweep over the train-step tuning levers (VERDICT r4 #3).

Runs the bench.py flagship train step (R1-Distill-Qwen-1.5B shape,
remat=save_attn) under a grid of the three unmeasured levers:

  - CE chunk size (AREAL_CE_CHUNK, ops/loss.fused_next_token_logprobs)
  - splash block-size targets (AREAL_SPLASH_BQ/BKV/BKVC,
    ops/attention._splash_kernel — ~25%% of step time at the 12q/2kv
    hd=128 shape per scripts/analyze_trace.py)
  - micro-batching (n_mbs: grad-accum scan slice cost vs one fused step)

Each configuration gets a FRESH engine (fresh jit trace — the env
overrides are read at trace time). Prints one JSON line per config to
stdout and a human table to stderr; best config last. Run on the real
chip; on CPU it only validates the harness (AREAL_SWEEP_TINY=1).

Usage:  python scripts/mfu_sweep.py [ce|blocks|mbs|all]
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.utils.jaxenv import apply_jax_platform_override

apply_jax_platform_override()

import jax
import numpy as np

from bench import (  # shared shape + formula: rows stay comparable
    flagship_cfg,
    train_step_flops,
)
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import count_params, init_params
from areal_tpu.ops.loss import sft_loss_from_logprobs


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(**kw):
    print(json.dumps(kw), flush=True)


TINY = bool(os.environ.get("AREAL_SWEEP_TINY"))


def cfg_and_shape():
    if TINY:
        cfg = TransformerConfig(
            n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2,
            head_dim=16, intermediate_dim=128, vocab_size=256,
            compute_dtype="float32",
        )
        return cfg, 128, 4, 1, 2
    return flagship_cfg(), 2048, 16, 2, 4


def measure(env: dict, n_mbs: int = 1, seqlen: int = 0) -> float:
    """TFLOP/s for one config. Fresh engine per call: the env overrides
    are trace-time, so a new jit (new engine) picks them up. seqlen > 0
    overrides the row length, holding total tokens constant."""
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        cfg, d_seqlen, d_n_seqs, n_warm, n_steps = cfg_and_shape()
        if seqlen:
            total_tokens = d_seqlen * d_n_seqs
            n_seqs = max(1, total_tokens // seqlen)
        else:
            seqlen, n_seqs = d_seqlen, d_n_seqs
        params = init_params(cfg, jax.random.PRNGKey(0))
        n_params = count_params(params)
        eng = JaxTrainEngine(
            cfg, params,
            optimizer_config=OptimizerConfig(lr=1e-4,
                                             warmup_steps_proportion=0.0),
            total_train_steps=1000, row_len_multiple=seqlen,
            max_row_len=seqlen,
            remat="full" if TINY else "save_attn",
        )
        rng = np.random.RandomState(0)
        seqlens = [seqlen] * n_seqs
        total = sum(seqlens)
        batch = SequenceSample.from_default(
            ids=[f"b{i}" for i in range(n_seqs)],
            seqlens=seqlens,
            data={
                "packed_input_ids": rng.randint(0, cfg.vocab_size,
                                                size=total),
                "loss_mask": np.ones(total, np.float32),
            },
        )

        def packed_loss(lp, rows):
            tot, _ = sft_loss_from_logprobs(lp, rows["loss_mask"])
            return tot, {}

        def weight(mb):
            return float(np.sum(mb.data["loss_mask"]))

        def one(i):
            return eng.train_batch(batch, MicroBatchSpec(n_mbs=n_mbs),
                                   packed_loss, weight, version_steps=i,
                                   loss_name="sweep")

        for i in range(n_warm):
            t = time.perf_counter()
            one(i)
            log(f"  warmup {i}: {time.perf_counter() - t:.2f}s")
        t0 = time.perf_counter()
        for i in range(n_steps):
            one(n_warm + i)
        jax.block_until_ready(eng.params)
        dt = (time.perf_counter() - t0) / n_steps
        tflops = train_step_flops(cfg, n_params, seqlens) / dt / 1e12
        del eng, params
        gc.collect()
        return tflops
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def sweep(name, configs):
    """configs: list of (label, env, n_mbs[, seqlen]). Emits one JSON row
    each and a winner row at the end."""
    best = None
    for label, env, n_mbs, *rest in configs:
        log(f"sweep {name}: {label} ...")
        try:
            tflops = measure(env, n_mbs=n_mbs,
                             seqlen=rest[0] if rest else 0)
        except Exception as e:  # OOM on one config must not kill the rest
            log(f"sweep {name}: {label} FAILED {type(e).__name__}: {e}")
            emit(sweep=name, config=label,
                 error=f"{type(e).__name__}: {e}"[:200])
            gc.collect()
            continue
        emit(sweep=name, config=label, tflops=round(tflops, 2))
        log(f"sweep {name}: {label:32s} {tflops:7.2f} TFLOP/s")
        if best is None or tflops > best[1]:
            best = (label, tflops)
    if best:
        emit(sweep=name, best=best[0], tflops=round(best[1], 2))
        log(f"sweep {name}: BEST {best[0]} @ {best[1]:.2f} TFLOP/s")


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which not in ("all", "ce", "blocks", "mbs", "seqlen"):
        sys.exit(
            f"unknown sweep {which!r}: expected all|ce|blocks|mbs|seqlen"
        )
    platform = jax.devices()[0].platform
    log(f"mfu_sweep: platform={platform} which={which}")
    if platform != "tpu" and not TINY:
        log("WARNING: not on TPU; numbers are not meaningful")

    if which in ("all", "ce"):
        # Default (byte-budget @32k vocab) resolves to 4096.
        sweep("ce_chunk", [
            (f"ce={c}", {"AREAL_CE_CHUNK": c}, 1)
            for c in ((64,) if TINY else (1024, 2048, 4096, 8192, 16384))
        ])
    if which in ("all", "blocks"):
        grid = ((128, 128, 128),) if TINY else (
            (512, 1024, 512),   # current default
            (256, 1024, 512),
            (512, 512, 512),
            (1024, 1024, 512),
            (512, 2048, 512),
            (512, 1024, 1024),
            (256, 512, 512),
        )
        sweep("splash_blocks", [
            (f"bq={bq},bkv={bkv},bkvc={bkvc}",
             {"AREAL_SPLASH_BQ": bq, "AREAL_SPLASH_BKV": bkv,
              "AREAL_SPLASH_BKVC": bkvc}, 1)
            for bq, bkv, bkvc in grid
        ])
    if which in ("all", "mbs"):
        sweep("n_mbs", [
            (f"n_mbs={m}", {}, m) for m in ((1, 2) if TINY else (1, 2, 4))
        ])
    if which in ("all", "seqlen"):
        # Row length at constant total tokens: longer rows raise the
        # attention-FLOPs fraction (higher arithmetic intensity in the
        # splash kernel) but deepen remat recompute; measure, don't guess.
        sweep("seqlen", [
            (f"seqlen={s}", {}, 1, s)
            for s in ((64, 128) if TINY else (1024, 2048, 4096, 8192))
        ])


if __name__ == "__main__":
    main()

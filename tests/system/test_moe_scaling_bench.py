"""ISSUE 17 acceptance (bench leg): the `moe_scaling` phase banks an
attested CPU-proxy record — dense vs MoE per-token step time at matched
active FLOPs, dropless EP1 vs EP2 loss-trajectory parity, the
capacity-vs-dropless dispatch A/B with its drop-rate sweep, and the
expert-sliced stream's ~1/EP per-rank ingress over a live origin — and
`validate_bench.py` refuses the three failure classes: parity-missing
records, dropless arms that realized drops, and EP streams whose
ingress did not shrink.

Loss parity, realized drop rates, and sha256 byte accounting are exact
and machine-independent, which is why a CPU-proxy record is real
evidence here; absolute step times only mean anything on-chip.

The phase runs through the REAL bench runner (own subprocess +
PhaseSpec.env 2-fake-device mesh + child-banked attested record) — the
exact path the daemon takes, and the same jax 0.4.x
suite-state-sensitivity sidestep test_train_sharded_bench.py documents.

Time budget: ~40 s (child imports + live compiles; the phase opts out
of the persistent XLA cache)."""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank, runner
from tests.fixtures import scale_timeout

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.timeout(420)
def test_moe_scaling_record_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    # The child gets exactly the phase's requested device topology (the
    # runner APPENDS PhaseSpec.env XLA_FLAGS to inherited ones; the
    # suite's 8-device conftest flag would otherwise ride along).
    monkeypatch.setenv("XLA_FLAGS", "")
    rec = runner.run_phase(
        "moe_scaling", "measure", b, deadline_s=scale_timeout(360)
    )
    assert rec["status"] == "ok", rec
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("moe_scaling", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # THE acceptance numbers: dropless EP2 and the no-drop capacity arm
    # track dropless EP1, nothing dropped, per-rank ingress ~1/EP at
    # ~one origin payload, and the sweep shows drops vanishing.
    assert v["ep_parity_ok"] == 1.0 and v["capacity_parity_ok"] == 1.0
    assert v["ep_loss_max_rel_err"] < 1e-5
    assert v["dropless_drop_rate"] == 0.0 and v["ep2_drop_rate"] == 0.0
    assert v["ep_ingress_frac_max"] <= 1.0 / v["ep_degree"] + 0.25
    assert v["origin_full_payloads"] <= 1.05
    assert v["capacity_sweep"][0]["drop_rate"] > 0.0
    assert v["capacity_sweep"][-1]["drop_rate"] == 0.0
    for k in ("dense_step_s", "moe_ep1_step_s", "moe_ep2_step_s",
              "capacity_step_s"):
        assert v[k] > 0  # the A/B step-time breakdown banked

    # Validator teeth, refusal class 1: parity-missing records.
    bad = json.loads(json.dumps(rec))
    del bad["value"]["ep_parity_ok"]
    assert validator.validate_phase_value("moe_scaling", bad)
    bad = json.loads(json.dumps(rec))
    bad["value"]["ep_parity_ok"] = 0.0
    assert any(
        "diverged" in p
        for p in validator.validate_phase_value("moe_scaling", bad)
    )
    # Refusal class 2: a "dropless" arm that realized drops.
    bad = json.loads(json.dumps(rec))
    bad["value"]["dropless_drop_rate"] = 0.02
    assert any(
        "broken dispatcher" in p
        for p in validator.validate_phase_value("moe_scaling", bad)
    )
    # Refusal class 3: an EP stream whose ingress did not shrink.
    bad = json.loads(json.dumps(rec))
    bad["value"]["ep_ingress_frac_max"] = 1.0
    assert any(
        "shrink" in p
        for p in validator.validate_phase_value("moe_scaling", bad)
    )
    # And the sweep is structural evidence: absent or non-monotone
    # drop-rate curves are refused too.
    bad = json.loads(json.dumps(rec))
    bad["value"]["capacity_sweep"] = []
    assert any(
        "capacity_sweep" in p
        for p in validator.validate_phase_value("moe_scaling", bad)
    )
    bad = json.loads(json.dumps(rec))
    bad["value"]["capacity_sweep"][-1]["drop_rate"] = 0.9
    assert any(
        "non-increasing" in p
        for p in validator.validate_phase_value("moe_scaling", bad)
    )


def test_moe_scaling_registered_as_default_proxy_phase():
    """The daemon picks moe_scaling up by default; CPU rounds self-label
    proxy evidence. Budget: <1 s (no phase body runs)."""
    from areal_tpu.bench import phases

    spec = phases.get("moe_scaling")
    assert spec.default and spec.proxy
    assert spec in phases.default_phases()
    assert "host_platform_device_count=2" in spec.env["XLA_FLAGS"]

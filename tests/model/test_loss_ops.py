import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.packing import pack_sequences
from areal_tpu.ops.loss import (
    gather_logprobs,
    masked_normalization,
    next_token_logprobs,
    sft_loss,
)


def test_gather_logprobs_matches_log_softmax():
    rng = np.random.RandomState(0)
    logits = rng.randn(4, 10).astype(np.float32)
    labels = rng.randint(0, 10, size=4)
    out = np.asarray(gather_logprobs(jnp.asarray(logits), jnp.asarray(labels)))
    ref = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))[
        np.arange(4), labels
    ]
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_next_token_logprobs_segment_boundaries():
    rng = np.random.RandomState(1)
    seqs = [rng.randint(0, 50, size=l) for l in [4, 3]]
    b = pack_sequences(seqs, row_len=16)
    logits = rng.randn(b.n_rows, b.row_len, 50).astype(np.float32)
    lp = np.asarray(
        next_token_logprobs(
            jnp.asarray(logits), jnp.asarray(b.input_ids), jnp.asarray(b.segment_ids)
        )
    )
    # Within a sequence, position t scores token t+1.
    for span in b.spans:
        seq = seqs[span.seq_index]
        for t in range(span.length - 1):
            col = span.start + t
            row_logits = logits[span.row, col]
            expect = row_logits[seq[t + 1]] - np.log(np.exp(row_logits).sum())
            np.testing.assert_allclose(lp[span.row, col], expect, atol=1e-4)
        # Final position of each sequence contributes 0.
        assert lp[span.row, span.start + span.length - 1] == 0.0
    # Padding positions are 0.
    assert (lp[b.segment_ids == 0] == 0).all()


def test_sft_loss_counts_masked_tokens():
    rng = np.random.RandomState(2)
    seqs = [rng.randint(0, 50, size=6)]
    b = pack_sequences(seqs, row_len=8)
    logits = rng.randn(1, 8, 50).astype(np.float32)
    mask = np.zeros((1, 8), np.float32)
    mask[0, 2:5] = 1.0  # predictions at t=2,3,4 count
    total, n = sft_loss(
        jnp.asarray(logits), jnp.asarray(b.input_ids), jnp.asarray(b.segment_ids),
        jnp.asarray(mask),
    )
    assert float(n) == 3.0
    assert float(total) > 0


def test_masked_normalization():
    x = jnp.asarray(np.array([[1.0, 2.0, 3.0, 100.0]]))
    mask = jnp.asarray(np.array([[1.0, 1.0, 1.0, 0.0]]))
    out = np.asarray(masked_normalization(x, mask))
    vals = out[0, :3]
    assert abs(vals.mean()) < 1e-5
    assert out[0, 3] == 0.0
    np.testing.assert_allclose(np.std(vals, ddof=1), 1.0, atol=0.05)

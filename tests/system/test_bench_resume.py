"""bench.py flap tolerance: per-phase checkpoint state (a run killed
mid-compile resumes finished phases instead of losing the round)."""

import pytest

import bench


@pytest.fixture(autouse=True)
def state_file(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_BENCH_STATE", str(tmp_path / "bench_state.json"))
    yield


def test_save_then_load_roundtrip():
    st = bench.save_phase({}, "cpu", "train_tflops", 12.5)
    st = bench.save_phase(st, "cpu", "gen_tps", 340.0)
    loaded = bench.load_state("cpu")
    assert loaded["train_tflops"] == 12.5
    assert loaded["gen_tps"] == 340.0


def test_platform_mismatch_discards():
    bench.save_phase({}, "tpu", "train_tflops", 99.0)
    assert bench.load_state("cpu") == {}


def test_stale_state_discards():
    bench.save_phase({}, "cpu", "train_tflops", 1.0)
    assert bench.load_state("cpu", max_age_s=0.0) == {}
    assert bench.load_state("cpu", max_age_s=3600.0) != {}


def test_clear_state():
    bench.save_phase({}, "cpu", "train_tflops", 1.0)
    bench.clear_state()
    assert bench.load_state("cpu") == {}
    bench.clear_state()  # idempotent


def test_corrupt_state_discards(tmp_path, monkeypatch):
    path = tmp_path / "bench_state.json"
    monkeypatch.setenv("AREAL_BENCH_STATE", str(path))
    path.write_text("{not json")
    assert bench.load_state("cpu") == {}

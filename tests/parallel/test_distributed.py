"""parallel/distributed.py exercised for real (VERDICT r2 item 7): two OS
processes form one jax.distributed world through the NFS name_resolve
rendezvous, and the multi-host SPMD SFT path trains in lockstep over a
cross-process global mesh."""

import json
import os
import subprocess
import sys

import pytest

from tests.fixtures import make_sft_rows, train_tiny_tokenizer

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import os, sys
rank, n, nr_root, out = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
import jax
jax.config.update("jax_platforms", "cpu")
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=nr_root)
from areal_tpu.parallel.distributed import setup_host_group
info = setup_host_group("exp-dist", "t0", "g0", rank, n)
import numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()).reshape(jax.device_count()), ("data",))
x = jax.device_put(np.ones((jax.device_count(), 2)), NamedSharding(mesh, P("data", None)))
s = jax.jit(lambda a: jnp.sum(a))(x)  # cross-process reduction
jax.block_until_ready(s)
import json
with open(out, "w") as f:
    json.dump({
        "rank": rank,
        "process_id": info.process_id,
        "coordinator": info.coordinator_address,
        "n_processes": jax.process_count(),
        "n_devices": jax.device_count(),
        "sum": float(np.asarray(s.addressable_data(0))),
    }, f)
"""


def _child_env(n_local_devices: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local_devices}"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_setup_host_group_two_processes(tmp_path):
    nr_root = str(tmp_path / "nr")
    outs = [str(tmp_path / f"out{r}.json") for r in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", CHILD, str(r), "2", nr_root, outs[r]],
            env=_child_env(2), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(2)
    ]
    logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-3000:]
    results = [json.load(open(o)) for o in outs]
    for r, res in enumerate(results):
        assert res["process_id"] == r
        assert res["n_processes"] == 2
        assert res["n_devices"] == 4  # 2 hosts x 2 local devices
        assert res["sum"] == 8.0  # global reduction saw all shards
    # Both ranks agreed on the elected coordinator.
    assert results[0]["coordinator"] == results[1]["coordinator"]


def test_setup_host_group_single_host_noop(monkeypatch):
    """n_hosts == 1 must not touch jax.distributed (local meshes work
    as-is; initialize() would grab a port and wedge single-host runs)."""
    import jax

    from areal_tpu.parallel.distributed import setup_host_group

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    info = setup_host_group("e", "t", "g", 0, 1)
    assert (info.process_id, info.num_processes) == (0, 1)
    assert calls == []  # no-op: initialize never invoked


def test_setup_host_group_coordinator_election_mocked(tmp_path, monkeypatch):
    """Unit pin for the rendezvous (PR 9 satellite: this ran only inside
    the slow 2-process e2e before): rank 0 elects itself coordinator and
    publishes ip:port through name_resolve; rank 1 waits for the key;
    both call jax.distributed.initialize with the SAME address and their
    own process ids. jax.distributed is mocked, so this pins the
    election protocol, not the collective fabric. Budget: <1 s."""
    import jax

    from areal_tpu.base import name_resolve
    from areal_tpu.parallel.distributed import setup_host_group

    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr"))
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    info0 = setup_host_group("exp-m", "t0", "g0", 0, 2)
    info1 = setup_host_group("exp-m", "t0", "g0", 1, 2, timeout=5.0)
    assert info0.coordinator_address == info1.coordinator_address
    host, port = info0.coordinator_address.rsplit(":", 1)
    assert host and 0 < int(port) < 65536
    assert [c["process_id"] for c in calls] == [0, 1]
    assert all(c["num_processes"] == 2 for c in calls)
    assert all(
        c["coordinator_address"] == info0.coordinator_address for c in calls
    )


def test_setup_host_group_wait_timeout(tmp_path, monkeypatch):
    """A non-zero rank whose coordinator never publishes must surface a
    TimeoutError from the name_resolve wait — not hang the worker or
    call jax.distributed.initialize with garbage. Budget: ~1 s."""
    import jax

    from areal_tpu.base import name_resolve
    from areal_tpu.parallel.distributed import setup_host_group

    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr"))
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    with pytest.raises(TimeoutError):
        setup_host_group("exp-to", "t0", "g0", 1, 2, timeout=0.5)
    assert calls == []  # initialize never reached


def test_verify_host_mesh_slice_single_process():
    """The startup mesh-slice check (model_worker mirrors the serving
    fleet's weight-shard check): a single-host mesh passes with its
    summary; the same mesh under a multi-host config fails fast with
    the actionable jax.distributed message. Budget: <1 s."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from areal_tpu.parallel.distributed import verify_host_mesh_slice

    mesh = Mesh(
        np.array(jax.devices()[:2]).reshape(1, 2, 1, 1),
        ("data", "fsdp", "seq", "tensor"),
    )
    info = verify_host_mesh_slice(mesh, 0, 1)
    assert info["local_devices"] == info["mesh_devices"] == 2
    with pytest.raises(RuntimeError, match="jax.distributed"):
        # A single-process mesh cannot satisfy train_n_hosts=2: the
        # peers never initialized, exactly what the check must name.
        verify_host_mesh_slice(mesh, 0, 2)


@pytest.mark.slow  # ~45s two-process SPMD run; kept out of the tier-1
# budget (and env-sensitive: needs shard_map-era jax)
@pytest.mark.timeout(900)
def test_multihost_sft_end_to_end(tmp_path):
    """training/multihost.py: 2 simulated hosts x 2 devices, d2f2 global
    mesh, lockstep SFT steps; rank 0 reports decreasing loss."""
    from training.multihost import launch_multihost

    data = tmp_path / "sft.jsonl"
    rows = make_sft_rows(8, seed=0)
    with open(data, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    train_tiny_tokenizer(
        [r["prompt"] + " " + r["answer"] for r in rows], tok_dir
    ).save_pretrained(str(tok_dir))

    out = str(tmp_path / "result.json")
    overrides = [
        "experiment_name=mh-test", "trial_name=t0", "seed=3",
        f"name_resolve_root={tmp_path / 'nr'}",
        f"dataset.path={data}", "dataset.type_=prompt_answer",
        f"tokenizer_path={tok_dir}",
        "train_batch_size=8",
        ('model.config={"n_layers":2,"hidden_dim":32,"n_q_heads":2,'
         '"n_kv_heads":1,"head_dim":16,"intermediate_dim":64,'
         '"vocab_size":192,"compute_dtype":"float32",'
         '"param_dtype":"float32"}'),
        "model.optimizer.lr=2e-3", "model.optimizer.warmup_steps_proportion=0",
        "model.row_len_multiple=32", "model.remat=false",
    ]
    result = launch_multihost(
        n_hosts=2, overrides=overrides, mesh_spec="d2f2", steps=5,
        out_path=out, host_env=_child_env(2), timeout=600,
    )
    assert result["n_processes"] == 2
    assert result["n_devices"] == 4
    assert result["mesh"] == {"data": 2, "fsdp": 2, "seq": 1, "tensor": 1}
    assert len(result["losses"]) == 5
    assert result["losses"][-1] < result["losses"][0]


@pytest.mark.timeout(900)
def test_dryrun_free_of_involuntary_remat(tmp_path):
    """VERDICT r2 weak #3 regression gate: the compiled multichip program
    must not contain GSPMD 'Involuntary full rematerialization' fallbacks
    (sharding-transition bounces that replicate tensors on real chips).

    Subsumes the old in-process dryrun test (asserts returncode AND the
    warning absence)."""
    env = _child_env(8)
    # The warning is emitted at XLA log level WARNING; an inherited
    # TF_CPP_MIN_LOG_LEVEL>=2 would silence it and make the gate vacuous.
    env["TF_CPP_MIN_LOG_LEVEL"] = "1"
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=800,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    combined = out.stdout + out.stderr  # warning routing may change streams
    assert "Involuntary full rematerialization" not in combined, (
        "sharding annotations regressed: XLA fell back to replication\n"
        + "\n".join(
            l for l in combined.splitlines() if "rematerial" in l
        )[:2000]
    )

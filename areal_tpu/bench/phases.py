"""Phase registry: what the bench can measure, what each phase costs.

A phase is the unit of banking. Each one declares:

- ``priority``       lower runs first — headline evidence (train
                     TFLOP/s, gen tok/s) outranks secondary probes, so
                     a short flap window is spent on what the round is
                     actually gated on
- ``est_compile_s``  estimated on-chip cost of the *compile pass*:
                     trace + XLA-compile every program the phase needs,
                     populating the persistent compilation cache. Banked
                     as a ``compile`` record — a later window never
                     re-pays it.
- ``est_measure_s``  estimated on-chip cost of the *measure pass*
                     (warm re-compile from cache + timed steady state)
- ``min_window_s``   the smallest window in which the measure pass can
                     still produce a steady-state number worth banking
- ``headline``       this phase backs a top-level report number and so
                     must be driver-verified to count as evidence
- ``proxy``          CPU/virtual-mesh evidence by construction; the
                     runner pins its subprocess to JAX_PLATFORMS=cpu
                     and the report labels it non-driver-verified
- ``entrypoint``     ``"module:function"``; the function takes the pass
                     name (``"compile"`` | ``"measure"``) and returns
                     the record's value dict

Phase bodies live in :mod:`areal_tpu.bench.workloads`; tests register
their own cheap phases (``AREAL_BENCH_PHASE_MODULES`` makes the runner
subprocess import them too).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Callable, Dict, List, Optional

from areal_tpu.base import env_registry

# How far a phase may overrun its estimate before the runner kills it.
DEADLINE_FACTOR = 3.0
MIN_DEADLINE_S = 120.0


@dataclasses.dataclass(frozen=True)
class PhaseSpec:
    name: str
    entrypoint: str
    priority: int = 100
    est_compile_s: float = 60.0
    est_measure_s: float = 60.0
    min_window_s: float = 30.0
    headline: bool = False
    proxy: bool = False
    # Included in a bare `python bench.py` run (non-default phases run
    # only when asked for by name or picked up by the daemon).
    default: bool = True
    # Extra env for the runner subprocess (applied before env_extra;
    # XLA_FLAGS values APPEND to the inherited flags so e.g. a phase
    # can request a fake multi-device CPU mesh without clobbering the
    # host's settings).
    env: Optional[Dict[str, str]] = None
    description: str = ""

    def resolve(self) -> Callable[[str], Dict]:
        mod, _, fn = self.entrypoint.partition(":")
        return getattr(importlib.import_module(mod), fn)

    def cost(self, pass_: str) -> float:
        return self.est_compile_s if pass_ == "compile" else self.est_measure_s

    def deadline_s(self, pass_: str) -> float:
        env = env_registry.get_float("AREAL_BENCH_PHASE_DEADLINE_S")
        if env is not None:
            return env
        return max(self.cost(pass_) * DEADLINE_FACTOR, MIN_DEADLINE_S)


_REGISTRY: Dict[str, PhaseSpec] = {}


def register(spec: PhaseSpec) -> PhaseSpec:
    if spec.name in _REGISTRY:
        raise ValueError(f"phase {spec.name!r} already registered")
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> PhaseSpec:
    load_extra_modules()
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown phase {name!r}; known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


def all_phases() -> List[PhaseSpec]:
    """Every registered phase, priority order (ties by name)."""
    load_extra_modules()
    return sorted(_REGISTRY.values(), key=lambda s: (s.priority, s.name))


def default_phases() -> List[PhaseSpec]:
    return [s for s in all_phases() if s.default]


_EXTRA_LOADED: Optional[str] = None


def load_extra_modules(spec: Optional[str] = None) -> None:
    """Import extra phase modules (comma-separated module names from
    AREAL_BENCH_PHASE_MODULES). The runner child calls this too, so a
    phase registered by a test exists in the subprocess that executes
    it."""
    global _EXTRA_LOADED
    if spec is None:
        spec = env_registry.get_str("AREAL_BENCH_PHASE_MODULES")
    if spec == _EXTRA_LOADED:
        return
    _EXTRA_LOADED = spec
    for mod in filter(None, (m.strip() for m in spec.split(","))):
        importlib.import_module(mod)


# ----------------------------------------------------------------------
# Built-in phases. On-chip estimates come from the banked rounds: r2's
# cold train warmup was ~13.5s/step with multi-minute XLA compiles on a
# tunneled device, and the one lost r5 window died inside a compile that
# a persistent cache would have made free.
# ----------------------------------------------------------------------

register(PhaseSpec(
    name="train_tflops",
    entrypoint="areal_tpu.bench.workloads:train_phase",
    priority=0,
    est_compile_s=180.0,
    est_measure_s=45.0,
    min_window_s=25.0,
    headline=True,
    description="Full train step (fwd+bwd+sharded optimizer) TFLOP/s per "
                "chip on the flagship packed-varlen model",
))

register(PhaseSpec(
    name="gen_tps",
    entrypoint="areal_tpu.bench.workloads:gen_phase",
    priority=1,
    est_compile_s=120.0,
    est_measure_s=60.0,
    min_window_s=40.0,
    headline=True,
    description="ServingEngine sustained output tok/s/chip, 32x512+512",
))

register(PhaseSpec(
    name="train_tflops_scaling",
    entrypoint="areal_tpu.bench.workloads:train_tflops_scaling_phase",
    priority=2,
    est_compile_s=300.0,
    est_measure_s=180.0,
    min_window_s=60.0,
    # Harmless on TPU (the flag only shapes the HOST platform); makes a
    # CPU round bank a labeled 2-point sanity curve instead of nothing.
    env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    description="Weak-scaling train curve 1->N chips: per-chip TFLOP/s "
                "per power-of-2 FSDP mesh (batch grows with the mesh), "
                "banked as points so scaling curves assemble across "
                "rounds — the daemon spends the next real multi-chip "
                "window here unattended",
))

register(PhaseSpec(
    name="gen_long_tps",
    entrypoint="areal_tpu.bench.workloads:gen_long_phase",
    priority=2,
    est_compile_s=120.0,
    est_measure_s=420.0,
    min_window_s=180.0,
    description="Long-form serving: 8 requests x 8192 new tokens through "
                "chunked prefill + the paged pool",
))

register(PhaseSpec(
    name="serving_http",
    entrypoint="areal_tpu.bench.workloads:serving_http_phase",
    priority=3,
    est_compile_s=120.0,
    est_measure_s=90.0,
    min_window_s=60.0,
    default=False,
    description="System-layer serving: GenerationServer worker behind "
                "HTTP (the SGLang-contract path the RL system drives)",
))

register(PhaseSpec(
    name="serving_openloop",
    entrypoint="areal_tpu.bench.workloads:serving_openloop_phase",
    priority=4,
    est_compile_s=90.0,
    est_measure_s=180.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Open-loop (Poisson) fleet serving against REAL server "
                "processes behind the manager: arrival-rate sweep -> "
                "p50/p99 TTFT + goodput, server-side 429 admission vs "
                "no-backpressure A/B at deliberate overload "
                "(scheduling-policy evidence; CPU-proxy)",
))

register(PhaseSpec(
    name="serving_disagg",
    entrypoint="areal_tpu.bench.workloads:serving_disagg_phase",
    priority=5,
    est_compile_s=90.0,
    est_measure_s=180.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Disaggregated prefill/decode A/B: unified vs 1P+1D "
                "real-process fleets under a mixed long-prefill/"
                "short-decode open-loop load -> decode ITL p99 + TTFT "
                "p99 for both arms + KV-handoff counters (CPU-proxy)",
))

register(PhaseSpec(
    name="sessions_resident",
    entrypoint="areal_tpu.bench.workloads:sessions_resident_phase",
    priority=6,
    est_compile_s=90.0,
    est_measure_s=240.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Tiered-KV plane: resident-session sweep past the HBM "
                "prefix budget on real server processes — returning-"
                "session TTFT with the host tier vs the full-re-prefill "
                "baseline, hit rate by tier (hbm/host/peer/miss), zero "
                "true prefix loss under pressure, and the int8-vs-float "
                "spill-wire byte ratio (CPU-proxy)",
))

register(PhaseSpec(
    name="fleet_elastic",
    entrypoint="areal_tpu.bench.workloads:fleet_elastic_phase",
    priority=7,
    est_compile_s=90.0,
    est_measure_s=300.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Elastic fleet control plane: one real-process fleet "
                "lives through runtime join (peer-bootstrap vs origin "
                "A/B on join-to-first-routed-token + origin bytes), a "
                "manager SIGKILL + lease-takeover successor, and a "
                "drain-then-leave KV migration — under sustained "
                "PartialRolloutManager load with zero failed rollouts "
                "(CPU-proxy)",
))

register(PhaseSpec(
    name="multi_model_serving",
    entrypoint="areal_tpu.bench.workloads:multi_model_serving_phase",
    priority=7,
    est_compile_s=90.0,
    est_measure_s=300.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Multi-model serving plane: two model families on one "
                "real-process fleet behind a multi-model manager — "
                "per-model routing with greedy parity vs single-model "
                "baseline fleets (zero cross-model contamination), "
                "unknown-model refusal, cross-model KV isolation, and "
                "an independent weight cutover of one family under the "
                "other family's sustained load (p99 TTFT holds, zero "
                "failures, zero prefix loss) (CPU-proxy)",
))

register(PhaseSpec(
    name="tenant_fairness",
    entrypoint="areal_tpu.bench.workloads:tenant_fairness_phase",
    priority=7,
    est_compile_s=90.0,
    est_measure_s=240.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Tenant gateway fairness A/B: a real gateway subprocess "
                "in front of a real-process fleet, noisy-aggressor flood "
                "vs an interactive victim — victim p99 TTFT (admission-"
                "to-first-token) solo vs fair-share ON vs FIFO, with the "
                "aggressor shed against its own stream cap and the DRR "
                "queue demonstrably engaged (CPU-proxy)",
))

# kernel_micro family (ROADMAP item 3): per-kernel parity + timing
# evidence for the hot-path kernels, DEFAULT phases so the daemon
# spends the next unattended TPU window banking all of it. Off-TPU the
# records self-label cpu_proxy (validate_bench refuses unlabeled ones);
# they are NOT proxy=True phases — that would pin the subprocess to
# JAX_PLATFORMS=cpu and the device window would never measure them.

register(PhaseSpec(
    name="kernel_micro_gae",
    entrypoint="areal_tpu.bench.workloads:kernel_micro_gae_phase",
    priority=8,
    est_compile_s=30.0,
    est_measure_s=40.0,
    min_window_s=10.0,
    description="Trainer GAE kernels: serial lax.scan baseline vs the "
                "associative scan 'auto' dispatches vs the blocked "
                "Pallas scan + host loop, parity per case "
                "(packed multi-segment rows, misaligned starts)",
))

register(PhaseSpec(
    name="kernel_micro_paged_decode",
    entrypoint="areal_tpu.bench.workloads:kernel_micro_paged_decode_phase",
    priority=8,
    est_compile_s=60.0,
    est_measure_s=60.0,
    min_window_s=15.0,
    description="Paged decode attention across the scheduler's pow2 "
                "admit batches: XLA gather baseline vs the 'auto'-"
                "resolved kernel for float AND int8 pools, parity + "
                "quant error per case",
))

register(PhaseSpec(
    name="kernel_micro_splash",
    entrypoint="areal_tpu.bench.workloads:kernel_micro_splash_phase",
    priority=9,
    est_compile_s=60.0,
    est_measure_s=40.0,
    min_window_s=10.0,
    description="Splash prefill attention vs the reference einsum "
                "oracle on a packed multi-segment stream (parity-only "
                "interpret case off-TPU)",
))

register(PhaseSpec(
    name="kernel_micro_decode_state",
    entrypoint="areal_tpu.bench.workloads:kernel_micro_decode_state_phase",
    priority=9,
    est_compile_s=90.0,
    est_measure_s=90.0,
    min_window_s=20.0,
    description="Device-resident decode-state A/B "
                "(AREAL_DECODE_RESIDENT on vs off): per-decode-block "
                "H2D transfers/bytes + throughput for both arms with "
                "greedy token parity asserted in-phase",
))

register(PhaseSpec(
    name="pack_density",
    entrypoint="areal_tpu.bench.workloads:pack_density_phase",
    priority=10,
    est_compile_s=0.0,  # host-only: nothing to compile, no compile pass
    est_measure_s=20.0,
    min_window_s=0.0,
    proxy=True,
    description="FFD packing density on realistic length mixes "
                "(host-side; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="rpc_resilience",
    entrypoint="areal_tpu.bench.workloads:rpc_resilience_phase",
    priority=12,
    est_compile_s=0.0,  # host + loopback HTTP only: no compile pass
    est_measure_s=30.0,
    min_window_s=0.0,
    proxy=True,
    description="RPC substrate tail-latency A/B: hedged vs unhedged "
                "hash-verified chunk pulls from two loopback holders "
                "under the injected-delay chaos action — hedged p99 "
                "must sit near the hedge delay, unhedged near the "
                "injected tail, with win/cancel accounting "
                "(host-side; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="recovery_slo",
    entrypoint="areal_tpu.bench.workloads:recovery_slo_phase",
    priority=12,
    est_compile_s=0.0,  # host + loopback ZMQ only: no compile pass
    est_measure_s=30.0,
    min_window_s=0.0,
    proxy=True,
    description="Durable-training-plane SLOs: async-vs-sync checkpoint "
                "stall A/B on synthetic engine state, cold-recovery "
                "MTTR (manifest + state + WAL replay against the "
                "checkpointed ledger cut), and exactly-once accounting "
                "under a forced redelivery storm — lost and duplicated "
                "must both be zero (host-side; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="weight_update",
    entrypoint="areal_tpu.bench.workloads:weight_update_phase",
    priority=12,
    est_compile_s=0.0,  # host + loopback HTTP only: no compile pass
    est_measure_s=30.0,
    min_window_s=0.0,
    proxy=True,
    description="Weight-distribution plane: origin + 3-holder peer "
                "fanout over loopback HTTP — weight_update_ms with the "
                "transfer/cutover split and the O(1)-origin-egress "
                "invariant (host-side; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="weight_plane_sharded",
    entrypoint="areal_tpu.bench.workloads:weight_plane_sharded_phase",
    priority=13,
    est_compile_s=0.0,  # host + loopback HTTP + tiny CPU-mesh engines
    est_measure_s=180.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    description="Shard-aware + quantized weight plane: per-server "
                "ingress bytes/version vs TP degree (1 vs 2) and wire "
                "dtype (raw vs int8) over a live origin, same-shard "
                "peer replica at zero origin cost, O(1)-origin "
                "invariant, dequant-parity, and greedy-decode parity "
                "of a 2-way-TP engine cut over from sliced shard "
                "streams (byte accounting is exact and "
                "machine-independent; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="train_sharded",
    entrypoint="areal_tpu.bench.workloads:train_sharded_phase",
    priority=14,
    est_compile_s=0.0,  # tiny CPU-mesh programs; the measure pass pays
    est_measure_s=120.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    description="Sharded training end-to-end on a 2-fake-device mesh: "
                "loss-trajectory parity single-device vs FSDP2 vs TP2, "
                "per-mesh step-time breakdown, and the shard-local "
                "trainer dump's host high-water reduction with a "
                "byte-identical round trip through the weight-plane "
                "origin (parity + byte accounting are exact and "
                "machine-independent; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="moe_scaling",
    entrypoint="areal_tpu.bench.workloads:moe_scaling_phase",
    priority=15,
    est_compile_s=0.0,  # tiny CPU-mesh programs; the measure pass pays
    est_measure_s=150.0,
    min_window_s=0.0,
    proxy=True,
    # Default: the daemon banks the MoE evidence unattended; CPU rounds
    # self-label proxy, on-chip rounds make the step times meaningful.
    env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    description="Expert-parallel MoE fast path: dense vs MoE per-token "
                "step time at matched active FLOPs, dropless EP1 vs EP2 "
                "loss-trajectory parity + step times, capacity-vs-"
                "dropless dispatch A/B with a capacity-factor drop-rate "
                "sweep, and the expert-sliced weight stream's ~1/EP "
                "per-rank ingress over a live origin (parity, drop "
                "rates, and byte accounting are exact and machine-"
                "independent; CPU-proxy evidence)",
))

register(PhaseSpec(
    name="agentic_rollout",
    entrypoint="areal_tpu.bench.workloads:agentic_rollout_phase",
    priority=16,
    est_compile_s=90.0,
    est_measure_s=240.0,
    min_window_s=0.0,
    proxy=True,
    default=False,
    description="Multi-turn tool-use rollouts over real server "
                "processes + the pooled reward executor: session-"
                "continuation vs session-blind A/B (re-prefill ratio + "
                "per-turn TTFT), real sandboxed tool-call latency, zero "
                "failed episodes, and an executor saturation sweep that "
                "must shed (429 backpressure) without starving any job "
                "(CPU-proxy)",
))

register(PhaseSpec(
    name="prefetch_overlap",
    entrypoint="areal_tpu.bench.workloads:prefetch_overlap_phase",
    priority=11,
    est_compile_s=30.0,
    est_measure_s=40.0,
    min_window_s=0.0,
    proxy=True,
    description="Input-pipeline overlap telemetry (packing_efficiency / "
                "h2d_wait / dispatch_gap) on the virtual-mesh engine "
                "(CPU-proxy evidence)",
))

"""Gemma HF conversion. Reference parity: realhf/api/from_hf/gemma.py.

Gemma quirks handled here:
- RMSNorm computes x * (1 + w): the +1 offset is folded into the weights
  at import (and removed at export) so the shared rms_norm op applies.
- Embeddings are scaled by sqrt(hidden_dim) (embedding_multiplier).
- gelu activation, tied embeddings, explicit head_dim.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import numpy as np

from areal_tpu.api.model_api import register_hf_family
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf import HFFamily
from areal_tpu.models.hf.llama import (
    params_from_hf_llama_style,
    params_to_hf_llama_style,
)


def _config_from_hf(hf: Dict[str, Any], is_critic: bool = False) -> TransformerConfig:
    return TransformerConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf["head_dim"],
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 8192),
        activation="gelu",
        mlp_type="gated",
        norm_type="rms",
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rotary_base=hf.get("rope_theta", 10000.0),
        tied_embeddings=True,
        embedding_multiplier=math.sqrt(hf["hidden_size"]),
        is_critic=is_critic,
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return {
        "architectures": ["GemmaForCausalLM"],
        "model_type": "gemma",
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "hidden_act": "gelu_pytorch_tanh",
        "rms_norm_eps": cfg.norm_eps,
        "rope_theta": cfg.rotary_base,
        "tie_word_embeddings": True,
        "torch_dtype": "bfloat16",
    }


def _shift_norms(params: Dict, offset: float) -> Dict:
    layers = params["layers"]
    for key in ("ln1", "ln2"):
        layers[key]["weight"] = layers[key]["weight"] + offset
    params["final_norm"]["weight"] = params["final_norm"]["weight"] + offset
    return params


def _params_from_hf(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict:
    return _shift_norms(params_from_hf_llama_style(sd, cfg), +1.0)


def _params_to_hf(params: Dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    import jax

    shifted = jax.tree_util.tree_map(np.asarray, params)
    shifted = {
        "embedding": dict(shifted["embedding"]),
        "layers": {
            k: dict(v) if isinstance(v, dict) else v
            for k, v in shifted["layers"].items()
        },
        "final_norm": dict(shifted["final_norm"]),
        **({"head": dict(shifted["head"])} if "head" in shifted else {}),
    }
    _shift_norms(shifted, -1.0)
    return params_to_hf_llama_style(shifted, cfg)


register_hf_family(
    "gemma",
    HFFamily(
        name="gemma",
        hf_model_type="gemma",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    ),
)

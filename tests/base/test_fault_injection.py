"""Chaos harness: deterministic k-th-hit arming, scoping, env specs."""

import asyncio
import time

import pytest

from areal_tpu.base.fault_injection import FaultInjected, FaultInjector, faults


@pytest.fixture(autouse=True)
def clean_global():
    faults.reset()
    yield
    faults.reset()


def test_unarmed_point_is_noop():
    inj = FaultInjector()
    for _ in range(5):
        inj.maybe_fail("test.some_point")
    assert inj.hits("test.some_point") == 5


def test_raise_on_kth_hit_only():
    inj = FaultInjector()
    inj.arm("test.p", action="raise", at_hit=3)
    inj.maybe_fail("test.p")
    inj.maybe_fail("test.p")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")
    # times=1: the fault fired once and is spent.
    inj.maybe_fail("test.p")
    assert inj.hits("test.p") == 4


def test_repeat_counts():
    inj = FaultInjector()
    inj.arm("test.p", action="raise", at_hit=2, times=2)
    inj.maybe_fail("test.p")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")
    inj.maybe_fail("test.p")


def test_every_hit_from_k():
    inj = FaultInjector()
    inj.arm("test.p", action="raise", at_hit=2, times=0)
    inj.maybe_fail("test.p")
    for _ in range(3):
        with pytest.raises(FaultInjected):
            inj.maybe_fail("test.p")


def test_delay_action():
    inj = FaultInjector()
    inj.arm("test.p", action="delay", delay_s=0.1)
    t0 = time.monotonic()
    inj.maybe_fail("test.p")
    assert time.monotonic() - t0 >= 0.1


def test_async_delay_action():
    inj = FaultInjector()
    inj.arm("test.p", action="delay", delay_s=0.05)

    async def go():
        t0 = time.monotonic()
        await inj.maybe_fail_async("test.p")
        return time.monotonic() - t0

    assert asyncio.run(go()) >= 0.05


def test_scope_filtering():
    inj = FaultInjector()
    inj.arm("test.p", action="raise", scope="generation_server/1")
    inj.set_scope("generation_server/0")
    inj.maybe_fail("test.p")  # wrong scope: no fire
    inj.set_scope("generation_server/1")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")


def test_on_trigger_callback():
    inj = FaultInjector()
    fired = []
    inj.arm("test.p", action="raise", on_trigger=lambda: fired.append(1))
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")
    assert fired == [1]


def test_env_spec_parsing(monkeypatch):
    inj = FaultInjector()
    inj.load_env(
        "gserver.generate@generation_server/1=raise:k=2;"
        "worker.poll=delay:delay=0.01"
    )
    inj.set_scope("generation_server/1")
    inj.maybe_fail("gserver.generate")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("gserver.generate")
    t0 = time.monotonic()
    inj.maybe_fail("worker.poll")
    assert time.monotonic() - t0 >= 0.01


def test_env_spec_loaded_lazily(monkeypatch):
    monkeypatch.setenv("AREAL_FAULTS", "test.lazy_point=raise")
    inj = FaultInjector()
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.lazy_point")


def test_bad_env_entry_ignored():
    inj = FaultInjector()
    inj.load_env("not-a-valid-entry;test.p=raise")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("test.p")


def test_declared_variants_verify_registry():
    # The dynamic-sweep API: declared and test.* points pass through,
    # an undeclared point raises instead of arming a silent no-op.
    inj = FaultInjector()
    inj.arm_declared("worker.poll", action="raise")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("worker.poll")
    assert inj.hits_declared("worker.poll") == 1

    inj.arm_declared("test.dynamic_ok", action="raise")
    with pytest.raises(ValueError, match="undeclared chaos point"):
        inj.arm_declared("renamed.or_typod", action="raise")
    with pytest.raises(ValueError, match="undeclared chaos point"):
        inj.hits_declared("renamed.or_typod")

"""ISSUE 6 satellite: 429 + Retry-After from a generation server is
DELIBERATE load-shedding, not a failure. The partial-rollout client must
back off (jittered, honoring the hint), resume against the fleet, report
a shed hint — never a failure report (which would evict the healthy
server) — and spend none of its failure-retry budget on sheds."""

import asyncio

import pytest
from aiohttp import web

from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.system.partial_rollout import PartialRolloutManager


async def _start_app(routes):
    app = web.Application()
    for method, path, handler in routes:
        app.router.add_route(method, path, handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def _scenario(n_sheds: int):
    """Stub server sheds the first `n_sheds` /generate calls with 429,
    then serves; stub manager records every schedule meta."""
    scheds = []
    gen_payloads = []

    async def h_gen(request):
        d = await request.json()
        gen_payloads.append(d)
        if len(gen_payloads) <= n_sheds:
            return web.json_response(
                {"error": "overloaded", "retry_after": 0.02,
                 "queue_depth": 9},
                status=429, headers={"Retry-After": "1"},
            )
        return web.json_response({
            "qid": d["qid"], "output_ids": [1, 2],
            "output_logprobs": [-0.1, -0.2], "no_eos": False,
            "interrupted": False, "version_start": 0, "version_end": 0,
            "latency": 0.0,
        })

    srv_runner, srv_url = await _start_app([("POST", "/generate", h_gen)])

    async def h_sched(request):
        meta = await request.json()
        scheds.append(meta)
        return web.json_response({"url": srv_url, "version": 0,
                                  "policy": "round_robin"})

    mgr_runner, mgr_url = await _start_app(
        [("POST", "/schedule_request", h_sched)]
    )
    try:
        # max_retries=0: ANY failure-classified retry raises, so the 429
        # path demonstrably consumes no failure budget.
        prm = PartialRolloutManager(mgr_url, max_retries=0)
        out = await prm._generate_one(
            "sess/0", [5, 6, 7],
            GenerationHyperparameters(max_new_tokens=2, greedy=True),
        )
        await prm.close()
        return out, scheds, gen_payloads
    finally:
        await srv_runner.cleanup()
        await mgr_runner.cleanup()


@pytest.mark.timeout(60)
def test_client_honors_429_with_backoff_and_shed_hint():
    out, scheds, gens = asyncio.run(_scenario(n_sheds=2))
    assert out.output_ids == [1, 2] and not out.no_eos
    assert len(gens) == 3  # 2 sheds + 1 success
    assert len(scheds) == 3
    # Sheds never become failure reports (no eviction pressure)...
    assert all(not m.get("failed_server_url") for m in scheds)
    # ...but the manager IS told, so it can spill affinity routing.
    assert not scheds[0].get("shed_server_url")
    for m in scheds[1:]:
        assert m["shed_server_url"]
        assert m["shed_retry_after"] == pytest.approx(0.02)
    # Session key + priority class ride along: fresh submissions are
    # class 1 (no accumulated prefix yet).
    assert all(m.get("qid") == "sess/0" for m in scheds)
    assert all(d.get("priority") == 1 for d in gens)


@pytest.mark.timeout(60)
def test_client_clears_shed_hint_after_success():
    out, scheds, _ = asyncio.run(_scenario(n_sheds=1))
    assert out.output_ids == [1, 2]
    assert scheds[1]["shed_server_url"]
    # A fresh sample afterwards starts with a clean hint (per-request
    # state, not manager-global).
    assert not scheds[0].get("shed_server_url")

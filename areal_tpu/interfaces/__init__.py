# Importing registers the bundled interfaces.
from areal_tpu.interfaces import sft as _sft  # noqa: F401
from areal_tpu.interfaces import ppo as _ppo  # noqa: F401
from areal_tpu.interfaces import reward as _reward  # noqa: F401
from areal_tpu.interfaces import fused as _fused  # noqa: F401

"""Request/reply + push/pull stream tests (mirrors reference
tests/system/test_push_pull_stream.py and the req/rep protocol of
realhf/system/request_reply_stream.py)."""

import threading
import time

import numpy as np
import pytest

from areal_tpu.system import push_pull_stream as pps
from areal_tpu.system import request_reply_stream as rrs


def test_request_reply_roundtrip(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "model_worker/0")

    try:
        [rid] = master.request(["model_worker/0"], "spec", [{"x": 1}])

        # Worker sees the request and replies.
        req = worker.poll(block=True, timeout_ms=5000)
        assert req.handle_name == "spec"
        assert req.data == {"x": 1}
        worker.reply_to(req, data={"y": 2})

        reply = master.poll(rid, block=True, timeout=10)
        assert reply.data == {"y": 2}
    finally:
        master.close()
        worker.close()


def test_request_reply_syn_ack(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "model_worker/0")
    try:
        [rid] = master.request(
            ["model_worker/0"], "train_step", [None], no_syn=False
        )
        req = worker.poll(block=True, timeout_ms=5000)
        # Syn arrives before the (delayed) reply.
        master.await_syn(rid, timeout=10)
        worker.reply_to(req, data="done")
        assert master.poll(rid, block=True, timeout=10).data == "done"
    finally:
        master.close()
        worker.close()


def test_request_reply_numpy_payload_compression(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "w0")
    try:
        big = np.zeros((1024, 64), dtype=np.float32)  # compresses well
        [rid] = master.request(["w0"], "data", [big])
        req = worker.poll(block=True, timeout_ms=5000)
        np.testing.assert_array_equal(req.data, big)
        worker.reply_to(req, data=req.data.sum())
        assert master.poll(rid, block=True, timeout=10).data == 0.0
    finally:
        master.close()
        worker.close()


def test_call_many_workers(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    workers = [rrs.make_worker_stream(exp, trial, f"w{i}") for i in range(4)]

    def serve(w):
        req = w.poll(block=True, timeout_ms=10000)
        w.reply_to(req, data=req.data * 2)

    threads = [threading.Thread(target=serve, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    try:
        out = master.call([f"w{i}" for i in range(4)], "double", [1, 2, 3, 4], timeout=15)
        assert out == [2, 4, 6, 8]
    finally:
        for t in threads:
            t.join(timeout=5)
        master.close()
        for w in workers:
            w.close()


def test_push_pull_grouping():
    assert pps.grouping(4, 2) == {0: [0, 1], 1: [2, 3]}
    assert pps.grouping(5, 2) == {0: [0, 1, 2], 1: [3, 4]}
    g = pps.grouping(7, 3)
    assert sorted(sum(g.values(), [])) == list(range(7))


def test_push_ack_roundtrip():
    """ISSUE 16 exactly-once transport: a pushed sample sits in the
    unacked window until the puller acks it durable; the seq/ack keys
    never leak into the delivered payload."""
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port, ack=True)
    try:
        pusher.push({"traj": [1, 2]}, seq="w0/0")
        assert pusher.unacked() == 1
        d = puller.pull(timeout_ms=5000)
        assert d == {"traj": [1, 2]}  # reserved keys stripped
        assert puller.last_seq == "w0/0"
        assert puller.last_ack_addr == pusher.ack_addr
        puller.ack(puller.last_seq, puller.last_ack_addr)
        deadline = time.monotonic() + 5
        while pusher.unacked() and time.monotonic() < deadline:
            pusher.drain_acks()
            time.sleep(0.01)
        assert pusher.unacked() == 0
        assert pusher.counters["areal:train_samples_lost_total"] == 0
        # A timeout resets the per-message attribution.
        with pytest.raises(TimeoutError):
            puller.pull(timeout_ms=20)
        assert puller.last_seq is None and puller.last_ack_addr is None
    finally:
        pusher.close()
        puller.close()


def test_push_without_seq_skips_window():
    """ack=True but no seq minted (AREAL_WAL off at the worker): plain
    fire-and-forget push, nothing windowed."""
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port, ack=True)
    try:
        pusher.push({"x": 1})
        assert pusher.unacked() == 0
        d = puller.pull(timeout_ms=5000)
        assert d == {"x": 1}
        assert puller.last_seq is None
    finally:
        pusher.close()
        puller.close()


def test_redeliver_after_ack_timeout():
    """An unacked sample is re-sent after the ack timeout; the puller
    sees the duplicate (dedup is the WAL/ledger's job) and a late ack
    still clears the window."""
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port, ack=True)
    try:
        pusher.push({"x": 1}, seq="w0/0")
        puller.pull(timeout_ms=5000)  # delivered but never acked
        assert pusher.redeliver(timeout_s=0.0) == 1
        d = puller.pull(timeout_ms=5000)
        assert d == {"x": 1} and puller.last_seq == "w0/0"
        assert pusher.unacked() == 1  # still windowed until acked
        # Not yet timed out again? timeout_s=1h: nothing redelivered.
        assert pusher.redeliver(timeout_s=3600) == 0
        puller.ack("w0/0", puller.last_ack_addr)
        deadline = time.monotonic() + 5
        while pusher.unacked() and time.monotonic() < deadline:
            pusher.drain_acks()
            time.sleep(0.01)
        assert pusher.unacked() == 0
    finally:
        pusher.close()
        puller.close()


def test_redeliver_budget_exhaustion_counts_lost():
    """With a finite AREAL_WAL_REDELIVER_MAX the drop is counted in
    areal:train_samples_lost_total — honest loss accounting, never a
    silent leak (the default budget 0 = retry forever)."""
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port, ack=True)
    try:
        pusher.push({"x": 1}, seq="w0/0")
        assert pusher.redeliver(timeout_s=0.0, max_redeliver=1) == 1
        assert pusher.redeliver(timeout_s=0.0, max_redeliver=1) == 0
        assert pusher.unacked() == 0
        assert pusher.counters["areal:train_samples_lost_total"] == 1
    finally:
        pusher.close()
        puller.close()


def test_reconnect_redelivers_to_restarted_puller():
    """The trainer-kill path: the old puller dies unacked, a new one
    binds a fresh port, the pusher reconnects and redelivery lands the
    sample on the survivor."""
    old = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", old.port, ack=True)
    try:
        pusher.push({"x": 42}, seq="w0/0")
        old.pull(timeout_ms=5000)
        old.close()  # SIGKILL'd trainer: sample journal never fsync'd
        new = pps.ZMQJsonPuller(host="127.0.0.1")
        try:
            pusher.reconnect("127.0.0.1", new.port)
            assert pusher.redeliver(timeout_s=0.0) == 1
            d = new.pull(timeout_ms=5000)
            assert d == {"x": 42} and new.last_seq == "w0/0"
        finally:
            new.close()
    finally:
        pusher.close()


def test_push_pull_json(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    puller = pps.NameResolvingZmqPuller(exp, trial, puller_index=0)
    pushers = [
        pps.NameResolvingZmqPusher(exp, trial, pusher_index=i, n_pushers=2, n_pullers=1)
        for i in range(2)
    ]
    try:
        for i, p in enumerate(pushers):
            p.push({"traj": [1, 2, 3], "src": i})
        got = sorted(
            (puller.pull(timeout_ms=5000) for _ in range(2)), key=lambda d: d["src"]
        )
        assert [g["src"] for g in got] == [0, 1]
        assert got[0]["traj"] == [1, 2, 3]
        with pytest.raises(TimeoutError):
            puller.pull(timeout_ms=50)
    finally:
        puller.close()
        for p in pushers:
            p.close()


def test_poll_batch_defers_same_id_collisions():
    """Epoch carryover: two episodes of the same dataset row landing in
    one drain must not poison the batch (gather refuses duplicate ids) —
    the collision is held back and served by the NEXT poll."""
    import queue as _queue
    from collections import deque

    from areal_tpu.api.data_api import SequenceSample
    from areal_tpu.system.stream_dataset import PullerStreamDataset

    def _traj(sample_id):
        return SequenceSample.from_default(
            ids=[sample_id], seqlens=[3],
            data={"packed_input_ids": np.arange(3)},
        )

    ds = object.__new__(PullerStreamDataset)
    ds._queue = _queue.Queue()
    ds._replayed = deque()
    ds._held = deque()
    ds._queue.put((0, _traj("x")))
    ds._queue.put((0, _traj("y")))
    ds._queue.put((0, _traj("x")))  # later-epoch episode of row x

    batch = ds.poll_batch()
    assert sorted(batch.ids) == ["x", "y"]
    assert ds.qsize() == 1  # the held-back copy still counts as queued
    batch2 = ds.poll_batch()
    assert batch2.ids == ["x"]
    assert ds.poll_batch() is None

"""MoE layer: routing correctness, aux losses, decode/forward parity,
and end-to-end training through the engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.moe import moe_mlp
from areal_tpu.models.transformer import forward, init_params

CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    vocab_size=64,
    max_position_embeddings=128,
    compute_dtype="float32",
    param_dtype="float32",
    # capacity_factor >= E/k = 2 -> no capacity drops, so the packed
    # forward and the per-step decode path route identically (drops are a
    # batch-global, non-causal approximation that would break parity).
    moe=MoEConfig(
        num_experts=4, top_k=2, capacity_factor=2.5,
        aux_loss_coef=1e-2, z_loss_coef=1e-3,
    ),
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def test_moe_mlp_shapes_and_gates(params):
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (3, 8, CFG.hidden_dim), jnp.float32)
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    y, aux = moe_mlp(x, lp, CFG, jnp.float32)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert 0.5 < float(aux["load_balance_loss"]) < 4.0  # ~1 near-uniform routing
    assert float(aux["z_loss"]) >= 0.0


def test_moe_capacity_drops_dont_crash(params):
    """Tiny capacity: some tokens get dropped, output stays finite."""
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 64, CFG.hidden_dim))
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    y, _ = moe_mlp(x, lp, CFG, jnp.float32, capacity_factor=0.25)
    assert np.isfinite(np.asarray(y)).all()


def test_moe_forward_and_grads(params):
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)), jnp.int32)
    seg = jnp.ones_like(ids)
    pos = jnp.tile(jnp.arange(16)[None, :], (2, 1))
    logits, aux = forward(params, CFG, ids, seg, pos, return_aux=True)
    assert logits.shape == (2, 16, 64)
    assert 0.5 * CFG.n_layers < float(aux["load_balance_loss"]) < 4.0 * CFG.n_layers

    def loss(p):
        lg, aux = forward(p, CFG, ids, seg, pos, return_aux=True)
        return jnp.mean(lg**2) + 0.01 * aux["load_balance_loss"]

    grads = jax.grad(loss)(params)
    gr = grads["layers"]["mlp"]["router"]
    assert np.abs(np.asarray(gr)).sum() > 0  # router receives gradient
    ge = grads["layers"]["mlp"]["w_gate"]
    assert np.isfinite(np.asarray(ge)).all()


def test_moe_decode_matches_forward(params):
    """Greedy generation through the decode path must match the packed
    forward's next-token argmax (same tokens step by step)."""
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.models.generation import generate_tokens

    prompt = [5, 9, 11]
    g = GenerationHyperparameters(max_new_tokens=6, greedy=True)
    out = generate_tokens(
        params, CFG, [prompt], g, jax.random.PRNGKey(0), eos_token_id=None,
        prompt_pad_multiple=8,
    )[0]
    toks = prompt + out["output_ids"]
    # Teacher-force through the packed forward; each next token must be the
    # argmax at the previous position.
    ids = jnp.asarray([toks], jnp.int32)
    seg = jnp.ones_like(ids)
    pos = jnp.tile(jnp.arange(len(toks))[None, :], (1, 1))
    logits = forward(params, CFG, ids, seg, pos)
    preds = np.asarray(jnp.argmax(logits[0], -1))
    for i in range(len(prompt) - 1, len(toks) - 1):
        assert preds[i] == toks[i + 1], f"mismatch at {i}"


def test_moe_engine_train_step():
    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss

    params = init_params(CFG, jax.random.PRNGKey(3))
    eng = JaxTrainEngine(
        CFG, params, optimizer_config=OptimizerConfig(lr=1e-3),
        total_train_steps=10, remat=False, row_len_multiple=8,
    )
    rng = np.random.RandomState(0)
    seqlens = [10, 14, 7]
    toks = np.concatenate([rng.randint(0, 64, n) for n in seqlens]).astype(np.int32)
    pm = np.concatenate(
        [np.r_[np.ones(3, bool), np.zeros(n - 3, bool)] for n in seqlens]
    )
    s = SequenceSample.from_default(
        ids=["a", "b", "c"],
        seqlens=seqlens,
        data=dict(packed_input_ids=toks, prompt_mask=pm),
    )
    stats = eng.train_batch(
        s, MicroBatchSpec(), loss_fn=sft_row_loss, loss_weight_fn=sft_loss_weight,
        loss_name="sft",
    )
    assert np.isfinite(stats["sft/loss"])
    assert stats["sft/moe_load_balance"] > 0

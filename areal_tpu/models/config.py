"""Transformer architecture configuration.

Counterpart of the reference's ReaLModelConfig (realhf/api/core/model_api.py:340),
covering the same architecture space: GQA attention, rotary variants,
RMS/LayerNorm, gated MLPs, optional MoE, actor (LM head) or critic (scalar
head) outputs, tied embeddings, and qk-norm (qwen3).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    # Expert capacity = capacity_factor * T * top_k / num_experts; tokens
    # beyond it are dropped (standard einsum-MoE training approximation;
    # >= num_experts / top_k guarantees no drops).
    capacity_factor: float = 1.25
    routed_scaling_factor: float = 1.0
    aux_loss_coef: float = 1e-3
    z_loss_coef: float = 0.0
    # Size of each expert's hidden dim; defaults to intermediate_dim.
    expert_intermediate_dim: Optional[int] = None
    # "capacity": GShard einsum dispatch, [T,E,C] tensors — three large
    #   MXU einsums, shards cleanly for expert parallelism, DROPS tokens
    #   beyond capacity (drop rate surfaced in train stats as
    #   moe_drop_rate). "dropless": sort-by-expert + lax.ragged_dot
    #   grouped matmuls — zero drops at any router skew (the reference
    #   dispatcher's guarantee, token_dispatcher.py), static shapes;
    #   when the mesh's fsdp extent divides num_experts it runs
    #   expert-parallel via shard_map (models/moe.py _moe_mlp_ep: each
    #   shard computes only its own experts' ragged grouped matmuls and
    #   results combine with psum_scatter), otherwise it falls back to
    #   the single-program GSPMD path. Tradeoff documented in
    #   docs/perf_notes.md (Round 17).
    dispatch: str = "capacity"
    # Dense layers interleaved with MoE (e.g. first k layers dense).
    first_k_dense: int = 0

    def __post_init__(self):
        if self.dispatch not in ("capacity", "dropless"):
            # A typo here would silently fall through to capacity
            # dispatch — the exact drop risk "dropless" exists to remove.
            raise ValueError(
                f"MoEConfig.dispatch must be 'capacity' or 'dropless', "
                f"got {self.dispatch!r}"
            )


@dataclasses.dataclass(eq=False)  # eq=False keeps it hashable (by id) for jit static args
class TransformerConfig:
    n_layers: int = 2
    hidden_dim: int = 64
    n_q_heads: int = 4
    n_kv_heads: int = 2
    head_dim: int = 16
    intermediate_dim: int = 128
    vocab_size: int = 128
    max_position_embeddings: int = 2048

    activation: str = "silu"  # silu | gelu
    mlp_type: str = "gated"  # gated | plain
    norm_type: str = "rms"  # rms | layer
    norm_eps: float = 1e-6

    # Position encoding: "rotary" (default) or "learned" absolute
    # embeddings (gpt2).
    pos_emb: str = "rotary"
    rotary_base: float = 10000.0
    rotary_scaling: Optional[float] = None
    rotary_scaling_type: Optional[str] = None  # linear | llama3 | None
    # Extra factors for llama3-style scaling (low/high_freq_factor,
    # original_max_position_embeddings), carried from the HF config.
    rotary_scaling_params: Optional[dict] = None
    rotary_interleaved: bool = False

    attn_bias: bool = False  # qwen2 uses qkv bias
    attn_out_bias: bool = False  # gpt2 also biases the output projection
    mlp_bias: bool = False
    qk_norm: bool = False  # qwen3 per-head RMSNorm on q/k
    tied_embeddings: bool = False
    embedding_multiplier: Optional[float] = None  # gemma normalizer

    is_critic: bool = False
    moe: Optional[MoEConfig] = None

    # Numerics: params kept in param_dtype, compute in compute_dtype.
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    def __post_init__(self):
        if self.n_q_heads % self.n_kv_heads != 0:
            raise ValueError("n_q_heads must be a multiple of n_kv_heads")
        if isinstance(self.moe, dict):
            # Experiment configs arrive as plain kwargs dicts
            # (cli_args ModelTrainEvalConfig.config -> factories.py
            # TransformerConfig(**config)); coerce the nested MoE block
            # so `model.config.moe.num_experts=8` works end-to-end.
            self.moe = MoEConfig(**self.moe)

    @property
    def q_dim(self) -> int:
        return self.n_q_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    def layer_uses_moe(self, layer_idx: int) -> bool:
        return self.moe is not None and layer_idx >= self.moe.first_k_dense

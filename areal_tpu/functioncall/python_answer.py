"""PAL-style python answer execution for the offline eval harness.

Role counterpart of the reference's evaluation/python_executor.py
(GenericRuntime/PythonExecutor: run model-generated programs and take
the return value / printed output as the answer, used by the 'pal' and
'tora' prompt styles). Rebuilt on this repo's sandboxed-subprocess
machinery instead of the reference's in-process exec() + ProcessPool:
every candidate runs in a fresh subprocess under the same rlimit +
os-neutering guard the code verifier uses (code_verify.py), so a
malicious or runaway program cannot touch the evaluator process.

Contract: extract the LAST fenced code block from the model output;
if it defines `solution()`, call it and use the repr of the return
value (PAL convention); otherwise run the block and use the last
non-empty stdout line (tora convention). Returns None when there is no
code block, execution fails, or nothing is produced.
"""

from __future__ import annotations

import re
from typing import Optional

from areal_tpu.functioncall.code_verify import (
    extract_code_block,
    run_one_case,
)

_SOLUTION_DRIVER = """
if __name__ == "__main__":
    _fn = globals().get("solution")
    if _fn is not None:
        _res = _fn()
        print("\\n___PY_ANSWER___")
        print(repr(_res) if not isinstance(_res, str) else _res)
"""

_MARKER = "___PY_ANSWER___"


def _extract_candidate_code(text: str) -> Optional[str]:
    """The program to run: the last COMPLETE fenced block when one
    exists; otherwise the continuation of a fence the PROMPT opened —
    the 'pal' template ends with '```python\\n', so a compliant
    completion is bare code (optionally ending in a closing fence) with
    no opening fence of its own. Prose-only text returns None."""
    block = extract_code_block(text)
    if block is not None:
        return block
    m = re.search(r"```(?:python|py)?[ \t]*\n?", text)
    if m is not None:
        # One unterminated fence (complete blocks were handled above).
        # Opening or closing? A language tag, or nothing before it,
        # means the model OPENED a fence and was truncated — the code
        # is after. Otherwise the prompt opened the fence and this one
        # closes it — the code is before.
        tagged = text[m.start():m.end()].rstrip("\n \t") != "```"
        before = text[: m.start()]
        after = text[m.end():]
        if (tagged or not before.strip()) and after.strip():
            return after
        return before
    # No fence at all (generation hit the token budget before closing):
    # only accept it when it plausibly IS the program — a bare
    # solution() definition — never arbitrary prose.
    if "def solution" in text:
        return text
    return None


def _default_timeout() -> float:
    # Wall-time per program INCLUDING interpreter spawn; on a loaded CI
    # machine the spawn alone can take seconds, so tests raise this via
    # AREAL_PYEXEC_TIMEOUT rather than loosening the eval-time default.
    from areal_tpu.base import env_registry

    return env_registry.get_float("AREAL_PYEXEC_TIMEOUT")


def execute_python_answer(
    text: str, timeout: Optional[float] = None,
) -> Optional[str]:
    """Run the candidate program in `text` (see
    _extract_candidate_code); return its answer string or None."""
    if timeout is None:
        timeout = _default_timeout()
    code = _extract_candidate_code(text)
    if code is None:
        return None
    has_solution = "def solution" in code
    if has_solution:
        code = code + _SOLUTION_DRIVER
    ok, stdout, _err = run_one_case(code, stdin_data="", timeout=timeout)
    if not ok:
        return None
    if has_solution and _MARKER in stdout:
        tail = stdout.rsplit(_MARKER, 1)[1].strip()
        return tail.splitlines()[0].strip() if tail else None
    lines = [ln.strip() for ln in stdout.splitlines() if ln.strip()]
    return lines[-1] if lines else None


def compare_python_answer(ans: Optional[str], reference) -> bool:
    """Grade an already-executed answer with the math grader's shared
    reference-normalization rule (compare_answers), so text and python
    modes score identically-stored ground truth identically."""
    from areal_tpu.functioncall.math_grader import compare_answers

    return compare_answers(ans, reference)


def grade_python_answer(
    text: str, reference, timeout: Optional[float] = None,
) -> bool:
    """Execute the candidate program and grade its answer."""
    return compare_python_answer(
        execute_python_answer(text, timeout=timeout), reference
    )

"""PPO actor/critic algorithm interfaces.

Counterpart of realhf/impl/model/interface/ppo_interface.py
(PPOActorInterface:210, PPOCriticInterface:984): generate -> rollout
sample assembly; inference -> proximal/ref logprob recompute; train_step ->
rewards (KL penalty + clipped task score) -> GAE -> advantage
normalization (global or per-group GRPO-style) -> minibatched decoupled-PPO
updates through the engine.

Data-layout conventions (all token-aligned keys live in the *shifted*
frame used by next_token_logprobs: position t scores token t+1):
- packed_input_ids: prompt + response tokens, grouped per prompt id
- prompt_mask: 1 on prompt token positions
- packed_logprobs: behavior logprobs from generation
- logprobs: proximal logprobs recomputed at train time (decoupled PPO)
- ref_logprobs: reference-model logprobs
- values: critic values (absent in group-reward / GRPO mode)
- rewards: per-sequence task scores; seq_no_eos_mask: per-sequence
- version_start / version_end: per-sequence weight versions (staleness)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    GenerationHyperparameters,
    Model,
    ModelInterface,
    register_interface,
)
from areal_tpu.base import logging as areal_logging
from areal_tpu.base import stats_tracker
from areal_tpu.interfaces import functional as F
from areal_tpu.base import env_registry
from areal_tpu.ops.gae import packed_gae
from areal_tpu.ops.loss import masked_normalization

logger = areal_logging.getLogger("ppo")


def response_scoring_mask(segment_ids, prompt_mask):
    """[R, T] 1.0 where position t scores a response token (t+1)."""
    seg = segment_ids
    next_seg = jnp.concatenate([seg[:, 1:], jnp.zeros_like(seg[:, :1])], axis=1)
    next_pm = jnp.concatenate(
        [prompt_mask[:, 1:], jnp.ones_like(prompt_mask[:, :1])], axis=1
    )
    return ((next_seg == seg) & (seg > 0) & (next_pm == 0)).astype(jnp.float32)


def last_response_position_mask(resp_mask):
    """[R, T] 1.0 at the final scoring position of each segment."""
    nxt = jnp.concatenate([resp_mask[:, 1:], jnp.zeros_like(resp_mask[:, :1])], axis=1)
    return resp_mask * (1.0 - nxt)


@dataclasses.dataclass
class PPOActorInterface(ModelInterface):
    n_minibatches: int = 4
    # 'global' | 'dp' — per-dp-shard gradient normalization (reference
    # ppo_interface.py:253; engine implements it via loss_mask reweight).
    token_normalize_scope: str = "global"
    eps_clip: float = 0.2
    c_clip: Optional[float] = None
    kl_ctl: float = 0.1
    adaptive_kl_ctl: bool = False
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    discount: float = 1.0
    gae_lambda: float = 1.0
    max_reward_clip: float = 20.0
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    adv_norm: bool = True
    group_adv_norm: bool = False
    mask_no_eos_with_zero: bool = False
    use_decoupled_loss: bool = False
    behav_imp_weight_cap: Optional[float] = None
    temperature: float = 1.0
    # Best-of-k: sample `generation_size` responses per prompt, verify
    # them, and keep only the top `gconfig.n` (by score, longer-first on
    # ties) for training (reference ppo_interface.py:376-408).
    generation_size: Optional[int] = None
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )

    def __post_init__(self):
        if isinstance(self.gconfig, dict):
            self.gconfig = GenerationHyperparameters(**self.gconfig)
        if self.adaptive_kl_ctl:
            self.kl_controller = F.AdaptiveKLController(
                self.kl_ctl, self.adaptive_kl_target, self.adaptive_kl_horizon
            )
        else:
            self.kl_controller = F.FixedKLController(self.kl_ctl)

    # ------------------------------------------------------------------
    # Generate (sync PPO path; async uses the rollout workers instead)
    # ------------------------------------------------------------------

    def _best_of_k(
        self, model: Model, input_: SequenceSample, outs: List[Dict], k: int
    ) -> List[Dict]:
        """Sample-then-select (reference ppo_interface.py:376-408 get_score
        + topk): verify all `generation_size` candidates per prompt and
        keep the k best, scores descending with longer generations
        breaking ties. The reference looks answers up in a global id2info
        table; here they ride in the sample metadata ('solutions').
        Verification goes through verify_all (thread pool / remote batch
        verifier) — bs * generation_size gradings would crawl serially."""
        from areal_tpu.interfaces.reward import verify_all

        g = self.generation_size
        tasks = input_.metadata.get("tasks") or ["math"] * input_.bs
        answers = input_.metadata.get("solutions") or input_.metadata.get(
            "answers"
        )
        if answers is None:
            raise ValueError(
                "generation_size > gconfig.n needs 'solutions'/'answers' "
                "metadata to score candidates"
            )
        jobs = [
            (
                tasks[pi],
                model.tokenizer.decode(outs[pi * g + ci]["output_ids"]),
                answers[pi],
            )
            for pi in range(input_.bs)
            for ci in range(g)
        ]
        oks = verify_all(jobs)
        selected: List[Dict] = []
        for pi in range(input_.bs):
            cand = outs[pi * g : (pi + 1) * g]
            scored = [
                (1.0 if oks[pi * g + ci] else 0.0, len(o["output_ids"]), ci)
                for ci, o in enumerate(cand)
            ]
            scored.sort(key=lambda t: (t[0], t[1]), reverse=True)
            selected.extend(cand[ci] for _, _, ci in scored[:k])
        return selected

    def generate(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        engine = model.module
        n = self.gconfig.n
        if self.generation_size is not None and self.generation_size > n:
            gcfg = dataclasses.replace(self.gconfig, n=self.generation_size)
            outs = engine.generate(input_, mb_spec, model.tokenizer, gcfg)
            outs = self._best_of_k(model, input_, outs, n)
        else:
            outs = engine.generate(
                input_, mb_spec, model.tokenizer, self.gconfig
            )
        prompt_key = "packed_prompts" if "packed_prompts" in input_.keys else input_._main_key()
        flat_prompts = np.asarray(input_.data[prompt_key])
        plens = [sum(sl) for sl in input_.seqlens[prompt_key]]
        offsets = np.concatenate([[0], np.cumsum(plens)])

        seqs, pmask, blogp, no_eos = [], [], [], []
        group_lens: List[List[int]] = []
        for pi in range(input_.bs):
            prompt = flat_prompts[offsets[pi] : offsets[pi + 1]].astype(np.int64)
            lens = []
            for gi in range(n):
                o = outs[pi * n + gi]
                out_ids = np.asarray(o["output_ids"], np.int64)
                full = np.concatenate([prompt, out_ids])
                lens.append(len(full))
                seqs.append(full)
                pm = np.zeros(len(full), np.int64)
                pm[: len(prompt)] = 1
                pmask.append(pm)
                # Shifted frame: gen token i (abs pos len(prompt)+i) is
                # scored at abs pos len(prompt)+i-1.
                lp = np.zeros(len(full), np.float32)
                lp[len(prompt) - 1 : len(full) - 1] = o["output_logprobs"]
                blogp.append(lp)
                no_eos.append(1.0 if o["no_eos"] else 0.0)
            group_lens.append(lens)

        n_seqs_per_prompt = [[1] * n for _ in range(input_.bs)]
        res = SequenceSample(
            ids=list(input_.ids),
            keys={
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask",
            },
            data={
                "packed_input_ids": np.concatenate(seqs),
                "prompt_mask": np.concatenate(pmask),
                "packed_logprobs": np.concatenate(blogp),
                "seq_no_eos_mask": np.asarray(no_eos, np.float32),
            },
            seqlens={
                "packed_input_ids": group_lens,
                "prompt_mask": group_lens,
                "packed_logprobs": group_lens,
                "seq_no_eos_mask": n_seqs_per_prompt,
            },
            metadata={
                "version_start": [model.version] * input_.bs,
                "version_end": [model.version] * input_.bs,
            },
        )
        return res

    # ------------------------------------------------------------------
    # Inference: recompute logprobs under the current (proximal) policy
    # ------------------------------------------------------------------

    def inference(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        engine = model.module
        return engine.forward(input_, mb_spec, output_key="logprobs")

    # ------------------------------------------------------------------
    # Train
    # ------------------------------------------------------------------

    def _prep_fn(self, engine):
        if not hasattr(self, "_jit_prep"):
            # GAE impl pinned when the prep program is first built (the
            # AREAL_CE_CHUNK snapshot discipline: a mid-run retrace must
            # not silently switch kernels). 'auto' resolves per shape at
            # trace time (ops/gae.resolve_gae_impl — the associative
            # scan; the serial lax.scan stays the oracle + explicit
            # fallback, the Pallas kernel the measured opt-in).
            gae_impl = env_registry.get_str("AREAL_GAE_IMPL")

            def prep(rows, kl_coef):
                resp_mask = response_scoring_mask(
                    rows["segment_ids"], rows["prompt_mask"]
                )
                last_mask = last_response_position_mask(resp_mask)
                values = rows.get("values")
                has_critic = values is not None
                if values is None:
                    values = jnp.zeros_like(resp_mask)
                no_eos = rows["seq_no_eos_mask"]
                rewards = F.packed_rewards(
                    kl_coef=kl_coef,
                    clip_reward_value=self.max_reward_clip,
                    score=rows["rewards"] * self.reward_output_scaling
                    + self.reward_output_bias,
                    logprobs=rows["packed_logprobs"],
                    ref_logprobs=rows.get("ref_logprobs", jnp.zeros_like(resp_mask)),
                    response_mask=resp_mask,
                    last_response_mask=last_mask,
                    mask_no_eos_with_zero=self.mask_no_eos_with_zero,
                    no_eos_mask=no_eos,
                )
                # GAE runs over the *scoring* region only: restricting the
                # segment ids to scoring positions makes each segment end at
                # its last scoring position, which is exactly where the
                # bootstrap value V(s_T) must enter the recursion for
                # truncated (no-EOS) sequences.
                score_seg = rows["segment_ids"] * resp_mask.astype(
                    rows["segment_ids"].dtype
                )
                # Bootstrap for truncated (no-EOS) sequences: V(s_{T+1}),
                # the critic value at the *final token* position — one to
                # the right of the last scoring position (values are
                # token-aligned, so shift left to read position t+1 at t).
                values_next = jnp.concatenate(
                    [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1
                )
                bootstrap = (
                    values_next * last_mask * no_eos
                    if has_critic
                    else jnp.zeros_like(resp_mask)
                )
                masked_values = values * resp_mask
                adv, ret = packed_gae(
                    rewards * resp_mask,
                    masked_values,
                    score_seg,
                    bootstrap,
                    gamma=self.discount,
                    lam=self.gae_lambda,
                    impl=gae_impl,
                )
                adv = adv * resp_mask
                ret = ret * resp_mask
                kl_sum = jnp.sum(
                    (rows["packed_logprobs"] - rows.get(
                        "ref_logprobs", jnp.zeros_like(resp_mask))) * resp_mask
                )
                if self.adv_norm and not self.group_adv_norm:
                    adv = masked_normalization(adv, resp_mask)
                return adv, ret, resp_mask, kl_sum

            self._jit_prep = jax.jit(prep)
        return self._jit_prep

    def train_step(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict:
        engine = model.module
        kl_coef = self.kl_controller.value

        # 1) Whole-batch advantage computation on device.
        batch, rows = engine._build_rows(input_)
        rows_dev = engine._device_rows(rows)
        adv_rows, ret_rows, resp_rows, kl_sum = self._prep_fn(engine)(
            rows_dev, jnp.asarray(kl_coef, jnp.float32)
        )
        adv_flat = batch.gather_flat(np.asarray(adv_rows))
        ret_flat = batch.gather_flat(np.asarray(ret_rows))
        resp_flat = batch.gather_flat(np.asarray(resp_rows))

        # 2) Optional group normalization (GRPO): per prompt-group over
        #    response positions.
        if self.adv_norm and self.group_adv_norm:
            adv_flat = adv_flat.copy()
            offset = 0
            for sl in input_.seqlens["packed_input_ids"]:
                glen = sum(sl)
                idx = np.arange(offset, offset + glen)[resp_flat[offset : offset + glen] > 0]
                if idx.size > 1:
                    vals = adv_flat[idx]
                    adv_flat[idx] = (vals - vals.mean()) / (vals.std() + 1e-5)
                offset += glen
        train_sample = input_
        train_sample.update_(
            SequenceSample(
                ids=list(input_.ids),
                keys={"advantages"},
                data={"advantages": adv_flat.astype(np.float32)},
                seqlens={
                    "advantages": [list(sl) for sl in input_.seqlens["packed_input_ids"]]
                },
            )
        )

        # 3) Minibatched PPO updates.
        mb_inputs, *_ = train_sample.split(
            MicroBatchSpec(n_mbs=self.n_minibatches)
        )
        use_decoupled = self.use_decoupled_loss and "logprobs" in train_sample.keys

        def actor_loss(lp, rows):
            # `lp` is the fused next-token logprobs [R, T] computed by the
            # engine (logits never materialized).
            mask = response_scoring_mask(rows["segment_ids"], rows["prompt_mask"])
            # Engine-injected per-shard normalization scale applies to the
            # LOSS weighting only (monitoring stats keep the raw mask).
            loss_w = (
                mask * rows["dp_loss_scale"] if "dp_loss_scale" in rows else mask
            )
            prox = rows["logprobs"] if use_decoupled else None
            loss_sum, st = F.actor_loss_fn(
                logprobs=lp,
                old_logprobs=rows["packed_logprobs"],
                advantages=rows["advantages"],
                eps_clip=self.eps_clip,
                loss_mask=loss_w,
                c_clip=self.c_clip,
                proximal_logprobs=prox,
                behav_imp_weight_cap=self.behav_imp_weight_cap if use_decoupled else None,
                stats_mask=mask,
            )
            # Approx KL(new || behavior) for monitoring.
            st["approx_kl"] = jnp.sum((rows["packed_logprobs"] - lp) * mask)
            return loss_sum, st

        def weight_fn(mb):
            return _n_response_tokens(mb)

        all_stats = []
        for mb in mb_inputs:
            st = engine.train_batch(
                mb, MicroBatchSpec(n_mbs=1, max_tokens_per_mb=mb_spec.max_tokens_per_mb),
                loss_fn=actor_loss, loss_weight_fn=weight_fn,
                token_normalize_scope=self.token_normalize_scope,
                version_steps=model.version, loss_name="ppo_actor",
            )
            all_stats.append(st)
        model.inc_version()

        n_resp = float(np.sum(resp_flat))
        mean_kl = float(kl_sum) / max(n_resp, 1.0)
        self.kl_controller.update(mean_kl, int(n_resp))

        agg = {k: float(np.mean([s[k] for s in all_stats])) for k in all_stats[0]}
        agg.update(
            {
                "ppo_actor/kl": mean_kl,
                "ppo_actor/kl_coef": kl_coef,
                "ppo_actor/adv_mean": float(
                    np.sum(adv_flat * resp_flat) / max(n_resp, 1.0)
                ),
                "ppo_actor/ret_mean": float(
                    np.sum(ret_flat * resp_flat) / max(n_resp, 1.0)
                ),
                "ppo_actor/reward_mean": float(np.mean(input_.data["rewards"]))
                if input_.data.get("rewards") is not None else 0.0,
                "ppo_actor/n_tokens": float(batch.total_tokens),
            }
        )
        # Staleness accounting (reference: ppo_interface.py:752-762).
        vs = input_.metadata.get("version_start")
        ve = input_.metadata.get("version_end")
        if vs:
            agg["ppo_actor/head_offpolicyness"] = float(model.version - 1 - np.min(vs))
            agg["ppo_actor/tail_offpolicyness"] = float(model.version - 1 - np.max(ve))
        stats_tracker.scalar(**agg)
        return agg

    def save(self, model: Model, save_dir: str):
        from areal_tpu.interfaces.sft import SFTInterface

        SFTInterface.save(self, model, save_dir)  # same HF export path


def _n_response_tokens(mb: SequenceSample) -> float:
    pm = np.asarray(mb.data["prompt_mask"])
    total, offset = 0, 0
    for sl in mb.seqlens["prompt_mask"]:
        for l in sl:
            total += int(np.sum(pm[offset + 1 : offset + l] == 0))
            offset += l
    return float(total)


@dataclasses.dataclass
class PPOCriticInterface(ModelInterface):
    n_minibatches: int = 4
    token_normalize_scope: str = "global"
    value_eps_clip: float = 0.2
    kl_ctl: float = 0.1
    adaptive_kl_ctl: bool = False
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    discount: float = 1.0
    gae_lambda: float = 1.0
    max_reward_clip: float = 20.0
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    value_norm: bool = True
    mask_no_eos_with_zero: bool = False

    def __post_init__(self):
        self.rms = F.RunningMeanStd()
        # Mirrors the actor's controller so returns use the same (possibly
        # drifting) KL coefficient: both controllers see the same per-step
        # observed KL and so stay in lockstep (reference keeps separate but
        # identically-updated adapters on actor and critic interfaces).
        if self.adaptive_kl_ctl:
            self.kl_controller = F.AdaptiveKLController(
                self.kl_ctl, self.adaptive_kl_target, self.adaptive_kl_horizon
            )
        else:
            self.kl_controller = F.FixedKLController(self.kl_ctl)
        # Returns must be computed with the SAME reward transform as the
        # actor's advantages; the helper is cached so its jitted prep
        # program survives across train steps.
        self._helper = PPOActorInterface(
            discount=self.discount, gae_lambda=self.gae_lambda,
            kl_ctl=self.kl_ctl, max_reward_clip=self.max_reward_clip,
            reward_output_scaling=self.reward_output_scaling,
            reward_output_bias=self.reward_output_bias,
            adv_norm=False, mask_no_eos_with_zero=self.mask_no_eos_with_zero,
        )

    def inference(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        engine = model.module
        out = engine.forward(input_, mb_spec, output_key="values", output="values")
        if self.value_norm:
            out.data["values"] = self.rms.denormalize(out.data["values"])
        return out

    def train_step(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict:
        engine = model.module
        # Returns are recomputed exactly like the actor does.
        batch, rows = engine._build_rows(input_)
        rows_dev = engine._device_rows(rows)
        _, ret_rows, resp_rows, kl_sum = self._helper._prep_fn(engine)(
            rows_dev, jnp.asarray(self.kl_controller.value, jnp.float32)
        )
        ret_flat = batch.gather_flat(np.asarray(ret_rows))
        resp_flat = batch.gather_flat(np.asarray(resp_rows))
        if self.value_norm:
            self.rms.update(ret_flat, mask=resp_flat > 0)
            norm_ret = np.where(resp_flat > 0, self.rms.normalize(ret_flat), 0.0)
            old_values = np.where(
                resp_flat > 0,
                self.rms.normalize(np.asarray(input_.data["values"])),
                0.0,
            )
        else:
            norm_ret = ret_flat
            old_values = np.asarray(input_.data["values"])

        sl = [list(s) for s in input_.seqlens["packed_input_ids"]]
        input_.update_(
            SequenceSample(
                ids=list(input_.ids), keys={"returns", "old_values_norm"},
                data={
                    "returns": norm_ret.astype(np.float32),
                    "old_values_norm": old_values.astype(np.float32),
                },
                seqlens={"returns": sl, "old_values_norm": sl},
            )
        )

        def critic_loss(values, rows):
            mask = response_scoring_mask(rows["segment_ids"], rows["prompt_mask"])
            loss_w = (
                mask * rows["dp_loss_scale"] if "dp_loss_scale" in rows else mask
            )
            loss_sum, st = F.critic_loss_fn(
                value=values,
                old_value=rows["old_values_norm"],
                target_value=rows["returns"],
                value_eps_clip=self.value_eps_clip,
                loss_mask=loss_w,
                stats_mask=mask,
            )
            return loss_sum, st

        mb_inputs, *_ = input_.split(MicroBatchSpec(n_mbs=self.n_minibatches))
        all_stats = []
        for mb in mb_inputs:
            st = engine.train_batch(
                mb, MicroBatchSpec(n_mbs=1, max_tokens_per_mb=mb_spec.max_tokens_per_mb),
                loss_fn=critic_loss, loss_weight_fn=_n_response_tokens,
                token_normalize_scope=self.token_normalize_scope,
                version_steps=model.version, loss_name="ppo_critic",
            )
            all_stats.append(st)
        model.inc_version()
        n_resp = float(np.sum(resp_flat))
        self.kl_controller.update(float(kl_sum) / max(n_resp, 1.0), int(n_resp))
        agg = {k: float(np.mean([s[k] for s in all_stats])) for k in all_stats[0]}
        stats_tracker.scalar(**agg)
        return agg


register_interface("ppo_actor", PPOActorInterface)
register_interface("ppo_critic", PPOCriticInterface)

"""Multi-turn tool-use agent (docs/agentic.md).

Each episode is a conversation: the model generates a turn; if the turn
contains a tool call (``<tool:python>code</tool>``, calculator, search)
the ToolEnv runs it and the tool's output text is spliced into the
conversation before the next turn; a turn without a tool call is the
final answer and grades through the same verifiers as the math agents.

Every turn after the first is a SESSION CONTINUATION through the
partial-rollout client: the same qid re-enters the fleet at priority 0
on the manager's sticky-affinity route, and only the turn delta (tool
output tokens) is accounted as re-prefill — the agentic_rollout bench
quantifies that against a session-blind full-re-prefill baseline.

Tiny-model harnesses (e2e tests, the CPU-proxy bench) can't make a
random model emit tool syntax, so ``scripted_tool_turns`` forces a
deterministic tool-call script for the first N turns — the system under
test is the episode plumbing (turn loop, executor pool, continuation
accounting, staleness tags), not the model's tool-calling ability.
"""

from __future__ import annotations

import asyncio
import json
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from areal_tpu.api.agent_api import Agent, register_agent
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.env_api import EnvironmentService
from areal_tpu.api.model_api import (
    BundledGenerationOutputs,
    GenerationHyperparameters,
)
from areal_tpu.base import logging, tracing

logger = logging.getLogger("tool_use_agent")

_TOOL_RE = re.compile(r"<tool:(\w+)>(.*?)</tool>", re.DOTALL)

# Raw (non-JSON) tool bodies map onto each tool's primary argument.
_BODY_KEY = {"python": "code", "calculator": "expr", "search": "query"}

# The deterministic script harnesses cycle through (one call per turn).
_DEFAULT_SCRIPT: List[Tuple[str, Dict[str, Any]]] = [
    ("python", {"code": "print(6 * 7)"}),
    ("calculator", {"expr": "6 * 7"}),
    ("search", {"query": "answer"}),
]


def parse_tool_call(text: str) -> Optional[Tuple[str, Dict[str, Any]]]:
    """First ``<tool:name>body</tool>`` in the text, as (name, payload).
    A JSON-object body is the payload verbatim; anything else becomes
    the tool's primary argument. None when the text calls no tool."""
    m = _TOOL_RE.search(text)
    if not m:
        return None
    name, body = m.group(1), m.group(2).strip()
    if body.startswith("{"):
        try:
            payload = json.loads(body)
            if isinstance(payload, dict):
                return name, payload
        except ValueError:
            pass
    return name, {_BODY_KEY.get(name, "input"): body}


class ToolUseAgent(Agent):
    def __init__(
        self,
        gconfig: Optional[GenerationHyperparameters] = None,
        tokenizer: Any = None,
        num_turns: int = 4,
        turn_level_discount: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
        correct_reward: float = 1.0,
        wrong_reward: float = -1.0,
        scripted_tool_turns: int = 0,
        task_tag: str = "agentic",
        **gconfig_kwargs,
    ):
        if gconfig is None:
            gconfig = GenerationHyperparameters(**gconfig_kwargs)
        elif isinstance(gconfig, dict):
            gconfig = GenerationHyperparameters(**gconfig)
        # One sequence per turn; grouping happens across episodes.
        self.gconfig = gconfig.new(n=1)
        self.tokenizer = tokenizer
        self.num_turns = max(1, num_turns)
        self.turn_level_discount = turn_level_discount
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias
        self.correct_reward = correct_reward
        self.wrong_reward = wrong_reward
        self.scripted_tool_turns = min(
            scripted_tool_turns, self.num_turns - 1
        )
        self.task_tag = task_tag

    def _encode(self, text: str) -> List[int]:
        return self.tokenizer(
            "\n" + text + "\n", add_special_tokens=False
        )["input_ids"]

    def _tool_call_for_turn(
        self, turn: int, text: str
    ) -> Optional[Tuple[str, Dict[str, Any]]]:
        if turn < self.scripted_tool_turns:
            return _DEFAULT_SCRIPT[turn % len(_DEFAULT_SCRIPT)]
        if turn >= self.num_turns - 1:
            return None  # last turn must answer
        return parse_tool_call(text)

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        await env.reset()
        assert prompt.bs == 1
        qid = prompt.ids[0]
        token_ids = np.asarray(prompt.data["packed_prompts"]).tolist()
        task = (prompt.metadata.get("tasks") or ["math"])[0]
        answer_info = (prompt.metadata.get("solutions") or [None])[0]

        turn_seqs: List[List[int]] = []
        turn_lps: List[np.ndarray] = []
        turn_prompt_lens: List[int] = []
        turn_no_eos: List[bool] = []
        turn_rewards: List[float] = []
        v_start: List[int] = []
        v_end: List[int] = []
        n_tool_calls = 0
        success = False

        for turn in range(self.num_turns):
            with tracing.span(
                "agent.turn", qid=str(qid), turn=turn, task=self.task_tag
            ):
                await obs_queue.put((qid, token_ids, self.gconfig))
                bundle: BundledGenerationOutputs = await act_queue.get()
            seq = list(bundle.seqs[0])
            plen = bundle.prompt_len
            text = self.tokenizer.decode(seq[plen:])

            turn_seqs.append(seq)
            turn_lps.append(np.asarray(bundle.logprobs[0], np.float32))
            turn_prompt_lens.append(plen)
            turn_no_eos.append(bool(bundle.no_eos[0]))
            v_start.append(min(bundle.version_start))
            v_end.append(max(bundle.version_end))

            call = self._tool_call_for_turn(turn, text)
            if call is not None:
                name, payload = call
                with tracing.span(
                    "tool.call", qid=str(qid), tool=name, turn=turn
                ):
                    obs_text, *_ = await env.step(
                        ("tool", str(qid), name, payload)
                    )
                n_tool_calls += 1
                turn_rewards.append(0.0)
                token_ids = seq + self._encode(
                    f"<tool_output>{obs_text}</tool_output>"
                )
                continue

            ok_list, *_ = await env.step(
                ("answer", str(qid), [text], task, answer_info)
            )
            success = bool(ok_list[0])
            turn_rewards.append(
                (self.correct_reward if success else self.wrong_reward)
                * self.reward_scaling
                + self.reward_bias
            )
            break

        # Tool turns earn their keep through the discounted return of
        # the final graded answer (math_multi_turn's reference scheme).
        for i in reversed(range(len(turn_rewards) - 1)):
            turn_rewards[i] += self.turn_level_discount * turn_rewards[i + 1]

        n = len(turn_seqs)
        seq_lens = [len(s) for s in turn_seqs]
        pmask = np.concatenate(
            [
                np.concatenate(
                    [np.ones(p, np.int64), np.zeros(l - p, np.int64)]
                )
                for l, p in zip(seq_lens, turn_prompt_lens)
            ]
        )
        shifted_lps = []
        for seq, lp, plen in zip(turn_seqs, turn_lps, turn_prompt_lens):
            out_lp = np.asarray(lp[plen:], np.float32)
            full = np.zeros(len(seq), np.float32)
            full[plen - 1 : len(seq) - 1] = out_lp
            shifted_lps.append(full)

        sample = SequenceSample(
            ids=[qid],
            keys={
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask", "rewards",
            },
            data={
                "packed_input_ids": np.concatenate(
                    [np.asarray(s, np.int32) for s in turn_seqs]
                ),
                "prompt_mask": pmask,
                "packed_logprobs": np.concatenate(shifted_lps),
                "seq_no_eos_mask": np.asarray(
                    [1.0 if x else 0.0 for x in turn_no_eos], np.float32
                ),
                "rewards": np.asarray(turn_rewards, np.float32),
            },
            seqlens={
                "packed_input_ids": [seq_lens],
                "prompt_mask": [seq_lens],
                "packed_logprobs": [seq_lens],
                "seq_no_eos_mask": [[1] * n],
                "rewards": [[1] * n],
            },
            metadata={
                "version_start": [min(v_start)],
                "version_end": [max(v_end)],
                "scores": [1.0 if success else 0.0],
                "birth_time": [0],
                # Agentic trajectories ride the LOOSE per-task staleness
                # window; the master's per-task scalars key off this.
                "task": [self.task_tag],
                "turns": [n],
                "tool_calls": [n_tool_calls],
            },
        )
        return [sample]


register_agent("tool-use", ToolUseAgent)

"""ISSUE 7 acceptance (bench leg): the `serving_disagg` phase banks an
attested CPU-proxy record whose unified-vs-1P+1D A/B shows decode ITL
p99 in the disaggregated fleet at or below the unified fleet's under
the same mixed long-prefill/short-decode open-loop load, with the KV
handoff really crossing process boundaries — and `validate_bench.py`
accepts the record (and rejects a record missing either arm).

Time budget: ~100 s (two 2-subprocess fleets run sequentially; warm
XLA cache).
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow  # ~42s A/B over two real-process fleets; moved out of
# the tier-1 budget in PR 9 (wall clock was brushing 870s). Coverage in
# tier-1: disagg pairing/rerole (test_disagg_rerole, ~4s), handoff
# engine parity (test_kv_handoff), and the phase still runs via
# `bench.py --phases serving_disagg` + the slow lane.
@pytest.mark.timeout(420)
def test_disagg_ab_banks_itl_win_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    # Trimmed interference script (defaults are sized for bench runs):
    # 3 decode streams alive through 3 long-prompt injections.
    monkeypatch.setenv("AREAL_DISAGG_STREAM_TOKENS", "200")
    monkeypatch.setenv("AREAL_DISAGG_N_LONG", "3")
    monkeypatch.setenv("AREAL_DISAGG_LONG_GAP_S", "0.7")
    from areal_tpu.bench.workloads import serving_disagg_phase

    val = serving_disagg_phase("measure")
    path = bank.write_record(
        bank.make_record("serving_disagg", "measure", "ok", value=val), b
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("serving_disagg", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # Zero failed rollouts in either arm; the handoff really ran (KV
    # crossed the process boundary, hash-verified, no local fallbacks).
    assert v["unified_failed"] == 0 and v["disagg_failed"] == 0
    assert v["kv_handoffs"] >= 3
    assert v["kv_handoff_bytes"] > 0
    assert v["kv_handoff_fallbacks"] == 0
    # THE acceptance number: the disaggregated fleet's decode ITL p99
    # never exceeds the unified fleet's under the same scripted load —
    # long prefills no longer steal decode batch slots.
    assert v["disagg_itl_p99_ms"] <= v["unified_itl_p99_ms"], v

    # The validator refuses a record missing either arm of the pair...
    for missing in ("unified_itl_p99_ms", "disagg_itl_p99_ms"):
        bad = json.loads(json.dumps(rec))
        del bad["value"][missing]
        assert any(
            missing in p
            for p in validator.validate_phase_value("serving_disagg", bad)
        )
    # ...and one whose disaggregated arm lost requests.
    lossy = json.loads(json.dumps(rec))
    lossy["value"]["disagg_failed"] = 2.0
    assert any(
        "loss-free" in p
        for p in validator.validate_phase_value("serving_disagg", lossy)
    )

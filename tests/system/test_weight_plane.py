"""Streaming weight-distribution plane, in-process (ISSUE 5 tentpole):
origin serving + chunk-hash verification, Range resume of torn
connections, peer-fanout planning, the O(1)-origin-egress invariant on
a chain fanout, and re-fanout from a surviving PEER (not the origin)
when a holder dies mid-chain. Multi-process acceptance lives in
test_weight_plane_e2e.py."""

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.base.chunking import chunk_spans, hash_chunk
from areal_tpu.base.wire_schemas import WEIGHT_CHUNKS_V1
from areal_tpu.base.fault_injection import faults
from areal_tpu.engine.weight_client import (
    ChunkStore,
    WeightFetchError,
    assemble_params,
    fetch_manifest,
)
from areal_tpu.system.weight_plane import (
    PeerStoreServer,
    WeightPlaneSource,
    _PlaneHTTP,
    chunk_manifest_for_dump,
    distribute_to_stores,
    fanout_edges,
    parse_range_start,
    plan_fanout,
)
from areal_tpu.system.weight_transfer import dump_raw_params


def _params(seed=0):
    import ml_dtypes

    rng = np.random.default_rng(seed)
    return {
        "emb": {"w": rng.standard_normal((64, 32)).astype(np.float32)},
        "l0": {
            "wq": rng.standard_normal((4, 32, 32)).astype(ml_dtypes.bfloat16)
        },
    }


def _assert_tree_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    for k in a:
        if isinstance(a[k], dict):
            _assert_tree_equal(a[k], b[k])
        else:
            assert a[k].dtype == b[k].dtype
            np.testing.assert_array_equal(
                np.asarray(a[k], np.float32), np.asarray(b[k], np.float32)
            )


@pytest.fixture
def clean_faults():
    faults.reset()
    yield
    faults.reset()


# ----------------------------------------------------------------------
# Fanout planning
# ----------------------------------------------------------------------


def test_plan_fanout_degree_bounds():
    origin = "http://o"
    servers = [f"http://s{i}" for i in range(7)]
    waves = plan_fanout(origin, servers, degree=2)
    edges = fanout_edges(waves)
    # Every server appears exactly once.
    assert sorted(u for u, _ in edges) == sorted(servers)
    # The origin uploads to at most `degree` children; every peer parent
    # has at most `degree` children too.
    children = {}
    for u, p in edges:
        children.setdefault(p, []).append(u)
    assert len(children[origin]) == 2
    assert all(len(c) <= 2 for c in children.values())
    # A parent always completes in an earlier wave than its children.
    wave_of = {u: i for i, w in enumerate(waves) for u, _ in w}
    for u, p in edges:
        if p != origin:
            assert wave_of[p] < wave_of[u]


def test_plan_fanout_rejects_bad_degree():
    with pytest.raises(ValueError, match="degree"):
        plan_fanout("http://o", ["http://s0"], degree=0)


# ----------------------------------------------------------------------
# Origin serving + client fetch
# ----------------------------------------------------------------------


def test_manifest_merges_dump_and_chunk_index(tmp_path):
    d = str(tmp_path / "dump")
    assert chunk_manifest_for_dump(d) is None  # no dump yet
    dump_raw_params(_params(), d, version=5)
    man = chunk_manifest_for_dump(d, chunk_bytes=1 << 12)
    assert man["version"] == 5
    assert man["n_chunks"] == len(man["hashes"]) > 1
    assert {e["path"] for e in man["leaves"]} == {"emb/w", "l0/wq"}


def test_manifest_uses_dump_time_sidecar(tmp_path, monkeypatch):
    """A dump whose sidecar matches the plane's chunk size must serve the
    precomputed index — no full bin re-read — while a mismatched chunk
    size falls back to hashing and yields the same content hashes."""
    import areal_tpu.system.weight_plane as wp

    d = str(tmp_path / "dump")
    dump_raw_params(_params(), d, version=3, chunk_bytes=1 << 12)
    baseline = chunk_manifest_for_dump(d, chunk_bytes=1 << 12)

    def _boom(*a, **k):
        raise AssertionError("sidecar fast path should not hash the bin")

    monkeypatch.setattr(wp, "build_chunk_index", _boom)
    man = chunk_manifest_for_dump(d, chunk_bytes=1 << 12)
    assert man["version"] == 3 and man["hashes"] == baseline["hashes"]
    # Mismatched chunk size: sidecar ignored, rebuild path taken.
    with pytest.raises(AssertionError, match="fast path"):
        chunk_manifest_for_dump(d, chunk_bytes=1 << 13)
    monkeypatch.undo()
    rebuilt = chunk_manifest_for_dump(d, chunk_bytes=1 << 13)
    assert rebuilt["total_bytes"] == man["total_bytes"]
    assert rebuilt["n_chunks"] != man["n_chunks"]


def test_fetch_verify_assemble_roundtrip(tmp_path):
    d = str(tmp_path / "dump")
    p = _params(1)
    dump_raw_params(p, d, version=2)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    try:
        # Pinned to a version the source doesn't hold: 404s.
        with pytest.raises(Exception):
            fetch_manifest(src.address, version=9)
        man = fetch_manifest(src.address, version=2)
        store = ChunkStore(man)
        stats = store.fetch([src.address], origin=src.address)
        assert store.complete()
        assert stats["bytes_from_origin"] == man["total_bytes"]
        assert stats["bytes_from_peers"] == 0
        got, v = assemble_params(store)
        assert v == 2
        _assert_tree_equal(p, got)
        # The origin counted exactly one full payload of egress.
        assert src.stats()["full_payload_equivalents"][2] == pytest.approx(1.0)
    finally:
        src.close()


def test_unpinned_manifest_tracks_newer_dump(tmp_path):
    """An unpinned /weights/manifest must re-check the dump dir: the
    cached manifest lagging a newer dump would hand out a version whose
    bin may already be GC'd."""
    d = str(tmp_path / "dump")
    dump_raw_params(_params(7), d, version=1)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    try:
        assert fetch_manifest(src.address)["version"] == 1  # cache warm
        dump_raw_params(_params(8), d, version=2)
        assert fetch_manifest(src.address)["version"] == 2
        # Pinned requests still pin.
        assert fetch_manifest(src.address, version=2)["version"] == 2
    finally:
        src.close()


def test_corrupt_peer_rejected_by_content_hash(tmp_path):
    """A peer serving tampered bytes fails per-chunk verification; the
    client falls through to the next upstream — the hash, not the peer,
    is the authority."""
    d = str(tmp_path / "dump")
    p = _params(2)
    dump_raw_params(p, d, version=1)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    peer = PeerStoreServer().start()
    try:
        man = fetch_manifest(src.address, version=1)
        peer.store = ChunkStore(man)
        peer.store.fetch([src.address], origin=src.address)
        # Tamper every byte the peer would serve (manifest hashes stay
        # the honest ones).
        for i in range(len(peer.store.buf)):
            peer.store.buf[i] ^= 0xFF
        src.chunks_served.clear()
        src.bytes_served.clear()

        store = ChunkStore(man)
        stats = store.fetch([peer.address, src.address], origin=src.address)
        assert store.complete()
        assert stats["bytes_from_origin"] == man["total_bytes"]
        got, _ = assemble_params(store)
        _assert_tree_equal(p, got)
    finally:
        peer.close()
        src.close()


class _TruncatingSource(_PlaneHTTP):
    """Serves each chunk torn in half on first contact, honoring Range
    on the retry — a flaky network link."""

    def __init__(self, manifest, payload: bytes):
        super().__init__()
        self.man, self.payload = manifest, payload
        self._seen = set()

    def routes(self, app):
        app.router.add_get("/weights/manifest", self._h_man)
        app.router.add_get("/weights/chunk", self._h_chunk)

    async def _h_man(self, request):
        return web.json_response(self.man)

    async def _h_chunk(self, request):
        idx = int(request.query["idx"])
        off, length = chunk_spans(
            self.man["total_bytes"], self.man["chunk_bytes"]
        )[idx]
        data = self.payload[off:off + length]
        start = parse_range_start(request)
        body = data[start:]
        if idx not in self._seen:
            self._seen.add(idx)
            body = body[: max(1, len(body) // 2)]  # torn connection
        return web.Response(
            body=bytes(body), status=206 if start else 200,
            content_type="application/octet-stream",
        )


def test_torn_chunk_resumes_with_range():
    payload = bytes(range(256)) * 64  # 16 KiB
    chunk_bytes = 1 << 12
    spans = chunk_spans(len(payload), chunk_bytes)
    man = {
        "schema": WEIGHT_CHUNKS_V1,
        "version": 1,
        "chunk_bytes": chunk_bytes,
        "total_bytes": len(payload),
        "n_chunks": len(spans),
        "hashes": [hash_chunk(payload[o:o + n]) for o, n in spans],
    }
    src = _TruncatingSource(man, payload).start()
    try:
        store = ChunkStore(man)
        stats = store.fetch([src.address])
        assert store.complete()
        assert bytes(store.buf) == payload
        # Every chunk was torn once and resumed mid-chunk, not refetched
        # from scratch.
        assert stats["resumed_chunks"] == len(spans)
    finally:
        src.close()


def test_fetch_fails_loudly_without_upstreams(tmp_path):
    d = str(tmp_path / "dump")
    dump_raw_params(_params(), d, version=1)
    man = chunk_manifest_for_dump(d, chunk_bytes=1 << 12)
    with pytest.raises(WeightFetchError, match="no upstreams"):
        ChunkStore(man).fetch([])
    # All-dead upstreams: a WeightFetchError naming the chunk, not a
    # silent partial store.
    with pytest.raises(WeightFetchError, match="unavailable"):
        ChunkStore(man).fetch(["http://127.0.0.1:9"], timeout=0.2)


# ----------------------------------------------------------------------
# Fanout over live holders
# ----------------------------------------------------------------------


def test_chain_fanout_costs_origin_one_payload(tmp_path):
    d = str(tmp_path / "dump")
    p = _params(3)
    dump_raw_params(p, d, version=4)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    holders = []
    try:
        holders, stats = distribute_to_stores(
            src.address, 3, degree=1, version=4
        )
        # The acceptance invariant: each byte leaves the origin ONCE;
        # wave 1+ holders are fed entirely by peers.
        assert src.stats()["full_payload_equivalents"][4] == pytest.approx(1.0)
        per = stats["per_holder"]
        origin_feeds = [
            s for s in per.values() if s["bytes_from_origin"] > 0
        ]
        assert len(origin_feeds) == 1
        assert sum(s["bytes_from_peers"] for s in per.values()) == (
            2 * stats["total_bytes"]
        )
        for h in holders:
            got, v = assemble_params(h.store)
            assert v == 4
            _assert_tree_equal(p, got)
    finally:
        for h in holders:
            h.close()
        src.close()


def test_dead_mid_chain_peer_refanouts_from_surviving_peer(
    tmp_path, clean_faults
):
    """Chaos: the middle holder of a 3-chain fails serving mid-transfer.
    Its child must re-fanout from the SURVIVING peer (wave-0 holder),
    not the origin — origin egress stays exactly one payload."""
    d = str(tmp_path / "dump")
    p = _params(4)
    dump_raw_params(p, d, version=1)
    chunk_bytes = 1 << 12
    src = WeightPlaneSource(d, chunk_bytes=chunk_bytes).start()
    man = chunk_manifest_for_dump(d, chunk_bytes=chunk_bytes)
    n_chunks = man["n_chunks"]
    assert n_chunks >= 3, "payload too small for a mid-transfer kill"
    # Shared hit counter across every /weights/chunk handler in this
    # process, waves strictly ordered: hits [1..n] = origin -> h0,
    # [n+1..2n] = h0 -> h1, [2n+1..3n] = h1 -> h2. Fire all 3 retry
    # attempts of h2's SECOND chunk from h1 — a peer dying mid-serve.
    faults.arm(
        "weight_plane.serve_chunk", action="raise",
        at_hit=2 * n_chunks + 2, times=3,
    )
    holders = []
    try:
        holders, stats = distribute_to_stores(
            src.address, 3, degree=1, version=1
        )
        assert src.stats()["full_payload_equivalents"][1] == pytest.approx(1.0)
        h2_stats = stats["per_holder"][holders[2].address]
        # h2 got chunk 0 from its parent (h1), then re-fanned the rest
        # from the surviving wave-0 holder — zero origin bytes.
        assert h2_stats["bytes_from_origin"] == 0
        assert set(h2_stats["bytes_from"]) == {
            holders[0].address, holders[1].address
        }
        got, _ = assemble_params(holders[2].store)
        _assert_tree_equal(p, got)
    finally:
        for h in holders:
            h.close()
        src.close()


# ----------------------------------------------------------------------
# /distribute_weights handler semantics (duplicate + supersede)
# ----------------------------------------------------------------------


class _SlowSource(WeightPlaneSource):
    """Origin that sleeps per chunk, holding a fetch in flight long
    enough for a duplicate/superseding request to land mid-transfer."""

    def __init__(self, dump_dir, delay: float, **kw):
        super().__init__(dump_dir, **kw)
        self._delay = delay

    async def _h_chunk(self, request):
        import asyncio

        await asyncio.sleep(self._delay)
        return await super()._h_chunk(request)


class _DistributeHarness(_PlaneHTTP):
    """A real GenerationServer's /distribute_weights handler mounted on
    a bare HTTP server — the prefetch state machine without the engine
    (cutover paths are covered by test_weight_plane_e2e.py). ``shard``
    = (rank, degree) makes it a shard-configured 'fake-device server':
    it accepts exactly its slice's chunk stream and serves it to
    same-shard siblings over the mounted /weights peer hop."""

    def __init__(self, shard=None):
        super().__init__()
        import threading
        import types

        from areal_tpu.system.generation_server import GenerationServer

        srv = object.__new__(GenerationServer)
        srv._wp_lock = threading.Lock()
        srv._wp_store = None
        srv._wp_state = "idle"
        srv._wp_transfer_ms = 0.0
        srv._wp_verify_ms = 0.0
        srv._wp_cutover_ms = 0.0
        srv._wp_bytes_from_origin = 0
        srv._wp_bytes_from_peers = 0
        srv._wp_chunks_served = 0
        srv._wp_bytes_served = 0
        srv._wp_expected_bytes = 0
        srv._wp_ingress_eq = 0.0
        srv._wp_wire = "raw"
        srv._weight_shard = shard
        srv.engine = types.SimpleNamespace(version=0, n_running=0)
        self.srv = srv

    def routes(self, app):
        app.router.add_post(
            "/distribute_weights", self.srv._h_distribute_weights
        )
        app.router.add_get(
            "/weights/manifest", self.srv._h_weights_manifest
        )
        app.router.add_get("/weights/chunk", self.srv._h_weights_chunk)


def _post_json(url, payload, timeout=60.0):
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url,
        data=_json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read()), r.status
    except urllib.error.HTTPError as e:
        return _json.loads(e.read()), e.code


def _wait_for(cond, timeout=10.0, interval=0.005):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_duplicate_distribute_joins_inflight_fetch(tmp_path):
    """A manager retry while the fetch is IN FLIGHT must join it, not
    replace the store: a restart discards every verified chunk (doubling
    origin egress) and a transfer slower than the manager's wave timeout
    could then never complete at all."""
    import threading

    d = str(tmp_path / "dump")
    dump_raw_params(_params(5), d, version=1)
    src = _SlowSource(d, delay=0.25, chunk_bytes=1 << 12).start()
    harness = _DistributeHarness().start()
    try:
        man = fetch_manifest(src.address, version=1)
        assert man["n_chunks"] >= 3
        body = {
            "version": 1,
            "manifest": man,
            "upstreams": [src.address],
            "origin": src.address,
        }
        first = {}

        def _first():
            first["resp"], first["status"] = _post_json(
                f"{harness.address}/distribute_weights", body
            )

        t = threading.Thread(target=_first)
        t.start()
        assert _wait_for(lambda: harness.srv._wp_state == "fetching")
        store_inflight = harness.srv._wp_store
        dup, status = _post_json(
            f"{harness.address}/distribute_weights", body
        )
        t.join(timeout=60)
        assert first["status"] == 200 and first["resp"]["success"]
        assert status == 200 and dup["success"] and dup["joined"]
        assert harness.srv._wp_state == "ready"
        # The duplicate joined the SAME store — origin egress stayed at
        # exactly one payload (a restart would have re-pulled chunks).
        assert harness.srv._wp_store is store_inflight
        assert src.stats()["full_payload_equivalents"][1] == pytest.approx(1.0)
    finally:
        harness.close()
        src.close()


def test_superseded_fetch_does_not_clobber_stats(tmp_path):
    """A NEWER /distribute_weights replaces an in-flight fetch; when the
    superseded fetch eventually finishes it must not flip the state or
    overwrite the live version's transfer numbers on /metrics."""
    import threading

    d1, d2 = str(tmp_path / "v1"), str(tmp_path / "v2")
    dump_raw_params(_params(6), d1, version=1)
    # v2's payload has a different size so a stats clobber is detectable.
    p2 = {"only": {"w": np.arange(512, dtype=np.float32)}}
    dump_raw_params(p2, d2, version=2)
    slow = _SlowSource(d1, delay=0.3, chunk_bytes=1 << 12).start()
    fast = WeightPlaneSource(d2, chunk_bytes=1 << 12).start()
    harness = _DistributeHarness().start()
    try:
        man1 = fetch_manifest(slow.address, version=1)
        man2 = fetch_manifest(fast.address, version=2)
        assert man1["total_bytes"] != man2["total_bytes"]
        first = {}

        def _first():
            first["resp"], first["status"] = _post_json(
                f"{harness.address}/distribute_weights",
                {"version": 1, "manifest": man1,
                 "upstreams": [slow.address], "origin": slow.address},
            )

        t = threading.Thread(target=_first)
        t.start()
        assert _wait_for(lambda: harness.srv._wp_state == "fetching")
        newer, status = _post_json(
            f"{harness.address}/distribute_weights",
            {"version": 2, "manifest": man2,
             "upstreams": [fast.address], "origin": fast.address},
        )
        assert status == 200 and newer["success"]
        assert harness.srv._wp_store.version == 2
        t.join(timeout=60)
        # The superseded v1 fetch completed afterwards, but v2 stays the
        # live store: state ready, stats = v2's payload size.
        assert first["status"] in (200, 500)
        assert harness.srv._wp_store.version == 2
        assert harness.srv._wp_state == "ready"
        assert harness.srv._wp_bytes_from_origin == man2["total_bytes"]
    finally:
        harness.close()
        fast.close()
        slow.close()


# ----------------------------------------------------------------------
# Shard-aware + quantized wire (ISSUE 8)
# ----------------------------------------------------------------------


def test_group_by_shard_partitions_and_validates():
    from areal_tpu.system.weight_plane import group_by_shard

    groups = group_by_shard(
        ["u0", "u1", "u2", "u3"],
        {"u0": (0, 2), "u1": (1, 2), "u2": (0, 2), "u3": None},
    )
    assert groups == {(2, 0): ["u0", "u2"], (2, 1): ["u1"], (1, 0): ["u3"]}
    with pytest.raises(ValueError, match="bad shard"):
        group_by_shard(["u"], {"u": (2, 2)})


def _tiny_model():
    import jax

    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params

    cfg = TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32",
        param_dtype="float32",
    )
    mk = lambda seed: jax.tree_util.tree_map(  # noqa: E731
        np.asarray, init_params(cfg, jax.random.PRNGKey(seed))
    )
    return cfg, mk


def _greedy(eng, ids, n=8):
    import queue as _q

    from areal_tpu.engine.serving import GenRequest

    q = _q.Queue()
    eng.submit(GenRequest(
        qid="q", input_ids=list(ids), max_new_tokens=n, greedy=True,
        done_cb=q.put,
    ))
    r = q.get(timeout=300)
    assert r.error is None, r.error
    return r.output_ids


@pytest.mark.timeout(600)
def test_sharded_pair_ingress_and_decode_parity(tmp_path):
    """ISSUE 8 satellite: a 2-way-TP pair of fake-device servers each
    ingresses <= ~0.5 + epsilon payloads per version (epsilon = the
    replicated norm/bias leaves every rank carries), rank 1's stream is
    servable peer-to-peer between same-shard holders, and a TP=2
    ServingEngine cut over from the two sliced streams matches the
    float unsharded engine's greedy decode token-for-token."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device virtual CPU platform")
    from areal_tpu.engine.serving import ServingEngine, serving_mesh

    cfg, mk = _tiny_model()
    p_serve, p_boot = mk(9), mk(0)
    d = str(tmp_path / "dump")
    dump_raw_params(p_serve, d, version=1, chunk_bytes=1 << 12)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    servers = {r: _DistributeHarness(shard=(r, 2)).start() for r in (0, 1)}
    engines = []
    try:
        full_bytes = fetch_manifest(src.address, version=1)["total_bytes"]
        for r, harness in servers.items():
            man = fetch_manifest(
                src.address, version=1, tp_degree=2, tp_rank=r
            )
            body, status = _post_json(
                f"{harness.address}/distribute_weights",
                {"version": 1, "manifest": man,
                 "upstreams": [src.address], "origin": src.address},
            )
            assert status == 200 and body["success"], body
            st = harness.srv._wp_store
            stats = st.stats(src.address)
            # Each server fetched ONLY its slice: <= 0.5 + epsilon of
            # the full payload, and complete by its own expectation.
            assert stats["bytes_from_origin"] <= 0.55 * full_bytes
            assert stats["expected_bytes"] == man["total_bytes"]
            assert stats["ingress_payload_equivalents"] == pytest.approx(1.0)
        # Wrong-rank stream at a shard-configured server: 409, before
        # any staging.
        man0 = fetch_manifest(src.address, version=1, tp_degree=2, tp_rank=0)
        body, status = _post_json(
            f"{servers[1].address}/distribute_weights",
            {"version": 1, "manifest": man0,
             "upstreams": [src.address], "origin": src.address},
        )
        assert status == 409 and "shard" in body["error"]
        # Same-shard peer hop: a rank-0 replica fed by the rank-0
        # holder costs the origin nothing; total origin egress for the
        # version stays ~1.0 full payloads.
        rep = ChunkStore(man0)
        rep_stats = rep.fetch(
            [servers[0].address, src.address], origin=src.address
        )
        assert rep_stats["bytes_from_origin"] == 0
        fpe = src.stats()["full_payload_equivalents"][1]
        assert 1.0 <= fpe <= 1.1, fpe

        # Decode parity: unsharded float baseline vs TP=2 engine cut
        # over from the two sliced streams.
        base = ServingEngine(
            cfg, p_serve, max_batch_size=2, max_seq_len=128,
            decode_block_steps=4, page_size=8, seed=0,
        )
        base.start()
        engines.append(base)
        want = _greedy(base, [5, 6, 7])

        from areal_tpu.engine.weight_client import assemble_leaves

        leaves_by_rank, gshapes = {}, {}
        for r, harness in servers.items():
            st = harness.srv._wp_store
            leaves_by_rank[r] = assemble_leaves(st)
            gshapes.update({
                e["path"]: tuple(e["global_shape"])
                for e in st.manifest["leaves"]
            })
        tp = ServingEngine(
            cfg, p_boot, max_batch_size=2, max_seq_len=128,
            decode_block_steps=4, page_size=8, seed=0,
            mesh=serving_mesh(2),
        )
        tp.start()
        engines.append(tp)
        cut_s = tp.cutover_shard_leaves(
            leaves_by_rank, 2, version=1, global_shapes=gshapes
        )
        assert cut_s < 120
        assert _greedy(tp, [5, 6, 7]) == want
    finally:
        for e in engines:
            e.stop()
        for h in servers.values():
            h.close()
        src.close()


def test_int8_wire_distribute_assembles_dequantized(tmp_path):
    """Quantized wire end to end: the int8 stream is ~half the raw
    bytes (bf16 leaves), the harness accepts and completes it, and
    assembly dequantizes to exactly the host-side reference
    (dequantize(quantize(w)) — slicing not involved here)."""
    import ml_dtypes

    from areal_tpu.engine.weight_client import assemble_params
    from areal_tpu.system.weight_transfer import (
        dequantize_wire_leaf, quantize_wire_leaf,
    )

    rng = np.random.default_rng(3)
    params = {
        "emb": {"w": rng.standard_normal((64, 32)).astype(ml_dtypes.bfloat16)},
        "l0": {"wq": rng.standard_normal((4, 32, 32)).astype(ml_dtypes.bfloat16),
               "norm": rng.standard_normal((4, 32)).astype(np.float32)},
    }
    d = str(tmp_path / "dump")
    dump_raw_params(params, d, version=2, chunk_bytes=1 << 12,
                    wire_dtype="int8")
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    harness = _DistributeHarness().start()
    try:
        raw_bytes = fetch_manifest(src.address, version=2)["total_bytes"]
        man = fetch_manifest(src.address, version=2, wire="int8")
        assert man["total_bytes"] < 0.75 * raw_bytes
        body, status = _post_json(
            f"{harness.address}/distribute_weights",
            {"version": 2, "manifest": man,
             "upstreams": [src.address], "origin": src.address},
        )
        assert status == 200 and body["success"], body
        st = harness.srv._wp_store
        assert harness.srv._wp_wire == "int8"
        assert harness.srv._wp_expected_bytes == man["total_bytes"]
        got, v = assemble_params(st)
        assert v == 2
        for path, orig in (
            ("emb/w", params["emb"]["w"]),
            ("l0/wq", params["l0"]["wq"]),
        ):
            node = got
            for p in path.split("/"):
                node = node[p]
            assert node.dtype == orig.dtype
            ref = dequantize_wire_leaf(
                *quantize_wire_leaf(np.asarray(orig)), orig.dtype
            )
            np.testing.assert_array_equal(
                np.asarray(node, np.float32), np.asarray(ref, np.float32)
            )
        # Norms ship raw: bit-exact.
        np.testing.assert_array_equal(
            np.asarray(got["l0"]["norm"]), params["l0"]["norm"]
        )
        # fpe divides by the WIRE's own payload: one int8 fetch == 1.0.
        assert src.stats()["full_payload_equivalents"][2] == pytest.approx(
            1.0
        )
    finally:
        harness.close()
        src.close()


def test_peer_store_404s_chunks_it_does_not_hold(tmp_path):
    d = str(tmp_path / "dump")
    dump_raw_params(_params(), d, version=1)
    src = WeightPlaneSource(d, chunk_bytes=1 << 12).start()
    peer = PeerStoreServer().start()
    try:
        man = fetch_manifest(src.address)
        # Not holding anything yet: manifest 404s, chunk 404s, and a
        # fetch routed at it falls through to the origin.
        with pytest.raises(Exception):
            fetch_manifest(peer.address, version=1)
        store = ChunkStore(man)
        stats = store.fetch([peer.address, src.address], origin=src.address)
        assert store.complete()
        assert stats["bytes_from_origin"] == man["total_bytes"]
    finally:
        peer.close()
        src.close()

"""Plain prompt dataset for RL rollout (reference impl/dataset/prompt_dataset.py).

jsonl rows need a "prompt" key; optional "id". Produces `packed_prompts`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api import data_api
from areal_tpu.base import logging

logger = logging.getLogger("prompt_dataset")


class PromptDataset:
    def __init__(
        self,
        util: data_api.DatasetUtility,
        max_length: Optional[int] = None,
        dataset_path: Optional[str] = None,
        dataset_builder: Optional[Callable[[], List[Dict]]] = None,
    ):
        self.util = util
        data = data_api.load_shuffle_split_dataset(util, dataset_path, dataset_builder)
        enc = util.tokenizer(
            [x["prompt"] for x in data],
            truncation=max_length is not None,
            max_length=max_length,
            padding=False,
            return_length=True,
            return_attention_mask=False,
        )
        self.ids = [str(x["id"]) for x in data]
        self.prompts: List[List[int]] = enc["input_ids"]
        self.prompt_lengths = [len(p) for p in self.prompts]
        logger.info(f"PromptDataset: {len(self.prompts)} prompts (dp={util.dp_rank})")

    def __len__(self):
        return len(self.prompts)

    def __getitem__(self, idx: int) -> data_api.SequenceSample:
        return data_api.SequenceSample.from_default(
            ids=[self.ids[idx]],
            seqlens=[self.prompt_lengths[idx]],
            data=dict(
                packed_prompts=np.asarray(self.prompts[idx], dtype=np.int32),
            ),
        )


data_api.register_dataset("prompt", PromptDataset)

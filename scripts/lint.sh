#!/usr/bin/env bash
# The one lint entry point (docs/static_analysis.md):
#
#   1. ruff  — generic hygiene (undefined names, unused imports;
#              baseline rule set in pyproject.toml). Skipped with a
#              note when ruff is not installed — the container image
#              does not bake it in.
#   2. areal-lint over areal_tpu/ — repo-specific AST contract checks
#              (loop-only, blocking-async, env-knob, wire-schema,
#              wire-contract, metrics-registry, chaos-registry,
#              lock-order) + the generated-docs drift gates
#              (env_vars.md, metrics.md, fault_points.md).
#   3. areal-lint over tests/ + scripts/ — the CLIENT side of the
#              cross-process contracts only (wire routes, metric
#              names, AREAL_FAULTS chaos specs): a chaos test arming
#              a renamed point must fail HERE, not silently no-op on
#              a chip window.
#
# Exit nonzero if any gate fails. Used by chip_runbook.sh preflight
# and intended as the single command future PRs/CI wire in.

set -u
cd "$(dirname "$0")/.."
rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "== lint: ruff =="
    ruff check areal_tpu scripts tests || rc=1
else
    echo "== lint: ruff not installed; skipping (baseline config in pyproject.toml) =="
fi

echo "== lint: areal-lint (areal_tpu + docs drift) =="
python scripts/areal_lint.py areal_tpu \
    --check-env-docs docs/env_vars.md \
    --check-metrics-docs docs/metrics.md \
    --check-fault-docs docs/fault_points.md || rc=1

echo "== lint: areal-lint (tests/scripts cross-process contracts) =="
python scripts/areal_lint.py tests scripts \
    --checker wire-contract \
    --checker metrics-registry \
    --checker chaos-registry || rc=1

exit $rc

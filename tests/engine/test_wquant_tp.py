"""int8 decode weights (W8A16, ops/wquant.py) under a tensor-parallel
mesh — the combination ISSUE 8 lifts the engine-construction ban on.

The quantize transform runs under jit on the SHARDED params, so GSPMD
places the scales (absmax reduces axis -2: an all-reduce max for
row-parallel weights, free for column-parallel ones). These tests pin
the two facts that make the combination safe to ship: the quantized
values themselves are identical to the unsharded transform's, and
greedy decode is token-identical to the unsharded int8 engine."""

import queue

import jax
import numpy as np
import pytest

from areal_tpu.engine.serving import GenRequest, ServingEngine, serving_mesh
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params

pytestmark = pytest.mark.serial


def _cfg():
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32",
        param_dtype="float32",
    )


def _greedy(eng, ids, n=8):
    q = queue.Queue()
    eng.submit(GenRequest(
        qid="q", input_ids=list(ids), max_new_tokens=n, greedy=True,
        done_cb=q.put,
    ))
    r = q.get(timeout=300)
    assert r.error is None, r.error
    return r.output_ids


def test_quantize_weight_invariant_under_sharding():
    """quantize_weight of a tensor-sharded leaf must equal the
    unsharded result exactly: max/clip/round are order-independent, so
    GSPMD's placement cannot change a single int8 code or scale."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device virtual CPU platform")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from areal_tpu.ops.wquant import quantize_weight

    rng = np.random.default_rng(0)
    w = rng.standard_normal((4, 32, 32)).astype(np.float32)
    q_ref, s_ref = jax.jit(quantize_weight)(w)
    mesh = serving_mesh(2)
    for spec in (P(None, None, "tensor"), P(None, "tensor", None)):
        ws = jax.device_put(w, NamedSharding(mesh, spec))
        q, s = jax.jit(quantize_weight)(ws)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
        np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))


@pytest.mark.slow  # ~26s (three engines + decode on the virtual mesh);
# moved out of the tier-1 budget in PR 9 (wall clock was brushing
# 870s). Tier-1 keeps the quantize-invariance pin above plus int8-TP
# decode coverage via tests/engine/test_kv_int8.py
# ::test_serving_engine_int8_tensor_parallel (~12s).
@pytest.mark.timeout(600)
def test_int8_decode_parity_tp_vs_unsharded():
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device virtual CPU platform")
    cfg = _cfg()
    params = jax.tree_util.tree_map(
        np.asarray, init_params(cfg, jax.random.PRNGKey(7))
    )
    kw = dict(max_batch_size=2, max_seq_len=128, decode_block_steps=4,
              page_size=8, seed=0, decode_weight_dtype="int8")
    ref = ServingEngine(cfg, params, **kw)
    ref.start()
    try:
        want = _greedy(ref, [9, 10, 11])
    finally:
        ref.stop()
    tp = ServingEngine(cfg, params, mesh=serving_mesh(2), **kw)
    tp.start()
    try:
        assert _greedy(tp, [9, 10, 11]) == want
    finally:
        tp.stop()

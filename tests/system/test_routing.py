"""Routing-policy units for the gserver manager's production scheduler:
prefix-/session-affinity, shed-aware + saturation spill, the in-flight
fold that keeps least_token_usage honest between /metrics polls (ISSUE
6 satellite: a burst must not pile onto one server just because the
snapshot is stale), and the ISSUE 11 global prefix index — affinity as
a fast path, with any other routing decision carrying a ``kv_source``
pull hint instead of forcing a re-prefill."""

import collections
import threading
import time

from areal_tpu.api.system_api import GserverManagerConfig
from areal_tpu.system.gserver_manager import GserverManager

A, B = "http://a:1", "http://b:2"


def _manager(policy="round_robin", **cfg_kw):
    m = GserverManager.__new__(GserverManager)
    m.cfg = GserverManagerConfig(
        n_servers=2, schedule_policy=policy, **cfg_kw
    )
    m.server_urls = [A, B]
    m._healthy = set(m.server_urls)
    m._rr = 0
    m._lock = threading.Lock()
    m._server_reqs = {u: 0 for u in m.server_urls}
    m._server_tokens = {u: 0.0 for u in m.server_urls}
    m._server_tokens_pending = {u: 0.0 for u in m.server_urls}
    m._server_shed_until = {u: 0.0 for u in m.server_urls}
    m._server_shed_total = {u: 0.0 for u in m.server_urls}
    m._affinity = collections.OrderedDict()
    # Tiered-KV global prefix index (ISSUE 11).
    m._kv_index_size = 65536
    m._prefix_index = collections.OrderedDict()
    m._server_kv_index = {}
    # Disaggregated-pool state (all-unified here: single-pool routing).
    m._server_roles = {u: "unified" for u in m.server_urls}
    m._server_queued_toks = {u: 0.0 for u in m.server_urls}
    m._server_free_pages = {}
    m._server_total_pages = {}
    m._server_elastic = {}
    m._server_shards = {}
    m._rerole_orig = {}
    m._rerole_log = []
    # Elastic fleet control plane (ISSUE 12): no drains/joins in these
    # units — routing just filters on the empty sets.
    m._draining = set()
    m._drain_deadline = {}
    m._join_t0 = {}
    m._join_info = {}
    m.weight_version = 0
    return m


def test_least_token_usage_folds_inflight_between_polls():
    """Equal snapshots + a burst of schedules: without the pending fold
    every request would land on the min-snapshot server; with it they
    alternate."""
    m = _manager("least_token_usage")
    placed = [
        m._route({"prompt_len": 100, "new_token_budget": 100})[0]
        for _ in range(6)
    ]
    assert placed.count(A) == 3 and placed.count(B) == 3


def test_affinity_routes_follow_up_to_prefix_holder_across_versions():
    m = _manager("least_requests")
    url1, policy1, _d, _k = m._route({"qid": "s/0", "prompt_len": 10})
    assert policy1 == "least_requests"
    # Load the affinity target heavily: affinity still wins (the prefix
    # is there), and survives a weight-version bump.
    m._server_reqs[url1] = 50
    m.weight_version = 7
    url2, policy2, _d, _k = m._route({"qid": "s/0", "prompt_len": 20})
    assert (url2, policy2) == (url1, "affinity")


def test_affinity_spills_on_shed_window_with_kv_source_then_returns():
    m = _manager("round_robin")
    url1, _, _d, _k = m._route({"qid": "s/1", "prompt_len": 10})
    other = B if url1 == A else A
    # The server shed a client with 429: routed around for Retry-After —
    # and the spill target gets a kv_source hint pointing back at the
    # prefix holder, so the spill costs a transfer, not a re-prefill.
    m._server_shed_until[url1] = time.monotonic() + 30.0
    url2, policy2, _d, kv_src = m._route({"qid": "s/1", "prompt_len": 10})
    assert (url2, policy2) == (other, "spill")
    assert kv_src == url1
    # Spill re-recorded the affinity on the server now holding the
    # session's newest prefix.
    m._server_shed_until[url1] = 0.0
    url3, policy3, _d, _k = m._route({"qid": "s/1", "prompt_len": 10})
    assert (url3, policy3) == (other, "affinity")


def test_affinity_spills_on_saturation_threshold():
    m = _manager("least_requests", affinity_saturation_requests=4)
    url1, _, _d, _k = m._route({"qid": "s/2", "prompt_len": 10})
    m._server_reqs[url1] = 4
    other = B if url1 == A else A
    m._server_reqs[other] = 0
    url2, policy2, _d, kv_src = m._route({"qid": "s/2", "prompt_len": 10})
    assert (url2, policy2) == (other, "spill")
    assert kv_src == url1


def test_affinity_ignores_unhealthy_target_and_map_is_bounded():
    m = _manager("round_robin", affinity_map_size=2)
    url1, _, _d, _k = m._route({"qid": "s/3", "prompt_len": 10})
    m._healthy.discard(url1)
    url2, policy2, _d, _k = m._route({"qid": "s/3", "prompt_len": 10})
    assert url2 != url1 and policy2 != "affinity"
    # LRU bound: oldest entries fall out.
    for i in range(5):
        m._route({"qid": f"lru/{i}", "prompt_len": 1})
    assert len(m._affinity) <= 2


def test_whole_fleet_shedding_still_routes():
    m = _manager("least_requests")
    now = time.monotonic()
    m._server_shed_until = {A: now + 30, B: now + 30}
    url, _, _d, _k = m._route({"qid": "s/4", "prompt_len": 10})
    assert url in (A, B)


# ----------------------------------------------------------------------
# Global prefix index (ISSUE 11): affinity becomes a fast path — the
# index recovers forgotten sessions and hands out pull hints.
# ----------------------------------------------------------------------


def test_index_recovers_session_after_affinity_map_forgot():
    """Affinity map empty (LRU'd out / restarted manager) but the
    global index knows server A spilled the prefix: route to A with the
    'kv-index' policy — the same fast path, from the durable map."""
    m = _manager("least_requests")
    m._prefix_index["q/0"] = {"url": A, "tier": "host", "n_tokens": 64,
                              "version": 0}
    m._server_kv_index[A] = {"q/0"}
    url, policy, _d, kv_src = m._route({"qid": "q/0", "prompt_len": 10})
    assert (url, policy, kv_src) == (A, "kv-index", None)


def test_affinity_disabled_routes_by_policy_with_pull_hint():
    """session_affinity=False: the configured policy places the request
    (round robin here), and when it lands AWAY from the holder the
    response carries kv_source so the target pulls the prefix —
    affinity is an optimization, never a correctness requirement."""
    m = _manager("round_robin", session_affinity=False)
    m._prefix_index["q/1"] = {"url": A, "tier": "host", "n_tokens": 64,
                              "version": 0}
    m._server_kv_index[A] = {"q/1"}
    seen = {}
    for _ in range(2):
        url, policy, _d, kv_src = m._route({"qid": "q/1", "prompt_len": 10})
        assert policy == "round_robin"
        seen[url] = kv_src
    # The round-robin pass that landed on B got the pull hint; the one
    # that landed on the holder itself did not.
    assert seen[B] == A
    assert seen[A] is None


def test_index_saturated_holder_spills_with_pull_hint():
    m = _manager("least_requests", affinity_saturation_requests=2)
    m._prefix_index["q/2"] = {"url": A, "tier": "disk", "n_tokens": 64,
                              "version": 0}
    m._server_kv_index[A] = {"q/2"}
    m._server_reqs[A] = 5
    url, policy, _d, kv_src = m._route({"qid": "q/2", "prompt_len": 10})
    assert (url, policy, kv_src) == (B, "spill", A)


def test_eviction_migrates_index_entries_away():
    """A dead server's process RAM (and so its KV tier) is gone: its
    index entries must vanish with it, or returning sessions would be
    routed into guaranteed pull failures."""
    m = _manager("least_requests")
    m._evicted = {}
    m._prefix_index["q/3"] = {"url": A, "tier": "host", "n_tokens": 8,
                              "version": 0}
    m._prefix_index["q/4"] = {"url": B, "tier": "host", "n_tokens": 8,
                              "version": 0}
    m._server_kv_index = {A: {"q/3"}, B: {"q/4"}}
    m._mark_unhealthy(A, "test")
    assert "q/3" not in m._prefix_index
    assert "q/4" in m._prefix_index
    url, policy, _d, kv_src = m._route({"qid": "q/3", "prompt_len": 10})
    assert url == B and kv_src is None

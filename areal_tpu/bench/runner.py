"""Isolated phase runner: one phase pass per subprocess, hard deadline.

The parent (:func:`run_phase`) spawns ``python -m areal_tpu.bench.runner``
for a single (phase, pass) and enforces a wall-clock deadline with
SIGKILL — a wedged XLA compile or a PJRT crash kills that one phase and
the bank still ends the day valid:

- child finishes OK       -> child banks the ok record itself (atomic
                             tmp+rename from inside the subprocess, so
                             even a parent crash right after cannot
                             lose it)
- child raises            -> child banks a failed record with the
                             traceback, exits 1
- child dies / is killed  -> parent banks a failed/timeout record with
                             the captured output tail

Chaos hooks (``base/fault_injection.py``): ``bench.runner.phase`` fires
inside the child right before the phase body — arm it with ``die`` to
simulate a PJRT crash or ``hang`` to simulate a wedged compile; the
``AREAL_FAULTS`` env spec crosses the process boundary on its own.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import traceback
from typing import Dict, Optional

from areal_tpu.base import env_registry
from areal_tpu.bench import bank, phases
from areal_tpu.bench._util import log, repo_root

TAIL_BYTES = 4000


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGKILL the child's whole process group (fall back to the child
    alone if the group is already gone)."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        proc.kill()


def _read_tail(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - TAIL_BYTES))
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return None


def run_phase(
    phase: str,
    pass_: str,
    bank_path: Optional[str] = None,
    deadline_s: Optional[float] = None,
    env_extra: Optional[Dict[str, str]] = None,
    python: str = sys.executable,
) -> Dict:
    """Execute one (phase, pass) in a subprocess; always returns a valid
    banked record (ok, failed, or timeout)."""
    spec = phases.get(phase)
    if deadline_s is None:
        deadline_s = spec.deadline_s(pass_)
    b = bank.bank_dir(bank_path)
    os.makedirs(b, exist_ok=True)

    repo = repo_root()
    env = dict(os.environ)
    env["AREAL_BENCH_BANK"] = b
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if spec.proxy:
        # Proxy phases are CPU evidence by construction — never let one
        # accidentally attest a TPU platform.
        env["JAX_PLATFORMS"] = "cpu"
    for k, v in (spec.env or {}).items():
        if k == "XLA_FLAGS":
            # Append: the phase asks for extra flags (e.g. a fake
            # multi-device CPU mesh) on top of whatever the host set.
            env[k] = (env.get(k, "") + " " + v).strip()
        else:
            env.setdefault(k, v)
    if env_extra:
        env.update(env_extra)

    out_fd, out_path = tempfile.mkstemp(prefix=f"bench_{phase}_", suffix=".log")
    started = time.time()
    status, error = "ok", None
    try:
        with os.fdopen(out_fd, "wb") as out_f:
            # start_new_session: the child leads its own process group, so
            # the deadline kill below reaps anything the phase spawned
            # (e.g. serving_http's GenerationServer grandchild) — an
            # orphaned jax process would hold the exclusive TPU client
            # and poison every later phase with 'device busy'.
            proc = subprocess.Popen(
                [python, "-m", "areal_tpu.bench.runner",
                 "--phase", phase, "--pass", pass_, "--bank", b],
                env=env, cwd=repo, stdout=out_f, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            try:
                rc = proc.wait(timeout=deadline_s)
            except subprocess.TimeoutExpired:
                _kill_group(proc)
                proc.wait()
                status, error = "timeout", (
                    f"phase {phase!r} ({pass_}) exceeded its {deadline_s:.0f}s "
                    f"deadline; subprocess killed"
                )
            else:
                if rc != 0:
                    status, error = "failed", (
                        f"phase {phase!r} ({pass_}) subprocess exited {rc}"
                    )
        tail = _read_tail(out_path)
    finally:
        try:
            os.remove(out_path)
        except OSError:
            pass

    rec = bank.load_latest(b, phase, pass_)
    fresh = rec is not None and rec["started_at"] >= started - 1.0
    if fresh and rec["status"] == "ok":
        # The child banked a completed pass. Even if the parent then saw
        # a nonzero exit or a timeout (e.g. interpreter teardown wedged
        # on the dying tunnel AFTER the atomic bank write), the
        # measurement exists — never clobber it with a failure record.
        return rec
    if status == "ok":
        # Exited 0 without banking: treat as a failure, never as silence.
        status, error = "failed", (
            f"phase {phase!r} ({pass_}) exited 0 without banking a record"
        )
    elif fresh:
        # The child banked its own failure (with the real traceback) —
        # richer than what the parent can reconstruct.
        return rec
    # probe=False: the parent must never touch jax.devices() — on the
    # very tunnel flap being recorded, that probe could wedge the one
    # process responsible for enforcing deadlines.
    rec = bank.make_record(
        phase, pass_, status, error=error, tail=tail,
        started_at=started, finished_at=time.time(), probe=False,
    )
    bank.write_record(rec, b)
    log(f"bench: {phase}/{pass_} -> {status}"
        + (f" ({error})" if error else ""))
    return rec


# ----------------------------------------------------------------------
# Child entry: python -m areal_tpu.bench.runner --phase X --pass Y
# ----------------------------------------------------------------------


def _child_main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--phase", required=True)
    parser.add_argument("--pass", dest="pass_", required=True,
                        choices=list(bank.PASSES))
    parser.add_argument("--bank", default=None)
    args = parser.parse_args(argv)

    from areal_tpu.utils.jaxenv import apply_jax_platform_override

    apply_jax_platform_override()
    enable_compilation_cache()

    from areal_tpu.base.fault_injection import faults

    # Scope = "bench/<phase>": an AREAL_FAULTS spec can wedge or kill ONE
    # phase's subprocess out of a multi-phase run.
    faults.set_scope(f"bench/{args.phase}")
    phases.load_extra_modules()
    spec = phases.get(args.phase)
    started = time.time()
    try:
        faults.maybe_fail("bench.runner.phase")
        fn = spec.resolve()
        value = fn(args.pass_)
        if not isinstance(value, dict):
            raise TypeError(
                f"phase {spec.name!r} returned {type(value).__name__}, "
                "expected dict"
            )
        rec = bank.make_record(
            spec.name, args.pass_, "ok", value=value,
            started_at=started, finished_at=time.time(),
        )
        path = bank.write_record(rec, args.bank)
        print(json.dumps({"banked": path, "status": "ok"}), flush=True)
        return 0
    except BaseException as e:  # bank the failure, then re-signal it
        err = f"{type(e).__name__}: {e}"
        log(f"bench: phase {spec.name!r} ({args.pass_}) failed: {err}")
        try:
            # probe=False: attesting the failure must not call
            # jax.devices() — on a half-up tunnel that probe can wedge
            # this child past its deadline and downgrade the rich
            # traceback record below to a parent-side 'timeout'.
            rec = bank.make_record(
                spec.name, args.pass_, "failed", error=err,
                tail=traceback.format_exc()[-TAIL_BYTES:],
                started_at=started, finished_at=time.time(), probe=False,
            )
            bank.write_record(rec, args.bank)
        except Exception:
            pass  # the parent will bank from the captured output tail
        if isinstance(e, KeyboardInterrupt):
            raise
        return 1


def enable_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a stable directory
    (min-compile-time floors dropped so every bench program caches).
    This is what makes the compile/measure split real: the compile pass
    subprocess dies, the cache entries survive."""
    import jax

    cache_dir = env_registry.get_str("AREAL_XLA_CACHE_DIR") or (
        os.path.join(tempfile.gettempdir(), "areal_xla_cache")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        log(f"bench: persistent compilation cache at {cache_dir}")
    except Exception as e:  # older jax: cache flags absent — bench still runs
        log(f"bench: compilation cache unavailable ({e!r})")


if __name__ == "__main__":
    sys.exit(_child_main())

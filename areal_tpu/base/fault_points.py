"""Single registry of every named chaos-injection point.

``base/fault_injection.py`` gives production code free no-op points
(``faults.maybe_fail("gserver.drain")``) that chaos tests arm by BARE
STRING — in-process (``faults.arm``) or across process boundaries via
the ``AREAL_FAULTS`` env spec. That name was never checked anywhere:
rename an injection point and every chaos test that armed it becomes a
silent no-op that still passes — the worst kind of rot, a fault-
tolerance suite that tests nothing.

Every point is declared ONCE here (name, modules, sync/async, what
failure it simulates); the ``chaos-registry`` checker in
``areal_tpu/lint`` flags ``maybe_fail``/``maybe_fail_async`` calls and
``arm``/``hits`` references naming undeclared points, ``AREAL_FAULTS``
spec strings naming unknown points, non-literal point names, and dead
registry entries no production site fires.

Names under ``test.`` are reserved for the injector's own unit suite
(synthetic points that exercise the arming machinery, not a production
contract) and are exempt from declaration.

``docs/fault_points.md`` is GENERATED from this registry
(``python scripts/areal_lint.py --emit-fault-docs
docs/fault_points.md``) and drift-gated in tier-1.

This module must stay stdlib-only: it is imported by the no-jax lint
gate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

# Reserved namespace for fault_injection's own unit tests.
TEST_PREFIX = "test."


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    name: str
    modules: Tuple[str, ...]  # repo-rel modules with maybe_fail sites
    kind: str  # "sync" | "async" | "both"
    doc: str  # the real-world failure this point simulates


def _p(name: str, modules: Tuple[str, ...], kind: str,
       doc: str) -> FaultPoint:
    return FaultPoint(name=name, modules=modules, kind=kind, doc=doc)


_GS = ("areal_tpu/system/generation_server.py",)

_POINTS: List[FaultPoint] = [
    _p("engine.kv_spill", ("areal_tpu/engine/serving.py",), "sync",
       "KV tier spill write fails (host allocation/disk error) — the "
       "eviction must fall back to a clean free, counted as "
       "kv_prefix_lost, never a wedge."),
    _p("gserver.generate", _GS, "async",
       "Generation request dies or stalls server-side (engine crash, "
       "wedged decode lap)."),
    _p("gserver.kv_export", _GS, "async",
       "Prefill side dies mid KV handoff export."),
    _p("gserver.kv_restore", _GS, "async",
       "Tier restore fails mid delta-prefill — session must fall "
       "back to full re-prefill, spill-not-loss."),
    _p("gserver.kv_import", _GS, "async",
       "Decode side dies mid KV handoff import (the disagg e2e kills "
       "a prefill server mid-handoff through this)."),
    _p("gserver.drain", _GS, "async",
       "Drain-then-leave dies at the start of the drain (server "
       "killed right as it begins quiescing)."),
    _p("gserver.kv_accept", _GS, "async",
       "Migration target fails while accepting a parked prefix from "
       "a draining peer."),
    _p("gserver.update_weights", _GS, "async",
       "Weight load from the shared dump dies mid-update."),
    _p("gserver.distribute_weights", _GS, "async",
       "Plane fanout transfer dies on this server (mid-fetch peer "
       "kill in the weight-plane e2e)."),
    _p("gserver.weight_fetch", _GS, "sync",
       "One chunk fetch inside the plane transfer fails (transient "
       "peer error; the stream must retry/re-source)."),
    _p("gserver.cutover_weights", _GS, "async",
       "Cutover window dies between interrupt and swap."),
    _p("weight_plane.serve_chunk",
       ("areal_tpu/system/weight_plane.py",
        "areal_tpu/system/generation_server.py"), "async",
       "A serving peer/origin fails mid-chunk (the bench kills a "
       "mid-transfer peer via serve_chunk=raise:k=40:n=3)."),
    _p("weight_plane.chunk_bytes",
       ("areal_tpu/system/weight_plane.py",), "sync",
       "Weight chunk payload corrupted on the wire AFTER its hash was "
       "stamped (bit-rot, torn proxy) — the puller's sha256 verify "
       "must reject and re-fetch; corrupt weights never cut over. "
       "Fires for every /weights/chunk byte path (origin, peer "
       "holders, gserver peer hop) via chunk_response."),
    _p("gserver.kv_chunk_bytes", _GS, "async",
       "KV chunk/blob payload corrupted after its chunk index was "
       "minted (tier chunk, handoff blob) — the puller's per-chunk "
       "sha256 verify must reject and re-fetch, never scatter corrupt "
       "KV into the paged pool."),
    _p("worker.poll",
       ("areal_tpu/system/worker_base.py",), "both",
       "A worker's poll loop dies or hangs — THE generic worker "
       "kill: the elastic e2e SIGKILLs the manager via "
       "worker.poll@gserver_manager=die."),
    _p("rollout.episode",
       ("areal_tpu/system/rollout_worker.py",), "sync",
       "One rollout episode dies mid-flight (agent/env crash)."),
    _p("master.step",
       ("areal_tpu/system/master_worker.py",), "sync",
       "The master dies mid training step (controller-restart "
       "recovery path)."),
    _p("manager.plane_fanout",
       ("areal_tpu/system/gserver_manager.py",), "sync",
       "The manager dies inside the weight-plane fanout push."),
    _p("manager.fanout",
       ("areal_tpu/system/gserver_manager.py",), "async",
       "The manager dies inside the update-weights fanout wave."),
    _p("bench.runner.phase",
       ("areal_tpu/bench/runner.py",), "sync",
       "A bench phase subprocess dies or wedges (daemon "
       "resume/attempt-budget machinery)."),
    _p("train.checkpoint",
       ("areal_tpu/engine/checkpoint.py",), "sync",
       "The trainer dies at the engine-checkpoint commit point, after "
       "artifacts landed but around the manifest rename — recovery "
       "must resume from the previous complete checkpoint, never a "
       "torn one."),
    _p("buffer.wal_append",
       ("areal_tpu/system/wal.py",), "sync",
       "The trainer dies inside a rollout-WAL append (possibly leaving "
       "a torn final record) — replay must drop the torn tail and the "
       "unacked sample must be redelivered by the pusher."),
    _p("buffer.consume",
       ("areal_tpu/system/buffer.py",), "sync",
       "The trainer dies handing a batch to training, after buffer "
       "admission but before the consumed-seq watermark persists — "
       "the ledger must re-admit exactly once on resume."),
    _p("rexec.case",
       ("areal_tpu/system/reward_executor.py",), "sync",
       "One sandboxed reward job fails inside a warm executor worker "
       "(guarded exec raises / worker OOM-kill) — the case must come "
       "back as a failed result, never take the pool or the caller "
       "down."),
    _p("rexec.die",
       ("areal_tpu/system/reward_executor.py",), "sync",
       "A whole reward-executor service dies mid-flight (container "
       "kill) — its heartbeat goes stale and clients must fail over "
       "to a surviving executor with zero failed episodes."),
    _p("manager.model_registry",
       ("areal_tpu/system/gserver_manager.py",), "sync",
       "The model-registry read flakes during the manager's "
       "multi-model refresh — the accepted-model set must stay at "
       "its last good value (live pools keep routing, unregistered "
       "joiners stay quarantined), never a poll-thread crash or a "
       "mass quarantine of registered models."),
    _p("gw.auth",
       ("areal_tpu/system/gateway.py",), "sync",
       "The gateway's API-key lookup dies mid-auth (key store "
       "flake) — the request must come back as a clean 401-class "
       "refusal the client can retry, never a hung stream or a "
       "half-admitted tenant slot."),
    _p("gw.shed",
       ("areal_tpu/system/gateway.py",), "sync",
       "The gateway dies inside the admission/shed decision (right "
       "as a 429 is being minted) — the tenant's bucket charge must "
       "not leak and the usage ledger must not double-count the shed "
       "after restart replay."),
]

REGISTRY: Dict[str, FaultPoint] = {p.name: p for p in _POINTS}
assert len(REGISTRY) == len(_POINTS), "duplicate fault-point declaration"


def render_docs() -> str:
    """Markdown for docs/fault_points.md — generated, drift-gated;
    never hand-edit the output file."""
    lines = [
        "# Chaos injection points",
        "",
        "<!-- GENERATED FILE — do not edit. Source of truth: "
        "areal_tpu/base/fault_points.py. Regenerate with: "
        "python scripts/areal_lint.py --emit-fault-docs "
        "docs/fault_points.md -->",
        "",
        "Every named `faults.maybe_fail(...)` injection point "
        "(base/fault_injection.py), generated from the registry the "
        "`chaos-registry` lint checker enforces. Arm one in-process "
        "with `faults.arm(point, action, ...)` or across process "
        "boundaries with the `AREAL_FAULTS` env spec "
        "(`<point>[@scope]=<action>[:k=N][:n=N][:delay=S]`). Names "
        "under `test.` are reserved for the injector's own unit "
        "suite.",
        "",
        "| Point | Kind | Module(s) | Simulates |",
        "|---|---|---|---|",
    ]
    for p in sorted(_POINTS, key=lambda p: p.name):
        mods = ", ".join(f"`{m}`" for m in p.modules)
        doc = p.doc.replace("|", "\\|")
        lines.append(f"| `{p.name}` | {p.kind} | {mods} | {doc} |")
    lines.append("")
    return "\n".join(lines)

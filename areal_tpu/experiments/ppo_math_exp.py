"""Sync PPO math experiment (reference experiments/common/ppo_math_exp.py).

DFG: actor_gen -> {rew_inf, ref_inf[, critic_inf]} ->
{actor_train[, critic_train]} with all models colocated on every model
worker (the reference's "global hybrid" allocation); generation runs
in-framework on the trainer mesh.
"""

from __future__ import annotations

import dataclasses
from typing import List

from areal_tpu.api.cli_args import PPOMATHExpConfig
from areal_tpu.api.config import (
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import ExperimentConfig, ModelShardSpec
from areal_tpu.experiments import register_experiment
from areal_tpu.experiments import common as C


def actor_interface_args(cfg: PPOMATHExpConfig) -> dict:
    p = cfg.ppo
    # group_size may be set at top level after construction (CLI override),
    # so resolve here instead of __post_init__.
    group = cfg.group_size if cfg.group_size > 1 else p.group_size
    p.group_size = group
    return dict(
        n_minibatches=p.ppo_n_minibatches,
        eps_clip=p.eps_clip,
        c_clip=p.c_clip,
        kl_ctl=p.kl_ctl,
        adaptive_kl_ctl=p.use_adaptive_kl_ctl,
        discount=p.discount,
        gae_lambda=p.gae_lambda,
        max_reward_clip=p.max_reward_clip,
        reward_output_scaling=p.reward_output_scaling,
        reward_output_bias=p.reward_output_bias,
        adv_norm=p.adv_norm,
        group_adv_norm=p.group_adv_norm,
        mask_no_eos_with_zero=p.mask_no_eos_with_zero,
        use_decoupled_loss=p.use_decoupled_loss,
        behav_imp_weight_cap=p.behav_imp_weight_cap,
        token_normalize_scope=p.token_normalize_scope,
        generation_size=p.generation_size,
        gconfig=dataclasses.asdict(p.gconfig.new(n=p.group_size)),
    )


def critic_interface_args(cfg: PPOMATHExpConfig) -> dict:
    """Critic-side hyperparameters (must stay consistent with the actor's
    where shared: KL/GAE/reward shaping and the token-normalization
    scope, or value and policy gradients normalize differently)."""
    p = cfg.ppo
    return dict(
        n_minibatches=p.ppo_n_minibatches,
        token_normalize_scope=p.token_normalize_scope,
        value_eps_clip=p.value_eps_clip,
        kl_ctl=p.kl_ctl,
        adaptive_kl_ctl=p.use_adaptive_kl_ctl,
        discount=p.discount,
        gae_lambda=p.gae_lambda,
        max_reward_clip=p.max_reward_clip,
        reward_output_scaling=p.reward_output_scaling,
        reward_output_bias=p.reward_output_bias,
        mask_no_eos_with_zero=p.mask_no_eos_with_zero,
    )


def build_ppo_math_experiment(cfg: PPOMATHExpConfig) -> ExperimentConfig:
    n_workers = C.resolve_n_workers(cfg)
    actor = ModelName("actor", 0)
    ref = ModelName("ref", 0)
    rew = ModelName("reward", 0)
    critic = ModelName("critic", 0)
    use_critic = not cfg.ppo.disable_value and cfg.critic is not None
    use_ref = cfg.ref is not None or (cfg.actor.path is not None)

    n_seqs = cfg.train_batch_size
    rpcs: List[MFCDef] = [
        MFCDef(
            name="actor_gen",
            model_name=actor,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            n_seqs=n_seqs,
            input_keys=("packed_prompts",),
            output_keys=(
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask",
            ),
            balanced_dp=True,
            mb_spec=C.mb_spec(cfg, cfg.actor_gen),
        ),
        MFCDef(
            name="rew_inf",
            model_name=rew,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("rw-math-code"),
            n_seqs=n_seqs,
            input_keys=("packed_input_ids", "prompt_mask"),
            output_keys=("rewards",),
            mb_spec=C.mb_spec(cfg, cfg.rew_inf),
        ),
    ]
    train_input_keys = [
        "packed_input_ids", "prompt_mask", "packed_logprobs",
        "rewards", "seq_no_eos_mask",
    ]
    if use_ref:
        rpcs.append(
            MFCDef(
                name="ref_inf",
                model_name=ref,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("ppo_actor"),
                n_seqs=n_seqs,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=("logprobs",),
                output_key_remap={"logprobs": "ref_logprobs"},
                mb_spec=C.mb_spec(cfg, cfg.ref_inf),
            )
        )
        train_input_keys.append("ref_logprobs")
    if use_critic:
        rpcs.append(
            MFCDef(
                name="critic_inf",
                model_name=critic,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction(
                "ppo_critic", args=critic_interface_args(cfg)
            ),
                n_seqs=n_seqs,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=("values",),
                mb_spec=C.mb_spec(cfg, cfg.critic_inf),
            )
        )
        train_input_keys.append("values")
        rpcs.append(
            MFCDef(
                name="critic_train",
                model_name=ModelName("critic", 1),
                interface_type=ModelInterfaceType.TRAIN_STEP,
                interface_impl=ModelInterfaceAbstraction(
                "ppo_critic", args=critic_interface_args(cfg)
            ),
                n_seqs=n_seqs,
                input_keys=tuple(train_input_keys),
                mb_spec=C.mb_spec(cfg, cfg.critic_train),
            )
        )
    rpcs.append(
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            n_seqs=n_seqs,
            input_keys=tuple(train_input_keys),
            mb_spec=C.mb_spec(cfg, cfg.actor_train),
        )
    )

    iface_args = actor_interface_args(cfg)
    workers = []
    for i in range(n_workers):
        # The allocation's train partition drives every jax engine on
        # this worker (actor + colocated ref/critic share the slice).
        t_mesh, t_devs = C.train_mesh_for_worker(cfg, i, n_workers)
        shards = [
            ModelShardSpec(
                id=ModelShardID(actor, host_rank=i, n_hosts=n_workers),
                model=C.model_abstraction(
                    cfg.actor, cfg.tokenizer_path,
                    mesh_spec=t_mesh, device_ids=t_devs,
                ),
                backend=C.backend_abstraction(cfg.actor, train=True),
                interface=ModelInterfaceAbstraction("ppo_actor", args=iface_args),
            ),
            ModelShardSpec(
                id=ModelShardID(rew, host_rank=i, n_hosts=n_workers),
                model=C.model_abstraction(cfg.actor, cfg.tokenizer_path),
                backend=ModelBackendAbstraction("mock_inference"),
                interface=ModelInterfaceAbstraction("rw-math-code"),
            ),
        ]
        if use_ref:
            ref_cfg = cfg.ref or cfg.actor
            shards.append(
                ModelShardSpec(
                    id=ModelShardID(ref, host_rank=i, n_hosts=n_workers),
                    model=C.model_abstraction(
                        ref_cfg, cfg.tokenizer_path,
                        mesh_spec=t_mesh, device_ids=t_devs,
                    ),
                    backend=C.backend_abstraction(ref_cfg, train=False),
                    interface=ModelInterfaceAbstraction(
                        "ppo_actor", args=iface_args
                    ),
                )
            )
        if use_critic:
            for replica in (0, 1):
                shards.append(
                    ModelShardSpec(
                        id=ModelShardID(
                            ModelName("critic", replica), host_rank=i, n_hosts=n_workers
                        ),
                        model=C.model_abstraction(
                            cfg.critic, cfg.tokenizer_path, is_critic=True,
                            mesh_spec=t_mesh, device_ids=t_devs,
                        ),
                        backend=C.backend_abstraction(
                            cfg.critic, train=(replica == 1)
                        ),
                        interface=ModelInterfaceAbstraction(
                            "ppo_critic", args=critic_interface_args(cfg)
                        ),
                    )
                )
        workers.append(C.base_model_worker(cfg, i, n_workers, shards))

    names = C.worker_names(n_workers)
    model_topos = {str(actor): names, str(rew): names}
    if use_ref:
        model_topos[str(ref)] = names
    if use_critic:
        model_topos[str(ModelName("critic", 0))] = names
        model_topos[str(ModelName("critic", 1))] = names
    master = C.base_master(cfg, rpcs, model_topos, n_workers)
    return ExperimentConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        master=master,
        model_workers=workers,
    )


register_experiment("ppo-math", build_ppo_math_experiment)

"""Packed variable-length batch contracts and the dataset registry.

Counterpart of the reference's data API (realhf/api/core/data_api.py):
`SequenceSample` is the universal exchange format between datasets, MFCs,
buffers and engines — every tensor is packed along a single leading
dimension with explicit per-sample sequence lengths, no padding. Padding
to static shapes (what XLA wants) happens at the last moment inside the
engines, with bucketed shapes to bound recompilation.

Host-side numpy throughout; engines convert to jnp on device entry.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from areal_tpu.base import datapack
from areal_tpu.api.config import DatasetAbstraction, Registry


@dataclasses.dataclass
class MicroBatchSpec:
    """How to split a batch into micro-batches.

    n_mbs: minimum number of micro-batches (DP ranks may sync to the max).
    max_tokens_per_mb: token budget per micro-batch (None = unbounded).
    """

    n_mbs: int = 1
    max_tokens_per_mb: Optional[int] = None

    @classmethod
    def new(cls, other: "MicroBatchSpec", **kwargs) -> "MicroBatchSpec":
        d = dataclasses.asdict(other)
        d.update(kwargs)
        return cls(**d)


@dataclasses.dataclass
class SequenceSample:
    """A batch of variable-length packed sequences.

    ids: unique sample identifiers (hashable strings).
    keys: the set of data keys present.
    data: key -> packed array of shape (sum(seqlens[key]), *trailing) or
        None for metadata-only (control-plane) samples.
    seqlens: key -> per-sample list of sequence lengths. A sample may hold
        several sequences under one key (e.g. grouped GRPO responses), hence
        the inner list.
    dtypes / trailing_shapes: per-key array metadata, kept even when data is
        None so receivers can preallocate.
    metadata: free-form per-batch lists (rewards, versions, ...), each value
        a list aligned with ids.
    """

    ids: List[str]
    keys: Set[str]
    data: Dict[str, Optional[np.ndarray]]
    seqlens: Dict[str, List[List[int]]]
    dtypes: Dict[str, Optional[np.dtype]] = dataclasses.field(default_factory=dict)
    trailing_shapes: Dict[str, Optional[Tuple[int, ...]]] = dataclasses.field(
        default_factory=dict
    )
    metadata: Dict[str, List[Any]] = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    def __post_init__(self):
        self.keys = set(self.keys)
        for k in self.keys:
            if k not in self.seqlens:
                raise ValueError(f"missing seqlens for key {k!r}")
            if len(self.seqlens[k]) != len(self.ids):
                raise ValueError(
                    f"seqlens[{k!r}] has {len(self.seqlens[k])} entries for "
                    f"{len(self.ids)} ids"
                )
            self.seqlens[k] = [[int(x) for x in sl] for sl in self.seqlens[k]]
            d = self.data.get(k)
            if d is not None:
                expected = sum(sum(sl) for sl in self.seqlens[k])
                if d.shape[0] != expected:
                    raise ValueError(
                        f"data[{k!r}] leading dim {d.shape[0]} != total seqlen {expected}"
                    )
                self.dtypes.setdefault(k, d.dtype)
                self.trailing_shapes.setdefault(k, tuple(d.shape[1:]))
            else:
                self.dtypes.setdefault(k, None)
                self.trailing_shapes.setdefault(k, None)
        for mk, mv in self.metadata.items():
            if not isinstance(mv, list) or len(mv) != len(self.ids):
                raise ValueError(
                    f"metadata[{mk!r}] must be a list aligned with ids "
                    f"({len(self.ids)}), got {mv!r}"
                )

    @classmethod
    def from_default(
        cls,
        ids: Sequence[str],
        seqlens: Sequence[int],
        data: Dict[str, np.ndarray],
        metadata: Optional[Dict[str, List[Any]]] = None,
    ) -> "SequenceSample":
        """All keys share one sequence per sample with the same lengths,
        except scalar-per-sequence keys (detected by data length == n_samples
        while total tokens differ)."""
        ids = [str(i) for i in ids]
        seqlens = [int(x) for x in seqlens]
        total = sum(seqlens)
        key_seqlens = {}
        for k, v in data.items():
            if v is None:
                key_seqlens[k] = [[l] for l in seqlens]
            elif v.shape[0] == total:
                key_seqlens[k] = [[l] for l in seqlens]
            elif v.shape[0] == len(ids):
                key_seqlens[k] = [[1] for _ in ids]
            else:
                raise ValueError(
                    f"cannot infer seqlens for key {k!r}: leading dim "
                    f"{v.shape[0]} is neither total tokens {total} nor batch {len(ids)}"
                )
        return cls(
            ids=ids,
            keys=set(data.keys()),
            data=dict(data),
            seqlens=key_seqlens,
            metadata=metadata or {},
        )

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    @property
    def bs(self) -> int:
        return len(self.ids)

    def sample_total_len(self, i: int, key: Optional[str] = None) -> int:
        key = key or self._main_key()
        return sum(self.seqlens[key][i])

    def _main_key(self) -> str:
        for k in ("packed_input_ids", "packed_prompts", "seq"):
            if k in self.keys:
                return k
        return sorted(self.keys)[0]

    def total_seqlen(self, key: Optional[str] = None) -> int:
        key = key or self._main_key()
        return sum(sum(sl) for sl in self.seqlens[key])

    def seqlens_of(self, key: Optional[str] = None) -> List[int]:
        """Per-sample total lengths under `key` (the packing weight)."""
        key = key or self._main_key()
        return [sum(sl) for sl in self.seqlens[key]]

    # ------------------------------------------------------------------
    # Gather / split
    # ------------------------------------------------------------------

    @classmethod
    def gather(
        cls, samples: Sequence["SequenceSample"], keys: Optional[Sequence[str]] = None
    ) -> "SequenceSample":
        if not samples:
            raise ValueError("cannot gather zero samples")
        keys = set(keys) if keys is not None else set(samples[0].keys)
        for s in samples:
            if not keys.issubset(s.keys):
                raise ValueError(f"sample missing keys {keys - s.keys}")
        ids = datapack.flat2d([s.ids for s in samples])
        if len(set(ids)) != len(ids):
            raise ValueError("duplicate ids in gathered samples")
        data = {}
        seqlens = {}
        dtypes = {}
        trailing = {}
        for k in keys:
            seqlens[k] = datapack.flat2d([s.seqlens[k] for s in samples])
            chunks = [s.data.get(k) for s in samples]
            if all(c is None for c in chunks):
                data[k] = None
            elif any(c is None for c in chunks):
                raise ValueError(f"mixed data/None for key {k!r} in gather")
            else:
                data[k] = np.concatenate(chunks, axis=0)
            dtypes[k] = samples[0].dtypes.get(k)
            trailing[k] = samples[0].trailing_shapes.get(k)
        metadata = {}
        meta_keys = set(itertools.chain.from_iterable(s.metadata for s in samples))
        for mk in meta_keys:
            vals = []
            for s in samples:
                if mk not in s.metadata:
                    # Mixed-stream batches (math + agentic episodes
                    # sharing one buffer) legally carry stream-specific
                    # metadata (turns/tool_calls vs task-only); pad the
                    # absent samples with None to keep the per-sample
                    # alignment — every consumer filters on isinstance.
                    vals.extend([None] * s.bs)
                else:
                    vals.extend(s.metadata[mk])
            metadata[mk] = vals
        return cls(
            ids=ids,
            keys=keys,
            data=data,
            seqlens=seqlens,
            dtypes=dtypes,
            trailing_shapes=trailing,
            metadata=metadata,
        )

    def _select_indices(self, indices: Sequence[int]) -> "SequenceSample":
        """New sample containing the given sample positions, in that order."""
        indices = list(indices)
        data = {}
        seqlens = {}
        for k in self.keys:
            seqlens[k] = [self.seqlens[k][i] for i in indices]
            d = self.data.get(k)
            if d is None:
                data[k] = None
                continue
            # Per-sample offsets into the packed dim.
            lens = [sum(sl) for sl in self.seqlens[k]]
            offsets = np.concatenate([[0], np.cumsum(lens)])
            data[k] = np.concatenate(
                [d[offsets[i] : offsets[i] + lens[i]] for i in indices], axis=0
            ) if indices else d[:0]
        return SequenceSample(
            ids=[self.ids[i] for i in indices],
            keys=set(self.keys),
            data=data,
            seqlens=seqlens,
            dtypes=dict(self.dtypes),
            trailing_shapes=dict(self.trailing_shapes),
            metadata={k: [v[i] for i in indices] for k, v in self.metadata.items()},
        )

    def select_ids(self, ids: Sequence[str]) -> "SequenceSample":
        pos = {i: p for p, i in enumerate(self.ids)}
        return self._select_indices([pos[i] for i in ids])

    def select_keys(self, keys: Sequence[str]) -> "SequenceSample":
        keys = set(keys)
        if not keys.issubset(self.keys):
            raise ValueError(f"missing keys: {keys - self.keys}")
        return SequenceSample(
            ids=list(self.ids),
            keys=keys,
            data={k: self.data.get(k) for k in keys},
            seqlens={k: self.seqlens[k] for k in keys},
            dtypes={k: self.dtypes.get(k) for k in keys},
            trailing_shapes={k: self.trailing_shapes.get(k) for k in keys},
            metadata=dict(self.metadata),
        )

    def split_with_partitions(
        self, partitions: Sequence[Sequence[int]]
    ) -> List["SequenceSample"]:
        return [self._select_indices(p) for p in partitions]

    def split(
        self, spec: MicroBatchSpec
    ) -> Tuple[List["SequenceSample"], List[int], List[int]]:
        """Token-budget micro-batch split (FFD bin packing).

        Returns (micro_batches, forward_indices, backward_indices):
        `forward_indices[j]` is the original position of the j-th sample in
        the concatenated micro-batch order; `backward_indices` inverts it,
        for `reorder_output`.
        """
        mb_iter, _, forward_indices, backward_indices = self.split_lazy(spec)
        return list(mb_iter), forward_indices, backward_indices

    def split_lazy(
        self, spec: MicroBatchSpec
    ) -> Tuple["Iterator[SequenceSample]", List[List[int]], List[int], List[int]]:
        """`split()` with lazily materialized micro-batches, for feeding a
        prefetch pipeline: the FFD plan (cheap — lengths only) is computed
        up front, but each micro-batch's packed-array copies happen only
        when the iterator yields it, so at most `prefetch depth` copies
        exist at once instead of all of them.

        Returns (mb_iterator, groups, forward_indices, backward_indices);
        `groups[j]` holds micro-batch j's sample indices, so callers can
        do per-mb pad-waste accounting (`datapack.packing_density` over
        the group's lengths) before the data is ever touched.
        """
        lens = self.seqlens_of()
        cap = spec.max_tokens_per_mb or int(np.sum(lens)) + 1
        groups = datapack.ffd_allocate(lens, capacity=cap, min_groups=spec.n_mbs)
        groups = [sorted(g) for g in groups]
        forward_indices = datapack.flat2d(groups)
        backward_indices = np.argsort(forward_indices).tolist()
        mb_iter = (self._select_indices(g) for g in groups)
        return mb_iter, groups, forward_indices, backward_indices

    @staticmethod
    def reorder_output(
        x: np.ndarray,
        mb_seqlens: Sequence[Sequence[int]],
        backward_indices: Sequence[int],
    ) -> np.ndarray:
        """Un-permute packed outputs concatenated over micro-batches.

        mb_seqlens: per-micro-batch per-sample total lengths, in mb order.
        """
        flat_lens = datapack.flat2d(mb_seqlens)
        offsets = np.concatenate([[0], np.cumsum(flat_lens)])
        chunks = [
            x[offsets[i] : offsets[i + 1]] for i in range(len(flat_lens))
        ]
        return np.concatenate([chunks[i] for i in backward_indices], axis=0)

    # ------------------------------------------------------------------
    # Mutation
    # ------------------------------------------------------------------

    def update_(self, other: "SequenceSample"):
        """Merge `other`'s keys into self (ids must match)."""
        if other.ids != self.ids:
            raise ValueError("update_ requires identical id order")
        for k in other.keys:
            self.keys.add(k)
            self.data[k] = other.data.get(k)
            self.seqlens[k] = other.seqlens[k]
            self.dtypes[k] = other.dtypes.get(k)
            self.trailing_shapes[k] = other.trailing_shapes.get(k)
        self.metadata.update(other.metadata)

    def remap_keys_(self, remap: Dict[str, str]):
        for src, dst in remap.items():
            if src not in self.keys:
                continue
            self.keys.discard(src)
            self.keys.add(dst)
            self.data[dst] = self.data.pop(src)
            self.seqlens[dst] = self.seqlens.pop(src)
            self.dtypes[dst] = self.dtypes.pop(src)
            self.trailing_shapes[dst] = self.trailing_shapes.pop(src)

    def meta(self) -> "SequenceSample":
        """Metadata-only copy (control-plane payloads carry no tensors)."""
        return SequenceSample(
            ids=list(self.ids),
            keys=set(self.keys),
            data={k: None for k in self.keys},
            seqlens={k: [list(sl) for sl in v] for k, v in self.seqlens.items()},
            dtypes=dict(self.dtypes),
            trailing_shapes=dict(self.trailing_shapes),
            metadata=dict(self.metadata),
        )

    def unpack(self) -> List["SequenceSample"]:
        return [self._select_indices([i]) for i in range(self.bs)]


# ---------------------------------------------------------------------------
# Dataset registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DatasetUtility:
    """Context handed to dataset constructors."""

    seed: int = 0
    dp_rank: int = 0
    world_size: int = 1
    tokenizer: Any = None


DATASET_REGISTRY = Registry("dataset")


def register_dataset(name: str, factory):
    DATASET_REGISTRY.register(name, factory)


def make_dataset(cfg: "DatasetAbstraction | str", util: DatasetUtility):
    return DATASET_REGISTRY.make(cfg, util=util)


def load_hf_tokenizer(path: str, fast: bool = True):
    import transformers

    tok = transformers.AutoTokenizer.from_pretrained(
        path, use_fast=fast, trust_remote_code=True
    )
    if tok.pad_token_id is None:
        tok.pad_token_id = tok.eos_token_id
    return tok


# ---------------------------------------------------------------------------
# Dataset loading helpers (counterpart of the reference data_api.py:747-792)
# ---------------------------------------------------------------------------

# Task vocabulary for RL datasets; indices are shipped as `task_ids`
# (reference data_api.py:47).
RL_TASKS = ["math", "code", "rlhf", "stem"]


def get_shuffle_indices(seed: int, size: int) -> np.ndarray:
    """Deterministic permutation used for dataset shuffling."""
    rng = np.random.RandomState(seed)
    return rng.permutation(size)


def load_shuffle_split_dataset(
    util: DatasetUtility,
    dataset_path: Optional[str] = None,
    dataset_builder: Optional[Any] = None,
) -> List[Dict[str, Any]]:
    """Load a jsonl dataset (or call a builder), assign missing ids,
    deterministically shuffle by `util.seed`, and return this DP rank's
    near-equal contiguous slice of the shuffled order (round-robin bin
    sizes so every rank gets data; reference data_api.py:754-792)."""
    import json

    if dataset_path is not None:
        if not str(dataset_path).endswith(".jsonl"):
            raise NotImplementedError(f"unknown dataset extension: {dataset_path}")
        with open(dataset_path, "r") as f:
            data = [json.loads(line) for line in f if line.strip()]
    else:
        assert dataset_builder is not None
        data = dataset_builder()

    if any("id" not in d for d in data):
        # Backfill with ids that cannot collide with explicit integer/str ids.
        for idx, d in enumerate(data):
            d.setdefault("id", f"__auto_{idx}")
    seen_ids = set()
    for d in data:
        sid = str(d["id"])
        if sid in seen_ids:
            raise ValueError(f"duplicate dataset id {sid!r}")
        seen_ids.add(sid)

    if len(data) < util.world_size:
        raise ValueError(
            f"dataset size {len(data)} smaller than DP world size {util.world_size}"
        )
    bins = np.zeros(util.world_size, dtype=np.int64)
    for idx in range(len(data)):
        bins[idx % util.world_size] += 1
    bounds = np.pad(np.cumsum(bins), (1, 0))
    shuffle = get_shuffle_indices(util.seed, len(data))
    subset = shuffle[bounds[util.dp_rank] : bounds[util.dp_rank + 1]]
    return [data[i] for i in subset]


class PackedDataLoader:
    """Minimal epoch-based loader over a map-style dataset of
    `SequenceSample`s: deterministic per-epoch shuffling, `SequenceSample.
    gather` collation, and an index cursor that can be checkpointed for
    exactly-once recovery (reference model_worker.py:374-385 snapshots the
    dataloader state the same way)."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True, seed: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self._cursor = 0
        self._order: Optional[np.ndarray] = None

    def _regen_order(self, n: int):
        self._order = (
            get_shuffle_indices(self.seed + self.epoch, n)
            if self.shuffle
            else np.arange(n)
        )

    def _ensure_order(self):
        n = len(self.dataset)
        if self._order is not None and len(self._order) != n:
            # The dataset changed size mid-epoch (curriculum filter): the old
            # permutation is invalid, so start a fresh epoch over the new set
            # rather than slicing past the end / repeating samples.
            self.epoch += 1
            self._cursor = 0
            self._order = None
        if self._order is None:
            self._regen_order(n)

    def __len__(self) -> int:
        return max(1, (len(self.dataset) + self.batch_size - 1) // self.batch_size)

    def next_batch(self) -> Tuple["SequenceSample", bool]:
        """Returns (batch, is_epoch_last). Advances epoch + reshuffles when
        the dataset is exhausted."""
        if len(self.dataset) == 0:
            raise RuntimeError("cannot draw a batch from an empty dataset")
        self._ensure_order()
        n = len(self._order)
        end = min(self._cursor + self.batch_size, n)
        idx = self._order[self._cursor : end]
        samples = [self.dataset[int(i)] for i in idx]
        batch = SequenceSample.gather(samples)
        self._cursor = end
        epoch_last = self._cursor >= n
        if epoch_last:
            self.epoch += 1
            self._cursor = 0
            self._order = None
        return batch, epoch_last

    def restart_epoch(self):
        """Rewind to the start of the current epoch (same permutation).

        Used on crash recovery: the epoch replays from the beginning and the
        master's ignore-list skips samples consumed before the checkpoint —
        restoring the mid-epoch cursor instead would make those skips land
        on the next epoch's legitimate deliveries.
        """
        self._cursor = 0

    def state_dict(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "cursor": self._cursor,
            "seed": self.seed,
            "size": len(self.dataset),
        }

    def load_state_dict(self, state: Dict[str, Any]):
        self.epoch = int(state["epoch"])
        self._cursor = int(state["cursor"])
        self.seed = int(state["seed"])
        n = len(self.dataset)
        if int(state.get("size", n)) != n:
            # Checkpoint taken against a different dataset size: the stored
            # cursor indexes a different permutation — restart the epoch.
            self._cursor = 0
        self._regen_order(n)


# ---------------------------------------------------------------------------
# JSON wire format (rollout -> trainer trajectories over the push stream)
# ---------------------------------------------------------------------------


def sample_to_json(s: "SequenceSample") -> Dict[str, Any]:
    """Lossless JSON encoding of a SequenceSample (token-scale arrays)."""
    return {
        "ids": list(s.ids),
        "keys": sorted(s.keys),
        "data": {
            k: (None if s.data.get(k) is None else np.asarray(s.data[k]).tolist())
            for k in s.keys
        },
        "seqlens": {k: s.seqlens[k] for k in s.keys},
        "dtypes": {
            k: (None if s.dtypes.get(k) is None else np.dtype(s.dtypes[k]).name)
            for k in s.keys
        },
        "trailing_shapes": {
            k: (None if s.trailing_shapes.get(k) is None else list(s.trailing_shapes[k]))
            for k in s.keys
        },
        "metadata": s.metadata,
    }


def sample_from_json(d: Dict[str, Any]) -> "SequenceSample":
    data = {}
    for k in d["keys"]:
        v = d["data"].get(k)
        if v is None:
            data[k] = None
        else:
            dt = d["dtypes"].get(k) or "float32"
            data[k] = np.asarray(v, dtype=np.dtype(dt))
    return SequenceSample(
        ids=list(d["ids"]),
        keys=set(d["keys"]),
        data=data,
        seqlens={k: [list(map(int, sl)) for sl in v] for k, v in d["seqlens"].items()},
        dtypes={
            k: (None if v is None else np.dtype(v))
            for k, v in d.get("dtypes", {}).items()
        },
        trailing_shapes={
            k: (None if v is None else tuple(v))
            for k, v in d.get("trailing_shapes", {}).items()
        },
        metadata=d.get("metadata", {}),
    )

"""Master-side per-MFC coroutine.

Counterpart of the reference's ModelFunctionCall
(realhf/system/model_function_call.py:54-509): acquire a batch from the
buffer once its input keys are ready, split it across the model's DP
workers (token-balanced FFD, or by sequence count for generation),
derive a data-transfer plan, ship requests with hooks, gather replies,
and amend the buffer with output metadata.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType, OffloadHook, ParamReallocHook, SaveHook, EvaluateHook
from areal_tpu.base import datapack, logging, stats_tracker, tracing
from areal_tpu.system import request_reply_stream as rrs
from areal_tpu.system.buffer import AsyncIOSequenceBuffer
from areal_tpu.system.redistributor import GlobalStorageTracker, RedistribPlanner

logger = logging.getLogger("mfc")


@dataclasses.dataclass
class RPCCorountineControl:
    """Shared step state (reference model_function_call.py:32)."""

    step_info: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {"epoch": 0, "epoch_step": 0, "global_step": 0}
    )
    train_stats: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Per-MFC stats for ALL interface types this step (perf telemetry:
    # timeperf/tflops per MFC, reference master_worker.py:497-533).
    mfc_stats: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    used_ids: set = dataclasses.field(default_factory=set)


async def async_poll(stream, request_id: str, timeout: Optional[float] = None):
    """Await one reply on a synchronous request client without blocking the
    event loop."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        try:
            return stream.poll(request_id, block=False)
        except rrs.NoMessage:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"no reply for {request_id}")
            await asyncio.sleep(0.002)


def _hook_dict(h) -> Dict:
    if isinstance(h, SaveHook):
        return {"type": "save"}
    if isinstance(h, EvaluateHook):
        return {"type": "evaluate"}
    if isinstance(h, OffloadHook):
        return {"type": "offload"}
    if isinstance(h, ParamReallocHook):
        return {
            "type": "param_realloc",
            "source": str(h.source) if h.source else None,
            "target": str(h.target) if h.target else None,
            "eta": h.eta,
        }
    if isinstance(h, dict):
        return h
    raise ValueError(f"unknown hook {h!r}")


class ModelFunctionCall:
    def __init__(
        self,
        rpc: MFCDef,
        stream,  # NameResolvingRequestClient
        buffer: AsyncIOSequenceBuffer,
        tracker: GlobalStorageTracker,
        planner: RedistribPlanner,
        workers: List[str],  # DP-ordered model worker names for rpc's model
        ctrl: RPCCorountineControl,
    ):
        self.rpc = rpc
        self.stream = stream
        self.buffer = buffer
        self.tracker = tracker
        self.planner = planner
        self.workers = workers
        self.ctrl = ctrl

    # ------------------------------------------------------------------

    def data_parallel_dispatch(self, ids: List[str], batch: SequenceSample):
        """Partition sample ids across DP workers.

        Generation balances by sequence count (decode steps dominate);
        everything else balances by token count via FFD bin packing
        (reference model_function_call.py:276-290).
        """
        n_dp = len(self.workers)
        if self.rpc.balanced_dp or self.rpc.interface_type == ModelInterfaceType.GENERATE:
            lens = [1] * len(ids)
        else:
            lens = [batch.sample_total_len(i) for i in range(batch.bs)]
        parts = datapack.balanced_partition(lens, n_dp)
        return [[ids[i] for i in p] for p in parts]

    async def run_step(self) -> Optional[Dict]:
        rpc = self.rpc
        ids, batch = await self.buffer.get_batch_for_rpc(rpc)
        self.ctrl.used_ids |= set(ids)

        # Master-side MFC span under the step's trace. A train MFC also
        # records which rollout traces it consumed, giving the merger the
        # rollout -> train-step flow links (capped: the attr is evidence,
        # not a database).
        consumed_traces: List[str] = []
        if tracing.enabled():
            for c in batch.metadata.get("trace_ctx") or []:
                if isinstance(c, dict) and c.get("trace_id"):
                    consumed_traces.append(str(c["trace_id"]))
            # Group sampling stamps bs copies of one episode ctx: dedup
            # (order-preserving) before the cap or duplicates eat it.
            consumed_traces = list(dict.fromkeys(consumed_traces))
        mfc_span = tracing.start_span(
            f"master.mfc.{rpc.name}",
            itype=rpc.interface_type.value,
            n_seqs=len(ids),
            **(
                {"consumed_traces": consumed_traces[:256]}
                if consumed_traces
                else {}
            ),
        )
        if mfc_span is not None:
            tracing.set_current(mfc_span.ctx)

        t0 = time.monotonic()
        # The try covers dispatch building and posting too: once
        # set_current is active, any posted request parents worker spans
        # under this span id — it must be recorded on EVERY exit path or
        # the validator sees a zero-drop dangling parent.
        try:
            assignments = self.data_parallel_dispatch(ids, batch)
            dests = {
                w: part for w, part in zip(self.workers, assignments) if part
            }
            plan = self.planner.derive_plan(dests, list(rpc.input_keys))

            handlers, datas, pre_hooks, post_hooks = [], [], [], []
            for w, part in dests.items():
                worker_steps = [
                    dataclasses.asdict(s) for s in plan if s.dst == w
                ]
                handlers.append(w)
                datas.append(
                    dict(
                        mfc_name=rpc.name,
                        model_name=str(rpc.model_name),
                        interface_type=rpc.interface_type.value,
                        ids=part,
                        input_keys=list(rpc.input_keys),
                        input_key_remap=dict(rpc.input_key_remap),
                        output_key_remap=dict(rpc.output_key_remap),
                        mb_spec=dataclasses.asdict(rpc.mb_spec),
                        plan=worker_steps,
                        step_info=dict(self.ctrl.step_info),
                    )
                )
                pre_hooks.append([_hook_dict(h) for h in rpc.pre_hooks])
                post_hooks.append([_hook_dict(h) for h in rpc.post_hooks])

            req_ids = self.stream.request(
                handlers,
                "mfc",
                datas,
                pre_hooks=pre_hooks,
                post_hooks=post_hooks,
            )
            t0 = time.monotonic()
            replies = await asyncio.gather(
                *[async_poll(self.stream, rid) for rid in req_ids]
            )
        finally:
            if mfc_span is not None:
                mfc_span.end()
        elapsed = time.monotonic() - t0

        # Collect outputs / stats.
        stats_list: List[Dict] = []
        out_metas: List[SequenceSample] = []
        for p in replies:
            if isinstance(p.data, dict) and p.data.get("error"):
                raise RuntimeError(
                    f"MFC {rpc.name} failed on {p.sender}: {p.data['error']}"
                )
            if p.data.get("output_meta") is not None:
                out_metas.append(p.data["output_meta"])
            if p.data.get("stats"):
                stats_list.append(p.data["stats"])
        stats = merge_worker_stats(stats_list)
        if rpc.interface_type == ModelInterfaceType.TRAIN_STEP:
            # Rollout-pipeline telemetry riding the consumed samples'
            # metadata (stamped by the rollout worker; absent on sync
            # runs): end-to-end episode latency percentiles and the
            # interruption re-prefill cost of this batch. Works with
            # tracing OFF — metadata is always stamped.
            e2e = [
                float(v)
                for v in batch.metadata.get("rollout_e2e_s") or []
                if isinstance(v, (int, float))
            ]
            if e2e:
                stats["perf/rollout_e2e_p50_ms"] = float(
                    np.percentile(e2e, 50) * 1e3
                )
                stats["perf/rollout_e2e_p95_ms"] = float(
                    np.percentile(e2e, 95) * 1e3
                )
            reprefill = [
                float(v)
                for v in batch.metadata.get("reprefill_tokens") or []
                if isinstance(v, (int, float))
            ]
            if reprefill:
                stats["perf/reprefill_tokens"] = float(np.sum(reprefill))
            turns = [
                int(v)
                for v in batch.metadata.get("turns") or []
                if isinstance(v, (int, float))
            ]
            if turns:
                stats["perf/episode_turns"] = float(np.mean(turns))
            tool_calls = [
                int(v)
                for v in batch.metadata.get("tool_calls") or []
                if isinstance(v, (int, float))
            ]
            if tool_calls:
                stats["perf/episode_tool_calls"] = float(np.mean(tool_calls))
            # Per-task staleness actually consumed this step: train-step
            # lag of each sample's version_end, split by its task tag, so
            # the tight math window and the loose agentic window are both
            # observable on the dashboard.
            tasks = batch.metadata.get("task") or []
            v_ends = batch.metadata.get("version_end") or []
            step = int(self.buffer.current_train_step)
            for tag, key in (
                ("math", "perf/task_staleness_math"),
                ("agentic", "perf/task_staleness_agentic"),
            ):
                lags = [
                    step - int(v)
                    for t, v in zip(tasks, v_ends)
                    if t == tag and isinstance(v, (int, float))
                ]
                if lags:
                    stats[key] = float(np.mean(lags))
            # Admission-side complement: per-task counts of samples the
            # buffer's staleness window DROPPED (mixed-stream runs
            # assert each window fires independently).
            for tag, key in (
                ("math", "perf/task_stale_dropped_math"),
                ("agentic", "perf/task_stale_dropped_agentic"),
            ):
                dropped = self.buffer.stale_dropped_by_task.get(tag, 0)
                if dropped:
                    stats[key] = float(dropped)
        # DP workers run concurrently: wall time is the max, flops add,
        # so MFC TFLOP/s is aggregate-over-workers per wall second.
        if stats.get("perf/flops") and stats.get("perf/sec"):
            stats["perf/tflops"] = stats["perf/flops"] / elapsed / 1e12
        if stats.get("perf/gen_tokens"):
            stats["perf/gen_tokens_per_sec"] = (
                stats["perf/gen_tokens"] / elapsed
            )
        stats["perf/elapsed"] = elapsed

        if out_metas:
            merged = SequenceSample.gather(out_metas)
            # Track new data locations.
            for p in replies:
                om = p.data.get("output_meta")
                if om is not None:
                    self.tracker.add_batch(list(om.ids), list(om.keys), p.sender)
            if not rpc.is_dst:
                await self.buffer.amend_batch(merged)

        logger.debug(
            f"MFC {rpc.name}: {len(ids)} seqs on {len(dests)} workers "
            f"in {elapsed:.3f}s"
        )
        self.ctrl.mfc_stats[rpc.name] = stats
        if rpc.interface_type == ModelInterfaceType.TRAIN_STEP:
            self.ctrl.train_stats[rpc.name] = stats
        return stats


# Reduce-type resolution for merging per-DP-worker stats: explicit types
# shipped by the worker (stats_tracker declared ReduceTypes) win; the
# suffix heuristic covers plain dicts.
_ADDITIVE_SUFFIXES = ("n_tokens", "n_mbs", "n_seqs", "count")
_ADDITIVE_KEYS = ("perf/flops", "perf/gen_tokens")
_MAX_KEYS = ("perf/sec",)


def merge_worker_stats(stats_list: List[Dict]) -> Dict[str, Any]:
    """Merge stats dicts from concurrent DP workers into one.

    Counterpart of the reference's cross-rank stats_tracker reduce
    (realhf/base/stats_tracker.py:105 reduces over the process group);
    here the master is the reduction point, so no collective is needed —
    multi-host runs reduce through the control plane. Workers may embed
    `__reduce_types__` (from stats_tracker.export(..., return_types=True))
    to pin per-key semantics.
    """
    stats: Dict[str, Any] = {}
    if not stats_list:
        return stats
    types: Dict[str, str] = {}
    for s in stats_list:
        types.update(s.get("__reduce_types__") or {})
    keys = [k for k in stats_list[0] if k != "__reduce_types__"]
    for k in keys:
        vals = [s[k] for s in stats_list if k in s and s[k] is not None]
        if not vals or not isinstance(vals[0], (int, float)):
            continue
        rt = types.get(k)
        if rt is None:
            if k in _MAX_KEYS:
                rt = "max"
            elif k in _ADDITIVE_KEYS or k.endswith(_ADDITIVE_SUFFIXES):
                rt = "sum"
            else:
                rt = "avg"
        if rt == "sum":
            stats[k] = float(np.sum(vals))
        elif rt == "min":
            stats[k] = float(np.min(vals))
        elif rt == "max":
            stats[k] = float(np.max(vals))
        else:
            stats[k] = float(np.mean(vals))
    return stats

"""Remote verifier-service client: batched async HTTP with retries.

Counterpart of the reference's remote functioncall client
(functioncall/base/call.py:81-240 — async_invoke_function with
exponential backoff, batch_function_call_async with a concurrency
semaphore, and the FUNCTIONCALL_SERVICE_DOMAIN switch in
math_rw_interface.py:37-39), built from scratch.

Service contract (same as the reference's verifier service): POST
`{domain}/{task}_verify` with a JSON list of payloads
`{"uid", "solution", "answer"/"test_cases"}`, response is a JSON list of
`{"uid", "success": bool}` in any order. A payload whose verification
ultimately fails (exhausted retries, malformed response) scores False —
a reward must never take the trainer down.

Enable by setting FUNCTIONCALL_SERVICE_DOMAIN (e.g.
"http://verifier.internal:8080"); when unset, `remote_enabled()` is
False and callers use the local verifiers.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging as areal_logging
from areal_tpu.base import rpc

logger = areal_logging.getLogger("functioncall.remote")

ENV_DOMAIN = "FUNCTIONCALL_SERVICE_DOMAIN"
DEFAULT_TIMEOUT_S = 60.0
MAX_RETRIES = 3
INITIAL_RETRY_S = 0.5
MAX_RETRY_S = 10.0
DEFAULT_CONCURRENCY = 256
DEFAULT_BATCH_SIZE = 64


def service_domain() -> Optional[str]:
    return os.environ.get(ENV_DOMAIN) or None


def remote_enabled() -> bool:
    return service_domain() is not None


async def _post_with_retries(
    session, url: str, batch: List[Dict], timeout_s: float
) -> List[Dict]:
    """One batch POST under the unified RPC policy (base/rpc.py):
    the substrate owns attempts/backoff/per-attempt timeout; the
    verifier keeps only its contract — every failure is retryable
    (a reward must never take the trainer down) and exhaustion scores
    the whole batch False via []."""
    import aiohttp

    async def attempt(attempt_timeout: float) -> List[Dict]:
        async with session.post(
            url, json=batch,
            timeout=aiohttp.ClientTimeout(total=attempt_timeout),
        ) as resp:
            if resp.status >= 500:
                raise OSError(f"server error {resp.status}")
            resp.raise_for_status()
            out = await resp.json()
            if not isinstance(out, list):
                raise ValueError(f"malformed response: {type(out)}")
            return out

    try:
        # No deadline on purpose: the historical contract grants every
        # attempt the FULL timeout_s with backoff sleeps on top (a
        # shared budget would silently shorten the last attempts) — a
        # reward verifier answers to the trainer's patience, not to a
        # propagated rollout budget.
        return await rpc.retry_async(
            attempt,
            policy=rpc.RetryPolicy(
                attempts=MAX_RETRIES + 1,
                backoff_base_s=INITIAL_RETRY_S,
                backoff_max_s=MAX_RETRY_S,
                attempt_timeout_s=timeout_s,
            ),
            retryable=(Exception,),
            what=f"verifier {url}",
        )
    except rpc.RpcError as e:
        logger.error(f"verifier batch failed permanently: {e!r}")
        return []


async def batch_verify_async(
    payloads: List[Dict[str, Any]],
    task: str,
    domain: Optional[str] = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
    concurrency: int = DEFAULT_CONCURRENCY,
    batch_size: int = DEFAULT_BATCH_SIZE,
) -> List[bool]:
    """Verify payloads against `{domain}/{task}_verify`, split into
    batches under a concurrency cap. Returns per-payload success aligned
    with the input order; failed/missing entries are False."""
    import aiohttp

    domain = domain or service_domain()
    assert domain, f"{ENV_DOMAIN} not configured"
    url = f"{domain.rstrip('/')}/{task}_verify"
    for i, p in enumerate(payloads):
        p.setdefault("uid", str(i))

    sem = asyncio.Semaphore(concurrency)
    results: Dict[str, bool] = {}

    async with aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit=concurrency)
    ) as session:

        async def one_batch(batch: List[Dict]):
            async with sem:
                out = await _post_with_retries(session, url, batch, timeout_s)
            for entry in out:
                if isinstance(entry, dict) and "uid" in entry:
                    results[str(entry["uid"])] = bool(entry.get("success"))

        batches = [
            payloads[i : i + batch_size]
            for i in range(0, len(payloads), batch_size)
        ]
        await asyncio.gather(*[one_batch(b) for b in batches])

    return [results.get(str(p["uid"]), False) for p in payloads]


def batch_verify(
    payloads: List[Dict[str, Any]],
    task: str,
    domain: Optional[str] = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
) -> List[bool]:
    """Sync wrapper (used from the reward interface's thread pool)."""
    return asyncio.run(
        batch_verify_async(payloads, task, domain=domain, timeout_s=timeout_s)
    )

"""Verification environments.

Counterpart of the reference's math-code environment
(realhf/impl/environment/math_code_single_step_env.py:75): a single-step
env whose action is (qid, answer_texts, task, answer_info) and whose
"observation" is the per-answer success list from the verifiers.

`ToolEnv` extends this to multi-turn tool-use episodes (docs/agentic.md):
tool actions (python exec through the pooled reward executor, calculator,
search stub) return observation TEXT mid-episode; the final answer action
grades like the single-step env.
"""

from __future__ import annotations

import ast
import asyncio
import json
import operator
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.api.env_api import EnvironmentService, register_environment
from areal_tpu.functioncall.code_verify import code_verify, run_one_case
from areal_tpu.functioncall.math_grader import grade_answer


class MathCodeSingleStepEnv(EnvironmentService):
    def __init__(self, max_workers: int = 8):
        self._pool = ThreadPoolExecutor(max_workers=max_workers)

    def _verify_one(self, task: str, text: str, answer_info: Any) -> bool:
        if task == "code":
            cases = answer_info
            if isinstance(cases, str):
                cases = json.loads(cases)
            return code_verify(text, cases)
        return grade_answer(text, answer_info)

    async def step(self, action) -> Tuple[Any, float, bool, bool, dict]:
        qid, answers, task, answer_info = action
        loop = asyncio.get_running_loop()
        successes: List[bool] = list(
            await asyncio.gather(
                *[
                    loop.run_in_executor(
                        self._pool, self._verify_one, task, a, answer_info
                    )
                    for a in answers
                ]
            )
        )
        return successes, 0.0, True, False, {}


register_environment("math-code-single-step", MathCodeSingleStepEnv)


# Safe arithmetic for the calculator tool: AST-walked, numbers and
# + - * / // % ** only — never eval() on model output.
_CALC_BIN = {
    ast.Add: operator.add, ast.Sub: operator.sub, ast.Mult: operator.mul,
    ast.Div: operator.truediv, ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod, ast.Pow: operator.pow,
}
_CALC_UNARY = {ast.USub: operator.neg, ast.UAdd: operator.pos}


def _calc_eval(expr: str) -> float:
    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)
        ):
            return node.value
        if isinstance(node, ast.BinOp) and type(node.op) in _CALC_BIN:
            left, right = ev(node.left), ev(node.right)
            if isinstance(node.op, ast.Pow) and abs(right) > 64:
                raise ValueError("exponent too large")
            return _CALC_BIN[type(node.op)](left, right)
        if isinstance(node, ast.UnaryOp) and type(node.op) in _CALC_UNARY:
            return _CALC_UNARY[type(node.op)](ev(node.operand))
        raise ValueError(f"unsupported expression node {type(node).__name__}")

    return ev(ast.parse(expr.strip(), mode="eval"))


class ToolEnv(EnvironmentService):
    """Multi-turn tool-use environment (docs/agentic.md).

    Two action shapes:

    - ``("tool", qid, tool_name, payload)`` — run one tool call; the
      observation is the tool's output TEXT the agent splices into the
      conversation. Episode continues (done=False).
    - ``("answer", qid, answer_texts, task, answer_info)`` — grade the
      final answer exactly like MathCodeSingleStepEnv; observation is
      the per-answer success list, done=True.

    The python tool routes through the pooled reward-executor service
    when one is registered and live (functioncall/remote.py) — warm
    sandboxes, no per-call interpreter fork — and degrades to the
    fork-per-call code_verify sandbox otherwise. A tool failure is an
    observation (the model sees the error text), never an exception:
    a broken tool call must not kill the episode.
    """

    def __init__(
        self,
        max_workers: int = 8,
        tool_timeout_s: float = 10.0,
        search_corpus: Optional[Dict[str, str]] = None,
    ):
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self.tool_timeout_s = tool_timeout_s
        self._search_corpus = dict(search_corpus or {})

    # -- tools (each: payload dict -> observation text, sync) ----------

    def _tool_python(self, payload: Dict[str, Any]) -> str:
        code = str(payload.get("code") or "")
        from areal_tpu.functioncall import remote

        pool = remote.get_executor_pool()
        if pool is not None and pool.available():
            res = pool.submit(
                [{"kind": "python", "code": code,
                  "stdin": str(payload.get("stdin") or "")}],
                timeout_s=self.tool_timeout_s,
            )[0]
            if res.get("ok"):
                return res.get("stdout", "")
            return (
                f"error: {res.get('stderr') or res.get('error') or 'failed'}"
            )
        ok, out, err = run_one_case(
            code, str(payload.get("stdin") or ""),
            timeout=self.tool_timeout_s,
        )
        return out if ok else f"error: {err}"

    def _tool_calculator(self, payload: Dict[str, Any]) -> str:
        try:
            return str(_calc_eval(str(payload.get("expr") or "")))
        except Exception as e:
            return f"error: {e}"

    def _tool_search(self, payload: Dict[str, Any]) -> str:
        # Deliberate stub: keyed lookup over an injected corpus — the
        # tool-call plumbing (turns, spans, latency) is what the system
        # exercises; a real retrieval backend plugs in here.
        query = str(payload.get("query") or "").strip().lower()
        for key, text in self._search_corpus.items():
            if key.lower() in query or query in key.lower():
                return text
        return "no results"

    def run_tool(self, name: str, payload: Dict[str, Any]) -> str:
        fn = getattr(self, f"_tool_{name}", None)
        if fn is None:
            return f"error: unknown tool {name!r}"
        try:
            return fn(payload)
        except Exception as e:  # tool crash -> observation, not abort
            return f"error: {e}"

    def _verify_one(self, task: str, text: str, answer_info: Any) -> bool:
        if task == "code":
            cases = answer_info
            if isinstance(cases, str):
                cases = json.loads(cases)
            return code_verify(text, cases)
        return grade_answer(text, answer_info)

    async def step(self, action) -> Tuple[Any, float, bool, bool, dict]:
        loop = asyncio.get_running_loop()
        if action and action[0] == "tool":
            _, _qid, name, payload = action
            # Blocking tool execution (pool HTTP round-trip or local
            # sandbox subprocess) off-loop: other live episodes keep
            # being serviced while this one waits on its tool.
            text = await loop.run_in_executor(
                self._pool, self.run_tool, name, payload or {}
            )
            return text, 0.0, False, False, {"tool": name}
        if action and action[0] == "answer":
            _, qid, answers, task, answer_info = action
        else:  # single-step compatibility shape
            qid, answers, task, answer_info = action
        successes: List[bool] = list(
            await asyncio.gather(
                *[
                    loop.run_in_executor(
                        self._pool, self._verify_one, task, a, answer_info
                    )
                    for a in answers
                ]
            )
        )
        return successes, 0.0, True, False, {}


register_environment("tool-use", ToolEnv)

"""Offline math evaluation harness.

Counterpart of the reference's evaluation/math_eval.py: load a saved
checkpoint, greedy/sampled generation over a benchmark jsonl
(prompt + solutions rows), grade with the math verifier, write
results.json with pass@1-style accuracy. Invoked standalone or by the
AutomaticEvaluator per saved checkpoint.

Usage:
    python evaluation/math_eval.py ckpt=/save/actor/step10/dp0 \
        data=/data/aime24.jsonl benchmark=aime24 output=/tmp/results.json
    # benchmark= selects a preset (aime24/aime25/amc23/math500/gsm8k:
    # field mapping + prompt template + few-shot demos + sampling
    # defaults, evaluation/presets.py); prompt_type=/num_shots=/
    # n_samples=/max_new_tokens= override it. Without benchmark=, rows
    # are the repo's prompt/solutions schema taken verbatim.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Eval jobs are schedulable onto CPU workers: honor JAX_PLATFORMS before
# any device use (utils/jaxenv.py explains the early-import dance).
from areal_tpu.utils.jaxenv import apply_jax_platform_override

apply_jax_platform_override()

import numpy as np


def evaluate_checkpoint(
    ckpt: str,
    data: str,
    output: str = "",
    benchmark: str = "",
    prompt_type: str = "",
    num_shots: int = -1,
    max_new_tokens: int = 0,
    greedy: bool = True,
    # None = take the preset's (or 1.0); 0.0 is a VALID explicit value
    # (temperature-0 sampling), not a sentinel.
    temperature: Optional[float] = None,
    n_samples: int = 0,
    max_prompts: int = 0,
    seed: int = 1,
    answer_mode: str = "text",
) -> dict:
    """benchmark= selects a preset (aime24/aime25/amc23/math500/gsm8k,
    see evaluation/presets.py) carrying the field mapping, prompt
    template, few-shot count, and sampling defaults; prompt_type=,
    num_shots=, max_new_tokens=, n_samples= override it. Without
    benchmark=, rows use the repo's prompt/solutions schema with the
    prompt taken verbatim (the pre-round-5 behavior).

    answer_mode='text' extracts the answer from the generated text
    (boxed / "answer is" / last number); answer_mode='python' executes
    the generated program in a sandboxed subprocess and grades its
    output (PAL style; pairs with prompt_type='pal')."""
    import jax

    from areal_tpu.api import data_api
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.functioncall.math_grader import (
        extract_answer,
        grade_answer,
        normalize_answer,
    )
    from areal_tpu.models.generation import generate_tokens
    from areal_tpu.models.hf import load_hf_model

    from evaluation.presets import (
        BENCHMARKS, PROMPT_TEMPLATES, build_prompt, load_benchmark,
    )

    # Validate EVERYTHING and build the prompt rows BEFORE the (multi-GB)
    # checkpoint load: a typo'd benchmark/prompt_type or an over-asked
    # num_shots should fail instantly, not after minutes of loading.
    if benchmark and benchmark not in BENCHMARKS:
        raise ValueError(
            f"unknown benchmark {benchmark!r}; available: "
            f"{sorted(BENCHMARKS)}"
        )
    if answer_mode not in ("text", "python"):
        raise ValueError(
            f"answer_mode must be 'text' or 'python', got {answer_mode!r}"
        )
    preset = BENCHMARKS[benchmark] if benchmark else None
    if preset is not None:
        # Explicit args override the preset's defaults.
        prompt_type = prompt_type or preset.prompt_type
        num_shots = preset.num_shots if num_shots < 0 else num_shots
        max_new_tokens = max_new_tokens or preset.max_new_tokens
        n_samples = n_samples or preset.n_samples
        if temperature is None:
            temperature = preset.temperature
        if n_samples > 1:
            greedy = False  # pass@k/maj@k need sample diversity
        if prompt_type not in PROMPT_TEMPLATES:
            raise ValueError(
                f"unknown prompt_type {prompt_type!r}; available: "
                f"{sorted(PROMPT_TEMPLATES)}"
            )
        # (num_shots bounds are enforced by build_prompt below, which
        # also runs before the checkpoint load.)
        bench_rows = load_benchmark(data, preset)
        if max_prompts:
            bench_rows = bench_rows[:max_prompts]
        rows = [
            # gt may already be a list (e.g. a 'solutions' field):
            # wrapping it again would make grade_answer compare against
            # the list's repr and score everything wrong.
            {"query_id": r["query_id"],
             "solutions": (r["gt"] if isinstance(r["gt"], (list, tuple))
                           else [r["gt"]]),
             "prompt": build_prompt(r["question"], prompt_type, num_shots)}
            for r in bench_rows
        ]
    else:
        # No preset = prompts taken verbatim; prompt args would be
        # silently ignored, so refuse them rather than record a
        # methodology that never ran.
        if prompt_type or num_shots >= 0:
            raise ValueError(
                "prompt_type=/num_shots= require benchmark=<preset>; "
                "without one, prompts are used verbatim (the 'generic' "
                "preset wraps prompt/solutions rows in the boxed "
                "template)"
            )
        max_new_tokens = max_new_tokens or 512
        n_samples = n_samples or 1
        if temperature is None:
            temperature = 1.0
        with open(data) as f:
            rows = [json.loads(l) for l in f if l.strip()]
        if max_prompts:
            rows = rows[:max_prompts]

    cfg, params = load_hf_model(ckpt)
    tokenizer = data_api.load_hf_tokenizer(ckpt)

    g = GenerationHyperparameters(
        max_new_tokens=max_new_tokens, greedy=greedy, temperature=temperature
    )
    prompts = [tokenizer(r["prompt"])["input_ids"] for r in rows]

    n_correct, per_prompt = 0, []
    # Per-prompt sample records for multi-sample metrics (pass@k +
    # majority vote, reference evaluation/rm_maj_eval.py).
    by_prompt: dict = {}
    batch = 8
    for s in range(n_samples):
        rng = jax.random.PRNGKey(seed + s)
        for i in range(0, len(prompts), batch):
            chunk = prompts[i : i + batch]
            outs = generate_tokens(
                params, cfg, chunk, g, jax.random.fold_in(rng, i),
                eos_token_id=tokenizer.eos_token_id,
            )
            texts = [tokenizer.decode(o["output_ids"]) for o in outs]
            if answer_mode == "python":
                # PAL: run each generated program ONCE in its sandbox
                # subprocess; the executed output is graded AND is the
                # vote for maj@k. Candidates run concurrently — each
                # non-terminating program burns its full timeout, and
                # serializing those would dominate eval wall-clock.
                from concurrent.futures import ThreadPoolExecutor

                from areal_tpu.functioncall.python_answer import (
                    compare_python_answer,
                    execute_python_answer,
                )

                with ThreadPoolExecutor(max_workers=len(texts)) as pool:
                    answers = list(pool.map(execute_python_answer, texts))
            else:
                answers = [None] * len(texts)
            for j, text in enumerate(texts):
                row = rows[i + j]
                refs = row.get("solutions") or row.get("answers")
                if answer_mode == "python":
                    ans = answers[j]
                    ok = compare_python_answer(ans, refs)
                else:
                    ok = grade_answer(text, refs)
                    ans = extract_answer(text)
                n_correct += bool(ok)
                qid = str(row.get("query_id", i + j))
                per_prompt.append({"query_id": qid, "correct": bool(ok)})
                by_prompt.setdefault(qid, []).append(
                    (normalize_answer(ans) if ans else None, bool(ok))
                )

    total = len(prompts) * n_samples
    result = {
        "ckpt": ckpt,
        "data": data,
        "benchmark": benchmark or "none",
        "prompt_type": prompt_type or "verbatim",
        "answer_mode": answer_mode,
        "num_shots": max(0, num_shots),
        "n_prompts": len(prompts),
        "n_samples": n_samples,
        "accuracy": n_correct / max(1, total),
        "details": per_prompt,
    }
    if n_samples > 1:
        # pass@k: any sample correct; maj@k: the most common extracted
        # answer is correct (unextractable answers never win the vote).
        from collections import Counter

        pass_k = maj_k = 0
        for samples in by_prompt.values():
            pass_k += any(ok for _, ok in samples)
            counts = Counter(a for a, _ in samples if a is not None)
            if counts:
                top_ans, _ = counts.most_common(1)[0]
                maj_k += any(ok for a, ok in samples if a == top_ans)
        result["pass_at_k"] = pass_k / max(1, len(by_prompt))
        result["maj_at_k"] = maj_k / max(1, len(by_prompt))
    if output:
        os.makedirs(os.path.dirname(output) or ".", exist_ok=True)
        with open(output, "w") as f:
            json.dump(result, f)
    print(json.dumps({k: v for k, v in result.items() if k != "details"}))
    return result


if __name__ == "__main__":
    kwargs = {}
    for arg in sys.argv[1:]:
        k, v = arg.split("=", 1)
        if k in ("max_new_tokens", "n_samples", "max_prompts", "seed",
                 "num_shots"):
            v = int(v)
        elif k in ("greedy",):
            v = v.lower() in ("1", "true")
        elif k in ("temperature",):
            v = float(v)
        kwargs[k] = v
    evaluate_checkpoint(**kwargs)

"""Math grader tests (mirrors reference tests/reward/test_math_reward.py)."""

import pytest

from areal_tpu.functioncall.math_grader import (
    answers_equal,
    extract_answer,
    extract_boxed,
    grade_answer,
    normalize_answer,
)


def test_extract_boxed_nested():
    assert extract_boxed(r"so \boxed{\frac{1}{2}} is it") == r"\frac{1}{2}"
    assert extract_boxed(r"a \boxed{1} then \boxed{2}") == "2"
    assert extract_boxed("no box") is None


def test_extract_answer_fallbacks():
    assert extract_answer("The answer is 42.") == "42"
    assert extract_answer("blah 3 then 7 end") == "7"
    assert extract_answer("") is None


@pytest.mark.parametrize(
    "a,b",
    [
        ("42", "42"),
        (r"\frac{1}{2}", "0.5"),
        (r"\frac{1}{2}", "1/2"),
        ("1,234", "1234"),
        (r"2\pi", "2pi"),
        (r"\sqrt{2}", "sqrt(2)"),
        ("0.50", "1/2"),
        (r"\text{east}", "east"),
        ("(1, 2)", "(1,2)"),
        ("-1/3", r"-\frac{1}{3}"),
    ],
)
def test_answers_equal(a, b):
    assert answers_equal(a, b)


@pytest.mark.parametrize("a,b", [("42", "43"), ("1/2", "1/3"), ("x+1", "x+2")])
def test_answers_not_equal(a, b):
    assert not answers_equal(a, b)


def test_sympy_equivalence():
    assert answers_equal("2*(x+1)", "2x+2")
    assert answers_equal(r"\frac{x^2-1}{x-1}", "x+1")


def test_grade_answer_end_to_end():
    sol = r"We compute ... therefore the result is $\boxed{\dfrac{3}{4}}$."
    assert grade_answer(sol, "0.75")
    assert grade_answer(sol, "3/4")
    assert not grade_answer(sol, "0.8")
    assert not grade_answer("no final answer here", "5") or True  # must not crash


def test_grade_multiple_refs():
    assert grade_answer(r"\boxed{2}", ["1", "2"])

"""Child trainer process for the kill-anywhere durable-plane e2e.

One incarnation of a minimal-but-REAL training data plane: a
PullerStreamDataset (ZMQ pull + rollout WAL) feeding an
AsyncIOSequenceBuffer (exactly-once seq ledger) feeding a trivially
verifiable "training" step — a fold over the integer encoded in each
sample id. Checkpoints go through the real engine-checkpoint machinery
(`save_engine_state` manifest commit, async writer when
AREAL_CKPT_ASYNC), each barrier into a fresh version directory, with
the ledger snapshot riding `dataset_cursors` so fold state and
consumed-cut commit ATOMICALLY (one manifest rename covers both).

The parent arms AREAL_FAULTS `die` actions at the declared points and
SIGKILL-respawns this process until a clean run completes; because the
fold is exact integer arithmetic, "every sample trained exactly once"
is a single equality at the end — any lost or duplicated sample across
any kill shifts the sum.

Run: python tests/system/durable_harness.py '<json spec>'
Spec keys: nr_root, exp, trial, ckpt_root, recover_root, progress_path,
result_path, n_total, batch, ckpt_every.
"""

import json
import os
import sys


class FoldEngine:
    """The smallest engine the checkpoint path accepts: params is the
    fold accumulator [sum, count], REPLACED (never mutated) per step so
    async snapshot references stay crash-consistent."""

    def __init__(self):
        import numpy as np

        self.params = {"fold": np.zeros(2, dtype=np.float64)}
        self.opt_state = None
        self.version = 0

    def set_params(self, params):
        self.params = params

    def fold(self, values):
        import numpy as np

        f = self.params["fold"]
        self.params = {
            "fold": np.array(
                [f[0] + sum(values), f[1] + len(values)], dtype=np.float64
            )
        }


def latest_committed(ckpt_root):
    """Newest version directory with a COMMITTED manifest — a kill
    mid-save leaves a manifest-less directory recovery must skip."""
    from areal_tpu.engine.checkpoint import load_manifest

    if not os.path.isdir(ckpt_root):
        return None, None
    for step in sorted(
        (d for d in os.listdir(ckpt_root) if d.isdigit()),
        key=int, reverse=True,
    ):
        d = os.path.join(ckpt_root, step)
        man = load_manifest(d)
        if man is not None:
            return d, man
    return None, None


def run(spec):
    import asyncio

    from areal_tpu.api.config import ModelName
    from areal_tpu.api.dfg import MFCDef, ModelInterfaceType, build_graph
    from areal_tpu.base import constants, name_resolve, recover
    from areal_tpu.base.recover import RecoverInfo, StepInfo
    from areal_tpu.engine import checkpoint
    from areal_tpu.system.buffer import AsyncIOSequenceBuffer
    from areal_tpu.system.stream_dataset import PullerStreamDataset
    from areal_tpu.system.wal import SeqLedger

    name_resolve.reconfigure("nfs", record_root=spec["nr_root"])
    constants.RECOVER_ROOT = spec["recover_root"]
    exp, trial = spec["exp"], spec["trial"]
    ckpt_root = spec["ckpt_root"]
    progress = open(spec["progress_path"], "a")

    def log(event, **kw):
        progress.write(json.dumps({"event": event, **kw}) + "\n")
        progress.flush()

    train = MFCDef(
        name="train",
        model_name=ModelName("actor", 0),
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=spec["batch"],
        input_keys=("packed_prompts",),
        output_keys=(),
    )
    build_graph([train])

    eng = FoldEngine()
    buf = AsyncIOSequenceBuffer([train])

    # -- recovery: the committed manifest is the single source of truth
    # for BOTH fold state and the consumed-seq cut.
    ckpt_dir, man = latest_committed(ckpt_root)
    if ckpt_dir is not None:
        checkpoint.load_engine_state(eng, ckpt_dir)
        cursors = man.get("dataset_cursors") or {}
        buf.seed_consumed_seqs(cursors.get("consumed_seqs"))

    # Constructing the dataset replays the WAL (admission dedup against
    # the seeded ledger makes over-replay harmless).
    ds = PullerStreamDataset(exp, trial, puller_index=0)
    log("resume", version=eng.version,
        count=int(eng.params["fold"][1]),
        replayed=ds.counters["areal:train_wal_replayed_total"])

    def barrier():
        eng.version += 1
        snap = buf.consumed_seqs()
        d = os.path.join(ckpt_root, str(eng.version))
        # One atomic commit point (the manifest rename) covers fold
        # state AND the ledger cut it was taken at.
        checkpoint.save_engine_state(
            eng, d, dataset_cursors={"consumed_seqs": snap}
        )
        # The recover record rides the same snapshot (master-worker
        # parity: test asserts it stays loadable + schema-versioned).
        recover.dump(
            RecoverInfo(
                last_step_info=StepInfo(global_step=eng.version),
                consumed_seqs=snap,
            ),
            exp, trial,
        )
        # WAL truncation must never LEAD durable state: compact against
        # the newest manifest actually committed on disk (with the
        # async writer that can lag the snapshot just taken — safe, GC
        # only).
        _, committed = latest_committed(ckpt_root)
        dropped = 0
        if committed is not None:
            cur = committed.get("dataset_cursors") or {}
            dropped = ds.compact_wal(
                SeqLedger.from_dict(cur.get("consumed_seqs"))
            )
        log("barrier", version=eng.version,
            count=int(eng.params["fold"][1]),
            wal_dropped=dropped,
            dup=buf.counters["areal:train_samples_duplicated_total"])

    async def train_loop():
        steps = 0
        while int(eng.params["fold"][1]) < spec["n_total"]:
            batch = ds.poll_batch(max_samples=spec["batch"] * 2)
            if batch is not None:
                await buf.put_batch([batch])
            if await buf.poll_ready_count(train) >= train.n_seqs:
                ids, _ = await buf.get_batch_for_rpc(train)
                eng.fold([int(i[1:]) for i in ids])  # ids are "s<int>"
                steps += 1
                if steps % spec["ckpt_every"] == 0:
                    barrier()
            else:
                await asyncio.sleep(0.01)
        barrier()  # the final cut
        checkpoint.wait_pending_writes(timeout=60)

    asyncio.run(train_loop())
    # Give the WAL's deferred acks one idle cycle to flush, then report.
    result = {
        "fold_sum": float(eng.params["fold"][0]),
        "count": int(eng.params["fold"][1]),
        "version": eng.version,
        "replayed": ds.counters["areal:train_wal_replayed_total"],
        "stream_dup_dropped": ds.counters["areal:train_wal_dup_dropped_total"],
        "ledger_filtered": buf.n_ledger_filtered,
        "duplicated_total": buf.counters["areal:train_samples_duplicated_total"],
    }
    tmp = spec["result_path"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, spec["result_path"])
    log("done", **result)
    ds.close()
    progress.close()


if __name__ == "__main__":
    run(json.loads(sys.argv[1]))

"""areal_tpu — a TPU-native asynchronous RL training framework for LLMs.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of AReaL
(reference: /root/reference, surveyed in SURVEY.md): asynchronous rollout
with staleness control, decoupled-PPO training under GSPMD/pjit on TPU
meshes, an MFC dataflow runtime with a metadata-only control plane, an
interruptible JAX generation server, HF checkpoint conversion, and
fault-tolerant recovery.
"""

__version__ = "0.1.0"

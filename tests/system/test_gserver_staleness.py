"""Staleness accounting across a gserver-manager restart (VERDICT r3
weak #7): after a restart `rollout_stat.submitted` resets to 0, so the
gate must reach the same decision from the KV `training_samples` counter
alone (the reference resumes version/statistics explicitly,
realhf/system/gserver_manager.py:74-93; here the KV service carries the
durable count)."""

import pytest

from areal_tpu.api.system_api import GserverManagerConfig
from areal_tpu.base import name_resolve, names
from areal_tpu.system.gserver_manager import GserverManager, RolloutStat


@pytest.fixture()
def kv(tmp_path):
    name_resolve.reconfigure(
        backend="nfs", record_root=str(tmp_path / "name_resolve")
    )
    yield
    name_resolve.reconfigure(backend="memory")


def _manager(exp, trial, weight_version, submitted, offpolicyness=2, tbs=8):
    m = GserverManager.__new__(GserverManager)
    m.cfg = GserverManagerConfig(
        experiment_name=exp,
        trial_name=trial,
        train_batch_size=tbs,
        max_head_offpolicyness=offpolicyness,
    )
    m.weight_version = weight_version
    m.rollout_stat = RolloutStat()
    m.rollout_stat.submitted = submitted
    # is_staled() reads a snapshot the poll thread maintains (the
    # name_resolve read is file I/O and must stay off the HTTP loop —
    # areal-lint blocking-async); _configure primes it the same way
    # before the HTTP server starts serving /allocate_rollout.
    m._training_samples_cache = 0
    m._refresh_training_samples()
    return m


def _set_training_samples(exp, trial, n):
    name_resolve.add(
        names.training_samples(exp, trial), str(n), replace=True
    )


def test_restart_reaches_same_decision(kv):
    """Pre-restart (submitted mirrors KV) and post-restart (submitted=0)
    managers agree for every weight version."""
    exp, trial = "stale-restart", "t0"
    _set_training_samples(exp, trial, 64)
    for wv in range(0, 12):
        before = _manager(exp, trial, wv, submitted=64)
        after = _manager(exp, trial, wv, submitted=0)
        assert before.is_staled() == after.is_staled(), f"wv={wv}"
    # Sanity on the boundary itself: 64/8 = version 8, offpolicyness 2.
    assert _manager(exp, trial, 5, 0).is_staled()
    assert not _manager(exp, trial, 6, 0).is_staled()


def test_restart_before_any_training(kv):
    """No KV entry yet (restart before the first train step publishes):
    the gate must allow rollouts, like a fresh start."""
    exp, trial = "stale-fresh", "t0"
    assert not _manager(exp, trial, 0, submitted=0).is_staled()


def test_submitted_ahead_of_kv_still_counts(kv):
    """In-flight rollouts of THIS incarnation (submitted > trained) keep
    gating: max(KV, submitted) preserves the reference's semantics where
    submitted alone drives the gate."""
    exp, trial = "stale-ahead", "t0"
    _set_training_samples(exp, trial, 8)
    m = _manager(exp, trial, 0, submitted=40, offpolicyness=2)
    assert m.is_staled()  # expected version 5 vs weight 0, off by > 2
    m2 = _manager(exp, trial, 3, submitted=40, offpolicyness=2)
    assert not m2.is_staled()


def test_corrupt_kv_value_falls_back(kv):
    exp, trial = "stale-corrupt", "t0"
    name_resolve.add(
        names.training_samples(exp, trial), "not-a-number", replace=True
    )
    assert not _manager(exp, trial, 0, submitted=0).is_staled()

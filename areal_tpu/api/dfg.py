"""The dataflow graph of Model Function Calls (MFCs).

Counterpart of the reference's DFG module (realhf/api/core/dfg.py). An
experiment is a small DAG of MFCs — e.g. PPO: actor.generate →
{rew.inference, ref.inference, critic.inference} → {actor.train_step,
critic.train_step} — whose edges are induced by key production/consumption.
The master worker traverses this graph once per training step; data
dependencies are resolved through the sequence buffer, so the graph here
only needs parents/children and hook metadata.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Set, Tuple

from areal_tpu.api.config import ModelAbstraction, ModelFamily, ModelInterfaceAbstraction, ModelName
from areal_tpu.api.data_api import MicroBatchSpec


class ModelInterfaceType(enum.Enum):
    GENERATE = "generate"
    TRAIN_STEP = "train_step"
    INFERENCE = "inference"
    EVALUATE = "evaluate"


@dataclasses.dataclass
class OffloadHook:
    """Move params to host memory after the MFC (TPU: device→host DMA)."""


@dataclasses.dataclass
class ParamReallocHook:
    """Resharding weights from/to another model replica around an MFC."""

    source: Optional[ModelName] = None
    target: Optional[ModelName] = None
    eta: float = 1.0  # EMA coefficient: new = eta * src + (1 - eta) * dst


@dataclasses.dataclass
class SaveHook:
    pass


@dataclasses.dataclass
class EvaluateHook:
    pass


@dataclasses.dataclass
class MFCDef:
    """One model function call in the dataflow graph.

    name: unique MFC name (e.g. 'actor_gen', 'actor_train').
    model_name: which model replica executes it.
    interface_type/interface_impl: what to run and with which algorithm
        implementation (resolved via the interface registry).
    n_seqs: how many sequences this MFC consumes per step (the train batch
        size for the root MFCs).
    input_keys/output_keys: data keys consumed/produced; edges of the DFG
        are derived from these.
    input_key_remap/output_key_remap: rename keys on the way in/out.
    mb_spec: micro-batch splitting spec for this call.
    balanced_dp: split the batch across DP groups by equal sequence count
        rather than token count (generation dispatch).
    min_n_seqs_per_pass: require at least this many seqs per model pass
        (e.g. PPO minibatching: n_seqs / n_mbs per update).
    """

    name: str
    model_name: ModelName
    interface_type: ModelInterfaceType
    interface_impl: Any
    n_seqs: int = 1
    input_keys: Tuple[str, ...] = ()
    output_keys: Tuple[str, ...] = ()
    input_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)
    output_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    balanced_dp: bool = False
    log_return_value: bool = False
    min_n_seqs_per_pass: float = 1
    model_type: Optional[ModelFamily] = None
    model_path: Optional[str] = None
    pre_hooks: List[Any] = dataclasses.field(default_factory=list)
    post_hooks: List[Any] = dataclasses.field(default_factory=list)

    # Filled by build_graph:
    _parents: List[str] = dataclasses.field(default_factory=list)
    _children: List[str] = dataclasses.field(default_factory=list)
    _G: Optional["DFGraph"] = None

    def __post_init__(self):
        self.input_keys = tuple(self.input_keys)
        self.output_keys = tuple(self.output_keys)

    @property
    def role(self) -> str:
        return self.model_name.role

    @property
    def is_src(self) -> bool:
        return not self._parents

    @property
    def is_dst(self) -> bool:
        return not self._children

    @property
    def parents(self) -> List[str]:
        return list(self._parents)

    @property
    def children(self) -> List[str]:
        return list(self._children)

    def produced_key(self, key: str) -> str:
        """External name of an output key after remapping."""
        return self.output_key_remap.get(key, key)

    def add_pre_hook(self, hook):
        self.pre_hooks.append(hook)

    def add_post_hook(self, hook):
        self.post_hooks.append(hook)

    def __repr__(self):
        return f"MFCDef({self.name}, {self.interface_type.value}@{self.model_name})"


@dataclasses.dataclass
class DFGraph:
    rpcs: Dict[str, MFCDef]
    # key -> producing MFC name (None if supplied by the dataset)
    producers: Dict[str, Optional[str]]
    topo_order: List[List[str]]  # levels of the DAG

    def topological_levels(self) -> List[List[MFCDef]]:
        return [[self.rpcs[n] for n in level] for level in self.topo_order]

    @property
    def data_keys(self) -> Set[str]:
        """Keys that must come from the dataset (no MFC produces them)."""
        return {k for k, p in self.producers.items() if p is None}


def build_graph(rpcs: List[MFCDef], verbose: bool = False) -> DFGraph:
    """Wire parents/children from key production/consumption and
    topologically sort. Raises on duplicate producers or cycles."""
    by_name = {r.name: r for r in rpcs}
    if len(by_name) != len(rpcs):
        raise ValueError("duplicate MFC names")

    produced: Dict[str, str] = {}
    for r in rpcs:
        for k in r.output_keys:
            ext = r.produced_key(k)
            if ext in produced:
                raise ValueError(
                    f"key {ext!r} produced by both {produced[ext]} and {r.name}"
                )
            produced[ext] = r.name

    producers: Dict[str, Optional[str]] = {}
    for r in rpcs:
        r._parents.clear()
        r._children.clear()
    for r in rpcs:
        for k in r.input_keys:
            src = produced.get(k)
            producers.setdefault(k, src)
            if src is not None and src != r.name:
                if src not in r._parents:
                    r._parents.append(src)
                if r.name not in by_name[src]._children:
                    by_name[src]._children.append(r.name)
    for k, src in produced.items():
        producers.setdefault(k, src)

    # Kahn levels.
    indeg = {r.name: len(r._parents) for r in rpcs}
    levels: List[List[str]] = []
    remaining = set(by_name)
    frontier = sorted([n for n in remaining if indeg[n] == 0])
    while frontier:
        levels.append(frontier)
        remaining -= set(frontier)
        nxt = []
        for n in frontier:
            for c in by_name[n]._children:
                indeg[c] -= 1
                if indeg[c] == 0:
                    nxt.append(c)
        frontier = sorted(set(nxt))
    if remaining:
        raise ValueError(f"cycle in MFC graph involving: {sorted(remaining)}")

    g = DFGraph(rpcs=by_name, producers=producers, topo_order=levels)
    for r in rpcs:
        r._G = g
    return g

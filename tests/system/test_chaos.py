"""Chaos suite: fault-domain isolation under deterministic injected
failures (ISSUE 1 tentpole). Fast, CPU-only, tier-1.

The serving fleet is faked at the HTTP contract level (tiny aiohttp
servers speaking the generation-server protocol, heartbeating through
the real health registry) while everything under test is real: the
GserverManager worker (routing, eviction, quorum fanout, readmission),
the PartialRolloutManager failover client, and a RolloutWorker episode
loop pushing trajectories over the real ZMQ stream."""

import asyncio
import itertools
import json
import os
import threading
import time
import uuid

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api import data_api
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.api.system_api import GserverManagerConfig, RolloutWorkerConfig
from areal_tpu.base import constants, health, name_resolve, names
from areal_tpu.base.fault_injection import faults
from areal_tpu.system.gserver_manager import GserverManager
from areal_tpu.system.partial_rollout import PartialRolloutManager
from areal_tpu.system.push_pull_stream import ZMQJsonPuller, ZMQJsonPusher
from areal_tpu.system.rollout_worker import RolloutWorker
from tests import fixtures

pytestmark = pytest.mark.chaos

HB_TTL = 0.25


# ----------------------------------------------------------------------
# Harness
# ----------------------------------------------------------------------


class FakeGenServer:
    """Speaks the generation-server HTTP contract; heartbeats through the
    real health registry. `kill()` = crash (stop beating + 500s);
    `revive()` = restarted process (beats resume, serves again)."""

    def __init__(self, exp: str, trial: str, idx: int, beating: bool = True):
        self.exp, self.trial, self.idx = exp, trial, idx
        self.dead = False
        self.beating = beating
        self.versions = []  # weight versions received, in order
        self.n_generate = 0
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        assert self._ready.wait(10)
        # beating=False defers Heartbeat creation to the first beat, so
        # the member has truly NEVER appeared in the registry until
        # revived; beating=True registers eagerly (like a real worker's
        # configure()).
        self.hb = self._mk_heartbeat() if beating else None
        self._beat_thread = threading.Thread(target=self._beat_loop, daemon=True)
        self._beat_thread.start()

    def _mk_heartbeat(self):
        return health.Heartbeat(
            self.exp, self.trial, f"generation_server/{self.idx}",
            payload={"url": self.address}, ttl=HB_TTL,
        )

    def _beat_loop(self):
        while not self._stop.wait(HB_TTL / 3):
            if not self.beating:
                continue
            if self.hb is None:
                self.hb = self._mk_heartbeat()
            else:
                self.hb.beat(force=True)

    def _serve(self):
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        app.router.add_post("/generate", self._h_generate)
        app.router.add_post("/update_weights_from_disk", self._h_update)
        app.router.add_get("/metrics", self._h_metrics)
        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        self._loop.run_until_complete(site.start())
        port = site._server.sockets[0].getsockname()[1]
        self.address = f"http://127.0.0.1:{port}"
        self._ready.set()
        self._loop.run_forever()

    async def _h_generate(self, request):
        self.n_generate += 1
        if self.dead:
            return web.json_response({"error": "dead"}, status=500)
        await faults.maybe_fail_async(f"test.fake{self.idx}.generate")
        d = await request.json()
        n = int(d["gconfig"]["max_new_tokens"])
        return web.json_response({
            "qid": d["qid"],
            "output_ids": [self.idx + 1] * n,
            "output_logprobs": [-0.1] * n,
            "no_eos": False,
            "interrupted": False,
            "version_start": self.versions[-1] if self.versions else 0,
            "version_end": self.versions[-1] if self.versions else 0,
        })

    async def _h_update(self, request):
        if self.dead:
            return web.json_response({"error": "dead"}, status=500)
        d = await request.json()
        self.versions.append(int(d["version"]))
        return web.json_response(
            {"success": True, "load_s": 0.0, "source": "fake"}
        )

    async def _h_metrics(self, request):
        return web.Response(text="areal:num_running_reqs 0\n")

    def kill(self):
        self.dead = True
        self.beating = False

    def revive(self):
        self.dead = False
        self.beating = True

    def close(self):
        self._stop.set()
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


def _wait_until(cond, timeout=10.0, interval=0.05, msg="condition"):
    timeout = fixtures.scale_timeout(timeout)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def chaos_env(tmp_path, monkeypatch):
    """nfs name_resolve + tmp filesystem roots + fast heartbeats + a
    clean injector, torn down in order."""
    monkeypatch.setenv("AREAL_HEALTH_TTL", str(HB_TTL))
    monkeypatch.setattr(
        constants, "PARAM_REALLOC_ROOT", str(tmp_path / "realloc")
    )
    repo = name_resolve.reconfigure(
        "nfs", record_root=str(tmp_path / "name_resolve")
    )
    faults.reset()
    exp, trial = f"chaos-{uuid.uuid4().hex[:6]}", "t0"
    state = {"exp": exp, "trial": trial, "cleanup": []}
    yield state
    # Tell workers/manager to exit, then close fakes.
    try:
        name_resolve.add(
            names.experiment_status(exp, trial), "COMPLETE", replace=True
        )
    except Exception:
        pass
    for fn in state["cleanup"]:
        try:
            fn()
        except Exception:
            pass
    faults.reset()
    repo.reset()


def _start_manager(env, n_servers, policy="round_robin"):
    cfg = GserverManagerConfig(
        experiment_name=env["exp"],
        trial_name=env["trial"],
        model_name="actor",
        n_servers=n_servers,
        schedule_policy=policy,
        train_batch_size=4,
        max_head_offpolicyness=1000,
        flush_request_timeout=5.0,
        health_check_interval=0.1,
    )
    m = GserverManager()
    m.configure(cfg)
    t = threading.Thread(target=m.run, daemon=True)
    t.start()
    env["cleanup"].append(lambda: t.join(timeout=10))
    return m


def _mk_rollout_worker(env, manager_addr, pusher_port):
    """Harness-built partial RolloutWorker (the established idiom for
    unit-level worker tests): real episode loop, real failover client,
    real ZMQ push — no dataset/tokenizer bootstrapping."""

    class _OnePromptLoader:
        def next_batch(self):
            return (
                data_api.SequenceSample.from_default(
                    ids=[f"p{uuid.uuid4().hex[:4]}"],
                    seqlens=[3],
                    data={"packed_prompts": np.array([5, 6, 7], np.int32)},
                ),
                False,
            )

    from areal_tpu.agents.null import NullAgent

    w = RolloutWorker.__new__(RolloutWorker)
    w.cfg = RolloutWorkerConfig(
        experiment_name=env["exp"],
        trial_name=env["trial"],
        max_concurrent_rollouts=2,
        rollout_max_retries=8,
    )
    w.manager_addr = manager_addr
    w.prm = PartialRolloutManager(
        manager_addr, request_timeout=5.0, max_retries=8,
        retry_backoff_s=0.02,
    )
    w.agent = NullAgent(gconfig=dict(n=1, max_new_tokens=4))
    w.env = None
    w.dataset = None
    w.dataloader = _OnePromptLoader()
    w.pusher = ZMQJsonPusher("127.0.0.1", pusher_port)
    w._session = None
    w._tasks = {}
    w._push_count = 0
    w._episode_counter = itertools.count()
    return w


async def _drive_episodes(w, n):
    """Run the worker's poll loop until n episodes were launched, then
    await them (and close its HTTP session)."""
    seen = set()
    deadline = time.monotonic() + 20
    while len(seen) < n:
        assert time.monotonic() < deadline, "episode launch stalled"
        await w._poll_async()
        seen |= set(w._tasks)
    await asyncio.gather(*w._tasks.values())
    if w._session is not None:
        await w._session.close()
    await w.prm.close()


# ----------------------------------------------------------------------
# Acceptance: degraded-mode serving fleet
# ----------------------------------------------------------------------


def test_server_death_mid_rollout_degrades_then_recovers(chaos_env):
    """With 2 generation servers, killing one mid-rollout (1) lets the
    in-flight rollout retry to the survivor and complete its training
    step input, (2) evicts the dead server from all three routing
    policies, (3) lets the weight-update fanout proceed on the survivor
    alone, and (4) re-syncs the dead server to the latest weights on
    readmission before it re-enters rotation."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    servers = [FakeGenServer(exp, trial, i) for i in range(2)]
    env["cleanup"].extend(s.close for s in servers)
    for s in servers:
        name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=2)

    # Round-robin from sorted urls: the FIRST generate lands on the
    # lexicographically-first server — kill exactly that one, mid-rollout.
    victim, survivor = sorted(servers, key=lambda s: s.address)
    faults.arm(
        f"test.fake{victim.idx}.generate", action="raise", at_hit=1,
        on_trigger=victim.kill,
    )

    # --- (1) the in-flight rollout retries to the survivor and the
    # trajectory reaches the trainer stream.
    puller = ZMQJsonPuller(host="127.0.0.1")
    env["cleanup"].append(puller.close)
    w = _mk_rollout_worker(env, m.address, puller.port)
    asyncio.run(_drive_episodes(w, 1))
    traj = puller.pull(timeout_ms=5000)
    sample = data_api.sample_from_json(traj)
    # NullAgent seq = prompt + 4 generated tokens; the survivor stamps
    # its idx+1 into every generated token.
    ids = np.asarray(sample.data["packed_input_ids"]).tolist()
    assert ids[:3] == [5, 6, 7] and ids[3:] == [survivor.idx + 1] * 4
    assert victim.n_generate >= 1  # the fault really hit mid-rollout
    # Quota slot released despite the failover.
    _wait_until(lambda: m.rollout_stat.running == 0, msg="quota release")
    assert m.rollout_stat.accepted == 1

    # --- (2) evicted from every routing policy.
    _wait_until(lambda: victim.address in m._evicted, msg="eviction")
    for policy in ("round_robin", "least_requests", "least_token_usage"):
        m.cfg.schedule_policy = policy
        with m._lock:
            choices = {m._choose_server({})[0] for _ in range(4)}
        assert choices == {survivor.address}, policy

    # --- (3) quorum fanout: publish v1; it must land on the survivor
    # and advance weight_version without the dead server aborting it.
    dump_dir = os.path.join(
        constants.get_param_realloc_path(exp, trial), "actor"
    )
    os.makedirs(dump_dir, exist_ok=True)
    with open(os.path.join(dump_dir, "engine_state.pkl"), "wb") as f:
        f.write(b"fake")
    name_resolve.add(names.model_version(exp, trial, "actor"), "1", replace=True)
    _wait_until(lambda: m.weight_version == 1, msg="quorum fanout")
    assert survivor.versions == [1]
    assert victim.versions == []

    # --- (4) readmission: heartbeat returns -> re-synced to v1 FIRST,
    # then back in rotation.
    victim.revive()
    _wait_until(
        lambda: victim.address in m._healthy, timeout=15, msg="readmission"
    )
    assert victim.versions == [1]  # re-synced before re-entering rotation
    assert m._server_versions[victim.address] == 1
    m.cfg.schedule_policy = "round_robin"
    with m._lock:
        routed = {m._choose_server({})[0] for _ in range(4)}
    assert routed == {victim.address, survivor.address}

    m.exit()


def test_restarted_server_at_new_address_migrates_routing(chaos_env):
    """A controller-restarted generation server re-registers the SAME
    health member at a NEW port: the manager migrates its routing-table
    entry, re-syncs the new incarnation to the current weights, and
    readmits it."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    servers = [FakeGenServer(exp, trial, i) for i in range(2)]
    env["cleanup"].extend(s.close for s in servers)
    for s in servers:
        name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=2)
    old, keeper = servers

    # Give the manager one healthy fanout first, so re-sync has a
    # version to push.
    dump_dir = os.path.join(
        constants.get_param_realloc_path(exp, trial), "actor"
    )
    os.makedirs(dump_dir, exist_ok=True)
    with open(os.path.join(dump_dir, "engine_state.pkl"), "wb") as f:
        f.write(b"fake")
    name_resolve.add(names.model_version(exp, trial, "actor"), "1", replace=True)
    _wait_until(lambda: m.weight_version == 1, msg="initial fanout")
    # Let the manager observe the original member->url mapping.
    _wait_until(
        lambda: m._member_urls.get("generation_server/0") == old.address,
        msg="member mapping",
    )

    old.kill()
    _wait_until(lambda: old.address in m._evicted, timeout=15, msg="eviction")

    # "Restart": same member (idx 0), fresh port.
    replacement = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(replacement.close)
    _wait_until(
        lambda: replacement.address in m._healthy, timeout=15,
        msg="migration + readmission",
    )
    assert old.address not in m.server_urls
    assert replacement.address in m.server_urls
    assert replacement.versions == [1]  # re-synced before rotation
    with m._lock:
        routed = {m._choose_server({})[0] for _ in range(4)}
    assert routed == {replacement.address, keeper.address}
    m.exit()


def test_never_seen_member_adopted_after_eviction(chaos_env):
    """A server that crashed before the manager ever saw it heartbeat and
    came back at a new address: once the stale url is evicted (client
    report), the unknown member's new address replaces it."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    silent = FakeGenServer(exp, trial, 0, beating=False)
    keeper = FakeGenServer(exp, trial, 1)
    env["cleanup"].extend([silent.close, keeper.close])
    for s in (silent, keeper):
        name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=2)

    # The silent server dies without one beat on record; a client
    # reports the failure (the real eviction path for never-beat urls).
    silent.kill()
    m._mark_unhealthy(silent.address, "client-reported request failure")

    # Its "restarted" incarnation beats at a brand-new port.
    replacement = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(replacement.close)
    _wait_until(
        lambda: replacement.address in m._healthy, timeout=15,
        msg="adoption of never-seen member",
    )
    assert silent.address not in m.server_urls
    assert replacement.address in m.server_urls
    with m._lock:
        routed = {m._choose_server({})[0] for _ in range(4)}
    assert routed == {replacement.address, keeper.address}
    m.exit()


def test_whole_fleet_down_backs_off_then_succeeds(chaos_env):
    """503 (no healthy servers) makes the client back off and retry, not
    fail: once the server returns, the pending sample completes."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    s = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(s.close)
    name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=1)

    s.kill()
    _wait_until(lambda: s.address in m._evicted, msg="eviction")

    prm = PartialRolloutManager(
        m.address, request_timeout=5.0, max_retries=30, retry_backoff_s=0.05
    )

    async def gen():
        out = await prm._generate_one(
            "q0", [1, 2], GenerationHyperparameters(max_new_tokens=2)
        )
        await prm.close()
        return out

    result = {}

    def run():
        result["out"] = asyncio.run(gen())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)  # let it hit the 503 path
    s.revive()
    t.join(timeout=20)
    assert not t.is_alive()
    assert result["out"].output_ids == [1, 1]
    m.exit()


def test_crashing_episode_releases_quota_slot(chaos_env):
    """A rollout episode that dies (armed rollout.episode fault) must
    release its quota slot — N crashes in a row cannot starve the
    manager's rollout quota."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    s = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(s.close)
    name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=1)

    puller = ZMQJsonPuller(host="127.0.0.1")
    env["cleanup"].append(puller.close)
    w = _mk_rollout_worker(env, m.address, puller.port)
    # Crash the first 3 episodes; the 4th succeeds.
    faults.arm("rollout.episode", action="raise", at_hit=1, times=3)
    asyncio.run(_drive_episodes(w, 4))
    _wait_until(lambda: m.rollout_stat.running == 0, msg="quota release")
    assert m.rollout_stat.accepted == 1
    # Rejected episodes gave their staleness budget back too.
    assert m.rollout_stat.submitted == 1
    m.exit()


def test_dead_rollout_worker_slots_reclaimed(chaos_env):
    """A killed rollout worker can never /finish_rollout its episodes:
    once its heartbeat goes stale, the manager reclaims the outstanding
    slots so the capacity gate doesn't wedge shut."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    s = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(s.close)
    name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=1)
    m.cfg.max_concurrent_rollouts = 2

    # The worker heartbeats once (registration) and then "crashes":
    # no further beats, no graceful stop marker.
    health.Heartbeat(exp, trial, "rollout_worker/0", ttl=HB_TTL)
    _wait_until(
        lambda: "rollout_worker/0" in m._rollout_seen,
        msg="manager observed the rollout worker",
    )

    async def allocate():
        async with __import__("aiohttp").ClientSession() as sess:
            async with sess.post(
                f"{m.address}/allocate_rollout",
                json={"worker": "rollout_worker/0"},
            ) as r:
                return await r.json()

    assert asyncio.run(allocate())["success"]
    assert asyncio.run(allocate())["success"]
    third = asyncio.run(allocate())
    assert not third["success"] and third["reason"] == "capacity"

    # Heartbeat stale -> slots reclaimed -> the gate reopens.
    _wait_until(
        lambda: m.rollout_stat.running == 0, timeout=15, msg="reclamation"
    )
    assert m.rollout_stat.submitted == 0
    assert asyncio.run(allocate())["success"]
    m.exit()


def test_allocate_window_failure_releases_quota_slot(chaos_env):
    """A failure AFTER quota allocation but BEFORE the episode task owns
    the slot (e.g. the dataloader raising) must release the slot."""
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    s = FakeGenServer(exp, trial, 0)
    env["cleanup"].append(s.close)
    name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
    m = _start_manager(env, n_servers=1)

    puller = ZMQJsonPuller(host="127.0.0.1")
    env["cleanup"].append(puller.close)
    w = _mk_rollout_worker(env, m.address, puller.port)

    class _ExplodingLoader:
        def next_batch(self):
            raise RuntimeError("dataset exploded")

    w.dataloader = _ExplodingLoader()

    async def drive():
        with pytest.raises(RuntimeError, match="dataset exploded"):
            await w._poll_async()
        if w._session is not None:
            await w._session.close()
        await w.prm.close()

    asyncio.run(drive())
    _wait_until(lambda: m.rollout_stat.running == 0, msg="quota release")
    assert m.rollout_stat.submitted == 0
    m.exit()


# ----------------------------------------------------------------------
# RL-trace emitter well-formedness under failover (ISSUE 3 CI satellite)
# ----------------------------------------------------------------------


def test_rl_trace_emitters_wellformed_under_failover(
    chaos_env, tmp_path, monkeypatch
):
    """Tier-1 canary for the RL-trace emitters on their hardest path: a
    server killed mid-rollout forces the retry/failover emitters
    (gen.chunk resubmission, manager.schedule with failure report) to
    fire, and the resulting shards must still validate — a malformed
    emitter fails here, not in a debugging session."""
    from areal_tpu.base import tracing
    from areal_tpu.utils import rl_trace

    monkeypatch.setenv("AREAL_RL_TRACE", "1")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", str(tmp_path / "rl_trace"))
    tracing.reconfigure()
    env = chaos_env
    exp, trial = env["exp"], env["trial"]
    try:
        servers = [FakeGenServer(exp, trial, i) for i in range(2)]
        env["cleanup"].extend(s.close for s in servers)
        for s in servers:
            name_resolve.add_subentry(names.gen_servers(exp, trial), s.address)
        m = _start_manager(env, n_servers=2)
        victim, _ = sorted(servers, key=lambda s: s.address)
        faults.arm(
            f"test.fake{victim.idx}.generate", action="raise", at_hit=1,
            on_trigger=victim.kill,
        )

        puller = ZMQJsonPuller(host="127.0.0.1")
        env["cleanup"].append(puller.close)
        w = _mk_rollout_worker(env, m.address, puller.port)
        asyncio.run(_drive_episodes(w, 2))
        _wait_until(lambda: m.rollout_stat.running == 0, msg="quota release")
        m.exit()
        tracing.flush()

        shards = rl_trace.load_shards(str(tmp_path / "rl_trace"))
        assert rl_trace.validate(shards) == [], (
            "RL-trace emitters produced malformed shards under failover"
        )
        names_seen = {sp["name"] for s in shards for sp in s.spans}
        # The full client-side chain plus the manager's admission/routing
        # events (everything runs in this process, so one shard).
        assert {
            "rollout.allocate", "rollout.episode", "gen.sample",
            "gen.chunk", "manager.allocate", "manager.schedule",
        } <= names_seen, names_seen
        # Episode spans parent correctly under their allocate span.
        spans = [sp for s in shards for sp in s.spans]
        by_id = {sp["span"]: sp for sp in spans}
        for ep in (sp for sp in spans if sp["name"] == "rollout.episode"):
            assert ep["parent"] in by_id
            assert by_id[ep["parent"]]["name"] == "rollout.allocate"
        # The trajectory pushed through ZMQ carried the episode ctx.
        traj = puller.pull(timeout_ms=5000)
        sample = data_api.sample_from_json(traj)
        ctx = (sample.metadata.get("trace_ctx") or [None])[0]
        assert ctx and ctx.get("trace_id"), sample.metadata
    finally:
        tracing.reconfigure()

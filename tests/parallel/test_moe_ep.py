"""Expert parallelism: MoE expert weights shard E over the fsdp mesh
axis (parallel/sharding.py), the GShard-style einsum dispatch makes XLA
insert the token all-to-all, and sharded results match single-device
bit-for-near (the reference has no expert parallelism — this exceeds
parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from areal_tpu.base.topology import MeshSpec
from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.transformer import forward, init_params
from areal_tpu.parallel.mesh import make_mesh
from areal_tpu.parallel.sharding import param_shardings, shard_params

CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=4,
    n_kv_heads=2,
    head_dim=8,
    intermediate_dim=64,
    vocab_size=64,
    compute_dtype="float32",
    param_dtype="float32",
    moe=MoEConfig(
        num_experts=8, top_k=2, expert_intermediate_dim=32,
        capacity_factor=2.0,
    ),
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def test_expert_weights_shard_over_fsdp(params):
    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sh = param_shardings(params, mesh)
    mlp = sh["layers"]["mlp"]
    assert mlp["w_gate"].spec == P(None, "fsdp", None, "tensor")
    assert mlp["w_up"].spec == P(None, "fsdp", None, "tensor")
    assert mlp["w_down"].spec == P(None, "fsdp", "tensor", None)
    assert mlp["router"].spec == P(None, None, None)
    # 8 experts / fsdp=4 -> 2 experts per shard.
    shard_shape = mlp["w_gate"].shard_shape(
        params["layers"]["mlp"]["w_gate"].shape
    )
    assert shard_shape[1] == 2


@pytest.mark.parametrize("spec_str", ["d1f4t2", "d2f2s1t2", "f8"])
def test_moe_forward_matches_single_device(params, spec_str):
    rng = np.random.RandomState(0)
    R, T = 2, 32
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)

    ref = forward(params, CFG, ids, seg, pos, attn_impl="reference")

    mesh = make_mesh(MeshSpec.parse(spec_str))
    sharded = shard_params(params, mesh)

    @jax.jit
    def f(p, i, s, po):
        return forward(p, CFG, i, s, po, attn_impl="reference")

    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else mesh:
        out = f(sharded, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_moe_ep_gradients_match(params):
    """Grad parity: expert-sharded backward (all-to-all transposes) ==
    single-device backward."""
    rng = np.random.RandomState(1)
    R, T = 2, 16
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)

    def loss(p):
        lg = forward(p, CFG, ids, seg, pos, attn_impl="reference")
        return jnp.mean(lg.astype(jnp.float32) ** 2)

    g_ref = jax.grad(loss)(params)

    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sharded = shard_params(params, mesh)
    g_sh = jax.jit(jax.grad(loss))(sharded)

    ref_leaf = g_ref["layers"]["mlp"]["w_gate"]
    sh_leaf = g_sh["layers"]["mlp"]["w_gate"]
    np.testing.assert_allclose(
        np.asarray(sh_leaf), np.asarray(ref_leaf), rtol=2e-3, atol=2e-4
    )


def test_indivisible_experts_fall_back_to_zero_sharding():
    """E=6 on fsdp=4 can't shard experts — the hidden dim takes the fsdp
    axis instead, so ZeRO-3 never silently degrades to replication."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG,
        moe=dataclasses.replace(CFG.moe, num_experts=6),
    )
    p6 = init_params(cfg, jax.random.PRNGKey(3))
    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sh = param_shardings(p6, mesh)
    mlp = sh["layers"]["mlp"]
    assert mlp["w_gate"].spec == P(None, None, "fsdp", "tensor")
    assert mlp["w_down"].spec == P(None, None, "tensor", "fsdp")
    # And the fallback numerics still match single-device.
    rng = np.random.RandomState(2)
    R, T = 2, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)
    ref = forward(p6, cfg, ids, seg, pos, attn_impl="reference")
    sharded = shard_params(p6, mesh)
    out = jax.jit(
        lambda p, i, s, po: forward(p, cfg, i, s, po, attn_impl="reference")
    )(sharded, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

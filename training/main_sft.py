"""SFT entry point (reference training/main_sft.py).

Usage:
    python training/main_sft.py \
        experiment_name=my-sft model.path=/ckpts/qwen2.5-1.5b \
        dataset.path=/data/sft.jsonl train_batch_size=64
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.api.cli_args import SFTExpConfig
from training.utils import main

if __name__ == "__main__":
    main("sft", SFTExpConfig)

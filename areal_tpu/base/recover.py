"""Recovery metadata: step counters, frequency-control state, consumed data.

Counterpart of the reference's recover module (realhf/base/recover.py).
`RecoverInfo` is dumped at checkpoint time by the master worker and loaded
on relaunch so training resumes exactly where it stopped, with already-
consumed samples excluded via their hashes.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Dict, List, Optional

from areal_tpu.base import constants
from areal_tpu.base.wire_schemas import RECOVER_INFO_V1


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def next(self):
        return StepInfo(
            epoch=self.epoch,
            epoch_step=self.epoch_step + 1,
            global_step=self.global_step + 1,
        )


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    save_ctl_info: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ckpt_ctl_info: Dict[str, Any] = dataclasses.field(default_factory=dict)
    eval_ctl_info: Dict[str, Any] = dataclasses.field(default_factory=dict)
    data_loading_dp_idx: int = 0
    hash_vals_to_ignore: List[int] = dataclasses.field(default_factory=list)
    # Exactly-once sample ledger snapshot (system/wal.py SeqLedger
    # to_dict form): which rollout sequence ids were fully consumed as
    # of this checkpoint barrier. Persisted atomically WITH the step
    # counters so a resume filters WAL replay and pusher redelivery
    # against the same cut the engine state was taken at.
    consumed_seqs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Per-dataset read cursors (worker_name -> dataloader state dict),
    # the master-side copy of what each model worker checkpoints.
    dataset_cursors: Dict[str, Any] = dataclasses.field(default_factory=dict)


def dump_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    return os.path.join(constants.get_recover_path(experiment, trial), "recover_info.pkl")


def dump(info: RecoverInfo, experiment: Optional[str] = None, trial: Optional[str] = None):
    """Atomic, schema-versioned dump: tmp + fsync + rename so a crash
    mid-write can never poison the next recover_mode=auto start, and a
    reader from a different protocol generation rejects the payload."""
    path = dump_path(experiment, trial)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump({"schema": RECOVER_INFO_V1, "info": info}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(path), os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def load(experiment: Optional[str] = None, trial: Optional[str] = None) -> RecoverInfo:
    path = dump_path(experiment, trial)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no recover info at {path}")
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if isinstance(payload, RecoverInfo):
        # Legacy (pre-schema) record written by an older master.
        return payload
    schema = payload.get("schema")
    if schema != RECOVER_INFO_V1:
        raise ValueError(f"unsupported recover-info schema {schema!r} at {path}")
    return payload["info"]


def discover_ckpt(model_name: str, experiment=None, trial=None) -> Optional[str]:
    """Latest recover checkpoint directory for a model role, if any."""
    root = os.path.join(constants.get_recover_path(experiment, trial), "ckpt", model_name)
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))

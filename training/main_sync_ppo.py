"""Sync PPO entry point (reference training/main_sync_ppo.py).

Usage:
    python training/main_sync_ppo.py \
        experiment_name=ppo actor.path=/ckpts/qwen dataset.path=/data/math.jsonl \
        ppo.gconfig.max_new_tokens=1024 group_size=8
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.api.cli_args import PPOMATHExpConfig
from training.utils import main

if __name__ == "__main__":
    main("ppo-math", PPOMATHExpConfig)

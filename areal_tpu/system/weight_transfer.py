"""Trainer -> generation-server weight transfer with a same-host fast path.

Counterpart of the reference's param-realloc transfer stack
(realhf/system/model_worker.py:1046-1148 — disk-mediated by default, with
NCCL/GDRDMA fast paths keeping it under the <3 s bar of
blog/AReaL_v0_2.md:52-54). The TPU single-host equivalent of the CUDA-IPC
path is raw parameter bytes in tmpfs (/dev/shm) read back with mmap: no
pickle serialize/deserialize copies, no disk IO, and `jax.device_put`
streams straight from the mapped pages. The pickle-on-NFS dump
(engine/checkpoint.py) remains the cross-host fallback.

Format (per dump directory):
- ``params-v{N}.bin``  — every leaf's contiguous bytes, concatenated.
- ``params.json``      — manifest: schema version, dump version N, bin
  filename, and per-leaf (path, dtype, shape, offset). Written via
  tmp+rename AFTER the bin, so a reader that sees a manifest always sees
  its complete bin. Older bins are garbage-collected down to the last 2;
  a reader racing the GC gets FileNotFoundError and falls back.

The tree is assumed to be nested dicts of arrays (what
models/transformer.init_params builds); list/tuple nodes are rejected at
dump time rather than silently mis-rebuilt.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from areal_tpu.base import logging

logger = logging.getLogger("weight_transfer")

_MANIFEST = "params.json"
_SCHEMA = 1


def shm_transfer_dir(experiment_name: str, trial_name: str, role: str) -> Optional[str]:
    """tmpfs dump directory for the same-host fast path, or None when
    /dev/shm is unavailable (then only the disk path is used)."""
    base = "/dev/shm"
    if not os.path.isdir(base) or not os.access(base, os.W_OK):
        return None
    return os.path.join(base, "areal_tpu", experiment_name, trial_name, role)


def _flatten(params: Any, prefix: Tuple[str, ...] = ()) -> list:
    out = []
    if isinstance(params, dict):
        for k in sorted(params.keys()):
            out.extend(_flatten(params[k], prefix + (str(k),)))
        return out
    if isinstance(params, (list, tuple)):
        raise TypeError(
            f"weight_transfer supports dict-of-array trees only; found "
            f"{type(params).__name__} at {'/'.join(prefix)}"
        )
    return [("/".join(prefix), params)]


def dump_raw_params(params: Any, dump_dir: str, version: int) -> float:
    """Write the raw dump; returns seconds spent. Safe against concurrent
    readers (see module docstring); single writer assumed (the dp-rank-0
    dump rule, system/model_worker._param_realloc)."""
    t0 = time.monotonic()
    os.makedirs(dump_dir, exist_ok=True)
    leaves = _flatten(params)
    bin_name = f"params-v{version}.bin"
    manifest: Dict[str, Any] = {
        "schema": _SCHEMA, "version": int(version), "bin": bin_name,
        "leaves": [],
    }
    offset = 0
    tmp_bin = os.path.join(dump_dir, bin_name + f".tmp.{os.getpid()}")
    with open(tmp_bin, "wb") as f:
        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            f.write(arr.tobytes())
            # dtype.name (not .str): ml_dtypes types like bfloat16 have
            # .str '<V2' which round-trips to a raw void type.
            manifest["leaves"].append(
                {"path": path, "dtype": arr.dtype.name,
                 "shape": list(arr.shape), "offset": offset}
            )
            offset += arr.nbytes
    manifest["total_bytes"] = offset
    os.replace(tmp_bin, os.path.join(dump_dir, bin_name))
    tmp_man = os.path.join(dump_dir, _MANIFEST + f".tmp.{os.getpid()}")
    with open(tmp_man, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp_man, os.path.join(dump_dir, _MANIFEST))
    # GC old bins (keep the newest 2 so an in-flight reader can finish).
    bins = sorted(
        (b for b in os.listdir(dump_dir)
         if b.startswith("params-v") and b.endswith(".bin")),
        key=lambda b: int(b[len("params-v"):-len(".bin")]),
    )
    for b in bins[:-2]:
        try:
            os.unlink(os.path.join(dump_dir, b))
        except OSError:
            pass
    return time.monotonic() - t0


def _unflatten(leaves: Dict[str, np.ndarray]) -> Any:
    root: Dict[str, Any] = {}
    for path, arr in leaves.items():
        node = root
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def load_raw_params(dump_dir: str) -> Optional[Tuple[Any, int]]:
    """mmap the latest raw dump: (params pytree of memory-mapped arrays,
    dump version), or None if absent/torn (caller falls back)."""
    try:
        import ml_dtypes  # noqa: F401  registers bfloat16 et al. by name

        with open(os.path.join(dump_dir, _MANIFEST)) as f:
            manifest = json.load(f)
        if manifest.get("schema") != _SCHEMA:
            return None
        mm = np.memmap(
            os.path.join(dump_dir, manifest["bin"]), mode="r", dtype=np.uint8
        )
        if mm.size != manifest["total_bytes"]:
            return None  # torn write
        leaves = {}
        for e in manifest["leaves"]:
            dt = np.dtype(e["dtype"])
            n = int(np.prod(e["shape"])) * dt.itemsize
            leaves[e["path"]] = (
                mm[e["offset"]: e["offset"] + n].view(dt).reshape(e["shape"])
            )
        return _unflatten(leaves), int(manifest["version"])
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None


def load_for_serving(
    model_path: str, shm_dir: Optional[str] = None
) -> Tuple[Any, Dict[str, Any]]:
    """Load params for a generation server's weight update, fastest source
    first. Returns (params, info) where info records the source and load
    seconds for the /metrics surface:

    1. ``shm_dir`` raw dump      — same-host tmpfs fast path
    2. ``model_path`` raw dump   — mmap from page cache / NFS
    3. ``model_path`` pickle     — engine_state.pkl (checkpoint fallback)
    4. ``model_path`` HF dir     — cold start from an HF checkpoint
    """
    t0 = time.monotonic()
    if shm_dir is not None:
        got = load_raw_params(shm_dir)
        if got is not None:
            params, v = got
            return params, {"source": "shm_raw", "version": v,
                            "load_s": time.monotonic() - t0}
    got = load_raw_params(model_path)
    if got is not None:
        params, v = got
        return params, {"source": "disk_raw", "version": v,
                        "load_s": time.monotonic() - t0}
    state_file = os.path.join(model_path, "engine_state.pkl")
    if os.path.exists(state_file):
        import pickle

        with open(state_file, "rb") as f:
            params = pickle.load(f)["params"]
        return params, {"source": "pickle", "version": -1,
                        "load_s": time.monotonic() - t0}
    from areal_tpu.models.hf import load_hf_model

    _, params = load_hf_model(model_path)
    return params, {"source": "hf", "version": -1,
                    "load_s": time.monotonic() - t0}

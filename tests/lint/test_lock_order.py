"""lock-order checker fixtures: the three deadlock classes (await
under a sync lock, loop-door crossing under a lock, AB/BA acquisition
cycles) plus the exempt patterns (asyncio locks, closures that run
later, consistent ordering)."""

import textwrap

from areal_tpu.lint.runner import LintConfig, run_lint


def _lint(tmp_path, source, *, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    cfg = LintConfig(root=str(tmp_path), checkers={"lock-order"})
    return run_lint([str(p)], cfg)


_HEADER = """\
import asyncio
import threading


class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._tier_lock = threading.Lock()
        self._alock = asyncio.Lock()

"""


def _cls(body):
    """Class source with ``body`` as additional methods of S."""
    return _HEADER + textwrap.indent(textwrap.dedent(body), "    ")


def test_await_under_sync_lock_flagged(tmp_path):
    findings = _lint(tmp_path, _cls("""
        async def handler(self):
            with self._lock:
                await asyncio.sleep(0.1)
    """))
    assert len(findings) == 1
    assert "await while holding sync lock S._lock" in findings[0].message


def test_asyncio_lock_not_flagged(tmp_path):
    findings = _lint(tmp_path, _cls("""
        async def handler(self):
            async with self._alock:
                await asyncio.sleep(0.1)
    """))
    assert findings == []


def test_await_after_release_clean(tmp_path):
    findings = _lint(tmp_path, _cls("""
        async def handler(self):
            with self._lock:
                x = 1
            await asyncio.sleep(x)
    """))
    assert findings == []


def test_loop_door_under_lock_flagged(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def snapshot(self, eng):
            with self._lock:
                return eng._run_on_loop(lambda: 1)
    """))
    assert len(findings) == 1
    assert "_run_on_loop under sync lock" in findings[0].message


def test_blocking_bridge_under_lock_flagged(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def push(self, coro, loop):
            with self._lock:
                return asyncio.run_coroutine_threadsafe(
                    coro, loop
                ).result()
    """))
    assert len(findings) == 1
    assert "run_coroutine_threadsafe" in findings[0].message


def test_nonblocking_bridge_under_lock_clean(tmp_path):
    # Scheduling without .result() does not block the lock holder on
    # the loop; only the blocking chain is the deadlock.
    findings = _lint(tmp_path, _cls("""
        def push(self, coro, loop):
            with self._lock:
                fut = asyncio.run_coroutine_threadsafe(coro, loop)
            return fut.result()
    """))
    assert findings == []


def test_closure_under_lock_runs_later_clean(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def arm(self, eng):
            with self._lock:
                def later():
                    return eng._run_on_loop(lambda: 2)
            return later
    """))
    assert findings == []


def test_lock_cycle_flagged(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def spill(self):
            with self._lock:
                with self._tier_lock:
                    pass

        def drain(self):
            with self._tier_lock:
                with self._lock:
                    pass
    """))
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message


def test_consistent_order_clean(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def spill(self):
            with self._lock:
                with self._tier_lock:
                    pass

        def restore(self):
            with self._lock:
                with self._tier_lock:
                    pass
    """))
    assert findings == []


def test_class_body_lock_attr_flagged(tmp_path):
    # ``_lock = threading.Lock()`` in the class body (the name_resolve
    # MemoryNameRecordRepository spelling) is read back as
    # ``self._lock`` — it must be attributed to the class, not the
    # module, or the whole class is invisible to the checker.
    findings = _lint(tmp_path, """
        import asyncio
        import threading


        class R:
            _lock = threading.Lock()

            async def handler(self):
                with self._lock:
                    await asyncio.sleep(0.1)
    """)
    assert len(findings) == 1
    assert "await while holding sync lock R._lock" in findings[0].message


def test_multi_item_with_cycle_flagged(tmp_path):
    # ``with self._a, self._b:`` acquires left-to-right; the one-line
    # form must feed the same AB/BA edges as the nested spelling.
    findings = _lint(tmp_path, _cls("""
        def spill(self):
            with self._lock, self._tier_lock:
                pass

        def drain(self):
            with self._tier_lock, self._lock:
                pass
    """))
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message


def test_multi_item_with_consistent_order_clean(tmp_path):
    findings = _lint(tmp_path, _cls("""
        def spill(self):
            with self._lock, self._tier_lock:
                pass

        def restore(self):
            with self._lock, self._tier_lock:
                pass
    """))
    assert findings == []


def test_function_local_lock_stays_local(tmp_path):
    # A function-local lock must not leak into the module bucket: an
    # unrelated same-named ``with lock:`` elsewhere is NOT under it —
    # but an await under the local lock in its own function still is.
    findings = _lint(tmp_path, """
        import asyncio
        import threading


        def make():
            lock = threading.Lock()
            return lock


        async def elsewhere(lock):
            with lock:
                await asyncio.sleep(0.1)
    """)
    assert findings == []

    findings = _lint(tmp_path, """
        import asyncio
        import threading


        async def own(self):
            lock = threading.Lock()
            with lock:
                await asyncio.sleep(0.1)
    """)
    assert len(findings) == 1
    assert "own.lock" in findings[0].message


def test_other_context_managers_ignored(tmp_path):
    findings = _lint(tmp_path, _cls("""
        async def handler(self, path):
            with open(path) as f:
                await asyncio.sleep(0.1)
                return f
    """))
    assert findings == []

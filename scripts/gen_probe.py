"""Decode-path perf probe: time the paged decode step vs sampling warp on
the real chip (diagnosing the gen tok/s bottleneck before optimizing)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.engine import paged

def log(*a): print(*a, file=sys.stderr, flush=True)

def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup): jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        jax.block_until_ready(fn(*args))  # per-call block: the tunneled
        # device otherwise reports dispatch time, not execution time
    return (time.perf_counter() - t0) / n

cfg = TransformerConfig(
    n_layers=16, hidden_dim=1536, n_q_heads=12, n_kv_heads=2,
    head_dim=128, intermediate_dim=8960, vocab_size=32768,
    attn_bias=True, compute_dtype="bfloat16", param_dtype="bfloat16",
)
params = init_params(cfg, jax.random.PRNGKey(0))
B, pg, P = 32, 128, 9   # ~1152 tokens per slot
N = B * P + 1
kp = jnp.zeros((cfg.n_layers, cfg.n_kv_heads, N, pg, cfg.head_dim), jnp.bfloat16)
vp = jnp.zeros_like(kp)
pt = jnp.asarray(np.arange(1, B*P+1, dtype=np.int32).reshape(B, P))
lengths = jnp.full((B,), 600, jnp.int32)
active = jnp.ones((B,), bool)
tokens = jnp.ones((B,), jnp.int32)

step = jax.jit(lambda p, t, k, v, pi, l, a: paged.paged_decode_step(p, cfg, t, k, v, pi, l, a)[0], static_argnames=())
t_step = timeit(step, params, tokens, kp, vp, pt, lengths, active)
log(f"decode_step (B={B}): {t_step*1e3:.2f} ms")

logits = jax.random.normal(jax.random.PRNGKey(1), (B, cfg.vocab_size), jnp.float32)
temps = jnp.ones((B,), jnp.float32); tps = jnp.ones((B,), jnp.float32)
tks = jnp.full((B,), -1, jnp.int32); gm = jnp.zeros((B,), bool)
fr = jnp.zeros((B,), bool); em = jnp.zeros((cfg.vocab_size,), bool)
ws = jax.jit(paged.warp_sample)
t_ws = timeit(ws, logits, jax.random.PRNGKey(2), temps, tps, tks, gm, fr, em)
log(f"warp_sample (B={B}, V=32768): {t_ws*1e3:.2f} ms")

# plain categorical for comparison
cat = jax.jit(lambda l, r: jax.random.categorical(r, l, axis=-1))
t_cat = timeit(cat, logits, jax.random.PRNGKey(3))
log(f"plain categorical: {t_cat*1e3:.2f} ms")

# attention-only: paged attention at this shape
q = jax.random.normal(jax.random.PRNGKey(4), (B, cfg.n_q_heads, cfg.head_dim), jnp.bfloat16)
pa = jax.jit(lambda q, k, v, l, pi: paged.paged_decode_attention(q, k, v, l, pi))
t_pa = timeit(pa, q, kp[0], vp[0], lengths, pt)
log(f"paged attention single layer: {t_pa*1e3:.3f} ms  (x{cfg.n_layers} = {t_pa*cfg.n_layers*1e3:.2f} ms)")

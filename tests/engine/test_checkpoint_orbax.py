"""Orbax engine-state backend: shard-wise save/restore (each host writes
only its shards; restore lands directly on the engine's NamedShardings
with no host gather) — the pod-scale alternative to the pickle backend.
Auto-detection means old pickle checkpoints keep loading."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.engine.checkpoint import (
    has_engine_state,
    load_engine_state,
    save_engine_state,
)
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params


def small_cfg():
    return TransformerConfig(
        n_layers=2,
        hidden_dim=32,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=8,
        intermediate_dim=64,
        vocab_size=64,
        compute_dtype="float32",
        param_dtype="float32",
    )


def make_engine(seed, mesh_spec=None):
    cfg = small_cfg()
    kw = {}
    if mesh_spec:
        from areal_tpu.base.topology import MeshSpec
        from areal_tpu.parallel.mesh import make_mesh

        kw["mesh"] = make_mesh(MeshSpec.parse(mesh_spec))
    return JaxTrainEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(seed)),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10,
        row_len_multiple=32,
        **kw,
    )


def make_batch(n=4, seed=0):
    rng = np.random.RandomState(seed)
    lens = [16] * n
    total = sum(lens)
    return SequenceSample.from_default(
        ids=[f"s{i}" for i in range(n)],
        seqlens=lens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )


def loss_fn(lp, rows):
    return -jnp.sum(lp * rows["loss_mask"]), {}


def weight(mb):
    return float(np.sum(mb.data["loss_mask"]))


def _step(eng, seed=0):
    eng.train_batch(
        make_batch(seed=seed), MicroBatchSpec(n_mbs=1), loss_fn, weight,
        loss_name="l",
    )


def _assert_same_params(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a.get_params()),
        jax.tree_util.tree_leaves(b.get_params()),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("mesh_spec", [None, "d2f2t2"])
def test_orbax_roundtrip(tmp_path, mesh_spec):
    eng = make_engine(1, mesh_spec)
    _step(eng)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    assert has_engine_state(str(tmp_path))

    eng2 = make_engine(99, mesh_spec)
    load_engine_state(eng2, str(tmp_path))  # auto-detects orbax
    _assert_same_params(eng, eng2)
    assert eng2.version == eng.version
    # Optimizer state restored too: another identical step stays in sync.
    _step(eng, seed=5)
    _step(eng2, seed=5)
    _assert_same_params(eng, eng2)


def test_orbax_restore_keeps_shardings(tmp_path):
    eng = make_engine(2, "d2f2t2")
    _step(eng)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    eng2 = make_engine(98, "d2f2t2")
    load_engine_state(eng2, str(tmp_path))
    ref = jax.tree_util.tree_leaves(eng.params)
    got = jax.tree_util.tree_leaves(eng2.params)
    for r, g in zip(ref, got):
        assert r.sharding.is_equivalent_to(g.sharding, r.ndim)


def test_orbax_overwrite_allowed(tmp_path):
    """Recover checkpoints replace the previous one by contract."""
    eng = make_engine(3)
    _step(eng)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    _step(eng, seed=7)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    eng2 = make_engine(97)
    load_engine_state(eng2, str(tmp_path))
    _assert_same_params(eng, eng2)


def test_env_selects_backend(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_CKPT_BACKEND", "orbax")
    eng = make_engine(4)
    _step(eng)
    save_engine_state(eng, str(tmp_path))
    assert (tmp_path / "engine_state_orbax").is_dir()
    assert not (tmp_path / "engine_state.pkl").exists()


def test_backend_switch_never_shadows(tmp_path):
    """Saving with one backend removes the other's artifact, so a stale
    orbax dir can never shadow a newer pickle checkpoint (or vice
    versa)."""
    eng = make_engine(5)
    _step(eng)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    _step(eng, seed=11)
    save_engine_state(eng, str(tmp_path), backend="pickle")
    assert not (tmp_path / "engine_state_orbax").is_dir()
    eng2 = make_engine(96)
    load_engine_state(eng2, str(tmp_path))
    _assert_same_params(eng, eng2)  # the NEWER (pickle) state
    _step(eng, seed=12)
    save_engine_state(eng, str(tmp_path), backend="orbax")
    assert not (tmp_path / "engine_state.pkl").exists()


def test_params_only_checkpoint_into_training_engine(tmp_path):
    """A gradient-free engine's checkpoint (no optimizer state) loads
    into a training engine, leaving its Adam moments untouched (pickle
    path contract, mirrored by the metadata-driven orbax target)."""
    cfg = small_cfg()
    src = JaxTrainEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(41)),
        optimizer_config=None,  # gradient-free (ref/reward engines)
        row_len_multiple=32,
    )
    save_engine_state(src, str(tmp_path), backend="orbax")
    eng = make_engine(95)
    _step(eng)
    opt_before = jax.tree_util.tree_leaves(eng.opt_state)
    load_engine_state(eng, str(tmp_path))
    _assert_same_params(src, eng)
    opt_after = jax.tree_util.tree_leaves(eng.opt_state)
    for a, b in zip(opt_before, opt_after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_version_roundtrip(tmp_path):
    eng = make_engine(6)
    _step(eng)
    eng.version = 7
    save_engine_state(eng, str(tmp_path), backend="orbax")
    eng2 = make_engine(94)
    load_engine_state(eng2, str(tmp_path))
    assert eng2.version == 7

"""Mirrors reference tests/data/test_stats_tracker.py semantics."""

import numpy as np
import pytest

from areal_tpu.base.stats_tracker import DistributedStatsTracker, ReduceType


def test_masked_avg_sum_min_max():
    t = DistributedStatsTracker()
    mask = np.array([True, True, False, True])
    vals = np.array([1.0, 2.0, 100.0, 3.0])
    t.denominator(tokens=mask)
    t.stat(denominator="tokens", loss=vals)
    t.stat(denominator="tokens", reduce_type=ReduceType.SUM, total=vals)
    t.stat(denominator="tokens", reduce_type=ReduceType.MAX, mx=vals)
    t.stat(denominator="tokens", reduce_type=ReduceType.MIN, mn=vals)
    out = t.export()
    assert out["tokens"] == 3
    assert out["loss"] == pytest.approx(2.0)
    assert out["total"] == pytest.approx(6.0)
    assert out["mx"] == pytest.approx(3.0)
    assert out["mn"] == pytest.approx(1.0)


def test_scopes_and_accumulation():
    t = DistributedStatsTracker()
    with t.scope("ppo"):
        t.denominator(n=np.array([True, True]))
        t.stat(denominator="n", x=np.array([1.0, 3.0]))
        with t.scope("inner"):
            t.scalar(lr=0.1)
    # Second batch accumulates before export.
    with t.scope("ppo"):
        t.denominator(n=np.array([True]))
        t.stat(denominator="n", x=np.array([5.0]))
    out = t.export()
    assert out["ppo/n"] == 3
    assert out["ppo/x"] == pytest.approx(3.0)
    assert out["ppo/inner/lr"] == pytest.approx(0.1)
    assert t.export() == {}  # reset


def test_shape_mismatch_raises_at_record_time():
    t = DistributedStatsTracker()
    t.denominator(n=np.array([True, False]))
    with pytest.raises(ValueError):
        t.stat(denominator="n", x=np.array([1.0, 2.0, 3.0]))


def test_conditional_stat_pairs_with_latest_mask():
    # A stat recorded only on some batches must pair with the mask that was
    # current when it was recorded, not positionally with the first mask.
    t = DistributedStatsTracker()
    t.denominator(n=np.array([True, True]))
    t.denominator(n=np.array([True, False]))
    t.stat(denominator="n", x=np.array([10.0, 99.0]))
    out = t.export()
    assert out["x"] == pytest.approx(10.0)


def test_partial_export_reset_is_scope_safe():
    t = DistributedStatsTracker()
    with t.scope("train"):
        t.denominator(n=np.array([True]))
        t.stat(denominator="n", x=np.array([1.0]))
    with t.scope("train_eval"):
        t.scalar(acc=0.5)
    out = t.export(key="train")
    assert "train/x" in out and "train_eval/acc" not in out
    out2 = t.export()
    assert out2["train_eval/acc"] == pytest.approx(0.5)
    assert "train/x" not in out2


def test_unknown_denominator_raises():
    t = DistributedStatsTracker()
    with pytest.raises(ValueError):
        t.stat(denominator="nope", x=np.array([1.0]))


def test_empty_mask_skips_stat():
    t = DistributedStatsTracker()
    t.denominator(n=np.zeros(3, dtype=bool))
    t.stat(denominator="n", x=np.array([1.0, 2.0, 3.0]))
    out = t.export()
    assert out["n"] == 0
    assert "x" not in out

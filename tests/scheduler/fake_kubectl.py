#!/usr/bin/env python3
"""Fake `kubectl` for scheduler tests: emulates a k8s cluster at the
subprocess boundary (the same seam the reference's tests fake sbatch at).

Jobs are real local processes: `apply` launches the manifest's container
command under a supervisor that records the exit code; `get job -o json`
reports active/succeeded/failed the way the Job controller would; pods
can be SIGKILLed out-of-band (pid in the state record) to simulate a
lost node — a dead supervisor with no exit record reads as failed=1.

State lives under $FAKE_K8S_STATE:
  <job>.json  {"pid": ..., "manifest": ...}
  <job>.exit  container exit code (written on normal completion)
  <job>.log   combined stdout/stderr
"""

import json
import os
import signal
import subprocess
import sys


def main() -> int:
    state = os.environ["FAKE_K8S_STATE"]
    os.makedirs(state, exist_ok=True)
    args = sys.argv[1:]
    if args[:1] == ["-n"]:
        args = args[2:]
    op = args[0]

    def rec_path(name):
        return os.path.join(state, name + ".json")

    def exit_path(name):
        return os.path.join(state, name + ".exit")

    if op == "apply":
        manifest = json.load(sys.stdin)
        name = manifest["metadata"]["name"]
        c = manifest["spec"]["template"]["spec"]["containers"][0]
        env = dict(os.environ)
        for e in c.get("env", []):
            env[e["name"]] = e["value"]
        log = open(os.path.join(state, name + ".log"), "ab")
        sup = subprocess.Popen(
            [
                sys.executable,
                "-c",
                "import subprocess, sys\n"
                "rc = subprocess.call(sys.argv[2:])\n"
                "open(sys.argv[1], 'w').write(str(rc))\n",
                exit_path(name),
                *c["command"],
            ],
            env=env,
            cwd=c.get("workingDir") or None,
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        with open(rec_path(name), "w") as f:
            json.dump({"pid": sup.pid, "manifest": manifest}, f)
        print(f"job.batch/{name} created")
        return 0

    if op == "get":
        name = args[2]
        if not os.path.exists(rec_path(name)):
            print(
                f'Error from server (NotFound): jobs.batch "{name}" not found',
                file=sys.stderr,
            )
            return 1
        with open(rec_path(name)) as f:
            rec = json.load(f)
        if os.path.exists(exit_path(name)):
            with open(exit_path(name)) as f:
                rc = int(f.read().strip() or 1)
            status = {"succeeded": 1} if rc == 0 else {"failed": 1}
        else:
            try:
                os.kill(rec["pid"], 0)
                status = {"active": 1}
            except (ProcessLookupError, PermissionError):
                # Supervisor died without writing an exit record: the pod
                # was killed (lost node / OOM-kill) -> Job sees a failure.
                status = {"failed": 1}
        print(
            json.dumps(
                {"metadata": {"name": name}, "status": status}
            )
        )
        return 0

    if op == "delete":
        name = args[2]
        if os.path.exists(rec_path(name)):
            with open(rec_path(name)) as f:
                rec = json.load(f)
            try:
                os.killpg(rec["pid"], signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            for p in (rec_path(name), exit_path(name)):
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass
            print(f'job.batch "{name}" deleted')
        elif "--ignore-not-found" not in args:
            print(
                f'Error from server (NotFound): jobs.batch "{name}" not found',
                file=sys.stderr,
            )
            return 1
        return 0

    print(f"fake kubectl: unknown op {op!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())

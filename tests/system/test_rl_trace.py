"""Tier-1 cross-process RL-trace e2e (ISSUE 3 tentpole + CI satellite).

Three real OS processes play three worker roles (rollout worker ->
generation server -> trainer), propagating one rollout's trace context
through files the way the system threads it through transport metadata.
The parent then merges the shards and asserts the acceptance shape: one
trace's spans on >= 3 worker tracks, parent/flow links intact, and the
derived report producing a staleness histogram and an overlap score.

The merge SCRIPT runs here too (exit-0 smoke + report), so a malformed
emitter or a broken validator fails tier-1, not a debugging session.
"""

import json
import os
import subprocess
import sys

from areal_tpu.utils import rl_trace

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Each role script reads/writes small JSON handoff files, mimicking the
# transport-metadata propagation (inject -> send -> extract -> child
# span) across real process boundaries with real per-process shards.
ROLLOUT_ROLE = """
import json, os, sys, time
from areal_tpu.base import tracing
tracing.configure_worker("rollout_worker/0")
ep = tracing.start_span("rollout.episode", qid="q0")
tracing.set_current(ep.ctx)
with tracing.span("gen.chunk", server="s0", reprefill_tokens=7):
    time.sleep(0.02)
with open(sys.argv[1], "w") as f:
    json.dump({"ctx": tracing.inject(), "trace": ep.ctx.trace_id}, f)
time.sleep(0.03)
ep.end(accepted=True)
tracing.flush()
"""

SERVER_ROLE = """
import json, sys, time
from areal_tpu.base import tracing
tracing.configure_worker("generation_server/0")
with open(sys.argv[1]) as f:
    handoff = json.load(f)
ctx = tracing.extract(handoff["ctx"])
with tracing.span("server.generate", ctx=ctx, qid="q0", n_tokens=8):
    time.sleep(0.05)
t0 = tracing.now_ns()
time.sleep(0.02)
tracing.record_span("server.decode_block", t0, n_running=1)
tracing.flush()
"""

TRAINER_ROLE = """
import json, sys, time
from areal_tpu.base import tracing
tracing.configure_worker("model_worker/0")
with open(sys.argv[1]) as f:
    handoff = json.load(f)
ctx = tracing.extract(handoff["ctx"])
t0 = tracing.now_ns()
time.sleep(0.02)
tracing.record_span(
    "buffer.wait", t0, ctx=ctx, rpc="actor_train",
    version_start=1, version_end=1, train_step=4,
)
with tracing.span(
    "mfc.actor_train", itype="train_step",
    consumed_traces=[handoff["trace"]],
):
    time.sleep(0.05)
tracing.flush()
"""


def _run_role(script, handoff, trace_dir):
    env = dict(os.environ)
    env["AREAL_RL_TRACE"] = "1"
    env["AREAL_RL_TRACE_DIR"] = trace_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Keep the child interpreters light: no jax, no sitecustomize device
    # init beyond what the env forces.
    r = subprocess.run(
        [sys.executable, "-c", script, handoff],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, f"role failed:\n{r.stdout}\n{r.stderr}"


def test_three_roles_merge_with_flow_links(tmp_path):
    trace_dir = str(tmp_path / "rl_trace")
    handoff = str(tmp_path / "handoff.json")
    _run_role(ROLLOUT_ROLE, handoff, trace_dir)
    _run_role(SERVER_ROLE, handoff, trace_dir)
    _run_role(TRAINER_ROLE, handoff, trace_dir)

    shards = rl_trace.load_shards(trace_dir)
    assert len(shards) == 3
    assert rl_trace.validate(shards) == []

    # One rollout's spans across >= 3 worker roles, with intact parents.
    by_trace = {}
    for s in shards:
        for sp in s.spans:
            by_trace.setdefault(sp["trace"], set()).add(s.worker)
    rollout_traces = [t for t, w in by_trace.items() if len(w) >= 3]
    assert rollout_traces, f"no trace spanned 3 roles: {by_trace}"

    merged = rl_trace.merge_to_chrome(shards)
    events = merged["traceEvents"]
    procs = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert procs == {
        "rollout_worker/0", "generation_server/0", "model_worker/0"
    }
    # Flow events stitch the rollout across >= 3 pids.
    fid_pids = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f"):
            fid_pids.setdefault(e["id"], set()).add(e["pid"])
    assert any(len(p) >= 3 for p in fid_pids.values()), fid_pids

    # Derived reports: staleness histogram (4 - 1 = 3) + overlap score
    # (server busy and train busy overlap was arranged by the sleeps).
    assert rl_trace.staleness_histogram(shards) == {3: 1}
    ov = rl_trace.overlap_score(shards)
    assert ov["wall_s"] > 0
    assert ov["gen_busy_frac"] > 0 and ov["train_busy_frac"] > 0
    report = rl_trace.format_report(shards)
    assert "staleness histogram" in report and "overlap score" in report
    phases = rl_trace.phase_latency(shards)
    assert phases["interrupted_reprefill"]["tokens"] == 7


def test_merge_script_smoke(tmp_path):
    """The CI wiring: the script validates, merges, and reports with exit
    code 0 on a well-formed shard set."""
    trace_dir = str(tmp_path / "rl_trace")
    handoff = str(tmp_path / "handoff.json")
    _run_role(ROLLOUT_ROLE, handoff, trace_dir)
    _run_role(TRAINER_ROLE, handoff, trace_dir)

    out_json = str(tmp_path / "merged.json")
    r = subprocess.run(
        [
            sys.executable, "scripts/merge_rl_trace.py", trace_dir,
            "-o", out_json, "--report",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "overlap score" in r.stdout
    assert "staleness histogram" in r.stdout
    with open(out_json) as f:
        merged = json.load(f)
    assert any(e.get("ph") == "X" for e in merged["traceEvents"])

import pytest

from areal_tpu.base.topology import MeshSpec, ProcessTopology


def test_rank_coord_roundtrip():
    topo = ProcessTopology(axes=["data", "pipe", "tensor"], dims=[2, 3, 4])
    assert topo.world_size == 24
    for r in range(24):
        coord = topo.get_coord(r)
        assert topo.get_rank(**coord) == r


def test_filter_match():
    topo = ProcessTopology(axes=["data", "tensor"], dims=[2, 4])
    ranks = topo.filter_match(data=1)
    assert ranks == [4, 5, 6, 7]
    assert topo.get_axis_list("tensor", 5) == [4, 5, 6, 7]
    assert topo.get_axis_list("data", 5) == [1, 5]


def test_mesh_spec_parse():
    s = MeshSpec.parse("d2t4")
    assert s.data == 2 and s.tensor == 4 and s.size == 8
    s = MeshSpec.parse("d2f2s1t2")
    assert s.dp_size == 4 and s.size == 8
    # Megatron-style 'm' alias for tensor; p1 tolerated.
    s = MeshSpec.parse("d4p1m2")
    assert s.data == 4 and s.tensor == 2
    with pytest.raises(ValueError):
        MeshSpec.parse("d2p2m1")  # real PP stages unsupported by design
    assert str(MeshSpec(data=2, tensor=4)) == "d2f1s1t4"

"""The ONE module allowed to spell ``areal-*/vN`` wire-schema strings.

Every serialized artifact that crosses a process boundary stamps a
schema tag so a reader can reject payloads from a different protocol
generation (kv handoff, weight chunk manifests, trainer slab layouts,
bench records). Those tags used to be module-local literals in four
files — a version bump touching three of them would silently fork the
protocol. The ``wire-schema`` checker in ``areal_tpu/lint`` now flags
any ``areal-*/vN`` string literal outside this module, so a bump is a
one-line change here plus the readers' compat logic.

Bumping a version: add the new constant (keep the old one while any
reader in the fleet still accepts it), update the producers, then
retire the old constant — the env-knob checker's dead-entry analogue
here is simply the unused-name report from ruff.

Stdlib-only; imported by the no-jax lint gate.
"""

# Paged-KV prefill->decode handoff payload (engine/kv_handoff.py).
KV_HANDOFF_V1 = "areal-kv-handoff/v1"

# Tiered-KV manifest: a spilled/parked prefix advertised by a holder
# (engine/kv_tier.py store entries; the /kv/{manifest,index} surface on
# generation servers; the manager's global prefix index). The payload
# bytes inside stay byte-identical KV_HANDOFF_V1 blobs — this schema
# only wraps WHERE a prefix lives (holder url + tier), never HOW its
# KV is encoded.
KV_TIER_V1 = "areal-kv-tier/v1"

# Content-hashed weight chunk stream + manifest (base/chunking.py).
WEIGHT_CHUNKS_V1 = "areal-weight-chunks/v1"

# Trainer dump layout sidecar (system/weight_transfer.py).
WEIGHT_LAYOUT_V1 = "areal-weight-layout/v1"

# Shard-local trainer slab index (system/weight_transfer.py).
WEIGHT_SLABS_V1 = "areal-weight-slabs/v1"

# Banked bench evidence record / aggregated report (bench/bank.py).
BENCH_RECORD_V1 = "areal-bench-record/v1"
BENCH_REPORT_V1 = "areal-bench-report/v1"

# Gserver-manager HA lease: the tiny epoch + weight-version record a
# manager persists in name_resolve so a successor can fence the old
# generation and resume at the right version
# (system/fleet_controller.py).
FLEET_LEASE_V1 = "areal-fleet-lease/v1"

# Trainer checkpoint manifest: the commit record written LAST (atomic
# rename) after every engine-state artifact landed, carrying the
# version, LR-schedule position, RNG state, and dataset cursors a
# resume needs to continue bit-identically (engine/checkpoint.py).
TRAIN_CKPT_V1 = "areal-train-ckpt/v1"

# Rollout-buffer write-ahead log: the append-only journal of samples
# accepted into the training plane, replayed on restart so in-flight
# rollouts survive a trainer kill (system/wal.py).
BUFFER_WAL_V1 = "areal-buffer-wal/v1"

# Master recovery record: RecoverInfo pickle wrapper, including the
# consumed-sequence ledger persisted atomically with each checkpoint
# barrier (base/recover.py).
RECOVER_INFO_V1 = "areal-recover-info/v1"

# Multi-tenant gateway public wire: the OpenAI-compatible request /
# SSE-chunk envelope served on /v1/completions and /v1/chat/completions
# (api/public.py, system/gateway.py). Stamped into every non-SSE JSON
# response and the /v1/usage report.
GATEWAY_V1 = "areal-gateway/v1"

# Gateway usage-ledger write-ahead log: one journaled record per
# completed request / shed, replayed at gateway restart with
# request-id dedup for exactly-once tenant accounting
# (system/gateway.py over the system/wal.py journal machinery).
GW_USAGE_WAL_V1 = "areal-gw-usage-wal/v1"

# Model-registry record: one name_resolve JSON document per served
# model family (system/model_registry.py) — model_id, config hash,
# tokenizer/family metadata, pool policy. The gserver manager
# partitions the fleet into per-model pools from these records; a
# heartbeat naming a model_id with no record here is quarantined, and
# the gateway resolves tenant entitlements against the same ids.
MODEL_REGISTRY_V1 = "areal-model-registry/v1"

"""Generation-server manager: router + staleness controller + weight updater.

Counterpart of the reference's GserverManager
(realhf/system/gserver_manager.py:32-496). Singleton worker that:

- routes generation requests across servers (/schedule_request) with
  round_robin / least_requests / least_token_usage policies
- gates new rollouts by capacity and staleness (/allocate_rollout):
  a rollout may start only if (expected model version when it trains) -
  (current weight version) <= max_head_offpolicyness
- watches the trainer's published model version and fans out
  /update_weights_from_disk (interrupting running requests) to servers
- GCs old param-realloc dumps
"""

from __future__ import annotations

import asyncio
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

import aiohttp
from aiohttp import web

from areal_tpu.api.system_api import GserverManagerConfig
from areal_tpu.base import constants, logging, name_resolve, names, network
from areal_tpu.system.worker_base import PollResult, Worker

logger = logging.getLogger("gserver_manager")


class RolloutStat:
    def __init__(self):
        self.submitted = 0
        self.running = 0
        self.accepted = 0

    def as_dict(self):
        return dict(
            submitted=self.submitted, running=self.running, accepted=self.accepted
        )


class GserverManager(Worker):
    def _configure(self, config: GserverManagerConfig):
        self.cfg = config
        constants.set_experiment_trial_names(
            config.experiment_name, config.trial_name
        )
        # Wait for all generation servers to register.
        key = names.gen_servers(config.experiment_name, config.trial_name)
        deadline = time.monotonic() + 300
        while True:
            urls = name_resolve.get_subtree(key)
            if len(urls) >= config.n_servers:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(urls)}/{config.n_servers} generation servers up"
                )
            time.sleep(0.2)
        self.server_urls: List[str] = sorted(urls)
        self._rr = 0
        self._server_reqs = {u: 0 for u in self.server_urls}  # in-flight est.
        self._server_tokens = {u: 0.0 for u in self.server_urls}
        self.weight_version = 0
        self.last_weight_sync_s = 0.0
        self.rollout_stat = RolloutStat()
        self._lock = threading.Lock()
        self._last_metrics_poll = 0.0
        self._server_gen_totals = {u: 0.0 for u in self.server_urls}
        self._server_prefix_hits = {u: 0.0 for u in self.server_urls}
        self._server_prefix_reused = {u: 0.0 for u in self.server_urls}
        self._server_spec_yield = {u: 0.0 for u in self.server_urls}
        self._last_gen_total = 0.0
        self._last_throughput_log = time.monotonic()
        self._throughput_log_interval = 10.0

        self._http_loop = asyncio.new_event_loop()
        self._http_ready = threading.Event()
        self._http_thread = threading.Thread(target=self._serve_http, daemon=True)
        self._http_thread.start()
        if not self._http_ready.wait(30):
            raise RuntimeError("gserver manager HTTP failed to start")
        name_resolve.add(
            names.gen_server_manager(config.experiment_name, config.trial_name),
            self.address,
            keepalive_ttl=60,
            replace=True,
        )
        logger.info(
            f"gserver manager at {self.address}, servers={self.server_urls}"
        )

    # ------------------------------------------------------------------
    # Scheduling / staleness
    # ------------------------------------------------------------------

    def _choose_server(self, meta: Dict) -> str:
        prev = meta.get("previous_server_url") or ""
        prev_version = int(meta.get("previous_version", -1))
        # Sticky routing while the version is unchanged (KV prefix reuse).
        if prev in self.server_urls and prev_version == self.weight_version:
            return prev
        policy = self.cfg.schedule_policy
        if policy == "least_requests":
            return min(self.server_urls, key=lambda u: self._server_reqs[u])
        if policy == "least_token_usage":
            return min(self.server_urls, key=lambda u: self._server_tokens[u])
        url = self.server_urls[self._rr % len(self.server_urls)]
        self._rr += 1
        return url

    def _training_samples(self) -> int:
        try:
            return int(
                name_resolve.get(
                    names.training_samples(
                        self.cfg.experiment_name, self.cfg.trial_name
                    )
                )
            )
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return 0

    def is_staled(self) -> bool:
        """Staleness gate (reference gserver_manager.py:351-366): if this
        rollout trained at the version implied by samples already produced,
        would it be more than max_head_offpolicyness behind?"""
        global_samples = max(
            self._training_samples(),
            self.rollout_stat.submitted,
        )
        expected_version = global_samples // self.cfg.train_batch_size
        return (
            expected_version - self.weight_version
            > self.cfg.max_head_offpolicyness
        )

    # ------------------------------------------------------------------
    # HTTP endpoints
    # ------------------------------------------------------------------

    def _serve_http(self):
        asyncio.set_event_loop(self._http_loop)
        app = web.Application()
        app.router.add_post("/schedule_request", self._h_schedule)
        app.router.add_post("/allocate_rollout", self._h_allocate)
        app.router.add_post("/finish_rollout", self._h_finish)
        app.router.add_get("/status", self._h_status)
        runner = web.AppRunner(app)
        self._http_loop.run_until_complete(runner.setup())
        host = network.gethostip()
        port = network.find_free_port()
        self._http_loop.run_until_complete(web.TCPSite(runner, host, port).start())
        self.address = f"http://{host}:{port}"
        self._http_ready.set()
        self._http_loop.run_forever()

    async def _h_schedule(self, request: web.Request) -> web.Response:
        meta = await request.json()
        with self._lock:
            url = self._choose_server(meta)
            self._server_reqs[url] += 1
        return web.json_response({"url": url, "version": self.weight_version})

    async def _h_allocate(self, request: web.Request) -> web.Response:
        await request.json()
        with self._lock:
            cap = self.cfg.max_concurrent_rollouts or (1 << 30)
            if self.rollout_stat.running >= cap:
                return web.json_response(
                    {"success": False, "reason": "capacity"}
                )
            if self.is_staled():
                return web.json_response(
                    {"success": False, "reason": "staled",
                     "version": self.weight_version}
                )
            self.rollout_stat.submitted += 1
            self.rollout_stat.running += 1
        return web.json_response({"success": True, "version": self.weight_version})

    async def _h_finish(self, request: web.Request) -> web.Response:
        d = await request.json()
        with self._lock:
            self.rollout_stat.running -= 1
            if d.get("accepted", True):
                self.rollout_stat.accepted += 1
            else:
                # Rejected rollouts give their staleness budget back.
                self.rollout_stat.submitted -= 1
        return web.json_response({"success": True})

    async def _h_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "weight_version": self.weight_version,
                "rollout_stat": self.rollout_stat.as_dict(),
                "servers": self.server_urls,
            }
        )

    # ------------------------------------------------------------------
    # Weight-update fanout (runs on the worker poll loop)
    # ------------------------------------------------------------------

    def check_new_params(self) -> Optional[str]:
        try:
            v = int(
                name_resolve.get(
                    names.model_version(
                        self.cfg.experiment_name,
                        self.cfg.trial_name,
                        self.cfg.model_name,
                    )
                )
            )
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None
        if v <= self.weight_version:
            return None
        path = os.path.join(
            constants.get_param_realloc_path(
                self.cfg.experiment_name, self.cfg.trial_name
            ),
            self.cfg.model_name,
        )
        if not os.path.exists(os.path.join(path, "engine_state.pkl")):
            return None
        self._new_version = v
        return path

    def flush_requests_and_update_weights(self, path: str):
        t_start = time.monotonic()
        load_stats: list = []

        async def _update():
            async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.cfg.flush_request_timeout)
            ) as sess:
                tasks = [
                    sess.post(
                        f"{u}/update_weights_from_disk",
                        json={
                            "model_path": path,
                            "allow_interrupt": True,
                            # Pin the engines to the trainer's published
                            # version so routing/staleness accounting agree.
                            "version": self._new_version,
                        },
                    )
                    for u in self.server_urls
                ]
                resps = await asyncio.gather(*tasks, return_exceptions=True)
                for u, r in zip(self.server_urls, resps):
                    if isinstance(r, Exception):
                        raise RuntimeError(f"weight update to {u} failed: {r!r}")
                    body = await r.json()
                    if not body.get("success"):
                        raise RuntimeError(
                            f"weight update to {u} rejected: {body}"
                        )
                    load_stats.append(
                        (body.get("source", "?"), float(body.get("load_s", 0.0)))
                    )

        fut = asyncio.run_coroutine_threadsafe(_update(), self._http_loop)
        fut.result(timeout=self.cfg.flush_request_timeout + 10)
        with self._lock:
            self.weight_version = self._new_version
            self.last_weight_sync_s = time.monotonic() - t_start
        # Sync latency is the async-RL staleness floor (reference bar:
        # <3 s/transfer, blog/AReaL_v0_2.md:52-54) — always logged.
        logger.info(
            f"all servers updated to weight version {self.weight_version} "
            f"in {self.last_weight_sync_s:.3f}s "
            f"(loads: {', '.join(f'{s} {t:.3f}s' for s, t in load_stats)})"
        )

    async def _poll_metrics(self):
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=5)
        ) as sess:
            for u in list(self.server_urls):
                try:
                    async with sess.get(f"{u}/metrics") as r:
                        text = await r.text()
                    for line in text.splitlines():
                        if line.startswith("areal:num_used_tokens"):
                            self._server_tokens[u] = float(line.split()[-1])
                        elif line.startswith("areal:num_running_reqs"):
                            self._server_reqs[u] = int(float(line.split()[-1]))
                        elif line.startswith("areal:total_generated_tokens"):
                            self._server_gen_totals[u] = float(line.split()[-1])
                        elif line.startswith("areal:prefix_cache_hits"):
                            self._server_prefix_hits[u] = float(
                                line.split()[-1]
                            )
                        elif line.startswith("areal:prefix_tokens_reused"):
                            self._server_prefix_reused[u] = float(
                                line.split()[-1]
                            )
                        elif line.startswith("areal:spec_tokens_per_step"):
                            self._server_spec_yield[u] = float(
                                line.split()[-1]
                            )
                except Exception:
                    logger.warning(f"metrics poll failed for {u}")

    def _poll(self) -> Optional[PollResult]:
        try:
            status = name_resolve.get(
                names.experiment_status(
                    self.cfg.experiment_name, self.cfg.trial_name
                )
            )
            if status in ("COMPLETE", "ABORT"):
                return None
        except name_resolve.NameEntryNotFoundError:
            pass

        path = self.check_new_params()
        if path is not None:
            try:
                self.flush_requests_and_update_weights(path)
            except Exception:
                # Transient server failure: weight_version stays put, so the
                # next poll retries the (idempotent, version-pinned) fanout.
                logger.warning("weight-update fanout failed; will retry",
                               exc_info=True)
                time.sleep(1.0)
            return PollResult(batch_count=1)
        if time.monotonic() - self._last_metrics_poll > 2.0:
            fut = asyncio.run_coroutine_threadsafe(
                self._poll_metrics(), self._http_loop
            )
            try:
                fut.result(timeout=10)
            except Exception:
                pass
            self._last_metrics_poll = time.monotonic()
        # Periodic generation-throughput log (reference
        # gserver_manager.py:279-285): interval tokens/s over all servers
        # plus the rollout counters.
        now = time.monotonic()
        if now - self._last_throughput_log > self._throughput_log_interval:
            total_gen = sum(self._server_gen_totals.values())
            dt = now - self._last_throughput_log
            tps = (total_gen - self._last_gen_total) / dt
            with self._lock:
                rs = self.rollout_stat.as_dict()
            logger.info(
                f"generation throughput: {tps:.0f} tokens/s "
                f"(total {total_gen:.0f}) rollouts={rs} "
                f"weight_version={self.weight_version} "
                f"prefix_cache_hits={sum(self._server_prefix_hits.values()):.0f} "
                f"prefix_tokens_reused="
                f"{sum(self._server_prefix_reused.values()):.0f}"
                + (
                    # Realized speculation yield (mean over servers
                    # reporting >0; 0 means speculation is off fleet-wide).
                    f" spec_tokens_per_step="
                    f"{sum(y) / len(y):.2f}"
                    if (y := [v for v in self._server_spec_yield.values()
                              if v > 0])
                    else ""
                )
            )
            self._last_gen_total = total_gen
            self._last_throughput_log = now
        time.sleep(0.05)
        return PollResult(batch_count=0)

    def _exit_hook(self):
        try:
            self._http_loop.call_soon_threadsafe(self._http_loop.stop)
            self._http_thread.join(timeout=5)
        except Exception:
            pass

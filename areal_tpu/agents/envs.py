"""Verification environments.

Counterpart of the reference's math-code environment
(realhf/impl/environment/math_code_single_step_env.py:75): a single-step
env whose action is (qid, answer_texts, task, answer_info) and whose
"observation" is the per-answer success list from the verifiers.
"""

from __future__ import annotations

import asyncio
import json
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Tuple

from areal_tpu.api.env_api import EnvironmentService, register_environment
from areal_tpu.functioncall.code_verify import code_verify
from areal_tpu.functioncall.math_grader import grade_answer


class MathCodeSingleStepEnv(EnvironmentService):
    def __init__(self, max_workers: int = 8):
        self._pool = ThreadPoolExecutor(max_workers=max_workers)

    def _verify_one(self, task: str, text: str, answer_info: Any) -> bool:
        if task == "code":
            cases = answer_info
            if isinstance(cases, str):
                cases = json.loads(cases)
            return code_verify(text, cases)
        return grade_answer(text, answer_info)

    async def step(self, action) -> Tuple[Any, float, bool, bool, dict]:
        qid, answers, task, answer_info = action
        loop = asyncio.get_running_loop()
        successes: List[bool] = list(
            await asyncio.gather(
                *[
                    loop.run_in_executor(
                        self._pool, self._verify_one, task, a, answer_info
                    )
                    for a in answers
                ]
            )
        )
        return successes, 0.0, True, False, {}


register_environment("math-code-single-step", MathCodeSingleStepEnv)

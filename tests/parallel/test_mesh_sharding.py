"""Mesh/sharding tests on the 8-device virtual CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from areal_tpu.base.topology import MeshSpec
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.packing import pack_sequences
from areal_tpu.models.transformer import forward, init_params
from areal_tpu.parallel.mesh import AllocationMode, make_mesh
from areal_tpu.parallel.realloc import (
    gc_param_versions,
    latest_param_version,
    load_param_version,
    reshard_params,
    save_param_version,
)
from areal_tpu.parallel.sharding import (
    batch_sharding,
    param_partition_spec,
    param_shardings,
    shard_params,
)


def small_cfg():
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32",
    )


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_partition_specs():
    assert param_partition_spec("embedding/weight", 2) == P("tensor", "fsdp")
    assert param_partition_spec("layers/attn/wq", 3) == P(None, "fsdp", "tensor")
    assert param_partition_spec("layers/attn/wo", 3) == P(None, "tensor", "fsdp")
    assert param_partition_spec("layers/mlp/w_down", 3) == P(None, "tensor", "fsdp")
    assert param_partition_spec("layers/ln1/weight", 2) == P(None, None)
    assert param_partition_spec("head/weight", 2) == P("fsdp", "tensor")


@pytest.mark.parametrize("spec_str", ["d2t4", "d2f2t2", "d8", "t8", "d2f2s2t1"])
def test_sharded_forward_matches_single_device(spec_str):
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 64, size=l) for l in [12, 20, 9, 17]]
    batch = pack_sequences(seqs, row_len=32, n_rows_multiple=8)

    ref = forward(params, cfg, batch.input_ids, batch.segment_ids, batch.positions,
                  attn_impl="reference")

    mesh = make_mesh(MeshSpec.parse(spec_str))
    sharded = shard_params(params, mesh)
    bsh = batch_sharding(mesh)
    args = [jax.device_put(x, bsh) for x in
            (batch.input_ids, batch.segment_ids, batch.positions)]

    @jax.jit
    def f(p, i, s, pos):
        return forward(p, cfg, i, s, pos, attn_impl="reference")

    from areal_tpu.utils.jax_compat import set_mesh

    with set_mesh(mesh):
        out = f(sharded, *args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_reshard_between_meshes():
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(1))
    mesh_a = make_mesh(MeshSpec.parse("d4t2"))
    mesh_b = make_mesh(MeshSpec.parse("t8"))
    pa = shard_params(params, mesh_a)
    pb = reshard_params(pa, mesh_b)
    for x, y in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(pb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_allocation_mode_partitions():
    am = AllocationMode.parse("gen.d4t1+d2t2")
    assert am.decoupled
    parts = am.partitions(8)
    assert parts["gen"].device_ids == [0, 1, 2, 3]
    assert parts["train"].device_ids == [4, 5, 6, 7]
    am2 = AllocationMode.parse("d4t2")
    assert not am2.decoupled
    assert am2.partitions(8)["train"].mesh_spec.size == 8
    with pytest.raises(ValueError):
        AllocationMode.parse("gen.d8t1+d8t1").partitions(8)


def test_param_version_roundtrip(tmp_path):
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(2))
    root = str(tmp_path / "realloc")
    save_param_version(params, root, 0)
    save_param_version(params, root, 1, meta={"step": 10})
    assert latest_param_version(root) == 1
    loaded = load_param_version(root, 1)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    save_param_version(params, root, 2)
    gc_param_versions(root, keep_latest=1)
    assert latest_param_version(root) == 2
    assert load_param_version(root, 2) is not None
    with pytest.raises(FileNotFoundError):
        load_param_version(root, 0)


def test_critic_head_fits_tensor_mesh():
    # [D, 1] head cannot shard its size-1 dim over tensor; spec must degrade.
    from areal_tpu.parallel.sharding import fit_spec_to_shape
    mesh = make_mesh(MeshSpec.parse("d2t4"))
    fitted = fit_spec_to_shape(P("fsdp", "tensor"), (32, 1), mesh)
    assert fitted == P("fsdp", None)
    cfg = small_cfg()
    cfg.is_critic = True
    params = init_params(cfg, jax.random.PRNGKey(5))
    sharded = shard_params(params, mesh)  # must not raise
    assert sharded["head"]["weight"].shape == (32, 1)


def test_moe_fsdp_fallback_specs():
    """When num_experts doesn't divide fsdp, expert weights must fall
    back to hidden-dim ZeRO sharding, never silent replication (the
    expert leaves are the bulk of model memory)."""
    from areal_tpu.parallel.sharding import fitted_param_spec

    mesh = make_mesh(MeshSpec.parse("f2t2"), jax.devices()[:4])
    # E=4 divides fsdp=2: the expert dim shards.
    assert fitted_param_spec(
        "layers/mlp/w_gate", (2, 4, 32, 64), mesh
    ) == P(None, "fsdp", None, "tensor")
    # E=3 does not: hidden dim takes the fsdp shard instead.
    assert fitted_param_spec(
        "layers/mlp/w_gate", (2, 3, 32, 64), mesh
    ) == P(None, None, "fsdp", "tensor")
    assert fitted_param_spec(
        "layers/mlp/w_up", (2, 3, 32, 64), mesh
    ) == P(None, None, "fsdp", "tensor")
    assert fitted_param_spec(
        "layers/mlp/w_down", (2, 3, 64, 32), mesh
    ) == P(None, None, "tensor", "fsdp")


def test_fitted_param_spec_matches_devices_indices_map():
    """spec_slices (the weight plane's byte slicer) and
    NamedSharding.devices_indices_map (what the engine actually places)
    must agree per device for every MoE leaf shape — including the
    indivisible-E ZeRO fallback."""
    from jax.sharding import NamedSharding

    from areal_tpu.parallel.sharding import fitted_param_spec, spec_slices

    mesh = make_mesh(MeshSpec.parse("f2t2"), jax.devices()[:4])
    cases = [
        ("layers/mlp/w_gate", (2, 4, 32, 64)),   # EP-shardable
        ("layers/mlp/w_gate", (2, 3, 32, 64)),   # ZeRO fallback
        ("layers/mlp/w_down", (2, 3, 64, 32)),   # fallback, F/D swapped
        ("layers/mlp/router", (2, 32, 4)),       # non-expert leaf
        ("layers/attn/wq", (2, 32, 32)),
    ]
    sizes = dict(mesh.shape)
    for path, shape in cases:
        spec = fitted_param_spec(path, shape, mesh)
        idx_map = NamedSharding(mesh, spec).devices_indices_map(shape)
        for idx, dev in np.ndenumerate(mesh.devices):
            coords = dict(zip(mesh.axis_names, map(int, idx)))
            want = [
                (sl.start or 0, sl.stop if sl.stop is not None else d)
                for sl, d in zip(idx_map[dev], shape)
            ]
            got = spec_slices(spec, shape, sizes, coords)
            assert got == want, (path, shape, dev)

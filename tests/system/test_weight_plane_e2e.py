"""ISSUE 5 acceptance: the streaming weight-distribution plane across
real process boundaries — 1 trainer-side dump + source (parent) feeding
3 real GenerationServer processes (real ServingEngines on CPU jax)
through a real GserverManager peer-fanout tree.

Asserted end to end:
- each full weight payload leaves the trainer-side source EXACTLY once
  per version (peer hops serve the rest; transfer counters on the
  source and per-server /metrics)
- an in-flight /generate is interrupted by the cutover and resumed
  (client re-prefill) against the new version
- per-server weight_cutover_ms is reported separately from
  weight_transfer_ms in /metrics and in the manager /status surface
- chaos (AREAL_FAULTS): a peer killed mid-transfer on the next version
  bump -> the manager re-fanouts around it, survivors cut over, the
  dead server is evicted, and origin egress STAYS one payload.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
import uuid

import numpy as np
import pytest

from tests import fixtures

# Multi-process, compile-bound: keep off shared workers (pytest.ini).
pytestmark = [pytest.mark.serial, pytest.mark.chaos]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N_SERVERS = 3
CHUNK_BYTES = 1 << 15
MODEL_CFG = dict(
    n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
    intermediate_dim=64, vocab_size=64, compute_dtype="float32",
    param_dtype="float32",
)

CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
import jax; jax.config.update("jax_platforms", "cpu")
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.api.config import ModelAbstraction
from areal_tpu.system.generation_server import GenerationServer
import areal_tpu.engine.factories  # registry
cfg = GenerationServerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, server_index=%(idx)d,
    model=ModelAbstraction("tpu_transformer", args=dict(config=%(model_cfg)r)),
    max_concurrent_requests=2, max_seq_len=1024, kv_page_size=8,
    decode_block_steps=4, prompt_bucket=32, seed=0,
)
w = GenerationServer()
w.configure(cfg, experiment_name=cfg.experiment_name, trial_name=cfg.trial_name,
            worker_name=cfg.worker_name)
w.run()
'''


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url, path, payload, timeout=240):
    r = urllib.request.urlopen(
        urllib.request.Request(
            url + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json"},
        ),
        timeout=timeout,
    )
    return json.loads(r.read())


def _metrics(url):
    text = urllib.request.urlopen(url + "/metrics", timeout=30).read().decode()
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                # Non-scalar surfaces (latency histogram encodings).
                out[parts[0]] = parts[1]
    return out


def _wait_until(cond, timeout, msg, proc_check=None):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        if proc_check is not None:
            proc_check()
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.timeout(600)
def test_fleet_fanout_interrupt_resume_and_chaos_refanout(
    tmp_path, monkeypatch
):
    import jax

    from areal_tpu.base import constants, name_resolve, names
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params
    from areal_tpu.system.gserver_manager import GserverManager
    from areal_tpu.system.weight_plane import WeightPlaneSource
    from areal_tpu.system.weight_transfer import dump_raw_params

    nr = str(tmp_path / "nr")
    exp, trial = f"wplane-{uuid.uuid4().hex[:6]}", "t0"
    monkeypatch.setenv("AREAL_HEALTH_TTL", "60")
    monkeypatch.setattr(
        constants, "PARAM_REALLOC_ROOT", str(tmp_path / "realloc")
    )
    repo = name_resolve.reconfigure("nfs", record_root=nr)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["AREAL_HEALTH_TTL"] = "60"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs, logs, cleanup = [], [], []
    try:
        for idx in range(N_SERVERS):
            child_env = dict(env)
            if idx == 2:
                # Chaos arm for phase 2: this server's SECOND weight
                # fetch (the v2 distribute) kills the process outright —
                # a peer dying mid-fleet-transfer.
                child_env["AREAL_FAULTS"] = (
                    "gserver.weight_fetch@generation_server/2=die:k=2"
                )
            log_path = tmp_path / f"server{idx}.log"
            log_f = open(log_path, "w")
            logs.append(log_path)
            cleanup.append(log_f.close)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", CHILD % dict(
                    repo=REPO, nr=nr, exp=exp, trial=trial, idx=idx,
                    model_cfg=MODEL_CFG,
                )],
                env=child_env, cwd=REPO, stdout=log_f,
                stderr=subprocess.STDOUT,
            ))

        def alive(indices=range(N_SERVERS)):
            for i in indices:
                assert procs[i].poll() is None, (
                    f"server {i} died:\n" + logs[i].read_text()[-3000:]
                )

        urls = {}

        def discovered():
            alive()
            for i in range(N_SERVERS):
                if i not in urls:
                    try:
                        urls[i] = name_resolve.get(
                            names.gen_server_url(exp, trial, str(i))
                        )
                    except name_resolve.NameEntryNotFoundError:
                        return False
            return True

        _wait_until(discovered, 240, "server discovery")

        # Trainer-side dump + weight-plane source (the dump rank).
        role_dir = os.path.join(
            constants.get_param_realloc_path(exp, trial), "actor"
        )
        os.makedirs(role_dir, exist_ok=True)
        with open(os.path.join(role_dir, "engine_state.pkl"), "wb") as f:
            f.write(b"gate")  # existence gate for check_new_params
        cfg = TransformerConfig(**MODEL_CFG)
        p1 = jax.tree_util.tree_map(
            lambda x: np.asarray(x), init_params(cfg, jax.random.PRNGKey(7))
        )
        dump_raw_params(p1, role_dir, version=1)
        src = WeightPlaneSource(role_dir, chunk_bytes=CHUNK_BYTES).start()
        cleanup.append(src.close)
        src.register(exp, trial, "actor")

        # Real manager, plane enabled, degree-1 chain = max peer hops.
        m = GserverManager()
        m.configure(GserverManagerConfig(
            experiment_name=exp, trial_name=trial, model_name="actor",
            n_servers=N_SERVERS, train_batch_size=4,
            max_head_offpolicyness=1000,
            flush_request_timeout=fixtures.scale_timeout(60.0),
            health_check_interval=0.2,
            weight_plane=True, weight_chunk_bytes=CHUNK_BYTES,
            weight_fanout_degree=1,
            weight_cutover_budget_s=fixtures.scale_timeout(10.0),
        ))
        mt = threading.Thread(target=m.run, daemon=True)
        mt.start()
        cleanup.append(lambda: mt.join(timeout=10))
        _wait_until(
            lambda: len(m._healthy_urls()) == N_SERVERS, 60,
            "manager sees 3 healthy servers", proc_check=alive,
        )

        # Warm every server's serving programs (parallel: overlap the
        # prefill/decode compiles) so the interrupt-timing below isn't
        # dominated by first-request XLA compiles.
        def warm(i):
            out = _post(urls[i], "/generate", {
                "qid": f"warm{i}", "input_ids": [5, 6, 7],
                "gconfig": {"max_new_tokens": 4, "greedy": True},
            })
            assert len(out["output_ids"]) >= 1, out
        warm_threads = [
            threading.Thread(target=warm, args=(i,)) for i in range(N_SERVERS)
        ]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join(timeout=fixtures.scale_timeout(300))
            assert not t.is_alive(), "warm generate wedged"

        # ---- Phase 1: clean fanout. An in-flight long request on
        # server 0 must be interrupted by the cutover and resumable.
        long_res = {}

        def long_generate():
            long_res["out"] = _post(urls[0], "/generate", {
                "qid": "longq", "input_ids": [5, 6, 7],
                "gconfig": {"max_new_tokens": 900, "greedy": True},
            }, timeout=fixtures.scale_timeout(300))

        lt = threading.Thread(target=long_generate, daemon=True)
        lt.start()
        _wait_until(
            lambda: _metrics(urls[0])["areal:num_running_reqs"] >= 1, 30,
            "long request running", proc_check=alive,
        )
        name_resolve.add(
            names.model_version(exp, trial, "actor"), "1", replace=True
        )
        _wait_until(
            lambda: m.weight_version == 1, 120, "v1 plane fanout",
            proc_check=alive,
        )
        lt.join(timeout=fixtures.scale_timeout(120))
        assert not lt.is_alive(), "long generate never returned"
        out = long_res["out"]
        # Interrupted mid-decode by the cutover: partial tokens, old
        # version, explicit interrupted flag.
        assert out["interrupted"] is True, out
        assert 0 < len(out["output_ids"]) < 900
        assert out["version_start"] == 0
        # Client-side resume (the AReaL re-prefill protocol): continue
        # from prompt + partial output against the NEW weights.
        resumed = _post(urls[0], "/generate", {
            "qid": "longq", "input_ids": [5, 6, 7] + out["output_ids"],
            "gconfig": {"max_new_tokens": 16, "greedy": True},
        })
        assert resumed["version_start"] == 1, resumed
        assert len(resumed["output_ids"]) >= 1

        # O(1) origin egress: each byte left the trainer-side source
        # exactly once; the other two payload copies were peer hops.
        stats = src.stats()
        assert stats["full_payload_equivalents"][1] == pytest.approx(1.0)
        total = sum(stats["bytes_served"].values())
        per_server = [_metrics(urls[i]) for i in range(N_SERVERS)]
        assert sum(
            ms["areal:weight_bytes_from_origin"] for ms in per_server
        ) == total
        assert sum(
            ms["areal:weight_bytes_from_peers"] for ms in per_server
        ) == 2 * total
        # Transfer vs cutover: separate, nonzero numbers on every server.
        for ms in per_server:
            assert ms["areal:weight_transfer_ms"] > 0.0
            assert ms["areal:weight_cutover_ms"] > 0.0
        # ... and on the manager /status surface.
        status = _get_json(m.address + "/status")
        wp = status["weight_plane"]
        assert wp["version"] == 1 and wp["failures"] == {}
        assert set(wp["transfer_ms"]) == set(urls.values())
        assert set(wp["cutover_ms"]) == set(urls.values())
        assert all(v > 0 for v in wp["transfer_ms"].values())
        assert all(v > 0 for v in wp["cutover_ms"].values())
        assert status["server_versions"] == {u: 1 for u in urls.values()}

        # ---- Phase 2: chaos. Server 2's v2 fetch kills its process
        # mid-fleet-transfer; the manager re-parents its children onto
        # surviving holders, survivors cut over, the dead server is
        # evicted — and the origin still egresses ONE payload.
        p2 = jax.tree_util.tree_map(
            lambda x: np.asarray(x), init_params(cfg, jax.random.PRNGKey(8))
        )
        dump_raw_params(p2, role_dir, version=2)
        name_resolve.add(
            names.model_version(exp, trial, "actor"), "2", replace=True
        )
        _wait_until(
            lambda: m.weight_version == 2, 180, "v2 re-fanout",
            proc_check=lambda: alive([0, 1]),
        )
        _wait_until(
            lambda: procs[2].poll() is not None, 30, "chaos kill landed"
        )
        survivors = [urls[0], urls[1]]
        status = _get_json(m.address + "/status")
        wp = status["weight_plane"]
        assert wp["version"] == 2
        assert set(wp["failures"]) == {urls[2]}
        assert set(wp["transfer_ms"]) == set(survivors)
        assert set(wp["cutover_ms"]) == set(survivors)
        _wait_until(
            lambda: urls[2] in m._evicted, 30, "dead server evicted"
        )
        # Re-fanout stayed O(1) on the origin even with the mid-transfer
        # death (the survivor chain re-fed from peers, not the source).
        assert src.stats()["full_payload_equivalents"][2] == pytest.approx(1.0)
        for u in survivors:
            check = _post(u, "/generate", {
                "qid": f"v2check-{u[-5:]}", "input_ids": [9, 10],
                "gconfig": {"max_new_tokens": 4, "greedy": True},
            })
            assert check["version_start"] == 2, check

        name_resolve.add(
            names.experiment_status(exp, trial), "COMPLETE", replace=True
        )
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE", replace=True
            )
        except Exception:
            pass
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for fn in cleanup:
            try:
                fn()
            except Exception:
                pass
        repo.reset()

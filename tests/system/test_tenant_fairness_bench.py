"""ISSUE 19 acceptance (bench leg): the `tenant_fairness` phase banks
an attested CPU-proxy record — a real gateway subprocess in front of a
real-process fleet, noisy-aggressor flood vs an interactive victim,
victim p99 TTFT (admission-to-first-token) solo vs fair-share ON vs
FIFO — and `validate_bench.py` refuses the failure classes that would
make such a record meaningless: a fair arm that did not beat FIFO, a
flood that never shed (the arms measured an idle gateway), a DRR queue
that never arbitrated, a missing solo anchor, and any starved victim
request.

The teeth run in tier-1 against a synthetic record; the full phase run
(ProcessFleet + 3 gateway spawns, ~1-2 min) is slow-marked."""

import importlib.util
import os

import pytest

from areal_tpu.bench import bank, runner
from tests.fixtures import scale_timeout

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _good_record():
    """A record shaped like a healthy banked measure pass."""
    return {
        "status": "ok",
        "pass": "measure",
        "value": {
            "solo_p99_ttft_ms": 32.0,
            "fair_p99_ttft_ms": 128.0,
            "unfair_p99_ttft_ms": 512.0,
            "fair_over_solo": 4.0,
            "unfair_over_fair": 4.0,
            "aggressor_sheds": 445.0,
            "fairshare_picks": 12.0,
            "victim_failed": 0.0,
            "wall_s": 20.0,
        },
    }


def test_tenant_fairness_teeth():
    v = _load_validator()
    assert v.validate_phase_value("tenant_fairness", _good_record()) == []

    # Each mutation is one failure class the validator must refuse.
    cases = [
        # Fair arm no better than FIFO: the weighted queue bought nothing.
        ("fair_p99_ttft_ms", 512.0, "not below the FIFO arm"),
        # No solo anchor: the flood arms float unmoored.
        ("solo_p99_ttft_ms", 0.0, "no solo baseline"),
        # Flood never saturated: both arms measured an idle gateway.
        ("aggressor_sheds", 0.0, "zero aggressor sheds"),
        # Queue never arbitrated: fair share was never exercised.
        ("fairshare_picks", 0.0, "zero DRR picks"),
        # Fairness by starvation is not fairness.
        ("victim_failed", 1.0, "failed victim"),
    ]
    for key, bad, needle in cases:
        rec = _good_record()
        rec["value"][key] = bad
        problems = v.validate_phase_value("tenant_fairness", rec)
        assert problems, f"validator swallowed {key}={bad}"
        assert any(needle in p for p in problems), (key, problems)

    # A missing schema key is refused before the semantic teeth.
    rec = _good_record()
    del rec["value"]["unfair_p99_ttft_ms"]
    assert any(
        "unfair_p99_ttft_ms" in p
        for p in v.validate_phase_value("tenant_fairness", rec)
    )


@pytest.mark.serial
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_tenant_fairness_record_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    monkeypatch.setenv("XLA_FLAGS", "")
    rec = runner.run_phase(
        "tenant_fairness", "measure", b, deadline_s=scale_timeout(360)
    )
    assert rec["status"] == "ok", rec
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"

    validator = _load_validator()
    assert validator.validate_phase_value("tenant_fairness", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # THE acceptance numbers: weighted fair share held the victim's p99
    # below the FIFO collapse while the aggressor was shed against its
    # own stream cap and no victim request failed.
    assert v["fair_p99_ttft_ms"] < v["unfair_p99_ttft_ms"]
    assert v["aggressor_sheds"] >= 1
    assert v["fairshare_picks"] >= 1
    assert v["victim_failed"] == 0.0

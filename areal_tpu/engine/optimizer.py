"""Optimizer construction: AdamW + LR schedules + global-norm clipping.

Counterpart of the reference's Megatron DistributedOptimizer + LR scheduler
wiring (realhf/impl/model/backend/megatron.py:561-700). ZeRO sharding of
optimizer state is not code here — it falls out of giving Adam's mu/nu the
same NamedShardings as their parameters (fsdp/tensor axes), see
jax_engine.opt_state_shardings.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass
class OptimizerConfig:
    """Mirrors the reference's OptimizerConfig dataclass (api/cli_args.py)."""

    type: str = "adamw"
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "constant"  # constant | linear | cosine
    warmup_steps_proportion: float = 0.001
    gradient_clipping: float = 1.0


def make_lr_schedule(cfg: OptimizerConfig, total_train_steps: int):
    warmup = int(cfg.warmup_steps_proportion * total_train_steps)
    decay_steps = max(1, total_train_steps - warmup)
    end = cfg.lr * cfg.min_lr_ratio
    if cfg.lr_scheduler_type == "constant":
        after = optax.constant_schedule(cfg.lr)
    elif cfg.lr_scheduler_type == "linear":
        after = optax.linear_schedule(cfg.lr, end, decay_steps)
    elif cfg.lr_scheduler_type == "cosine":
        after = optax.cosine_decay_schedule(cfg.lr, decay_steps, alpha=cfg.min_lr_ratio)
    else:
        raise ValueError(f"unknown lr_scheduler_type {cfg.lr_scheduler_type!r}")
    if warmup == 0:
        return after
    # Ramp starts at lr/warmup (not 0) so the very first step trains.
    return optax.join_schedules(
        [optax.linear_schedule(cfg.lr / warmup, cfg.lr, warmup), after], [warmup]
    )


def _decay_mask(params):
    """No weight decay on 1D params (norms, biases) — standard practice."""
    import jax

    return jax.tree_util.tree_map(lambda p: p.ndim > 1, params)


def make_optimizer(
    cfg: OptimizerConfig, total_train_steps: int, params_example=None,
    external_lr: bool = False,
) -> optax.GradientTransformation:
    """With ``external_lr=True`` the transformation applies a UNIT
    learning rate (as a constant schedule, so the optimizer-state
    structure — including the schedule's count leaf — stays identical to
    the internal-schedule build and old checkpoints keep loading); the
    caller scales the returned updates by the schedule value it wants.
    This is how `JaxTrainEngine.train_batch` honors `version_steps` as
    the LR-schedule position (reference semantics: several PPO minibatch
    updates share one schedule step) while Adam's bias correction keeps
    counting actual updates."""
    if cfg.type != "adamw":
        raise NotImplementedError(f"optimizer type {cfg.type!r}")
    schedule = (
        optax.constant_schedule(1.0)
        if external_lr
        else make_lr_schedule(cfg, total_train_steps)
    )
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.gradient_clipping)
        if cfg.gradient_clipping
        else optax.identity(),
        optax.adamw(
            learning_rate=schedule,
            b1=cfg.beta1,
            b2=cfg.beta2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
            mask=_decay_mask if cfg.weight_decay else None,
        ),
    )
    return tx

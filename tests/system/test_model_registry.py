"""ISSUE 20 acceptance (registry leg): the model registry refuses a
model_id collision with a DIFFERENT config hash (idempotent same-hash
re-registration is fine), a heartbeat naming an UNREGISTERED model_id
is QUARANTINED by a multi-model manager — never adopted — until the
registry learns the model, and gateway entitlement parsing rejects an
entitlement naming a model the fleet does not serve.

Time budget: ~10 s (one in-process manager over fake heartbeat
servers; no jax engines)."""

import http.server
import json
import threading
import urllib.request

import pytest

from areal_tpu.base import name_resolve, names
from areal_tpu.base.health import Heartbeat
from areal_tpu.system import model_registry as mr


@pytest.fixture()
def kv(tmp_path):
    repo = name_resolve.reconfigure(
        "nfs", record_root=str(tmp_path / "name_resolve")
    )
    yield repo
    repo.reset()


EXP, TRIAL = "registry-units", "t0"


# ----------------------------------------------------------------------
# Registration: duplicate refusal vs idempotent re-run
# ----------------------------------------------------------------------

def _rec(model_id, cfg):
    return mr.ModelRecord(
        model_id=model_id,
        family="tpu_transformer",
        config_hash=mr.config_hash(cfg),
    )


def test_duplicate_model_id_refused_unless_same_hash(kv):
    """Same id + same hash = idempotent deployment re-run; same id with
    a DIFFERENT hash is exactly the two-deployments-disagree confusion
    the registry exists to refuse."""
    first = mr.register_model(EXP, TRIAL, _rec("actor", {"n_layers": 2}))
    again = mr.register_model(EXP, TRIAL, _rec("actor", {"n_layers": 2}))
    assert again.config_hash == first.config_hash
    assert again.ts == first.ts  # the existing record, untouched
    with pytest.raises(mr.DuplicateModelError):
        mr.register_model(EXP, TRIAL, _rec("actor", {"n_layers": 3}))
    # The losing write must not have clobbered the registered record.
    assert mr.get_model(EXP, TRIAL, "actor").config_hash \
        == first.config_hash
    # A second FAMILY under its own id coexists.
    mr.register_model(EXP, TRIAL, _rec("scout", {"n_layers": 3}))
    assert set(mr.list_models(EXP, TRIAL)) == {"actor", "scout"}


def test_model_id_charset_enforced(kv):
    for bad in ("", "a/b", ".hidden", "x" * 65, "a b"):
        with pytest.raises(ValueError):
            mr.validate_model_id(bad)
    with pytest.raises(ValueError):
        mr.register_model(EXP, TRIAL, _rec("a/b", {}))


def test_unregister_then_reregister_with_new_hash(kv):
    """Intentional replacement is unregister-then-register, per the
    DuplicateModelError message."""
    mr.register_model(EXP, TRIAL, _rec("actor", {"v": 1}))
    mr.unregister_model(EXP, TRIAL, "actor")
    mr.unregister_model(EXP, TRIAL, "actor")  # idempotent
    rec = mr.register_model(EXP, TRIAL, _rec("actor", {"v": 2}))
    assert mr.get_model(EXP, TRIAL, "actor").config_hash == rec.config_hash


def test_current_weight_version_reads_model_version_pointer(kv):
    assert mr.current_weight_version(EXP, TRIAL, "actor") is None
    name_resolve.add(
        names.model_version(EXP, TRIAL, "actor"), "3", replace=True
    )
    assert mr.current_weight_version(EXP, TRIAL, "actor") == 3


# ----------------------------------------------------------------------
# Manager quarantine: unregistered-model heartbeat is never adopted
# ----------------------------------------------------------------------

class _FakeGserver:
    """Heartbeat + minimal /metrics endpoint, with a model_id in the
    heartbeat payload (the multi-model discovery surface)."""

    def __init__(self, exp, index, model_id=None, announce=True):
        lines = [
            "areal:weight_version 0.0",
            "areal:role unified",
            "areal:elastic 1.0",
        ]
        body = ("\n".join(lines) + "\n").encode()

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self, _body=body):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(_body)

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self.member = f"generation_server/{index}"
        payload = {"url": self.url, "server_index": index}
        if model_id:
            payload["model_id"] = model_id
        self.hb = Heartbeat(exp, TRIAL, self.member, payload=payload,
                            ttl=60.0)
        if announce:
            name_resolve.add_subentry(names.gen_servers(exp, TRIAL),
                                      self.url)

    def close(self):
        self.httpd.shutdown()


def test_unregistered_model_heartbeat_quarantined_not_adopted(kv):
    """The multi-model gate in the health poll: a joiner whose
    heartbeat names a model_id the registry has never heard of lands in
    the quarantine ledger and NEVER enters the routing table (routing
    it would risk silent cross-model weight/KV hits). Registering the
    model and beating again earns adoption and clears the ledger —
    the re-read-on-miss path, pinned here per model_registry.py's
    docstring."""
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.system.gserver_manager import GserverManager

    exp = "registry-quarantine"
    seed = _FakeGserver(exp, 0)  # the manager's default model_name pool
    joiner = None
    m = GserverManager()
    try:
        m.configure(GserverManagerConfig(
            experiment_name=exp, trial_name=TRIAL, n_servers=1,
            train_batch_size=4, health_check_interval=3600.0,
            multi_model=True,
        ))
        assert m.server_urls == [seed.url]
        # A joiner beating with an UNREGISTERED model_id.
        joiner = _FakeGserver(exp, 1, model_id="ghost", announce=False)
        m._poll_health()
        assert m._quarantined == {joiner.member: "ghost"}
        assert joiner.url not in m.server_urls
        # Repolling neither adopts nor duplicates the ledger row.
        m._poll_health()
        assert m._quarantined == {joiner.member: "ghost"}
        assert joiner.url not in m.server_urls
        # /status surfaces the quarantine for operators.
        with urllib.request.urlopen(m.address + "/status",
                                    timeout=10) as r:
            st = json.loads(r.read())
        assert st["quarantined"] == {joiner.member: "ghost"}
        # Registration lands; the next poll's re-read-on-miss adopts
        # the same still-beating member and clears its row.
        mr.register_model(exp, TRIAL, _rec("ghost", {"n_layers": 3}))
        m._poll_health()
        assert joiner.member not in m._quarantined
        assert joiner.url in m.server_urls
        assert m._server_models[joiner.url] == "ghost"
        # Already at the fleet's weight version (0), so the normal
        # readmission path routes it within the same poll.
        assert joiner.url in m._healthy
    finally:
        try:
            m._exit_hook()
        except Exception:
            pass
        seed.close()
        if joiner is not None:
            joiner.close()


# ----------------------------------------------------------------------
# Gateway entitlements: unknown-model refusal at parse time
# ----------------------------------------------------------------------

def test_entitlement_parse_rejects_unknown_model():
    from areal_tpu.system.gateway import parse_tenant_spec

    spec = "acme:k1:2:100:200:4:modela|modelb"
    with pytest.raises(ValueError, match="unknown model"):
        parse_tenant_spec(spec, known_models={"modela"})
    # Same spec against a fleet serving both: entitlements parse.
    t = parse_tenant_spec(spec, known_models={"modela", "modelb"})
    assert t["acme"].models == frozenset({"modela", "modelb"})
    # No 7th field = entitled to everything the fleet serves.
    t = parse_tenant_spec("acme:k1:2:100:200:4",
                          known_models={"modela"})
    assert t["acme"].models is None
    # Entitlement ids go through the registry charset check too.
    with pytest.raises(ValueError):
        parse_tenant_spec("acme:k1:2:100:200:4:bad/id")

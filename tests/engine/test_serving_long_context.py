"""Long-context serving pin: a >=16k-token sequence through the paged
engine with a reduced KV pool (VERDICT r3 missing #4 — the reference's
headline workload generates ~31k-token sequences,
benchmark/verl_v0_3_0_post1_76084d3/README.md:38-44; this CPU test keeps
the >=16k path from rotting while the on-chip numbers live in
docs/perf_notes.md)."""

import threading

import jax
import numpy as np
import pytest

from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params

PLEN = 16256
MAX_NEW = 64
PAGE = 128


@pytest.mark.slow
@pytest.mark.parametrize("prefill_chunk", [2048, None])
def test_serving_16k_context_reduced_pool(prefill_chunk):
    cfg = TransformerConfig(
        n_layers=1,
        hidden_dim=32,
        n_q_heads=1,
        n_kv_heads=1,
        head_dim=16,
        intermediate_dim=64,
        vocab_size=128,
        max_position_embeddings=32768,
        compute_dtype="float32",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    # Pool sized to barely one 16k request (plus block headroom): total
    # context must run inside a REDUCED pool, exercising the token-budget
    # accounting at long-context scale rather than a B*S-sized pool.
    eng = ServingEngine(
        cfg,
        params,
        max_batch_size=2,
        max_seq_len=PLEN + MAX_NEW + PAGE,
        decode_block_steps=16,
        prompt_bucket=PAGE,
        eos_token_id=None,
        page_size=PAGE,
        kv_pool_tokens=PLEN + MAX_NEW + 2 * PAGE,
        # Both long-context paths stay pinned: fixed-shape chunked
        # prefill (the recommended one — one compile for any prompt
        # length) and the batched bucketed path (still the default).
        prefill_chunk=prefill_chunk,
    )
    eng.start()
    try:
        rng = np.random.RandomState(0)
        done = threading.Event()
        res_holder = {}

        def cb(res):
            res_holder["res"] = res
            done.set()

        eng.submit(
            GenRequest(
                qid="long0",
                input_ids=rng.randint(0, cfg.vocab_size, size=PLEN).tolist(),
                max_new_tokens=MAX_NEW,
                done_cb=cb,
            )
        )
        assert done.wait(900), "16k-context generation stalled"
        res = res_holder["res"]
        assert len(res.output_ids) == MAX_NEW
        assert PLEN + len(res.output_ids) >= 16000  # >=16k total context
    finally:
        eng.stop()

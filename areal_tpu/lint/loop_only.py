"""Checker ``loop-only``: engine-loop thread discipline.

``ServingEngine``'s hot state (backlog, page allocator, donated KV
pool arrays, per-slot bookkeeping) has **no locks by design** — the
engine loop thread owns it, and the single cross-thread door is
``_run_on_loop`` (closures run between decode laps). That contract was
previously guarded only by comments; this checker machine-verifies it.

A class opts in by declaring a module-level literal registry::

    AREAL_LINT_LOOP_ONLY = {
        "ServingEngine": {
            "roots": ["_loop"],          # thread-target call-graph roots
            "door": "_run_on_loop",      # the one legal crossing
            "attrs": ["_backlog", ...],  # loop-owned attributes
            "init_ok": ["__init__"],     # pre-thread-start methods
            "instance_hints": ["engine"],  # names other modules hold
        },
    }

Rules enforced:

- ``self.<attr>`` for a registered attr may appear only in methods
  reachable from the roots (the loop call graph), in ``init_ok``
  methods (construction precedes ``start()``), or inside closures that
  are passed to the door (transitively: helpers called from a
  door-passed closure are also loop context).
- In EVERY scanned module, ``<x>.<attr>`` where ``<x>``'s terminal
  name is an instance hint (e.g. ``self.engine._backlog`` in an HTTP
  handler) is flagged: other threads/processes go through the door or
  the public API, never through the state.

The call graph is per-class and intra-module — dynamic dispatch is out
of scope; the registry names what matters and the checker makes the
cheap races (direct off-thread pokes) impossible to land silently.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from areal_tpu.lint.common import Finding, Module

CHECKER = "loop-only"
REGISTRY_NAME = "AREAL_LINT_LOOP_ONLY"

_ALLOWED_KEYS = {"roots", "door", "attrs", "init_ok", "instance_hints"}


def collect_registry(mod: Module) -> Dict[str, Dict]:
    """Literal-eval the module's AREAL_LINT_LOOP_ONLY, if any."""
    tree = mod.tree
    if not isinstance(tree, ast.Module):
        return {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == REGISTRY_NAME
        ):
            try:
                value = ast.literal_eval(node.value)
            except (ValueError, TypeError, SyntaxError, MemoryError):
                # literal_eval raises TypeError/SyntaxError on some
                # non-literal shapes; all must land as a finding, not a
                # linter traceback.
                return {"__error__": {"line": node.lineno,
                                      "msg": "registry must be a literal"}}
            if not isinstance(value, dict):
                return {"__error__": {"line": node.lineno,
                                      "msg": "registry must be a dict"}}
            for cls, spec in value.items():
                bad = set(spec) - _ALLOWED_KEYS
                if bad or not spec.get("roots") or not spec.get("attrs"):
                    return {"__error__": {
                        "line": node.lineno,
                        "msg": f"class {cls!r}: needs 'roots' and 'attrs'"
                               + (f", unknown keys {sorted(bad)}" if bad
                                  else ""),
                    }}
            return value
    return {}


def _method_map(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _loop_reachable(methods: Dict[str, ast.AST],
                    roots: List[str]) -> Set[str]:
    """Transitive closure over ``self.X`` references (calls AND bound-
    method passes both create reachability)."""
    edges: Dict[str, Set[str]] = {}
    for name, fn in methods.items():
        refs = set()
        for node in ast.walk(fn):
            a = _self_attr(node)
            if a and a in methods:
                refs.add(a)
        edges[name] = refs
    seen: Set[str] = set()
    work = [r for r in roots if r in methods]
    while work:
        cur = work.pop()
        if cur in seen:
            continue
        seen.add(cur)
        work.extend(edges.get(cur, ()))
    return seen


def _door_exempt_functions(mod: Module, method: ast.AST,
                           door: str) -> Set[ast.AST]:
    """Nested defs/lambdas inside ``method`` whose bodies run on the
    loop because they are handed to the door (transitively)."""
    nested: Dict[str, ast.FunctionDef] = {}
    lambdas: List[ast.Lambda] = []
    for node in ast.walk(method):
        if isinstance(node, ast.FunctionDef) and node is not method:
            nested[node.name] = node
        elif isinstance(node, ast.Lambda):
            lambdas.append(node)

    exempt: Set[ast.AST] = set()
    for node in ast.walk(method):
        if not isinstance(node, ast.Call):
            continue
        if _self_attr(node.func) != door:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in nested:
                exempt.add(nested[arg.id])
            elif isinstance(arg, ast.Lambda):
                exempt.add(arg)

    # Transitive: a helper referenced from a door-passed closure also
    # runs on the loop.
    changed = True
    while changed:
        changed = False
        for fn in list(exempt):
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and node.id in nested
                    and nested[node.id] not in exempt
                ):
                    exempt.add(nested[node.id])
                    changed = True
    return exempt


def check_declaring_module(mod: Module, registry: Dict[str, Dict]
                           ) -> List[Finding]:
    findings: List[Finding] = []
    if "__error__" in registry:
        err = registry["__error__"]
        return [Finding(mod.rel, err["line"], CHECKER,
                        f"malformed {REGISTRY_NAME}: {err['msg']}")]

    classes = {
        n.name: n for n in mod.nodes if isinstance(n, ast.ClassDef)
    }
    for cls_name, spec in registry.items():
        cls = classes.get(cls_name)
        if cls is None:
            findings.append(Finding(
                mod.rel, 1, CHECKER,
                f"{REGISTRY_NAME} names unknown class {cls_name!r}",
            ))
            continue
        attrs = set(spec["attrs"])
        door = spec.get("door")
        init_ok = set(spec.get("init_ok", ["__init__"])) | {"__init__"}
        methods = _method_map(cls)
        loop_methods = _loop_reachable(methods, list(spec["roots"]))

        for name, fn in methods.items():
            if name in loop_methods or name in init_ok:
                continue
            exempt = (
                _door_exempt_functions(mod, fn, door) if door else set()
            )
            for node in ast.walk(fn):
                a = _self_attr(node)
                if a is None or a not in attrs:
                    continue
                # ok if inside (or nested within) a door-passed closure
                cur = mod.enclosing_function(node)
                ok = False
                while cur is not None and cur is not fn:
                    if cur in exempt:
                        ok = True
                        break
                    cur = mod.enclosing_function(cur)
                if ok:
                    continue
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"{cls_name}.{name} touches loop-only attr "
                    f"self.{a} off the engine-loop call graph "
                    f"(roots {spec['roots']}): route it through "
                    f"{door or 'the loop door'} or maintain a "
                    f"loop-updated snapshot",
                ))
    return findings


def check_instance_hints(mod: Module, hints: Dict[str, Set[str]]
                         ) -> List[Finding]:
    """In non-declaring modules: flag ``<hint>.<loop-only attr>``."""
    if not hints:
        return []
    findings: List[Finding] = []
    for node in mod.nodes:
        if not isinstance(node, ast.Attribute):
            continue
        hint_names = hints.get(node.attr)
        if not hint_names:
            continue
        recv = node.value
        terminal = None
        if isinstance(recv, ast.Name):
            terminal = recv.id
        elif isinstance(recv, ast.Attribute):
            terminal = recv.attr
        if terminal in hint_names:
            findings.append(Finding(
                mod.rel, node.lineno, CHECKER,
                f"{terminal}.{node.attr} pokes engine-loop-only state "
                f"from outside the engine: use the public API or the "
                f"loop door",
            ))
    return findings

"""Device acquisition for a flaky accelerator tunnel.

Two failure families look identical at `jax.devices()` but demand
opposite reactions:

- **tunnel-down** (UNAVAILABLE, connection refused/reset, deadline
  exceeded, device busy): the hardware is fine, the path to it flaps.
  Poll with backoff until the wall-clock budget is spent — a window may
  open any second.
- **driver/version** (jaxlib mismatch, incompatible libtpu,
  INVALID_ARGUMENT, plugin not found): retrying replays the same
  failure forever. Abort fast and surface the error — 9 hours of
  watcher probes against a version skew bank nothing.

`get_devices_with_retry` replaces the old bench retry loop that treated
both identically with a fixed attempt count.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional

from areal_tpu.base import env_registry
from areal_tpu.bench._util import log


# Matched against the lowered stringified exception. Driver markers are
# checked FIRST: they are the more specific diagnosis, and several
# driver failures also contain generic "failed to initialize" text.
DRIVER_MARKERS = (
    "version mismatch",
    "incompatible",
    "invalid_argument",
    "jaxlib is version",
    "libtpu version",
    "plugin not found",
    "no tpu library",
    "permission denied",
)
TUNNEL_MARKERS = (
    "unavailable",
    "connection refused",
    "connection reset",
    "connect",
    "tunnel",
    "socket",
    "deadline exceeded",
    "timed out",
    "device or resource busy",
    "already in use",
    "backend setup/compile error",
    "unable to initialize backend",
)


def classify_device_error(err) -> str:
    """'driver' (abort fast), 'tunnel' (poll/backoff), or 'unknown'
    (treated like tunnel, but the caller may cap retries)."""
    text = str(err).lower()
    if any(m in text for m in DRIVER_MARKERS):
        return "driver"
    if any(m in text for m in TUNNEL_MARKERS):
        return "tunnel"
    return "unknown"


class DriverError(RuntimeError):
    """A device failure classified as non-transient: do not retry."""


def get_devices_with_retry(
    budget_s: Optional[float] = None,
    backoff_s: Optional[float] = None,
    max_backoff_s: float = 60.0,
    devices_fn: Optional[Callable[[], List]] = None,
    sleep=time.sleep,
    clock=time.monotonic,
):
    """`jax.devices()` under a total wall-clock budget.

    Tunnel-class failures poll with exponential backoff until the budget
    is spent (each retry clears cached backends so the next attempt
    re-dials instead of replaying the cached failure); driver-class
    failures raise :class:`DriverError` immediately. Raises the last
    tunnel error once the budget runs out.

    `devices_fn`/`sleep`/`clock` are injectable for tests."""
    if budget_s is None:
        budget_s = env_registry.get_float("AREAL_BENCH_DEVICE_BUDGET_S")
    if backoff_s is None:
        backoff_s = env_registry.get_float("AREAL_BENCH_INIT_BACKOFF_S")

    if devices_fn is None:
        import jax

        devices_fn = jax.devices
    deadline = clock() + budget_s
    delay = backoff_s
    attempt = 0
    last = None
    while True:
        attempt += 1
        try:
            return devices_fn()
        except Exception as e:
            kind = classify_device_error(e)
            if kind == "driver":
                raise DriverError(
                    f"device init failed with a driver/version error "
                    f"(not retrying): {e!r}"
                ) from e
            last = e
            remaining = deadline - clock()
            log(f"bench: device init failed ({kind}, attempt {attempt}, "
                f"{remaining:.0f}s budget left): {e!r}")
            if remaining <= 0:
                break
            try:
                import jax

                jax.clear_backends()
            except Exception:
                pass  # older jax / partial init: retry cold
            sleep(min(delay, max(remaining, 0.0)))
            delay = min(delay * 2, max_backoff_s)
    raise last

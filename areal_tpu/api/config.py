"""Abstraction dataclasses and the generic named-factory registry.

Counterpart of the reference's core config module
(reference: realhf/api/core/config.py). An *abstraction* is a
(type-name, kwargs) pair resolved through a registry at runtime, which is
how experiments select dataset/interface/backend/agent implementations
declaratively.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass(unsafe_hash=True, order=True)
class ModelName:
    """A named model replica: role ('actor', 'critic', ...) + replica index.

    Different replicas of one role (e.g. 'actor' for training vs 'actor' for
    generation) share weights logically but may live on different meshes.
    """

    role: str = "default"
    replica_id: int = 0

    def __str__(self):
        return f"{self.role}@{self.replica_id}"

    @classmethod
    def parse(cls, s: str) -> "ModelName":
        if "@" in s:
            role, rid = s.split("@")
            return cls(role=role, replica_id=int(rid))
        return cls(role=s)


@dataclasses.dataclass(unsafe_hash=True)
class ModelShardID:
    """Identifies one host process's shard of a model deployment.

    On TPU a model spans a whole `jax.sharding.Mesh` as a single SPMD
    program; host processes each drive the same program over their local
    devices. So unlike the reference's per-GPU (dp, pp, tp) coordinates
    (realhf/api/core/config.py:85), a shard here is just (model, host
    index, host count) plus the mesh spec string for validation.
    """

    model_name: ModelName = dataclasses.field(default_factory=ModelName)
    host_rank: int = 0
    n_hosts: int = 1
    mesh_spec: str = "d1f1s1t1"

    def __str__(self):
        return f"{self.model_name}:{self.host_rank}of{self.n_hosts}"


@dataclasses.dataclass
class ModelFamily:
    """HF model family tag: which converter/architecture to use."""

    _class: str = "qwen2"
    is_critic: bool = False

    def __str__(self):
        return f"{self._class}{'-critic' if self.is_critic else ''}"


def _abstraction(cls_name: str):
    @dataclasses.dataclass
    class _Abstraction:
        type_: str = "default"
        args: Dict[str, Any] = dataclasses.field(default_factory=dict)

    _Abstraction.__name__ = cls_name
    _Abstraction.__qualname__ = cls_name
    return _Abstraction


ModelAbstraction = _abstraction("ModelAbstraction")
ModelInterfaceAbstraction = _abstraction("ModelInterfaceAbstraction")
ModelBackendAbstraction = _abstraction("ModelBackendAbstraction")
DatasetAbstraction = _abstraction("DatasetAbstraction")
AgentAbstraction = _abstraction("AgentAbstraction")
EnvServiceAbstraction = _abstraction("EnvServiceAbstraction")


class Registry:
    """Simple name -> factory registry with helpful errors."""

    def __init__(self, kind: str):
        self.kind = kind
        self._factories: Dict[str, Any] = {}

    def register(self, name: str, factory):
        if name in self._factories:
            raise ValueError(f"{self.kind} {name!r} already registered")
        self._factories[name] = factory

    def make(self, abstraction_or_name, *args, **kwargs):
        if isinstance(abstraction_or_name, str):
            name, extra = abstraction_or_name, {}
        else:
            name, extra = abstraction_or_name.type_, abstraction_or_name.args
        if name not in self._factories:
            raise KeyError(
                f"unknown {self.kind} {name!r}; registered: {sorted(self._factories)}"
            )
        return self._factories[name](*args, **{**extra, **kwargs})

    def __contains__(self, name: str):
        return name in self._factories

    def keys(self):
        return sorted(self._factories)

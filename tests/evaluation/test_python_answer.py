"""PAL-style python answer execution (role of the reference's
evaluation/python_executor.py): sandboxed run of model-written programs,
answer extraction, grading, and the 'pal' prompt template."""

import pytest

from areal_tpu.functioncall.python_answer import (
    execute_python_answer,
    grade_python_answer,
)


def test_solution_function_return_value():
    text = (
        "Let me compute this.\n"
        "```python\n"
        "def solution():\n"
        "    return 4 * 9 - 7\n"
        "```"
    )
    assert execute_python_answer(text) == "29"
    assert grade_python_answer(text, ["29"])
    assert not grade_python_answer(text, ["28"])


def test_print_style_last_line():
    text = "```python\nx = 2 * 12 + 3 * 5\nprint('total:')\nprint(x)\n```"
    assert execute_python_answer(text) == "39"


def test_last_code_block_wins():
    text = (
        "First try:\n```python\nprint(1)\n```\n"
        "Corrected:\n```python\nprint(2)\n```"
    )
    assert execute_python_answer(text) == "2"


def test_no_code_block_and_failures():
    assert execute_python_answer("The answer is 42.") is None
    assert execute_python_answer("```python\n1/0\n```") is None
    assert execute_python_answer("```python\npass\n```") is None
    assert not grade_python_answer("no code here", ["1"])


def test_runaway_program_times_out():
    text = "```python\nwhile True:\n    pass\n```"
    assert execute_python_answer(text, timeout=2.0) is None


def test_fractional_and_expression_answers():
    text = "```python\ndef solution():\n    return 15 * 2.5\n```"
    assert grade_python_answer(text, ["37.5"])


def test_pal_prompt_template_and_demos():
    from evaluation.presets import PAL_FEW_SHOT, build_prompt

    p = build_prompt("What is 6 * 7?", "pal", num_shots=2)
    assert p.rstrip().endswith("```python")
    assert PAL_FEW_SHOT[0][0] in p
    # The demo programs themselves execute to the right answers.
    assert execute_python_answer(PAL_FEW_SHOT[0][1]) == "29"
    assert execute_python_answer(PAL_FEW_SHOT[1][1]) == "39"
    # Over-asking demos fails loudly (pal pool has 2).
    with pytest.raises(ValueError, match="few-shot"):
        build_prompt("q", "pal", num_shots=3)


def test_open_fence_continuation_extracted():
    """The 'pal' template OPENS the fence in the prompt, so a compliant
    completion is bare code ending with a closing fence — it must
    execute, not fall through as 'no code block'."""
    # Model continuation with closing fence only.
    cont = "def solution():\n    return 4 * 9 - 7\n```\nThe answer is 29."
    assert execute_python_answer(cont) == "29"
    # Budget-truncated continuation: no fence at all.
    cont2 = "def solution():\n    return 2 + 2\n"
    assert execute_python_answer(cont2) == "4"
    # Prose with no fence and no solution() stays rejected.
    assert execute_python_answer("I think the answer is 4.") is None


def test_boxed_reference_unboxed_in_python_mode():
    """Solution-form ground truth ('\\boxed{4}') must grade the same in
    python mode as grade_answer does in text mode."""
    from areal_tpu.functioncall.python_answer import compare_python_answer

    text = "```python\ndef solution():\n    return 4\n```"
    assert grade_python_answer(text, ["\\boxed{4}"])
    assert compare_python_answer("4", ["\\boxed{4}"])
    assert not compare_python_answer("5", ["\\boxed{4}"])
    assert not compare_python_answer(None, ["\\boxed{4}"])


def test_model_opened_fence_truncated():
    """A completion that opens its OWN tagged fence and is truncated
    before closing it still yields the code (not the prose before)."""
    text = "Here is the code:\n```python\ndef solution():\n    return 42"
    assert execute_python_answer(text) == "42"
    # Bare unterminated fence with nothing before it: code follows.
    assert execute_python_answer("```\nprint(7)") == "7"
